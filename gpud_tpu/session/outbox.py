"""Durable store-and-forward outbox for the control-plane session.

The session's in-memory channels (``CHANNEL_CAP`` in session.py) are a
wire buffer, not a delivery contract: anything produced while the
control plane is unreachable — exactly the window the fleet operator
most needs this node's telemetry ("When GPUs Fail Quietly", PAPERS.md) —
was silently lost on overflow or daemon restart. The ``SessionOutbox``
closes that gap:

- producers (event inserts, health transitions, remediation audit rows,
  chaos campaign results, gossip) ``publish()`` outbound records; each
  is journaled to a SQLite table through the shared write-behind
  ``BatchWriter`` (docs/storage.md) and assigned a monotonic sequence
  number at publish time;
- a replay job drains everything above the last manager-acked watermark
  into the live session whenever it is connected — at-least-once
  delivery: a redelivered frame carries the same ``dedupe_key``, so the
  manager side deduplicates;
- the manager acks by calling the ``outboxAck`` session method with the
  highest contiguous sequence it has seen; the watermark only ever
  advances (``MAX(acked_seq, ?)`` both in memory and in SQL), so a crash
  or batch reorder can never regress it and re-deliver the world;
- retention bounds the journal by row count and age so a week-long
  partition degrades telemetry (oldest rows drop, with accounting in
  ``tpud_outbox_dropped_total``) instead of filling the disk.

The module also owns the session ``CircuitBreaker``
(closed → open on consecutive connect failures → half-open probe →
closed), exposed as ``tpud_session_circuit_state`` and consulted by the
session keep-alive loop so a hard-down manager stops costing connect
attempts. Delivery semantics are documented in docs/session.md.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge
from gpud_tpu.session import wire

logger = get_logger(__name__)

TABLE = "tpud_session_outbox_v0_1"
ACK_TABLE = "tpud_session_outbox_ack_v0_1"

DEFAULT_MAX_ROWS = 100_000        # journal hard cap (rows)
DEFAULT_MAX_AGE = 7 * 86400       # journal age cap: one week of partition
DEFAULT_REPLAY_BATCH = 500        # records packed into one delivery frame
DEFAULT_REDELIVER_AFTER = 30.0    # ack-stall window before redelivery

# delivery frames ride the normal agent→manager stream with this req_id
# prefix; the manager treats them as unsolicited data, not responses
REPLAY_REQ_PREFIX = "outbox-"

# write-behind contract (tools/storage_lint.py): these methods must route
# through the BatchWriter, never commit per-row via db.execute directly
HOT_WRITE_METHODS = ("publish", "ack")

_c_published = counter(
    "tpud_outbox_published_total",
    "records journaled into the session outbox, by kind",
)
_c_replayed = counter(
    "tpud_outbox_replayed_total",
    "outbox frames handed to the session transport (delivery attempts; "
    "at-least-once, so redeliveries count again)",
)
_c_dropped = counter(
    "tpud_outbox_dropped_total",
    "outbox records lost before ack, by reason (journal-full write drops, "
    "retention purging unacked rows past the hard cap)",
)
_c_purged = counter(
    "tpud_outbox_purged_total",
    "acked outbox rows removed by size/age retention (normal housekeeping, "
    "not data loss)",
)
_g_backlog = gauge(
    "tpud_outbox_backlog",
    "journaled outbox records not yet acked by the manager",
)
_g_acked = gauge(
    "tpud_outbox_acked_seq",
    "highest manager-acked outbox sequence number (the replay watermark)",
)
_g_circuit = gauge(
    "tpud_session_circuit_state",
    "control-plane circuit breaker state: 0=closed, 1=open, 2=half-open",
)
_c_circuit_transitions = counter(
    "tpud_session_circuit_transitions_total",
    "circuit breaker state transitions, by target state",
)
_c_circuit_blocked = counter(
    "tpud_session_circuit_blocked_total",
    "connect attempts suppressed because the circuit breaker was open",
)


class SessionOutbox:
    """Durable at-least-once delivery journal (module docstring).

    Thread-safe: ``publish`` may be called from any producer thread
    (component checks, the kmsg watcher, session dispatch, the chaos
    runner); ``ack`` arrives on the session serve thread; ``replay_once``
    runs on a scheduler worker. Sequence assignment and the watermark are
    guarded by one lock; SQL rides the shared ``BatchWriter`` buffer.
    """

    GUARDED_BY = {
        "_next_seq": "_mu",
        "_acked": "_mu",
        "_published": "_mu",
        "_replayed": "_mu",
        "_write_drops": "_mu",
        "_retention_drops": "_mu",
        "_delivered": "_mu",
        "_ack_progress_ts": "_mu",
        "_flushed_seq": "_mu",
        "_encoder": "_mu",
    }

    def __init__(
        self,
        db,
        writer=None,
        max_rows: int = DEFAULT_MAX_ROWS,
        max_age_seconds: float = DEFAULT_MAX_AGE,
        replay_batch: int = DEFAULT_REPLAY_BATCH,
        keyframe_interval: int = wire.DEFAULT_KEYFRAME_INTERVAL,
        redeliver_after_seconds: float = DEFAULT_REDELIVER_AFTER,
        time_now_fn: Callable[[], float] = time.time,
    ) -> None:
        self.db = db
        self.writer = writer
        self.max_rows = int(max_rows)
        self.max_age_seconds = float(max_age_seconds)
        self.replay_batch = max(1, int(replay_batch))
        self.redeliver_after_seconds = float(redeliver_after_seconds)
        self.time_now_fn = time_now_fn
        self._mu = threading.Lock()
        # per-stream delta encoder for delivery batches (docs/session.md
        # wire format); guarded by _mu — replay runs on a scheduler
        # worker, reset_delivery on the session keep-alive thread
        self._encoder = wire.DeltaEncoder(keyframe_interval)
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                seq INTEGER PRIMARY KEY,
                ts REAL NOT NULL,
                kind TEXT NOT NULL,
                dedupe_key TEXT NOT NULL,
                payload TEXT NOT NULL
            )"""
        )
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {ACK_TABLE} (
                id INTEGER PRIMARY KEY CHECK (id = 1),
                acked_seq INTEGER NOT NULL
            )"""
        )
        db.execute(
            f"INSERT OR IGNORE INTO {ACK_TABLE} (id, acked_seq) VALUES (1, 0)"
        )
        # restart: resume sequence numbering after the highest journaled
        # row and reload the persisted watermark — both only ever advance
        row = db.query_one(f"SELECT MAX(seq) FROM {TABLE}")
        self._next_seq = int(row[0] or 0) + 1 if row else 1
        row = db.query_one(f"SELECT acked_seq FROM {ACK_TABLE} WHERE id=1")
        self._acked = int(row[0] or 0) if row else 0
        # a restart may reload a watermark ahead of MAX(seq) if acked rows
        # were purged; never mint a seq at/below the watermark
        if self._acked >= self._next_seq:
            self._next_seq = self._acked + 1
        self._published = 0
        self._replayed = 0
        self._write_drops = 0
        self._retention_drops = 0
        # delivered-high-water: the highest seq already handed to the
        # live transport this connection. Purely in-memory — replay reads
        # above max(acked, delivered) so a slow ack doesn't cause a
        # redundant SELECT + re-encode every tick (dedupe keys make
        # redelivery safe; re-reading was pure wasted work). Falls back
        # to the durable watermark on reconnect (reset_delivery) or when
        # acks stall past redeliver_after_seconds.
        self._delivered = self._acked
        self._ack_progress_ts = self.time_now_fn()
        # journal-flush high-water: the highest seq known durable behind
        # the write-behind buffer. pending() only needs a flush barrier
        # when rows it could return are still buffered; skipping the
        # barrier otherwise keeps steady-state drain off the flusher's
        # critical path (the coalesced ack UPDATE is always buffered, but
        # it never gates a read — the replay floor is in-memory)
        self._flushed_seq = self._next_seq - 1
        _g_acked.set(self._acked)
        _g_backlog.set(self.backlog())

    # -- producer side -----------------------------------------------------
    def publish(
        self, kind: str, payload: Dict, dedupe_key: str = ""
    ) -> int:
        """Journal one outbound record; returns its sequence number.

        ``dedupe_key`` identifies the record across redeliveries (the
        manager's dedupe handle); empty derives a stable ``kind:seq`` key.
        """
        now = self.time_now_fn()
        with self._mu:
            seq = self._next_seq
            self._next_seq += 1
            self._published += 1
            # snapshot the watermark for the gauge below — reading
            # self._acked unlocked after the block raced ack()
            acked = self._acked
        key = dedupe_key or f"{kind}:{seq}"
        sql = (
            f"INSERT INTO {TABLE} (seq, ts, kind, dedupe_key, payload) "
            "VALUES (?, ?, ?, ?, ?)"
        )
        # wire.pack_obj: msgpack bytes when available (several times
        # faster to serialize AND to re-read on the replay hot path —
        # bench.py --wire), compact JSON otherwise; unpack_obj sniffs, so
        # journals mix encodings freely across upgrades
        params = (seq, now, kind, key, wire.pack_obj(payload))
        if self.writer is not None:
            if not self.writer.submit("outbox", sql, params):
                with self._mu:
                    self._write_drops += 1
                _c_dropped.inc(labels={"reason": "journal_full"})
        else:
            self.db.execute(sql, params)
        _c_published.inc(labels={"kind": kind})
        _g_backlog.set(max(0, seq - acked))
        return seq

    # -- manager ack path --------------------------------------------------
    def ack(self, seq: int) -> int:
        """Advance the replay watermark to ``seq``; returns the (possibly
        unchanged) watermark. Monotonic: a stale or duplicate ack — the
        manager replays acks too under at-least-once — never regresses it.
        """
        seq = int(seq)
        with self._mu:
            if seq <= self._acked:
                return self._acked
            self._acked = seq
            self._ack_progress_ts = self.time_now_fn()
            if seq > self._delivered:
                # an ack implies delivery even if this process never sent
                # the frame (restart raced a late manager ack)
                self._delivered = seq
        # MAX() in SQL too: group-commit batches may reorder vs. memory
        sql = f"UPDATE {ACK_TABLE} SET acked_seq = MAX(acked_seq, ?) WHERE id = 1"
        if self.writer is not None:
            # coalesce: many acks inside one flush window commit once
            self.writer.submit("outbox", sql, (seq,), key=("outbox-ack",))
        else:
            self.db.execute(sql, (seq,))
        _g_acked.set(seq)
        _g_backlog.set(self.backlog())
        return seq

    @property
    def acked_seq(self) -> int:
        with self._mu:
            return self._acked

    @property
    def last_seq(self) -> int:
        with self._mu:
            return self._next_seq - 1

    def backlog(self) -> int:
        with self._mu:
            return max(0, (self._next_seq - 1) - self._acked)

    @property
    def delivered_seq(self) -> int:
        with self._mu:
            return self._delivered

    def reset_delivery(self) -> None:
        """Reconnect hook (server on_connected): in-flight unacked frames
        may have died with the old connection and the manager's delta
        decoder is fresh — fall back to the durable watermark and restart
        every delta stream at a keyframe."""
        with self._mu:
            self._delivered = self._acked
            self._encoder.reset()
            self._ack_progress_ts = self.time_now_fn()

    # -- replay ------------------------------------------------------------
    def flush(self) -> None:
        """Read-after-write barrier (no-op without a writer, or when every
        published row is already known durable)."""
        if self.writer is None:
            return
        with self._mu:
            target = self._next_seq - 1
            if target <= self._flushed_seq:
                return
        if self.writer.flush():
            with self._mu:
                if target > self._flushed_seq:
                    self._flushed_seq = target

    def _read_pending(
        self, after: int, limit: int
    ) -> Tuple[List[Tuple], List]:
        """Rows above ``after`` plus their decoded payloads, as parallel
        lists (the replay hot path consumes them zipped without building
        combined 5-tuples). Callers handle the flush barrier."""
        sql = (
            f"SELECT seq, ts, kind, dedupe_key, payload FROM {TABLE} "
            "WHERE seq > ? ORDER BY seq"
        )
        params: list = [after]
        if limit:
            sql += " LIMIT ?"
            params.append(limit)
        rows = self.db.query(sql, params)
        raws = [r[4] for r in rows]
        try:
            payloads = wire.unpack_many(raws)
        except ValueError:
            # a corrupt row must not become a poison pill that fails every
            # replay tick — deliver it as an opaque blob instead
            payloads = []
            for raw in raws:
                try:
                    payloads.append(wire.unpack_obj(raw))
                except ValueError:
                    payloads.append({"raw": repr(raw)})
        return rows, payloads

    def pending(
        self, limit: int = 0, after: Optional[int] = None
    ) -> List[Tuple[int, float, str, str, Dict]]:
        """Journaled records above the watermark (or ``after``), oldest
        first: ``(seq, ts, kind, dedupe_key, payload)`` rows."""
        self.flush()
        rows, payloads = self._read_pending(
            self.acked_seq if after is None else int(after), limit
        )
        return [
            (seq, ts, kind, key, payloads[i])
            for i, (seq, ts, kind, key, _raw) in enumerate(rows)
        ]

    def replay_once(self, session) -> int:
        """Drain one delivery batch into a connected session.

        Packs up to ``replay_batch`` delta-encoded records into ONE
        ``outbox_batch`` frame (docs/session.md wire format); the manager
        answers a single cumulative ``outboxAck`` per batch. Returns the
        number of records handed to the transport (0 = nothing pending
        or the send was refused — the next tick retries keyframe-anchored,
        which is what at-least-once means). Reads above
        ``max(acked, delivered)`` so already-delivered-but-unacked rows
        aren't re-read and re-encoded every tick; an ack stalled past
        ``redeliver_after_seconds`` drops the delivered floor back to the
        durable watermark and restarts the delta streams. A disconnected
        or auth-parked session is a no-op: replay must not hammer a
        manager that just revoked the token.
        """
        if session is None or not session.connected or session.auth_failed:
            return 0
        from gpud_tpu.session.session import Frame

        now = self.time_now_fn()
        with self._mu:
            if (
                self._delivered > self._acked
                and now - self._ack_progress_ts >= self.redeliver_after_seconds
            ):
                # frames in flight on a previous connection (or a stalled
                # manager) never acked: redeliver from the durable
                # watermark, keyframe-anchored — this is also the repair
                # path for a peer whose delta decoder lost sync
                logger.warning(
                    "outbox ack stalled %.0fs at seq %d (delivered %d); "
                    "redelivering", now - self._ack_progress_ts,
                    self._acked, self._delivered,
                )
                self._delivered = self._acked
                self._encoder.reset()
                self._ack_progress_ts = now
            floor = max(self._acked, self._delivered)
        self.flush()
        rows, payloads = self._read_pending(floor, self.replay_batch)
        if not rows:
            return 0
        with self._mu:
            encode = self._encoder.encode_record
            records = [
                encode(row[0], row[1], row[2], row[3], payloads[i])
                for i, row in enumerate(rows)
            ]
        first, last = rows[0][0], rows[-1][0]
        frame = Frame(
            req_id=f"{REPLAY_REQ_PREFIX}batch-{first}-{last}",
            data=wire.build_batch(records),
        )
        if not session.send(frame):
            with self._mu:
                # the peer may have read a prefix of the frame's streams;
                # restart them so redelivery is keyframe-anchored
                self._encoder.reset()
            return 0
        sent = len(rows)
        with self._mu:
            self._replayed += sent
            if last > self._delivered:
                self._delivered = last
        _c_replayed.inc(sent)
        return sent

    # -- retention ---------------------------------------------------------
    def purge_once(self) -> int:
        """Size/age retention pass (scheduler "retention-purge" target).

        Acked rows older than ``max_age_seconds`` go first (normal
        housekeeping). Past ``max_rows`` the oldest rows drop regardless
        of ack state — unacked drops are data loss and are accounted in
        ``tpud_outbox_dropped_total{reason=retention}``.
        """
        self.flush()
        cutoff = self.time_now_fn() - self.max_age_seconds
        acked = self.acked_seq
        cur = self.db.execute(
            f"DELETE FROM {TABLE} WHERE seq <= ? AND ts < ?", (acked, cutoff)
        )
        purged = max(0, int(getattr(cur, "rowcount", 0) or 0))
        row = self.db.query_one(f"SELECT COUNT(*), MIN(seq) FROM {TABLE}")
        count, min_seq = (int(row[0] or 0), int(row[1] or 0)) if row else (0, 0)
        if count > self.max_rows:
            excess = count - self.max_rows
            horizon = min_seq + excess - 1
            lost = self.db.query_one(
                f"SELECT COUNT(*) FROM {TABLE} WHERE seq <= ? AND seq > ?",
                (horizon, acked),
            )
            lost_n = int(lost[0] or 0) if lost else 0
            self.db.execute(f"DELETE FROM {TABLE} WHERE seq <= ?", (horizon,))
            purged += excess
            if lost_n:
                with self._mu:
                    self._retention_drops += lost_n
                _c_dropped.inc(lost_n, {"reason": "retention"})
                logger.warning(
                    "outbox retention dropped %d unacked record(s) "
                    "(journal past %d rows)", lost_n, self.max_rows,
                )
                # rows below the horizon are gone; pretend the manager
                # acked them so replay doesn't spin on a hole forever
                self.ack(horizon)
        if purged:
            _c_purged.inc(purged)
        _g_backlog.set(self.backlog())
        return purged

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict:
        with self._mu:
            published = self._published
            replayed = self._replayed
            acked = self._acked
            delivered = self._delivered
            next_seq = self._next_seq
            write_drops = self._write_drops
            retention_drops = self._retention_drops
            keyframe_interval = self._encoder.keyframe_interval
        return {
            "last_seq": next_seq - 1,
            "acked_seq": acked,
            "delivered_seq": delivered,
            "backlog": max(0, (next_seq - 1) - acked),
            "keyframe_interval": keyframe_interval,
            "redeliver_after_seconds": self.redeliver_after_seconds,
            "published": published,
            "replayed": replayed,
            "dropped_journal_full": write_drops,
            "dropped_retention": retention_drops,
            "max_rows": self.max_rows,
            "max_age_seconds": self.max_age_seconds,
        }


# -- circuit breaker -------------------------------------------------------

CIRCUIT_CLOSED = "closed"
CIRCUIT_OPEN = "open"
CIRCUIT_HALF_OPEN = "half_open"

_CIRCUIT_GAUGE_VALUES = {CIRCUIT_CLOSED: 0, CIRCUIT_OPEN: 1, CIRCUIT_HALF_OPEN: 2}

DEFAULT_FAILURE_THRESHOLD = 5
DEFAULT_OPEN_SECONDS = 30.0
_HISTORY_CAP = 64


class CircuitBreaker:
    """Connect-path circuit breaker (closed → open → half-open → closed).

    ``allow()`` gates each connect attempt: closed always permits; open
    denies until ``open_seconds`` elapse, then transitions to half-open
    and permits exactly one probe; the probe's ``record_success`` closes
    the circuit, its ``record_failure`` re-opens it for a fresh cooldown.
    State rides ``tpud_session_circuit_state`` and a bounded transition
    history feeds the chaos expectation layer.

    With a ``peers`` list (HA manager tier, docs/session.md) the breaker
    also owns failover: every trip to OPEN rotates ``current_peer()`` to
    the next configured manager, and until one full sweep of the peer
    list has failed, the rotation grants an immediate probe at the new
    peer instead of sitting out the cooldown — a dead manager costs
    reconnect latency, not ``open_seconds`` per peer. Once every peer
    has failed in one sweep, the normal cooldown resumes (the whole
    tier is down; hammering it helps nobody). The acked-watermark
    contract is unaffected: ``SessionOutbox.ack`` is monotonic MAX, so
    acks arriving late from the old peer can never regress what the new
    peer has acknowledged.
    """

    GUARDED_BY = {
        "_state": "_mu",
        "_failures": "_mu",
        "_opened_at": "_mu",
        "_blocked": "_mu",
        "history": "_mu",
        "_peer_index": "_mu",
        "_failover_probe": "_mu",
        "_sweep": "_mu",
        "_failovers": "_mu",
    }

    def __init__(
        self,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        open_seconds: float = DEFAULT_OPEN_SECONDS,
        time_fn: Callable[[], float] = time.monotonic,
        peers: Optional[List[str]] = None,
    ) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_seconds = float(open_seconds)
        self.time_fn = time_fn
        # peer endpoints in failover order; entry 0 is the primary. Set
        # at configuration time, before the session's keep-alive thread
        # starts — only the index is guarded
        self.peers: List[str] = [p for p in (peers or []) if p]
        self._mu = threading.Lock()
        self._state = CIRCUIT_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._blocked = 0
        self._peer_index = 0
        self._failover_probe = False
        self._sweep = 0  # consecutive peers failed in the current sweep
        self._failovers = 0
        # (monotonic_ts, state) transitions, oldest first, bounded
        self.history: List[Tuple[float, str]] = [(self.time_fn(), CIRCUIT_CLOSED)]
        _g_circuit.set(0)

    def _transition_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        self.history.append((self.time_fn(), state))
        del self.history[:-_HISTORY_CAP]
        _g_circuit.set(_CIRCUIT_GAUGE_VALUES[state])
        _c_circuit_transitions.inc(labels={"to": state})

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def states_seen(self) -> List[str]:
        with self._mu:
            return [s for _ts, s in self.history]

    def allow(self) -> bool:
        """True when a connect attempt may proceed now."""
        with self._mu:
            if self._state == CIRCUIT_CLOSED:
                return True
            if self._state == CIRCUIT_OPEN:
                if self._failover_probe:
                    # a failover just rotated current_peer(): probe the
                    # new peer immediately instead of serving the dead
                    # peer's cooldown (one probe — it either closes the
                    # circuit or burns this peer too)
                    self._failover_probe = False
                    self._transition_locked(CIRCUIT_HALF_OPEN)
                    return True
                if self.time_fn() - self._opened_at >= self.open_seconds:
                    self._transition_locked(CIRCUIT_HALF_OPEN)
                    return True  # the single half-open probe
                self._blocked += 1
                _c_circuit_blocked.inc()
                return False
            # half-open: one probe is already in flight on the keep-alive
            # thread; there is exactly one caller, so permitting again is
            # harmless but keep the gate strict
            return True

    def recovery_age(self) -> Optional[float]:
        """Seconds since the breaker last closed out of half-open, or
        None when the latest transition isn't such a recovery. A fresh
        recovery means this connect is the first after an outage — the
        whole fleet is reconnecting at once, so the server jitters its
        outbox replay poke instead of bursting (docs/session.md)."""
        with self._mu:
            h = self.history
            if (
                len(h) >= 2
                and h[-1][1] == CIRCUIT_CLOSED
                and h[-2][1] == CIRCUIT_HALF_OPEN
            ):
                return max(0.0, self.time_fn() - h[-1][0])
        return None

    def seconds_until_probe(self) -> float:
        """Remaining cooldown while open (0 when an attempt may proceed)."""
        with self._mu:
            if self._state != CIRCUIT_OPEN:
                return 0.0
            if self._failover_probe:
                return 0.0  # a rotated peer is waiting for its probe
            return max(0.0, self.open_seconds - (self.time_fn() - self._opened_at))

    def record_success(self) -> None:
        with self._mu:
            self._failures = 0
            self._sweep = 0
            self._failover_probe = False
            self._transition_locked(CIRCUIT_CLOSED)

    def record_failure(self) -> None:
        with self._mu:
            self._failures += 1
            if self._state == CIRCUIT_HALF_OPEN:
                # failed probe: back to open for a fresh cooldown
                self._opened_at = self.time_fn()
                self._transition_locked(CIRCUIT_OPEN)
                self._rotate_peer_locked()
            elif (
                self._state == CIRCUIT_CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = self.time_fn()
                self._transition_locked(CIRCUIT_OPEN)
                self._rotate_peer_locked()

    def _rotate_peer_locked(self) -> None:
        """On every trip to OPEN with >1 configured peers: advance to
        the next peer and decide whether it gets an immediate probe
        (still inside the current sweep) or the normal cooldown (one
        full sweep failed — every peer is down)."""
        if len(self.peers) < 2:
            return
        self._peer_index = (self._peer_index + 1) % len(self.peers)
        self._failovers += 1
        self._sweep += 1
        if self._sweep < len(self.peers):
            self._failover_probe = True
        else:
            self._sweep = 0
            self._failover_probe = False

    def current_peer(self) -> str:
        """The endpoint spec the session should dial now ("" without a
        configured peer list)."""
        with self._mu:
            if not self.peers:
                return ""
            return self.peers[self._peer_index]

    @property
    def failover_count(self) -> int:
        with self._mu:
            return self._failovers

    @property
    def blocked_count(self) -> int:
        with self._mu:
            return self._blocked

    def stats(self) -> Dict:
        with self._mu:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "failure_threshold": self.failure_threshold,
                "open_seconds": self.open_seconds,
                "blocked_attempts": self._blocked,
                "states_seen": [s for _ts, s in self.history],
                "peers": list(self.peers),
                "peer_index": self._peer_index,
                "failovers": self._failovers,
            }
