"""Rev-2 typed-request adaptation: ManagerPacket → dispatcher request.

The dispatcher's contract is a method-keyed dict (session/dispatch.py) —
shared by v1 JSON and rev-1 Frames. Rev 2 replaces the *wire* encoding
with per-method protobuf messages (reference:
pkg/session/v2/session.proto:16-60 ManagerPacket oneof); this module maps
each typed request onto the dispatcher contract, so the method surface
stays identical across protocol revisions. Responses travel back as
``Result{request_id, payload_json}`` (built in v2/client.py).
"""

from __future__ import annotations

import json
from typing import Dict

from gpud_tpu.session.v2 import session_pb2 as pb

# oneof field name → dispatcher method name
FIELD_TO_METHOD = {
    "get_states": "states",
    "get_events": "events",
    "get_metrics": "metrics",
    "gossip": "gossip",
    "diagnostic": "diagnostic",
    "reboot": "reboot",
    "set_healthy": "setHealthy",
    "trigger_component": "triggerComponent",
    "deregister_component": "deregisterComponent",
    "inject_fault": "injectFault",
    "bootstrap": "bootstrap",
    "update_config": "updateConfig",
    "update_token": "updateToken",
    "get_token": "getToken",
    "logout": "logout",
    "delete_machine": "delete",
    "get_package_status": "packageStatus",
    "update": "update",
    "kap_mtls_status": "kapMTLSStatus",
    "kap_mtls_update_credentials": "kapMTLSUpdateCredentials",
    "kap_mtls_activate": "kapMTLSActivate",
    "get_plugin_specs": "getPluginSpecs",
    "set_plugin_specs": "setPluginSpecs",
}


class UnsupportedRequest(Exception):
    """The manager sent a payload this agent revision doesn't know —
    either a newer oneof field (decodes as no payload) or one without a
    dispatcher mapping. The agent answers an error Result rather than
    dropping the request_id on the floor."""


def request_to_dict(mpkt: pb.ManagerPacket) -> Dict:
    """Typed ManagerPacket → dispatcher request dict.

    Raises UnsupportedRequest for payloads outside the rev-2 method set.
    Parameter names match the v1 JSON contract exactly — the dispatcher
    is revision-agnostic.
    """
    kind = mpkt.WhichOneof("payload")
    if kind is None:
        raise UnsupportedRequest("no recognizable payload (manager newer than agent?)")
    method = FIELD_TO_METHOD.get(kind)
    if method is None:
        raise UnsupportedRequest(f"non-request payload {kind!r}")
    req: Dict = {"method": method}
    msg = getattr(mpkt, kind)

    if kind == "get_states":
        if msg.components:
            req["components"] = list(msg.components)
    elif kind in ("get_events", "get_metrics"):
        if msg.since_unix:
            req["since"] = msg.since_unix
    elif kind == "diagnostic":
        if msg.script_base64:
            req["script_base64"] = msg.script_base64
        if msg.since_unix:
            req["since"] = msg.since_unix
        if msg.timeout_seconds:
            req["timeout_seconds"] = msg.timeout_seconds
    elif kind == "reboot":
        if msg.delay_seconds:
            req["delay_seconds"] = msg.delay_seconds
    elif kind == "set_healthy":
        req["component"] = msg.component
    elif kind == "trigger_component":
        req["component"] = msg.component
        req["tag"] = msg.tag
    elif kind == "deregister_component":
        req["component"] = msg.component
    elif kind == "inject_fault":
        fault = msg.WhichOneof("fault")
        if fault == "tpu_error_name":
            req["tpu_error_name"] = msg.tpu_error_name
        elif fault == "kernel_message":
            req["kernel_message"] = msg.kernel_message.message
            if msg.kernel_message.HasField("priority"):
                req["priority"] = msg.kernel_message.priority
        if msg.chip_id:
            req["chip_id"] = msg.chip_id
        if msg.detail:
            req["detail"] = msg.detail
    elif kind == "bootstrap":
        req["script_base64"] = msg.script_base64
        if msg.timeout_seconds:
            req["timeout_seconds"] = msg.timeout_seconds
    elif kind == "update_config":
        configs: Dict = {}
        for section, raw in msg.configs_json.items():
            try:
                configs[section] = json.loads(raw)
            except ValueError as e:
                raise UnsupportedRequest(
                    f"updateConfig section {section!r}: invalid JSON ({e})"
                ) from e
        req["configs"] = configs
    elif kind == "update_token":
        req["token"] = msg.token
    elif kind == "update":
        req["version"] = msg.version
    elif kind == "kap_mtls_update_credentials":
        req["version"] = msg.version
        req["cert_pem"] = msg.cert_pem
        req["key_pem"] = msg.key_pem
        req["activate"] = msg.activate
    elif kind == "kap_mtls_activate":
        req["version"] = msg.version
    elif kind == "set_plugin_specs":
        req["specs"] = [_plugin_spec_to_dict(s) for s in msg.specs]
    # gossip / get_token / logout / delete_machine / get_package_status /
    # kap_mtls_status / get_plugin_specs carry no parameters

    return req


def _plugin_spec_to_dict(spec: pb.PluginSpec) -> Dict:
    """Typed PluginSpec → the plugins.spec JSON contract
    (plugins/spec.py PluginSpec.from_dict)."""
    out: Dict = {
        "name": spec.name,
        "steps": [
            {
                "name": st.name,
                **(
                    {"script_base64": st.script_base64}
                    if st.script_base64
                    else {"script": st.script}
                ),
            }
            for st in spec.steps
        ],
    }
    if spec.plugin_type:
        out["plugin_type"] = spec.plugin_type
    if spec.run_mode:
        out["run_mode"] = spec.run_mode
    if spec.interval_seconds:
        out["interval_seconds"] = spec.interval_seconds
    if spec.timeout_seconds:
        out["timeout_seconds"] = spec.timeout_seconds
    if spec.tags:
        out["tags"] = list(spec.tags)
    if spec.component_list:
        out["component_list"] = list(spec.component_list)
    if spec.HasField("parser"):
        out["parser"] = {
            "json_paths": dict(spec.parser.json_paths),
            "match_rules": [
                {
                    "regex": r.regex,
                    "field": r.field,
                    "health": r.health or "Unhealthy",
                    "suggested_actions": list(r.suggested_actions),
                    "description": r.description,
                }
                for r in spec.parser.match_rules
            ],
        }
    return out


METHOD_TO_FIELD = {m: f for f, m in FIELD_TO_METHOD.items()}


def dict_to_request(req: Dict, request_id: str) -> pb.ManagerPacket:
    """Manager-side encoder: dispatcher request dict → typed ManagerPacket.

    The exact inverse of :func:`request_to_dict` (roundtrip-tested per
    method); the standalone control plane uses it to speak rev 2 from the
    same method-dict surface the v1 transport uses.
    """
    method = req.get("method")
    field = METHOD_TO_FIELD.get(method or "")
    if field is None:
        raise UnsupportedRequest(f"no typed encoding for method {method!r}")
    mpkt = pb.ManagerPacket()
    mpkt.request_id = request_id
    msg = getattr(mpkt, field)
    msg.SetInParent()  # parameterless requests still select the oneof arm

    if field == "get_states":
        msg.components.extend(req.get("components") or [])
    elif field in ("get_events", "get_metrics"):
        if req.get("since"):
            msg.since_unix = float(req["since"])
    elif field == "diagnostic":
        if req.get("script_base64"):
            msg.script_base64 = req["script_base64"]
        if req.get("since"):
            msg.since_unix = float(req["since"])
        if req.get("timeout_seconds"):
            msg.timeout_seconds = float(req["timeout_seconds"])
    elif field == "reboot":
        if req.get("delay_seconds"):
            msg.delay_seconds = float(req["delay_seconds"])
    elif field in ("set_healthy", "deregister_component"):
        msg.component = req.get("component", "")
    elif field == "trigger_component":
        msg.component = req.get("component", "")
        msg.tag = req.get("tag", "")
    elif field == "inject_fault":
        if req.get("tpu_error_name"):
            msg.tpu_error_name = req["tpu_error_name"]
        elif req.get("kernel_message"):
            msg.kernel_message.message = req["kernel_message"]
            if req.get("priority") is not None:
                msg.kernel_message.priority = int(req["priority"])
        if req.get("chip_id"):
            msg.chip_id = int(req["chip_id"])
        if req.get("detail"):
            msg.detail = req["detail"]
    elif field == "bootstrap":
        msg.script_base64 = req.get("script_base64", "")
        if req.get("timeout_seconds"):
            msg.timeout_seconds = float(req["timeout_seconds"])
    elif field == "update_config":
        for section, value in (req.get("configs") or {}).items():
            msg.configs_json[section] = json.dumps(value)
    elif field == "update_token":
        msg.token = req.get("token", "")
    elif field == "update":
        msg.version = req.get("version", "")
    elif field == "kap_mtls_update_credentials":
        msg.version = req.get("version", "")
        msg.cert_pem = req.get("cert_pem", "")
        msg.key_pem = req.get("key_pem", "")
        msg.activate = bool(req.get("activate"))
    elif field == "kap_mtls_activate":
        msg.version = req.get("version", "")
    elif field == "set_plugin_specs":
        for spec in req.get("specs") or []:
            msg.specs.append(_plugin_spec_from_dict(spec))
    return mpkt


def _plugin_spec_from_dict(spec: Dict) -> pb.PluginSpec:
    out = pb.PluginSpec()
    out.name = spec.get("name", "")
    out.plugin_type = spec.get("plugin_type", "")
    out.run_mode = spec.get("run_mode", "")
    out.interval_seconds = float(spec.get("interval_seconds") or 0)
    out.timeout_seconds = float(spec.get("timeout_seconds") or 0)
    out.tags.extend(spec.get("tags") or [])
    out.component_list.extend(spec.get("component_list") or [])
    for st in spec.get("steps") or []:
        step = out.steps.add()
        step.name = st.get("name", "")
        if st.get("script_base64"):
            step.script_base64 = st["script_base64"]
        elif st.get("script"):
            step.script = st["script"]
    parser = spec.get("parser")
    if parser is not None:
        for k, v in (parser.get("json_paths") or {}).items():
            out.parser.json_paths[k] = v
        for r in parser.get("match_rules") or []:
            rule = out.parser.match_rules.add()
            rule.regex = r.get("regex", "")
            rule.field = r.get("field", "")
            rule.health = r.get("health", "Unhealthy")
            rule.suggested_actions.extend(r.get("suggested_actions") or [])
            rule.description = r.get("description", "")
        out.parser.SetInParent()
    return out


def make_result(
    request_id: str, payload: Dict, compress: bool = False
) -> pb.AgentPacket:
    """``compress=True`` applies the rev-3 wire framing (1-byte codec
    prefix, zlib above the size floor — session/wire.py); only valid
    once the handshake negotiated revision >= 3. Default is the rev-2
    bare-JSON encoding."""
    pkt = pb.AgentPacket()
    pkt.result.request_id = request_id
    if compress:
        from gpud_tpu.session import wire

        pkt.result.payload_json = wire.encode_payload(payload)
    else:
        pkt.result.payload_json = json.dumps(payload).encode("utf-8")
    return pkt


def error_result(
    request_id: str, message: str, compress: bool = False
) -> pb.AgentPacket:
    return make_result(request_id, {"error": message}, compress=compress)


def negotiate_revision(ack_revision: int, max_supported: int) -> int:
    """Manager's acked revision clamped to what this agent speaks; 0 (an
    old manager that never sets the field) means rev 1."""
    if ack_revision <= 0:
        return 1
    return min(ack_revision, max_supported)
