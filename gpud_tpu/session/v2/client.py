"""Session v2 transport: gRPC bidi stream.

Reference: pkg/session/session_v2.go:36-80 — a single
``Connect(AgentPacket) ↔ ManagerPacket`` stream with Hello/HelloAck
handshake and DrainNotice handling; protocol "auto" tries v2 first and
falls back to legacy v1 (session_v2.go:49-80).

Stubs are hand-written over ``channel.stream_stream`` (grpc_tools isn't in
the image); messages come from protoc-generated session_pb2.
"""

from __future__ import annotations

import queue
import threading
from typing import TYPE_CHECKING, Callable, Optional

import grpc

from gpud_tpu.log import get_logger
from gpud_tpu.session.session import is_auth_error
from gpud_tpu.session.v2 import session_pb2 as pb
from gpud_tpu.session.v2 import typed
from gpud_tpu.version import __version__

if TYPE_CHECKING:
    from gpud_tpu.session.session import Session

logger = get_logger(__name__)

METHOD = "/tpud.session.v2.Session/Connect"
# rev 1: JSON Frames over gRPC; rev 2: typed per-method ManagerPacket
# requests answered with Result packets (see session.proto header);
# rev 3: every Frame.data / Result.payload_json byte string carries the
# 1-byte wire-codec prefix (session/wire.py — "j" raw JSON, "z" zlib),
# negotiated exactly like rev 2 so a rev-2 peer still interoperates on
# bare JSON bytes
MIN_REVISION = 1
MAX_REVISION = 3
CAPABILITIES = ["typed-requests", "drain-notice", "wire-zlib"]
HANDSHAKE_TIMEOUT = 10.0


def grpc_target_from_endpoint(endpoint: str) -> str:
    """https://cp.example:8443/x → cp.example:8443 (gRPC dials host:port)."""
    from urllib.parse import urlparse

    u = urlparse(endpoint if "//" in endpoint else f"//{endpoint}")
    host = u.hostname or endpoint
    port = u.port or (443 if u.scheme == "https" else 80)
    return f"{host}:{port}"


def resolve_v2_target(endpoint: str, override: str) -> "tuple[str, bool]":
    """(host:port, use_tls) for the gRPC dial.

    Split-port deployments carry the gRPC target on the Session (param or
    TPUD_SESSION_V2_TARGET env — resolved in Session.__init__); an
    explicit scheme on the override pins its own TLS mode so a dev
    plaintext target doesn't get wrapped in ssl credentials, while a bare
    host:port inherits the endpoint's scheme."""
    if override:
        use_tls = (
            override.startswith("https://")
            if "//" in override
            else endpoint.startswith("https")
        )
        return grpc_target_from_endpoint(override), use_tls
    return grpc_target_from_endpoint(endpoint), endpoint.startswith("https")


class HandshakeRejected(Exception):
    """HelloAck rejection (or connect-time RpcError). ``auth_error``
    carries the structured auth-vs-network classification computed at the
    failure site — ``is_auth_error`` reads it before any text matching,
    so a revoked token parks the keep-alive loop the same way v1's HTTP
    401 does instead of retrying through backoff forever."""

    auth_error: bool = False


def start_v2_transport(session: "Session") -> Callable[[], None]:
    """Transport function with the (start_reader_fn) contract of
    Session: starts pump threads, returns a stop(). Raises on connection
    or handshake failure so the keep-alive loop can fall back to v1."""
    target, use_tls = resolve_v2_target(
        session.endpoint, getattr(session, "v2_target", "")
    )
    if use_tls:
        channel = grpc.secure_channel(target, grpc.ssl_channel_credentials())
    else:
        channel = grpc.insecure_channel(target)

    stream = channel.stream_stream(
        METHOD,
        request_serializer=pb.AgentPacket.SerializeToString,
        response_deserializer=pb.ManagerPacket.FromString,
    )

    out_q: "queue.Queue[Optional[pb.AgentPacket]]" = queue.Queue()
    stopped = threading.Event()
    handshake_ok = threading.Event()
    handshake_err: list = []
    # parallel to handshake_err: structured auth classification computed
    # while the failure object (grpc code / rejection reason) was live
    handshake_auth: list = []
    # reconnect signals are only valid once this transport was adopted —
    # a failed v2 probe must not tear down the v1 fallback that follows
    established = threading.Event()

    hello = pb.AgentPacket()
    hello.hello.machine_id = session.machine_id
    hello.hello.token = session.token
    hello.hello.machine_proof = session.machine_proof
    hello.hello.tpud_version = __version__
    # rev-1 compat field: an old manager reads `revision` and never sees
    # the range; a rev-2 manager negotiates from [min, max]
    hello.hello.revision = MIN_REVISION
    hello.hello.min_revision = MIN_REVISION
    hello.hello.max_revision = MAX_REVISION
    hello.hello.capabilities.extend(CAPABILITIES)
    out_q.put(hello)
    # negotiated revision, fixed at handshake before send_pump starts
    negotiated = [MIN_REVISION]

    def request_iter():
        while not stopped.is_set():
            try:
                pkt = out_q.get(timeout=0.5)
            except queue.Empty:
                continue
            if pkt is None:
                return
            yield pkt

    call = stream(request_iter())

    def _signal_if_established(reason: str, auth: Optional[bool] = None) -> None:
        """A disconnect after adoption must reconnect the session; one
        during a failed probe must not poison the v1 fallback. The drain/
        EOF may race the main thread between handshake-ok and adoption, so
        wait briefly for the verdict instead of sampling it. ``auth``
        forwards the structured classification to the keep-alive loop."""
        if stopped.is_set():
            return
        if established.wait(HANDSHAKE_TIMEOUT) and not stopped.is_set():
            session.signal_reconnect(reason, auth=auth)

    def _enqueue_request(req_id: str, data) -> bool:
        """Hand one inbound request to the session serve loop; False when
        the reader channel is saturated."""
        from gpud_tpu.session.session import Frame

        try:
            session.reader.put(Frame(req_id=req_id, data=data), timeout=5.0)
            return True
        except queue.Full:
            session.note_frame_dropped(
                "read", "v2 reader channel full; dropping request"
            )
            return False

    def recv_pump():
        try:
            for mpkt in call:
                if stopped.is_set():
                    return
                kind = mpkt.WhichOneof("payload")
                if kind == "hello_ack":
                    if not mpkt.hello_ack.accepted:
                        reason = mpkt.hello_ack.reason or "rejected"
                        handshake_err.append(reason)
                        # the HelloAck vocabulary is narrow ("bad token",
                        # "invalid machine proof" vs revision mismatch);
                        # classify here, at the authoritative site
                        handshake_auth.append(is_auth_error(reason))
                        handshake_ok.set()
                        return
                    negotiated[0] = typed.negotiate_revision(
                        mpkt.hello_ack.revision, MAX_REVISION
                    )
                    handshake_ok.set()
                elif kind == "frame":
                    import json

                    try:
                        if negotiated[0] >= 3:
                            from gpud_tpu.session import wire

                            data = wire.decode_payload(mpkt.frame.data)
                        else:
                            data = json.loads(mpkt.frame.data.decode("utf-8"))
                    except ValueError:
                        continue
                    _enqueue_request(mpkt.frame.req_id, data)
                elif kind == "drain_notice":
                    logger.info(
                        "manager drain notice: %s", mpkt.drain_notice.reason
                    )
                    _signal_if_established("manager draining")
                    return
                else:
                    # rev-2 typed request (or a payload newer than this
                    # agent): adapt onto the same serve loop as rev-1
                    # frames; unknowns and overload answer an error Result
                    # so the manager's request_id never dangles
                    try:
                        req = typed.request_to_dict(mpkt)
                    except typed.UnsupportedRequest as e:
                        if mpkt.request_id:
                            out_q.put(typed.error_result(
                                mpkt.request_id, str(e),
                                compress=negotiated[0] >= 3,
                            ))
                        continue
                    if not _enqueue_request(mpkt.request_id, req) and mpkt.request_id:
                        out_q.put(
                            typed.error_result(
                                mpkt.request_id, "agent busy: request dropped",
                                compress=negotiated[0] >= 3,
                            )
                        )
            if not stopped.is_set():
                handshake_err.append("stream closed before ack")
                handshake_auth.append(False)
                handshake_ok.set()
                _signal_if_established("v2 stream closed", auth=False)
        except grpc.RpcError as e:
            # classify while the live error object still carries its grpc
            # code — the formatted string a later is_auth_error would see
            # loses UNAUTHENTICATED/PERMISSION_DENIED structure (v1 parity:
            # the HTTP transports classify from the response status)
            auth = is_auth_error(e)
            handshake_err.append(str(e))
            handshake_auth.append(auth)
            handshake_ok.set()
            if not stopped.is_set():
                _signal_if_established(f"v2 stream: {e.code()}", auth=auth)

    def send_pump():
        import json

        while not stopped.is_set():
            try:
                frame = session.writer.get(timeout=0.5)
            except queue.Empty:
                continue
            if negotiated[0] >= 2:
                # rev 2: responses are Result packets keyed by request_id;
                # rev 3 adds the wire-codec framing on the payload bytes
                pkt = typed.make_result(
                    frame.req_id, frame.data, compress=negotiated[0] >= 3
                )
            else:
                pkt = pb.AgentPacket()
                pkt.frame.req_id = frame.req_id
                pkt.frame.data = json.dumps(frame.data).encode("utf-8")
            out_q.put(pkt)

    recv_t = threading.Thread(target=recv_pump, name="tpud-v2-recv", daemon=True)
    recv_t.start()

    if not handshake_ok.wait(HANDSHAKE_TIMEOUT):
        stopped.set()
        call.cancel()
        channel.close()
        raise TimeoutError("v2 handshake timed out")
    if handshake_err:
        stopped.set()
        call.cancel()
        channel.close()
        exc = HandshakeRejected(handshake_err[0])
        exc.auth_error = bool(handshake_auth[0]) if handshake_auth else False
        raise exc

    established.set()
    send_t = threading.Thread(target=send_pump, name="tpud-v2-send", daemon=True)
    send_t.start()

    def stop():
        stopped.set()
        out_q.put(None)
        try:
            call.cancel()
        except Exception:  # noqa: BLE001
            pass
        channel.close()

    return stop
