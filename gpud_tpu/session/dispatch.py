"""Control-plane request dispatch.

Reference: pkg/session/session_process_request.go:24-157 — the method set a
session must answer: reboot | metrics | states | events | delete | logout |
setHealthy | gossip | packageStatus | update | updateConfig | bootstrap |
injectFault | triggerComponent | deregisterComponent | setPluginSpecs |
getPluginSpecs | updateToken | getToken.

Requests arrive as ``{"method": "...", ...params}``; responses are plain
dicts. Slow operations (gossip: NFS can hang; triggerComponent: slow
checks) run asynchronously and return immediately (reference rationale:
session_process_request.go:64-84, 108-125).
"""

from __future__ import annotations

import base64
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict

from gpud_tpu import host as pkghost
from gpud_tpu import machine_info as machineinfo
from gpud_tpu.fault_injector import Request as InjectRequest
from gpud_tpu.log import audit, get_logger
from gpud_tpu.metadata import KEY_TOKEN
from gpud_tpu.metrics.registry import counter, histogram
from gpud_tpu.process import run_bash_script
from gpud_tpu.tracing import DEFAULT_TRACER

if TYPE_CHECKING:
    from gpud_tpu.server.server import Server

logger = get_logger(__name__)

# session dispatch latency: the serve loop is single-threaded per session,
# so one slow handler delays every queued control-plane request behind it
_h_dispatch = histogram(
    "tpud_session_dispatch_duration_seconds",
    "control-plane session request dispatch latency by method",
)
_c_dispatch = counter(
    "tpud_session_dispatch_total",
    "control-plane session dispatches by method and outcome (ok|error)",
)

DEFAULT_BOOTSTRAP_TIMEOUT = 10 * 60.0
# exit code asking the supervisor (systemd/DaemonSet) to restart us with
# new plugin specs (reference: session_process_request.go:137-141)
RESTART_EXIT_CODE = 245
# a finished diagnostic bundle answers matching re-polls for this long;
# far above the CP poll cadence (so a script runs once per request) but
# bounded so a later identical request gets fresh data
DIAGNOSTIC_CACHE_SECONDS = 300.0


class Dispatcher:
    def __init__(self, server: "Server") -> None:
        self.server = server
        self.reboot_fn: Callable = pkghost.reboot
        # restart-by-exit-code: the supervisor (systemd Restart=always,
        # SuccessExitStatus=244 245) brings us back with the new specs
        import os as _os

        self.exit_fn: Callable[[int], None] = _os._exit  # noqa: SLF001
        self._gossip_inflight = threading.Event()
        self._diagnostic_inflight = threading.Event()
        # injectFault rate limit — reuses the remediation token bucket
        # with its own capacity/refill knobs (config inject_rate_*)
        from gpud_tpu.remediation.policy import Policy as _BucketPolicy
        from gpud_tpu.remediation.policy import TokenBucket

        cfg = getattr(server, "config", None)
        self.time_now_fn: Callable[[], float] = time.time
        self._inject_bucket = TokenBucket(
            _BucketPolicy(
                rate_capacity=int(getattr(cfg, "inject_rate_capacity", 10)),
                rate_refill_seconds=float(
                    getattr(cfg, "inject_rate_refill_seconds", 6.0)
                ),
            )
        )

    def _spawn(self, name: str, fn: Callable[[], None]) -> None:
        """Async session work (gossip/diagnostic can hang on NFS stat)
        runs as a one-shot on the unified scheduler pool — the watchdog
        reclaims a wedged slot and ad-hoc threads stop accumulating. A
        scheduler-less server (older tests) falls back to a thread."""
        scheduler = getattr(self.server, "scheduler", None)
        if scheduler is not None and scheduler.submit(f"session:{name}", fn):
            return
        threading.Thread(target=fn, name=f"tpud-{name}", daemon=True).start()

    def __call__(self, req: Dict) -> Dict:
        if not isinstance(req, dict):
            return {"error": "request must be an object"}
        method = req.get("method", "")
        if not isinstance(method, str):
            return {"error": f"invalid method {method!r}"}
        handler = getattr(self, f"_m_{method.replace('-', '_')}", None)
        if handler is None:
            # the method name comes off the wire: label with a sentinel, not
            # the raw string, or a hostile peer mints unbounded label sets
            _c_dispatch.inc(labels={"method": "<unknown>", "outcome": "error"})
            return {"error": f"unknown method {method!r}"}
        audit("session_request", method=method)
        outcome = "ok"
        t0 = time.monotonic()
        try:
            with DEFAULT_TRACER.span(
                "session.dispatch", component="session", attrs={"method": method}
            ):
                resp = handler(req)
            if isinstance(resp, dict) and "error" in resp:
                outcome = "error"
            return resp
        except Exception as e:  # noqa: BLE001
            outcome = "error"
            logger.exception("session method %s failed", method)
            return {"error": str(e)}
        finally:
            _h_dispatch.observe(time.monotonic() - t0, {"method": method})
            _c_dispatch.inc(labels={"method": method, "outcome": outcome})

    # -- state/introspection ----------------------------------------------
    def _m_states(self, req: Dict) -> Dict:
        comps = req.get("components") or None
        out = []
        for c in self.server.registry.all():
            if comps and c.name() not in comps:
                continue
            if not comps and c.name() not in self.server.supported_names:
                continue
            out.append(
                {
                    "component": c.name(),
                    "states": [s.to_dict() for s in c.last_health_states()],
                }
            )
        return {"states": out}

    def _m_events(self, req: Dict) -> Dict:
        since = float(req.get("since", time.time() - 3 * 3600))
        out = []
        for c in self.server.registry.all():
            evs = c.events(since)
            out.append(
                {"component": c.name(), "events": [e.to_dict() for e in evs]}
            )
        return {"events": out}

    def _m_stateHistory(self, req: Dict) -> Dict:
        """Persisted health-transition timeline from the ledger, with
        eventstore correlation — the control-plane view of what the HTTP
        route ``/v1/states/history`` serves locally."""
        ledger = self.server.health_ledger
        component = req.get("component", "") or None
        since = float(req.get("since", time.time() - 24 * 3600))
        limit = int(req.get("limit", 256))
        transitions = ledger.history(component=component, since=since, limit=limit)
        ledger.annotate_with_events(transitions)
        out: Dict = {
            "history": transitions,
            "count": len(transitions),
            "flapping": ledger.flapping_components(),
        }
        if component:
            av = ledger.availability(component)
            if av is not None:
                out["availability"] = av
        return out

    def _m_predictStatus(self, req: Dict) -> Dict:
        """Predict engine rollup for the control plane: config + run
        state plus per-component precursor scores (``component`` narrows,
        ``history`` appends bounded score history) — the session twin of
        ``GET /v1/predict/scores``."""
        eng = getattr(self.server, "predictor", None)
        if eng is None:
            return {"error": "predict engine disabled"}
        component = req.get("component", "")
        history = int(req.get("history", 0))
        out = eng.scores(
            component=component, history_limit=max(0, history)
        )
        out["status"] = eng.status()
        return out

    def _m_predictCalibration(self, req: Dict) -> Dict:
        """Threshold-calibration state for the control plane: per-class
        fitted thresholds/weights replayed from the node's own ledger
        history (``refit`` re-fits synchronously first) — the session
        twin of ``GET /v1/predict/calibration``."""
        eng = getattr(self.server, "predictor", None)
        if eng is None:
            return {"error": "predict engine disabled"}
        if bool(req.get("refit")):
            eng.calibrate_now()
        return eng.calibration()

    def _m_fabricStatus(self, req: Dict) -> Dict:
        """Fabric plane rollup for the control plane: discovered mesh +
        sweep state + the current per-link matrix (``link``/``since``/
        ``limit`` append matrix history) — the session twin of
        ``GET /v1/fabric``."""
        plane = getattr(self.server, "fabric", None)
        if plane is None:
            return {"error": "fabric plane disabled"}
        link = str(req.get("link", "") or "")
        since = float(req.get("since", 0.0))
        limit = int(req.get("limit", 0))
        out = {"status": plane.status(), "matrix": plane.matrix()}
        if link or since > 0 or limit > 0:
            out["history"] = plane.history(
                link=link, since=since, limit=limit if limit > 0 else 256
            )
        return out

    def _m_remediationStatus(self, req: Dict) -> Dict:
        """Remediation engine rollup for the control plane: policy + guard
        state plus the most recent audit rows (``limit``, ``since``,
        ``component`` filters mirror ``GET /v1/remediation/audit``)."""
        eng = getattr(self.server, "remediation", None)
        if eng is None:
            return {"error": "remediation engine disabled"}
        limit = int(req.get("limit", 32))
        since = float(req.get("since", 0.0))
        component = req.get("component", "") or None
        attempts = eng.audit.read(
            component=component, since=since, limit=limit
        )
        return {
            "remediation": eng.status(),
            "attempts": attempts,
            "count": len(attempts),
        }

    def _m_remediationPolicy(self, req: Dict) -> Dict:
        """Runtime remediation-policy push (same field-by-field contract
        as updateConfig: one invalid key must not block the rest)."""
        eng = getattr(self.server, "remediation", None)
        if eng is None:
            return {"error": "remediation engine disabled"}
        updated, errors = eng.policy.update(req.get("policy", {}))
        if updated:
            audit("remediation_policy_update", updated=",".join(updated))
        out: Dict = {"status": "ok", "updated": updated}
        if errors:
            out["errors"] = errors
        return out

    def _m_metrics(self, req: Dict) -> Dict:
        since = float(req.get("since", time.time() - 3 * 3600))
        ms = self.server.metrics_store.read(since)
        return {"metrics": [m.to_dict() for m in ms]}

    def _m_traces(self, req: Dict) -> Dict:
        """Trace-ring snapshot for the control plane — the session twin
        of ``GET /v1/debug/traces``. The manager uses the
        ``correlation_id`` filter to fetch the live agent-side spans
        behind a fleet record (docs/fleet.md)."""
        tracer = self.server.tracer
        spans = tracer.snapshot(
            component=req.get("component", "") or None,
            limit=int(req.get("limit", 64)),
            since=float(req.get("since", 0.0)),
            correlation_id=req.get("correlation_id", "") or None,
        )
        return {"spans": spans, "stats": tracer.stats()}

    def _m_gossip(self, req: Dict) -> Dict:
        # async: machine info can hang on NFS stat (reference:
        # session_process_request.go:64-84) — compute in a thread and
        # return immediately; the control plane polls again
        result: Dict = {"status": "started"}

        def work():
            try:
                mi = machineinfo.get_machine_info(
                    tpu=self.server.tpu_instance,
                    machine_id=self.server.machine_id,
                )
                self.server.last_gossip = mi.to_dict()
                # journal a compact gossip marker into the durable outbox
                # (the full tree is poll-on-demand; what must survive a
                # partition is that this node gossiped, and when)
                outbox = getattr(self.server, "outbox", None)
                if outbox is not None:
                    outbox.publish(
                        "gossip",
                        {
                            "machine_id": self.server.machine_id,
                            "ts": time.time(),
                        },
                    )
            except Exception:  # noqa: BLE001
                logger.exception("gossip failed")
            finally:
                self._gossip_inflight.clear()

        # in-flight guard: when machine-info hangs (NFS stat), re-polls
        # must not stack additional stuck threads
        if not self._gossip_inflight.is_set():
            self._gossip_inflight.set()
            self._spawn("gossip", work)
        if getattr(self.server, "last_gossip", None):
            result["machine_info"] = self.server.last_gossip
            result["status"] = "ok"
        return result

    @staticmethod
    def _decode_script(b64: str):
        """Shared base64-script decode → (script, error) (bootstrap +
        diagnostic use the same contract)."""
        try:
            script = base64.b64decode(b64, validate=True).decode("utf-8")
        except Exception:  # noqa: BLE001
            return "", "invalid base64 script"
        if not script.strip():
            return "", "empty script"
        return script, None

    @staticmethod
    def _script_result(r) -> Dict:
        return {"exit_code": r.exit_code, "output": r.output[-4096:], "error": r.error}

    def _m_diagnostic(self, req: Dict) -> Dict:
        """Diagnostic bundle: states + recent events + machine info, plus an
        optional base64 diagnostic script (reference:
        session_process_request.go:104). Async like gossip — collection can
        hang on NFS stat or a slow script, so the serve loop returns
        immediately and the control plane re-polls for the finished bundle.

        Scripted requests are answered only by a bundle produced for the
        SAME script (matched on the base64), and a finished bundle is not
        re-collected by the completion poll — a non-idempotent diagnostic
        script must run exactly once per request."""
        b64 = req.get("script_base64", "")
        script = ""
        if b64:
            script, err = self._decode_script(b64)
            if err:
                return {"error": err}
        since = float(req.get("since", time.time() - 3 * 3600))
        timeout = float(req.get("timeout_seconds", DEFAULT_BOOTSTRAP_TIMEOUT))

        last = getattr(self.server, "last_diagnostic", None)
        if (
            last
            and last.get("script_b64", "") == b64
            and time.time() - last.get("collected_at", 0) < DIAGNOSTIC_CACHE_SECONDS
        ):
            # this exact request already has a fresh finished bundle; a
            # repeat request after the cache window re-collects (and
            # re-runs the script — that recurrence is a new intent)
            return {"status": "ok", "diagnostic": last}
        if self._diagnostic_inflight.is_set():
            return {"status": "busy" if script else "started"}

        def work():
            try:
                bundle: Dict = {"collected_at": time.time(), "script_b64": b64}
                bundle["states"] = self._m_states({})["states"]
                bundle["events"] = self._m_events({"since": since})["events"]
                try:
                    mi = machineinfo.get_machine_info(
                        tpu=self.server.tpu_instance,
                        machine_id=self.server.machine_id,
                    )
                    bundle["machine_info"] = mi.to_dict()
                except Exception as e:  # noqa: BLE001
                    bundle["machine_info_error"] = str(e)
                if script:
                    audit("diagnostic_script", length=len(script))
                    bundle["script"] = self._script_result(
                        run_bash_script(script, timeout=timeout)
                    )
                self.server.last_diagnostic = bundle
            except Exception:  # noqa: BLE001
                logger.exception("diagnostic bundle failed")
            finally:
                self._diagnostic_inflight.clear()

        self._diagnostic_inflight.set()
        self._spawn("diagnostic", work)
        return {"status": "started"}

    # -- actions -----------------------------------------------------------
    def _m_reboot(self, req: Dict) -> Dict:
        delay = float(req.get("delay_seconds", 0))
        audit("session_reboot", delay=delay)

        def work():
            if delay:
                time.sleep(delay)
            err = self.reboot_fn()
            if err:
                logger.error("reboot failed: %s", err)

        # NOT pooled: a delayed reboot sleeping on a worker would idle a
        # pool slot for the whole delay
        threading.Thread(target=work, name="tpud-reboot", daemon=True).start()
        return {"status": "rebooting"}

    def _m_setHealthy(self, req: Dict) -> Dict:
        name = req.get("component", "")
        c = self.server.registry.get(name)
        if c is None:
            return {"error": f"component {name!r} not found"}
        fn = getattr(c, "set_healthy", None)
        if fn is None:
            return {"error": f"component {name!r} is not health-settable"}
        fn()
        return {"status": "ok"}

    def _m_triggerComponent(self, req: Dict) -> Dict:
        # async: checks can be slow (reference: 108-125)
        name = req.get("component", "")
        tag = req.get("tag", "")
        comps = []
        if name:
            c = self.server.registry.get(name)
            if c is None:
                return {"error": f"component {name!r} not found"}
            comps = [c]
        elif tag:
            comps = [c for c in self.server.registry.all() if tag in c.tags()]
        for c in comps:
            # a scheduler-driven poller is poked to the front of the heap
            # (keeps the no-overlapping-runs invariant); anything else
            # gets a one-shot on the pool
            job = getattr(c, "_job", None)
            if job is not None:
                job.poke()
            else:
                self._spawn(f"trigger:{c.name()}", c.check)
        return {"status": "triggered", "components": [c.name() for c in comps]}

    def _m_deregisterComponent(self, req: Dict) -> Dict:
        name = req.get("component", "")
        c = self.server.registry.get(name)
        if c is None:
            return {"error": f"component {name!r} not found"}
        if not c.can_deregister():
            return {"error": f"component {name!r} is not deregisterable"}
        self.server.registry.deregister(name)
        c.close()
        return {"status": "ok"}

    def _m_injectFault(self, req: Dict) -> Dict:
        # token bucket: a hostile or buggy control plane must not be able
        # to spam kmsg writes through the session (burst requests already
        # multiply writes server-side via repeat)
        if not self._inject_bucket.take(self.time_now_fn()):
            return {
                "error": "fault injection rate limit exhausted",
                "retryable": True,
            }
        ir = InjectRequest.from_dict(req)
        res = self.server.fault_injector.inject(ir)
        out = res.to_dict()
        if res.ok:
            out["status"] = "ok"
        else:
            out["status"] = "error"
            out["error"] = res.error
        return out

    def _m_chaosRun(self, req: Dict) -> Dict:
        """Launch a chaos campaign (scenario name or inline mapping).
        Defaults to wait=false: the serve loop is single-threaded per
        session, so a campaign must not stall queued requests behind it."""
        chaos = getattr(self.server, "chaos", None)
        if chaos is None:
            return {"error": "chaos is disabled (chaos_enabled)"}
        out, err = chaos.run_campaign(
            req.get("scenario"), wait=bool(req.get("wait", False))
        )
        if err:
            return {"error": err}
        return out

    def _m_chaosStatus(self, req: Dict) -> Dict:
        chaos = getattr(self.server, "chaos", None)
        if chaos is None:
            return {"error": "chaos is disabled (chaos_enabled)"}
        limit = int(req.get("limit") or 0)
        return chaos.campaigns(limit=max(0, limit))

    # -- durable outbox (session/outbox.py) --------------------------------
    def _m_outboxAck(self, req: Dict) -> Dict:
        """Manager acks the outbox replay watermark: everything at/below
        ``seq`` was received (and deduped by key) on its side. Monotonic —
        a stale or replayed ack never regresses the watermark."""
        outbox = getattr(self.server, "outbox", None)
        if outbox is None:
            return {"error": "outbox is disabled (outbox_enabled)"}
        try:
            seq = int(req.get("seq"))
        except (TypeError, ValueError):
            return {"error": "outboxAck requires an integer 'seq'"}
        if seq < 0:
            return {"error": "outboxAck requires seq >= 0"}
        return {"acked_seq": outbox.ack(seq)}

    def _m_outboxStatus(self, req: Dict) -> Dict:
        """Outbox journal + circuit-breaker state (the session-method
        mirror of ``GET /v1/session/status``)."""
        outbox = getattr(self.server, "outbox", None)
        if outbox is None:
            return {"error": "outbox is disabled (outbox_enabled)"}
        out: Dict = {"outbox": outbox.stats()}
        circuit = getattr(self.server, "session_circuit", None)
        if circuit is not None:
            out["circuit"] = circuit.stats()
        # wire codec byte accounting (docs/session.md wire format)
        from gpud_tpu.session import wire

        out["wire"] = wire.codec_stats()
        return out

    def _m_peerStatus(self, req: Dict) -> Dict:
        """Which manager this agent is parked on and how failover stands
        (docs/session.md "Peer failover"): the breaker's peer list,
        current index, and failover count, plus the session's active
        endpoint/transport."""
        out: Dict = {}
        circuit = getattr(self.server, "session_circuit", None)
        if circuit is not None:
            stats = circuit.stats()
            out["peers"] = stats["peers"]
            out["peer_index"] = stats["peer_index"]
            out["failovers"] = stats["failovers"]
            out["circuit_state"] = stats["state"]
        session = getattr(self.server, "session", None)
        if session is not None:
            out["endpoint"] = session.endpoint
            out["v2_target"] = session.v2_target
            out["connected"] = session.connected
            out["active_protocol"] = session.active_protocol
            out["reconnects"] = session.reconnect_count
        if not out:
            return {"error": "no session or circuit configured"}
        return out

    def _m_bootstrap(self, req: Dict) -> Dict:
        """base64 script exec (reference: session bootstrap)."""
        script, err = self._decode_script(req.get("script_base64", ""))
        if err:
            return {"error": err}
        timeout = float(req.get("timeout_seconds", DEFAULT_BOOTSTRAP_TIMEOUT))
        audit("bootstrap_script", length=len(script))
        return self._script_result(run_bash_script(script, timeout=timeout))

    # -- config/token ------------------------------------------------------
    def _m_updateConfig(self, req: Dict) -> Dict:
        """Runtime re-config pushed by the control plane (reference:
        session/update_config.go:19 → setters, session.go:222-227).
        Overrides are persisted to the metadata table and re-applied at
        boot (reference: cmd/gpud/run persistMetadataOverrides)."""
        cfgs = req.get("configs", {})
        updated, applied, errors = self.apply_config_overrides(cfgs)
        if applied:
            self._persist_config_overrides(applied)
        out: Dict = {"status": "ok", "updated": updated}
        if errors:
            out["errors"] = errors
        return out

    def _persist_config_overrides(self, applied: Dict) -> None:
        """Merge ONLY the successfully-applied subset into the persisted
        overrides — unknown or invalid keys must not be replayed forever."""
        import json as _json

        from gpud_tpu.metadata import KEY_CONFIG_OVERRIDES

        existing = {}
        raw = self.server.metadata.get(KEY_CONFIG_OVERRIDES)
        if raw:
            try:
                loaded = _json.loads(raw)
                if isinstance(loaded, dict):
                    existing = loaded
            except ValueError:
                pass
        for k, v in applied.items():
            if isinstance(v, dict):
                prev = existing.get(k)
                merged = dict(prev) if isinstance(prev, dict) else {}
                merged.update(v)
                existing[k] = merged
            else:
                existing[k] = v
        self.server.metadata.set(KEY_CONFIG_OVERRIDES, _json.dumps(existing))

    def _apply_numeric_section(
        self,
        section: str,
        comp_name: str,
        cfgs: Dict,
        key_min: Dict[str, float],
        updated: list,
        applied: Dict,
        errors: list,
    ) -> None:
        """Shared coerce/validate/apply/record loop for a section of
        numeric component attributes. Values are coerced to the attribute's
        current type; `not >=` rejects NaN (json.loads accepts the NaN
        token) as well as below-minimum values; a valid push against a
        disabled component errors instead of vanishing silently."""
        cfg = cfgs.get(section)
        if cfg is None:
            return
        if not isinstance(cfg, dict):
            errors.append(f"{section}: must be an object")
            return
        comp = self.server.registry.get(comp_name)
        if comp is None:
            if cfg:
                errors.append(f"{section}: component disabled on this host")
            return
        for key, minv in key_min.items():
            if key not in cfg:
                continue
            try:
                val = type(getattr(comp, key))(cfg[key])
                if not val >= minv:
                    raise ValueError(f"must be >= {minv}")
                setattr(comp, key, val)
                updated.append(f"{section}.{key}")
                applied.setdefault(section, {})[key] = val
            except (TypeError, ValueError) as e:
                errors.append(f"{section}.{key}: {e}")

    def apply_config_overrides(self, cfgs: Dict):
        """Apply overrides key-by-key; one invalid value must not block the
        rest. Returns (updated_names, applied_subset, errors)."""
        updated: list = []
        applied: Dict = {}
        errors: list = []
        if not isinstance(cfgs, dict):
            return updated, applied, ["configs must be an object"]
        if "expected_chip_count" in cfgs:
            comp = self.server.registry.get("accelerator-tpu-chip-counts")
            try:
                n = int(cfgs["expected_chip_count"])
                if comp is not None:
                    comp.expected_count = n
                    updated.append("expected_chip_count")
                    applied["expected_chip_count"] = n
            except (TypeError, ValueError) as e:
                errors.append(f"expected_chip_count: {e}")
        self._apply_numeric_section(
            "ici", "accelerator-tpu-ici", cfgs,
            {
                "flap_threshold": 0,
                "crc_delta_degraded": 0,
                "auto_clear_window": 0,   # 0 = sticky until set-healthy
                # any positive window is accepted: a stricter floor here
                # would silently drop previously-persisted overrides at
                # boot replay
                "scan_window": 1,
                "expected_links": 0,      # 0 = derive from topology
            },
            updated, applied, errors,
        )
        nfs_cfg = cfgs.get("nfs_groups")
        if nfs_cfg is not None and not isinstance(nfs_cfg, list):
            errors.append("nfs_groups: must be a list of group objects")
            nfs_cfg = None
        if isinstance(nfs_cfg, list):
            from gpud_tpu.nfs_checker import GroupConfig

            comp = self.server.registry.get("nfs")
            groups = []
            group_errs = []
            for i, g in enumerate(nfs_cfg):
                if not isinstance(g, dict) or not g.get("dir"):
                    group_errs.append(f"nfs_groups[{i}]: dir required")
                    continue
                try:
                    gc = GroupConfig(
                        dir=str(g["dir"]),
                        ttl_seconds=float(g.get("ttl_seconds", 300.0)),
                        expected_members=int(g.get("expected_members", 0)),
                    )
                except (TypeError, ValueError) as e:
                    group_errs.append(f"nfs_groups[{i}]: {e}")
                    continue
                verr = gc.validate()
                if verr:
                    group_errs.append(f"nfs_groups[{i}]: {verr}")
                    continue
                groups.append(gc)
            if group_errs:
                # all-or-nothing: a partially-applied group list would
                # silently stop monitoring the rejected groups
                errors.extend(group_errs)
            elif comp is None:
                # valid push must not vanish silently on a host where the
                # component is disabled — signal the no-op to the CP
                errors.append("nfs_groups: nfs component disabled on this host")
            else:
                comp.group_configs = groups
                updated.append("nfs_groups")
                applied["nfs_groups"] = [
                    {
                        "dir": gc.dir,
                        "ttl_seconds": gc.ttl_seconds,
                        "expected_members": gc.expected_members,
                    }
                    for gc in groups
                ]
        thr_cfg = cfgs.get("error_thresholds")
        if thr_cfg is not None and not isinstance(thr_cfg, dict):
            errors.append("error_thresholds: must be an object of name->threshold")
            thr_cfg = None
        if isinstance(thr_cfg, dict):
            from gpud_tpu.components.tpu import catalog as tpu_catalog

            comp = self.server.registry.get("accelerator-tpu-error-kmsg")
            if comp is None and thr_cfg:
                errors.append(
                    "error_thresholds: error-kmsg component disabled on this host"
                )
            for name, raw_thr in thr_cfg.items() if comp is not None else ():
                if tpu_catalog.lookup(name) is None:
                    errors.append(f"error_thresholds.{name}: unknown error name")
                    continue
                if raw_thr is None:
                    # null removes the override: back to the catalog
                    # default (incl. future catalog changes)
                    comp.reboot_threshold_overrides.pop(name, None)
                    updated.append(f"error_thresholds.{name}")
                    applied.setdefault("error_thresholds", {})[name] = None
                    continue
                try:
                    thr = int(raw_thr)
                    if thr < 0:
                        raise ValueError("must be >= 0")
                except (TypeError, ValueError) as e:
                    errors.append(f"error_thresholds.{name}: {e}")
                    continue
                comp.reboot_threshold_overrides[name] = thr
                updated.append(f"error_thresholds.{name}")
                applied.setdefault("error_thresholds", {})[name] = thr
        self._apply_numeric_section(
            "anomaly", "accelerator-tpu-anomaly", cfgs,
            {
                # zero would silently disable scoring (or flag everything)
                # while reporting 'applied' — require sane floors
                "score_degraded": 0.1,
                "lookback_seconds": 60,
                "min_samples": 2,
            },
            updated, applied, errors,
        )
        self._apply_numeric_section(
            "temperature", "accelerator-tpu-temperature", cfgs,
            {"degraded_c": 1, "unhealthy_c": 1},
            updated, applied, errors,
        )
        return updated, applied, errors

    def _m_updateToken(self, req: Dict) -> Dict:
        token = req.get("token", "")
        if not token:
            return {"error": "token required"}
        # persist the PAIR: the rotation came from the control plane the
        # session is talking to, and must survive a process restart that
        # re-supplies stale boot flags (server.py precedence rule)
        # single read: the FIFO watch thread nulls server.session under its
        # own lock, so a check-then-deref here would race to AttributeError
        session = self.server.session
        if session is not None:
            # atomic pair write — a crash between two separate writes
            # would durably record a mismatched endpoint/token pair
            self.server.persist_credential_pair(session.endpoint, token)
            session.token = token
        else:
            self.server.persist_token(token)
        return {"status": "ok"}

    def _m_getToken(self, req: Dict) -> Dict:
        return {"token": self.server.metadata.get(KEY_TOKEN)}

    def _m_logout(self, req: Dict) -> Dict:
        """Deregister from the control plane: purge credentials
        (reference: logout.go:14-36 purges metadata + stops the daemon)."""
        from gpud_tpu import metadata as md

        for key in (md.KEY_TOKEN, md.KEY_MACHINE_PROOF, md.KEY_MACHINE_ID):
            self.server.metadata.delete(key)
        return {"status": "ok"}

    def _m_delete(self, req: Dict) -> Dict:
        """Machine deletion cleanup: mark every managed package for
        deletion so the package manager's delete loop collects them
        (reference: session_serve.go:188-218 createNeedDeleteFiles —
        'needDelete' there, our contract's 'delete' marker here).

        Deliberately does NOT purge credentials: that is logout's job, and
        the reference control plane sends both methods for a machine
        deletion (delete → package cleanup, logout → creds purge + stop)."""
        import os as _os

        pkgs_dir = self.server.config.packages_dir()
        marked = []
        errors = []
        if _os.path.isdir(pkgs_dir):
            for name in sorted(_os.listdir(pkgs_dir)):
                d = _os.path.join(pkgs_dir, name)
                if not _os.path.isdir(d):
                    continue
                try:
                    with open(_os.path.join(d, "delete"), "w", encoding="utf-8"):
                        pass
                    marked.append(name)
                except OSError as e:
                    # keep going: one unwritable dir must not block the
                    # cleanup of every other package
                    errors.append(f"{name}: {e}")
        audit("session_delete", packages=len(marked), errors=len(errors))
        out: Dict = {"status": "ok", "packages_marked": marked}
        if errors:
            out["errors"] = errors
        return out

    # -- packages / update / plugins --------------------------------------
    def _m_packageStatus(self, req: Dict) -> Dict:
        if self.server.package_manager is None:
            return {"packages": []}
        # probe=False: status.sh probes are subprocesses (30s timeout each)
        # and this runs on the session serve loop — same slow-op rule as
        # gossip/triggerComponent
        return {
            "packages": [
                s.to_dict()
                for s in self.server.package_manager.status(probe=False)
            ]
        }

    def _m_update(self, req: Dict) -> Dict:
        """Write the target-version file; the update watcher acts on it
        (reference: pkg/update/version_file.go:16)."""
        version = req.get("version", "")
        if not version:
            return {"error": "version required"}
        from gpud_tpu.update import write_target_version

        write_target_version(self.server.config.target_version_file(), version)
        return {"status": "ok", "target_version": version}

    # -- kapmtls (reference: kapMTLS{Status,UpdateCredentials,Activate},
    #    session_process_request.go) --------------------------------------
    def _kapmtls(self):
        from gpud_tpu.kapmtls import CertManager

        mgr = getattr(self.server, "kapmtls_manager", None)
        if mgr is None:
            import os as _os

            root = _os.path.join(
                self.server.config.resolved_data_dir(), "kapmtls"
            )
            mgr = CertManager(root=root)
            self.server.kapmtls_manager = mgr
        return mgr

    def _m_kapMTLSStatus(self, req: Dict) -> Dict:
        return {"kapmtls": self._kapmtls().status().to_dict()}

    def _m_kapMTLSUpdateCredentials(self, req: Dict) -> Dict:
        version = req.get("version", "")
        err = self._kapmtls().install(
            version, req.get("cert_pem", ""), req.get("key_pem", "")
        )
        if err:
            return {"error": err}
        if req.get("activate", False):
            err = self._kapmtls().activate(version)
            if err:
                return {"error": f"installed but activation failed: {err}"}
        return {"status": "ok", "version": version}

    def _m_kapMTLSActivate(self, req: Dict) -> Dict:
        err = self._kapmtls().activate(req.get("version", ""))
        return {"error": err} if err else {"status": "ok"}

    def _m_getPluginSpecs(self, req: Dict) -> Dict:
        specs = self.server.plugin_specs or []
        return {"specs": [s.to_dict() for s in specs]}

    def _m_setPluginSpecs(self, req: Dict) -> Dict:
        """Persist new specs; ask the supervisor for a restart so the new
        plugin set takes effect (reference: 137-141 exit-code restart)."""
        from gpud_tpu.plugins.spec import save_specs, specs_from_list

        try:
            specs = specs_from_list(req.get("specs", []))
        except (ValueError, KeyError) as e:
            return {"error": f"invalid specs: {e}"}
        # a spec named like a built-in component would crash-loop the next
        # boot at registration time — reject before persisting
        from gpud_tpu.components.all import all_components

        builtin = {getattr(f, "NAME", "") for f in all_components()}
        clashes = [s.name for s in specs if s.name in builtin]
        if clashes:
            return {"error": f"plugin name(s) clash with built-in components: {clashes}"}
        save_specs(self.server.config.resolved_plugin_specs_file(), specs)
        needs_restart = True
        if needs_restart and self.exit_fn is not None:
            threading.Timer(1.0, lambda: self.exit_fn(RESTART_EXIT_CODE)).start()
        return {"status": "ok", "restarting": needs_restart}
