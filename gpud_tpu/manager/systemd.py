"""systemd unit management for tpud.

Reference: pkg/gpud-manager/systemd/gpud.service:1-37 (Type=notify,
Restart=always, EnvironmentFile) + pkg/systemd helpers. tpud runs as a
python module; Restart=always also covers the self-update and
plugin-change restart-by-exit-code paths (update.py EXIT_CODE_UPDATE,
dispatch.py RESTART_EXIT_CODE).
"""

from __future__ import annotations

import os
from typing import Optional

from gpud_tpu.process import run_command

UNIT_NAME = "tpud.service"
UNIT_PATH = f"/etc/systemd/system/{UNIT_NAME}"
ENV_FILE = "/etc/default/tpud"

UNIT_TEMPLATE = """[Unit]
Description=tpud — TPU fleet health monitoring daemon
Wants=network-online.target
After=network-online.target

[Service]
Type=notify
NotifyAccess=main
EnvironmentFile=-{env_file}
ExecStart={python} -m gpud_tpu run $TPUD_FLAGS
Restart=always
RestartSec=5
# self-update and plugin changes restart via dedicated exit codes
SuccessExitStatus=244 245
StandardOutput=append:/var/log/tpud.log
StandardError=append:/var/log/tpud.log

[Install]
WantedBy=multi-user.target
"""


def render_unit(python: str = "", env_file: str = ENV_FILE) -> str:
    import sys

    return UNIT_TEMPLATE.format(python=python or sys.executable, env_file=env_file)


def install_unit(flags: str = "", unit_path: str = UNIT_PATH,
                 env_file: str = ENV_FILE) -> Optional[str]:
    """Write unit + env file, daemon-reload, enable+start. Returns error
    string or None (reference: gpud up systemd path, SURVEY §3.5)."""
    try:
        os.makedirs(os.path.dirname(unit_path), exist_ok=True)
        with open(unit_path, "w", encoding="utf-8") as f:
            f.write(render_unit(env_file=env_file))
        with open(env_file, "w", encoding="utf-8") as f:
            f.write(f'TPUD_FLAGS="{flags}"\n')
    except OSError as e:
        return f"cannot write unit files: {e}"
    for argv in (
        ["systemctl", "daemon-reload"],
        ["systemctl", "enable", UNIT_NAME],
        ["systemctl", "restart", UNIT_NAME],
    ):
        r = run_command(argv, timeout=60)
        if r.exit_code != 0:
            return f"{' '.join(argv)} failed: {r.error or r.output.strip()}"
    return None


def uninstall_unit(unit_path: str = UNIT_PATH) -> Optional[str]:
    errs = []
    for argv in (
        ["systemctl", "stop", UNIT_NAME],
        ["systemctl", "disable", UNIT_NAME],
    ):
        r = run_command(argv, timeout=60)
        if r.exit_code != 0:
            errs.append(f"{' '.join(argv)}: {r.error or r.output.strip()}")
    try:
        if os.path.exists(unit_path):
            os.unlink(unit_path)
    except OSError as e:
        errs.append(str(e))
    run_command(["systemctl", "daemon-reload"], timeout=60)
    return "; ".join(errs) if errs else None


def is_active(unit: str = UNIT_NAME) -> bool:
    return run_command(["systemctl", "is-active", unit], timeout=10).exit_code == 0
