"""Package manager — control-plane-pushed add-on packages.

Reference: pkg/gpud-manager — a file informer watches
``<dataDir>/packages/*/init.sh`` dirs (informer/file_informer.go:22-34) and
a PackageController runs reconcile/update/install/status/delete loops
(controllers/package_controller.go:46-52). Status is reported as
``PackageStatus{IsInstalled, Installing, Progress, Target/CurrentVersion}``
(packages/packages.go:13-35).

Contract per package dir ``<packages>/<name>/``:
- ``init.sh``      — installer; receives TARGET_VERSION env; writes
                     ``installed_version`` on success.
- ``version``      — target version (pushed by the control plane).
- ``status.sh``    — optional health probe; exit 0 = running.
- ``delete``       — deletion marker (pushed by the control plane); the
                     delete loop runs ``uninstall.sh`` (if present) and
                     removes the package dir (reference: deleteRunner,
                     package_controller.go:274-294 — there the package's
                     script answers needDelete; our contract is file-
                     marker-driven like ``version``).
- ``uninstall.sh`` — optional cleanup hook run before dir removal.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import PackagePhase, PackageStatus
from gpud_tpu.log import get_logger
from gpud_tpu.process import run_command

logger = get_logger(__name__)

# reference reconciles at 3s with an fsnotify informer; the no-op pass
# here is a handful of stat()s, so a 15s poll keeps pushes responsive
# without a watcher thread (footprint discipline, SURVEY §7)
RECONCILE_INTERVAL = 15.0
INSTALL_TIMEOUT = 15 * 60.0


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""


class PackageManager:
    """Reference: gpudmanager.Manager Start/Status (manager.go:24-46);
    the five controller loops are collapsed into one reconcile thread."""

    def __init__(self, packages_dir: str) -> None:
        self.packages_dir = packages_dir
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self._progress: Dict[str, int] = {}
        self._installing: Dict[str, bool] = {}

    # -- discovery ---------------------------------------------------------
    def package_names(self) -> List[str]:
        if not os.path.isdir(self.packages_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.packages_dir)):
            d = os.path.join(self.packages_dir, name)
            if os.path.isdir(d) and os.path.isfile(os.path.join(d, "init.sh")):
                out.append(name)
        return out

    # -- status ------------------------------------------------------------
    def status(self, probe: bool = True) -> List[PackageStatus]:
        """``probe=False`` skips status.sh subprocesses for callers on
        latency-sensitive paths (the session serve loop)."""
        out = []
        for name in self.package_names():
            d = os.path.join(self.packages_dir, name)
            target = _read(os.path.join(d, "version"))
            current = _read(os.path.join(d, "installed_version"))
            with self._mu:
                installing = self._installing.get(name, False)
                progress = self._progress.get(name, 0)
            if installing:
                phase = PackagePhase.INSTALLING
            elif current and (not target or current == target):
                phase = PackagePhase.INSTALLED
            elif not target:
                phase = PackagePhase.SKIPPED
            else:
                phase = PackagePhase.UNKNOWN
            out.append(
                PackageStatus(
                    name=name,
                    phase=phase,
                    status="running" if (probe and self._probe(d)) else "",
                    current_version=current,
                    target_version=target,
                    progress=100 if phase == PackagePhase.INSTALLED else progress,
                    is_installed=phase == PackagePhase.INSTALLED,
                    installing=installing,
                )
            )
        return out

    def _probe(self, pkg_dir: str) -> bool:
        probe = os.path.join(pkg_dir, "status.sh")
        if not os.path.isfile(probe):
            return False
        return run_command(["bash", probe], timeout=30.0).exit_code == 0

    # -- reconcile ---------------------------------------------------------
    def reconcile_once(self) -> None:
        # delete pass scans ALL subdirs, not just installable ones — a
        # partial push without init.sh must still honor its delete marker
        if os.path.isdir(self.packages_dir):
            for name in sorted(os.listdir(self.packages_dir)):
                d = os.path.join(self.packages_dir, name)
                if os.path.isdir(d) and os.path.exists(os.path.join(d, "delete")):
                    self._delete(name, d)
        for name in self.package_names():
            d = os.path.join(self.packages_dir, name)
            target = _read(os.path.join(d, "version"))
            current = _read(os.path.join(d, "installed_version"))
            if not target or target == current:
                continue
            self._install(name, d, target)

    def _delete(self, name: str, pkg_dir: str) -> None:
        """Reference: deleteRunner (package_controller.go:274-294) — run
        the package's cleanup hook, then drop the package entirely."""
        with self._mu:
            if self._installing.get(name):
                return  # let the in-flight install finish first
            self._installing[name] = True
        logger.info("deleting package %s", name)
        try:
            hook = os.path.join(pkg_dir, "uninstall.sh")
            # run the hook at most once even when dir removal fails and the
            # delete retries every reconcile — uninstall hooks are often
            # non-idempotent (stop a service, deregister, ...). The "done"
            # signal is removing the hook script itself: unlike a marker
            # file inside the dir, a partially-failed rmtree can only move
            # this in the safe direction (hook gone → never re-run).
            if os.path.isfile(hook):
                r = run_command(
                    ["bash", hook], timeout=INSTALL_TIMEOUT,
                    env={"PACKAGE_DIR": pkg_dir},
                )
                if r.exit_code != 0:
                    logger.warning(
                        "package %s uninstall hook failed (exit %d): %s — "
                        "removing anyway", name, r.exit_code, r.output[-500:],
                    )
                try:
                    os.unlink(hook)
                except OSError:
                    pass
            import shutil

            try:
                shutil.rmtree(pkg_dir)
                logger.info("package %s deleted", name)
            except OSError as e:
                # marker survives → retried next reconcile (hook skipped)
                logger.warning(
                    "package %s dir removal failed (%s); will retry", name, e
                )
        finally:
            with self._mu:
                self._installing.pop(name, None)
                self._progress.pop(name, None)

    def _install(self, name: str, pkg_dir: str, target: str) -> None:
        with self._mu:
            if self._installing.get(name):
                return
            self._installing[name] = True
            self._progress[name] = 10
        logger.info("installing package %s version %s", name, target)
        try:
            r = run_command(
                ["bash", os.path.join(pkg_dir, "init.sh")],
                timeout=INSTALL_TIMEOUT,
                env={"TARGET_VERSION": target, "PACKAGE_DIR": pkg_dir},
            )
            if r.exit_code == 0:
                with open(
                    os.path.join(pkg_dir, "installed_version"), "w", encoding="utf-8"
                ) as f:
                    f.write(target)
                logger.info("package %s installed at %s", name, target)
            else:
                logger.warning(
                    "package %s install failed (exit %d): %s",
                    name, r.exit_code, r.output[-500:],
                )
        finally:
            with self._mu:
                self._installing[name] = False
                self._progress[name] = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tpud-package-manager", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(RECONCILE_INTERVAL):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001
                logger.exception("package reconcile failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
