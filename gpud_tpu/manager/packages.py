"""Package manager — control-plane-pushed add-on packages.

Reference: pkg/gpud-manager — a file informer watches
``<dataDir>/packages/*/init.sh`` dirs (informer/file_informer.go:22-34) and
a PackageController runs reconcile/update/install/status/delete loops
(controllers/package_controller.go:46-52). Status is reported as
``PackageStatus{IsInstalled, Installing, Progress, Target/CurrentVersion}``
(packages/packages.go:13-35).

Contract per package dir ``<packages>/<name>/``:
- ``init.sh``      — installer; receives TARGET_VERSION env; writes
                     ``installed_version`` on success.
- ``version``      — target version (pushed by the control plane).
- ``status.sh``    — optional health probe; exit 0 = running.
- ``delete``       — deletion marker (pushed by the control plane); the
                     delete loop runs ``uninstall.sh`` (if present) and
                     removes the package dir (reference: deleteRunner,
                     package_controller.go:274-294 — there the package's
                     script answers needDelete; our contract is file-
                     marker-driven like ``version``).
- ``uninstall.sh`` — optional cleanup hook run before dir removal.
- ``requires``     — optional dependency list (one package name per
                     line); install waits until every dependency is
                     installed (reference: Dependency gating,
                     package_controller.go installRunner).
- ``should_skip.sh`` — optional probe; exit 0 marks the package skipped
                     (already provided by the image/host) without
                     installing (reference: shouldSkip contract).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import PackagePhase, PackageStatus
from gpud_tpu.log import get_logger
from gpud_tpu.process import run_command

logger = get_logger(__name__)

# reference reconciles at 3s with an fsnotify informer; the no-op pass
# here is a handful of stat()s, so a 15s poll keeps pushes responsive
# without a watcher thread (footprint discipline, SURVEY §7)
RECONCILE_INTERVAL = 15.0
INSTALL_TIMEOUT = 15 * 60.0


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""


class PackageManager:
    """Reference: gpudmanager.Manager Start/Status (manager.go:24-46);
    the five controller loops are collapsed into one reconcile thread."""

    def __init__(self, packages_dir: str) -> None:
        self.packages_dir = packages_dir
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self._progress: Dict[str, int] = {}
        self._installing: Dict[str, bool] = {}
        self._skipped: set = set()  # should_skip.sh said the host provides it
        # probe results cached on (target version, probe mtime): a skipped
        # package would otherwise fork its probe every reconcile forever
        self._skip_cache: Dict[str, tuple] = {}
        self._dep_warned: set = set()  # (pkg, dep) pairs already logged

    # -- discovery ---------------------------------------------------------
    def package_names(self) -> List[str]:
        if not os.path.isdir(self.packages_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.packages_dir)):
            d = os.path.join(self.packages_dir, name)
            if os.path.isdir(d) and os.path.isfile(os.path.join(d, "init.sh")):
                out.append(name)
        return out

    # -- status ------------------------------------------------------------
    def status(self, probe: bool = True) -> List[PackageStatus]:
        """``probe=False`` skips status.sh subprocesses for callers on
        latency-sensitive paths (the session serve loop)."""
        out = []
        for name in self.package_names():
            d = os.path.join(self.packages_dir, name)
            target = _read(os.path.join(d, "version"))
            current = _read(os.path.join(d, "installed_version"))
            with self._mu:
                installing = self._installing.get(name, False)
                progress = self._progress.get(name, 0)
                host_provided = name in self._skipped
            if installing:
                phase = PackagePhase.INSTALLING
            elif host_provided:
                phase = PackagePhase.SKIPPED
            elif current and (not target or current == target):
                phase = PackagePhase.INSTALLED
            elif not target:
                phase = PackagePhase.SKIPPED
            else:
                phase = PackagePhase.UNKNOWN
            out.append(
                PackageStatus(
                    name=name,
                    phase=phase,
                    status="running" if (probe and self._probe(d)) else "",
                    current_version=current,
                    target_version=target,
                    progress=100 if phase == PackagePhase.INSTALLED else progress,
                    is_installed=phase == PackagePhase.INSTALLED,
                    installing=installing,
                )
            )
        return out

    def _probe(self, pkg_dir: str) -> bool:
        probe = os.path.join(pkg_dir, "status.sh")
        if not os.path.isfile(probe):
            return False
        return run_command(["bash", probe], timeout=30.0).exit_code == 0

    # -- reconcile ---------------------------------------------------------
    def reconcile_once(self) -> None:
        # delete pass scans ALL subdirs, not just installable ones — a
        # partial push without init.sh must still honor its delete marker
        if os.path.isdir(self.packages_dir):
            for name in sorted(os.listdir(self.packages_dir)):
                d = os.path.join(self.packages_dir, name)
                if os.path.isdir(d) and os.path.exists(os.path.join(d, "delete")):
                    self._delete(name, d)
        names = self.package_names()
        for name in names:
            d = os.path.join(self.packages_dir, name)
            target = _read(os.path.join(d, "version"))
            current = _read(os.path.join(d, "installed_version"))
            if not target or target == current:
                continue
            if self._should_skip(name, d):
                continue
            if not self._deps_ready(name, d, names):
                continue
            self._install(name, d, target)

    def _should_skip(self, name: str, pkg_dir: str) -> bool:
        """Optional should_skip.sh probe: exit 0 ⇒ the host already
        provides this package; mark skipped, never install (reference:
        shouldSkip, package_controller.go installRunner). The result is
        cached on (target version, probe mtime) so a skipped package does
        not fork its probe on every reconcile pass."""
        probe = os.path.join(pkg_dir, "should_skip.sh")
        if not os.path.isfile(probe):
            with self._mu:
                self._skipped.discard(name)
                self._skip_cache.pop(name, None)
            return False
        target = _read(os.path.join(pkg_dir, "version"))
        try:
            mtime = os.stat(probe).st_mtime_ns
        except OSError:
            mtime = 0
        key = (target, mtime)
        with self._mu:
            cached = self._skip_cache.get(name)
        if cached is not None and cached[0] == key:
            return cached[1]
        skip = run_command(["bash", probe], timeout=60.0).exit_code == 0
        with self._mu:
            self._skip_cache[name] = (key, skip)
            if skip:
                self._skipped.add(name)
            else:
                self._skipped.discard(name)
        return skip

    def _dep_satisfied(self, dep: str) -> bool:
        """Installed, or host-provided per its should_skip probe."""
        with self._mu:
            if dep in self._skipped:
                return True
        return bool(
            _read(os.path.join(self.packages_dir, dep, "installed_version"))
        )

    def _deps_ready(self, name: str, pkg_dir: str, known: List[str]) -> bool:
        """Optional requires file: every listed package must be installed
        (or host-provided/skipped) first (reference: Dependency gating).
        Gating is logged once per (package, dependency) pair; the warning
        re-arms when the dependency later satisfies, so a regression logs
        again rather than silently re-gating."""
        req = _read(os.path.join(pkg_dir, "requires"))
        if not req:
            return True
        for dep in (ln.strip() for ln in req.splitlines()):
            if not dep or dep.startswith("#"):
                continue
            if dep == name:
                continue  # self-dependency would deadlock
            if dep in known and self._dep_satisfied(dep):
                with self._mu:
                    self._dep_warned.discard((name, dep))
                continue
            why = "unknown package" if dep not in known else "not installed yet"
            with self._mu:
                first = (name, dep) not in self._dep_warned
                self._dep_warned.add((name, dep))
            if first:
                logger.warning(
                    "package %s waiting on dependency %s (%s)", name, dep, why
                )
            return False
        return True

    def _delete(self, name: str, pkg_dir: str) -> None:
        """Reference: deleteRunner (package_controller.go:274-294) — run
        the package's cleanup hook, then drop the package entirely."""
        with self._mu:
            if self._installing.get(name):
                return  # let the in-flight install finish first
            self._installing[name] = True
        logger.info("deleting package %s", name)
        try:
            hook = os.path.join(pkg_dir, "uninstall.sh")
            # run the hook at most once even when dir removal fails and the
            # delete retries every reconcile — uninstall hooks are often
            # non-idempotent (stop a service, deregister, ...). The "done"
            # signal is removing the hook script itself: unlike a marker
            # file inside the dir, a partially-failed rmtree can only move
            # this in the safe direction (hook gone → never re-run).
            if os.path.isfile(hook):
                r = run_command(
                    ["bash", hook], timeout=INSTALL_TIMEOUT,
                    env={"PACKAGE_DIR": pkg_dir},
                )
                if r.exit_code != 0:
                    logger.warning(
                        "package %s uninstall hook failed (exit %d): %s — "
                        "removing anyway", name, r.exit_code, r.output[-500:],
                    )
                try:
                    os.unlink(hook)
                except OSError:
                    pass
            import shutil

            try:
                shutil.rmtree(pkg_dir)
                logger.info("package %s deleted", name)
            except OSError as e:
                # marker survives → retried next reconcile (hook skipped)
                logger.warning(
                    "package %s dir removal failed (%s); will retry", name, e
                )
        finally:
            with self._mu:
                self._installing.pop(name, None)
                self._progress.pop(name, None)
                # a delete-then-repush of the same name must not inherit
                # stale skip/dep state
                self._skipped.discard(name)
                self._skip_cache.pop(name, None)
                self._dep_warned = {
                    pair for pair in self._dep_warned if pair[0] != name
                }

    def _install(self, name: str, pkg_dir: str, target: str) -> None:
        with self._mu:
            if self._installing.get(name):
                return
            self._installing[name] = True
            self._progress[name] = 10
        logger.info("installing package %s version %s", name, target)
        try:
            r = run_command(
                ["bash", os.path.join(pkg_dir, "init.sh")],
                timeout=INSTALL_TIMEOUT,
                env={"TARGET_VERSION": target, "PACKAGE_DIR": pkg_dir},
            )
            if r.exit_code == 0:
                with open(
                    os.path.join(pkg_dir, "installed_version"), "w", encoding="utf-8"
                ) as f:
                    f.write(target)
                logger.info("package %s installed at %s", name, target)
            else:
                logger.warning(
                    "package %s install failed (exit %d): %s",
                    name, r.exit_code, r.output[-500:],
                )
        finally:
            with self._mu:
                self._installing[name] = False
                self._progress[name] = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tpud-package-manager", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        """File-informer loop (reference: informer/file_informer.go uses
        fsnotify): inotify on the packages tree reconciles a push within
        ~0.5s; the RECONCILE_INTERVAL poll remains as the fallback
        heartbeat (and the only mechanism where inotify is unavailable)."""
        import time as _time

        from gpud_tpu.inotify import InotifyWatch

        try:
            os.makedirs(self.packages_dir, exist_ok=True)
        except OSError:
            pass
        informer = InotifyWatch.create(
            self.packages_dir, mask=InotifyWatch.TREE_MASK
        )
        if informer is None:
            # no inotify (non-Linux/sandbox): plain interval polling, one
            # blocking wait per cycle (footprint discipline)
            while not self._stop.wait(RECONCILE_INTERVAL):
                try:
                    self.reconcile_once()
                except Exception:  # noqa: BLE001
                    logger.exception("package reconcile failed")
            return
        watched: set = set()
        last = 0.0
        while not self._stop.is_set():
            try:
                # watch each package subdir so version/delete pushes INSIDE
                # them wake the loop too; prune vanished dirs so a
                # delete-then-repush of the same name is re-watched
                watched = {d for d in watched if os.path.isdir(d)}
                new_watch = False
                for name in self.package_names():
                    d = os.path.join(self.packages_dir, name)
                    if d not in watched and informer.add_path(d):
                        watched.add(d)
                        new_watch = True
                # a just-watched dir may have received writes BEFORE its
                # watch existed (push races dir creation) — reconcile now
                # rather than waiting for an event that already happened
                woke = True if new_watch else informer.wait(500)
                now = _time.monotonic()
                if woke or now - last >= RECONCILE_INTERVAL:
                    self.reconcile_once()
                    last = now
            except Exception:  # noqa: BLE001 — the loop must outlive any
                logger.exception("package informer cycle failed")
                if self._stop.wait(1.0):
                    break
        informer.close()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
