"""Manager federation: journal stream-replication + scatter-gather.

The HA manager tier (docs/fleet.md "Federation & failover"). Each
manager in a ``PeerSet`` (peers.py) runs three cooperating pieces:

- **JournalShipper** — ships this manager's rollup-journal appends
  (manager/rollup.py, ordered by SQLite rowid) to its ring successor
  over the same session transport agents use: a ``Session`` with a
  ``peer:`` machine id, delta-encoded ``outbox_batch`` frames, and the
  manager side's cumulative ``outboxAck`` watermark. The contract is
  the agent outbox contract (session/outbox.py) verbatim: at-least-once
  delivery above a monotonic acked watermark, keyframe-anchored
  redelivery after a reconnect or an ack stall.
- **ReplicaStore** — the receiving side: the successor journals every
  replicated row into a per-source replica table, byte-identical to the
  source's journal rows (payload blobs are carried hex-encoded, so the
  stored bytes ARE the source's bytes). The replica is kept apart from
  the local cohort so scatter-gather never double-counts a live peer.
- **FederationPlane** — owns the peer health probe loop, the dead-peer
  **adopt** path (replay the replicated journal prefix into the local
  rollup store, so the survivor's pane covers the dead peer's cohort —
  agents failing over then redeliver their unacked tail and dedupe
  against the adopted prefix exactly as after a manager SIGKILL), and
  the scatter-gather fan-out that keeps ``/v1/fleet/*`` a single pane
  (per-peer timeout, ``peers`` health block in every envelope).

Ack-vs-durability across peers: the shipper only reads journal rows the
BatchWriter has committed, and the receiver acks after submitting to its
own writer — so a replicated ack means "in the survivor's write-behind
buffer", with the same bounded durability window a single manager's
agent acks have (docs/fleet.md).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.manager.peers import PeerDescriptor, PeerSet
from gpud_tpu.manager.rollup import TABLE as JOURNAL_TABLE
from gpud_tpu.session import wire

logger = get_logger(__name__)

# machine-id namespace for manager→manager replication sessions: the
# receiving ControlPlane routes these handles' records into the replica
# store instead of its own cohort rollup
PEER_MACHINE_PREFIX = "peer:"

# record kind carried by replication frames (shows up in the receiving
# handle's dedupe ledger, never in the cohort rollup)
REPLICA_KIND = "fleet_journal"

REPLICA_TABLE = "tpud_fleet_replica_v0_1"

DEFAULT_REPLICATION_INTERVAL = 1.0   # shipper tick cadence (seconds)
DEFAULT_PROBE_INTERVAL = 5.0         # peer health probe cadence
DEFAULT_FANOUT_TIMEOUT = 2.0         # per-peer scatter-gather budget
DEFAULT_DEAD_AFTER_PROBES = 3        # consecutive failures → unreachable
DEFAULT_SHIP_BATCH = 2000            # journal rows per replication frame
DEFAULT_REDELIVER_AFTER = 30.0       # ack-stall window before redelivery

# write-behind contract (tools/storage_lint.py): replica journaling must
# ride the shared BatchWriter, never commit per-row on the ingest path
HOT_WRITE_METHODS = ("replica_ingest",)

_REPLICA_SCHEMA = f"""
CREATE TABLE IF NOT EXISTS {REPLICA_TABLE} (
    source_peer    TEXT    NOT NULL,
    src_rowid      INTEGER NOT NULL,
    agent          TEXT    NOT NULL,
    seq            INTEGER NOT NULL,
    ts             REAL    NOT NULL,
    ingested       REAL    NOT NULL,
    kind           TEXT    NOT NULL,
    dedupe_key     TEXT    NOT NULL,
    correlation_id TEXT    NOT NULL DEFAULT '',
    payload        BLOB,
    shard          INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (source_peer, src_rowid)
)
"""

_REPLICA_INSERT = (
    f"INSERT OR IGNORE INTO {REPLICA_TABLE} "
    "(source_peer, src_rowid, agent, seq, ts, ingested, kind, dedupe_key, "
    "correlation_id, payload, shard) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
)

_SHIP_SELECT = (
    "SELECT rowid, agent, seq, ts, ingested, kind, dedupe_key, "
    f"correlation_id, payload, shard FROM {JOURNAL_TABLE} "
    "WHERE rowid > ? ORDER BY rowid LIMIT ?"
)


def journal_row_body(row: Tuple) -> dict:
    """The shipped body for one journal row: every column, with the
    payload blob hex-encoded so the bytes survive any frame encoding
    (JSON v1 frames and rev-3 wire frames alike) unchanged."""
    rowid, agent, seq, ts, ingested, kind, key, cid, payload, shard = row
    return {
        "agent": agent,
        "seq": seq,
        "ts": ts,
        "ingested": ingested,
        "kind": kind,
        "dedupe_key": key,
        "correlation_id": cid or "",
        "payload_hex": payload.hex() if payload is not None else None,
        "shard": shard,
    }


class ReplicaStore:
    """Per-source replica of a peer's journal (receiving side)."""

    GUARDED_BY = {
        "_accepted": "_mu",
        "_malformed": "_mu",
        "_watermarks": "_mu",
    }

    def __init__(self, db, writer=None) -> None:
        self.db = db
        self.writer = writer
        self._mu = threading.Lock()
        self._accepted = 0
        self._malformed = 0
        # in-memory high-water mark per source (includes rows still in
        # the write-behind buffer; durable reads go through rows())
        self._watermarks: Dict[str, int] = {}
        db.execute(_REPLICA_SCHEMA)
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_replica_agent "
            f"ON {REPLICA_TABLE} (source_peer, agent, ts, seq)"
        )

    def replica_ingest(self, source_peer: str, records) -> int:
        """Journal one decoded replication batch. ``records`` are the
        receiving handle's fresh decoded outbox tuples
        ``(rep_seq, ts, kind, key, body)`` where ``rep_seq`` is the
        source journal rowid and ``body`` is ``journal_row_body()``."""
        rows: List[tuple] = []
        bad = 0
        for rep_seq, _ts, kind, _key, body in records:
            if kind != REPLICA_KIND or not isinstance(body, dict):
                bad += 1
                continue
            payload_hex = body.get("payload_hex")
            try:
                payload = (
                    bytes.fromhex(payload_hex)
                    if payload_hex is not None else None
                )
                rows.append((
                    source_peer,
                    int(rep_seq),
                    str(body.get("agent", "")),
                    int(body.get("seq", 0)),
                    float(body.get("ts", 0.0)),
                    float(body.get("ingested", 0.0)),
                    str(body.get("kind", "")),
                    str(body.get("dedupe_key", "")),
                    str(body.get("correlation_id", "") or ""),
                    payload,
                    int(body.get("shard", 0)),
                ))
            except (TypeError, ValueError):
                bad += 1
        with self._mu:
            self._malformed += bad
            if rows:
                self._accepted += len(rows)
                top = rows[-1][1]
                if top > self._watermarks.get(source_peer, 0):
                    self._watermarks[source_peer] = top
        if not rows:
            return 0
        if self.writer is not None:
            self.writer.submit_many("fleet-replica", _REPLICA_INSERT, rows)
        else:
            self.db.executemany(_REPLICA_INSERT, rows)
        return len(rows)

    def rows(self, source_peer: str) -> List[tuple]:
        """The durable replicated prefix for one source, in source
        journal order — the survivor-rebuild input, byte-identical to
        the dead peer's own journal rows."""
        return self.db.query(
            f"SELECT src_rowid, agent, seq, ts, ingested, kind, "
            f"dedupe_key, correlation_id, payload, shard "
            f"FROM {REPLICA_TABLE} WHERE source_peer = ? ORDER BY src_rowid",
            (source_peer,),
        )

    def count(self, source_peer: str) -> int:
        row = self.db.query_one(
            f"SELECT COUNT(*) FROM {REPLICA_TABLE} WHERE source_peer = ?",
            (source_peer,),
        )
        return int(row[0]) if row else 0

    def watermark(self, source_peer: str) -> int:
        with self._mu:
            return self._watermarks.get(source_peer, 0)

    def stats(self) -> Dict:
        with self._mu:
            return {
                "accepted": self._accepted,
                "malformed": self._malformed,
                "watermarks": dict(self._watermarks),
            }


class JournalShipper:
    """Replication sender: local journal rows → the successor peer.

    Mirrors ``SessionOutbox.replay_once`` (session/outbox.py): a
    monotonic acked watermark (``outboxAck`` frames from the peer, MAX
    semantics), a delivered cursor ahead of it, delta-encoded batches,
    encoder reset + delivered→acked fallback on reconnect or ack stall.
    """

    GUARDED_BY = {
        "_acked": "_mu",
        "_delivered": "_mu",
        "_encoder": "_mu",
        "_ack_progress_ts": "_mu",
        "_shipped": "_mu",
        "_frames": "_mu",
        "_redeliveries": "_mu",
    }

    def __init__(
        self,
        db,
        peer: PeerDescriptor,
        self_id: str,
        token: str = "",
        ship_batch: int = DEFAULT_SHIP_BATCH,
        redeliver_after: float = DEFAULT_REDELIVER_AFTER,
        time_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        from gpud_tpu.session.session import Session

        self.db = db
        self.peer = peer
        self.self_id = self_id
        self.ship_batch = max(1, int(ship_batch))
        self.redeliver_after = float(redeliver_after)
        self.time_fn = time_fn
        self._mu = threading.Lock()
        self._acked = 0
        self._delivered = 0
        self._encoder = wire.DeltaEncoder()
        self._ack_progress_ts = time_fn()
        self._shipped = 0
        self._frames = 0
        self._redeliveries = 0
        self.session = Session(
            endpoint=peer.endpoint,
            machine_id=f"{PEER_MACHINE_PREFIX}{self_id}",
            token=token or "",
            dispatch_fn=self._dispatch,
            protocol="auto" if peer.grpc_target else "v1",
            v2_target=peer.grpc_target,
        )
        self.session.on_connected = self._on_connected

    # -- session plumbing --------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        method = (req or {}).get("method", "")
        if method == "outboxAck":
            try:
                self.on_ack(int(req.get("seq", 0)))
            except (TypeError, ValueError):
                return {"error": "bad ack seq"}
            return {"ok": True}
        # peers are not agents: any other manager request is answered,
        # not served (the replication stream is one-purpose)
        return {"error": f"peer stream does not serve {method!r}"}

    def _on_connected(self) -> None:
        # fresh connection = fresh delta stream on the receiving handle:
        # restart keyframe-anchored from the acked watermark, exactly
        # like SessionOutbox.reset_delivery on an agent reconnect
        with self._mu:
            self._encoder = wire.DeltaEncoder()
            self._delivered = self._acked
            self._ack_progress_ts = self.time_fn()

    def on_ack(self, seq: int) -> None:
        """Cumulative ack from the peer; the watermark only advances."""
        with self._mu:
            if seq > self._acked:
                self._acked = seq
                self._ack_progress_ts = self.time_fn()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.session.start()

    def stop(self) -> None:
        self.session.stop()

    # -- replication tick --------------------------------------------------
    def tick(self) -> int:
        """Ship the next batch of journal rows above the delivered
        cursor; returns rows shipped this tick."""
        if not self.session.connected:
            return 0
        now = self.time_fn()
        with self._mu:
            if (
                self._delivered > self._acked
                and now - self._ack_progress_ts >= self.redeliver_after
            ):
                # ack stall: the in-flight tail may be lost (peer restart
                # without a stream close we saw) — rewind to the watermark
                # and re-encode keyframe-anchored
                self._encoder = wire.DeltaEncoder()
                self._delivered = self._acked
                self._redeliveries += 1
                self._ack_progress_ts = now
            cursor = self._delivered
        rows = self.db.query(_SHIP_SELECT, (cursor, self.ship_batch))
        if not rows:
            return 0
        with self._mu:
            records = [
                self._encoder.encode_record(
                    int(r[0]), float(r[3]), REPLICA_KIND,
                    f"j:{int(r[0])}", journal_row_body(r),
                )
                for r in rows
            ]
            first, last = int(rows[0][0]), int(rows[-1][0])
        from gpud_tpu.session.session import Frame

        sent = self.session.send(Frame(
            req_id=f"outbox-batch-{first}-{last}",
            data=wire.build_batch(records),
        ))
        with self._mu:
            if sent:
                self._delivered = last
                self._shipped += len(rows)
                self._frames += 1
            else:
                # the frame never entered the wire buffer: rewind so the
                # next tick re-encodes from a keyframe
                self._encoder = wire.DeltaEncoder()
                self._delivered = min(self._delivered, self._acked)
        return len(rows) if sent else 0

    def journal_head(self) -> int:
        row = self.db.query_one(f"SELECT MAX(rowid) FROM {JOURNAL_TABLE}")
        return int(row[0]) if row and row[0] is not None else 0

    def stats(self) -> Dict:
        with self._mu:
            acked, delivered = self._acked, self._delivered
            shipped, frames = self._shipped, self._frames
            redeliveries = self._redeliveries
        head = self.journal_head()
        return {
            "peer": self.peer.peer_id,
            "connected": self.session.connected,
            "transport": self.session.active_protocol,
            "acked_rowid": acked,
            "delivered_rowid": delivered,
            "journal_head_rowid": head,
            "lag_rows": max(0, head - acked),
            "shipped_rows": shipped,
            "frames": frames,
            "redeliveries": redeliveries,
        }


# -- scatter-gather merge helpers (pure; unit-tested directly) -------------

def _sum_into(dst: Dict, src: Dict, keys: Tuple[str, ...]) -> None:
    for k in keys:
        if isinstance(src.get(k), (int, float)):
            dst[k] = dst.get(k, 0) + src[k]


def _merge_counter(dst: Dict, src: Optional[Dict]) -> Dict:
    for k, v in (src or {}).items():
        dst[k] = dst.get(k, 0) + v
    return dst


def merge_rollup(local: Dict, remotes: Dict[str, Dict]) -> Dict:
    """One pane over every cohort. Sums and counter-merges are exact;
    availability/MTTR/MTBF are series-weighted means across peers (each
    peer's own number is exact for its cohort — docs/fleet.md)."""
    merged = dict(local)
    by_kind = _merge_counter({}, local.get("records_by_kind"))
    outcomes = _merge_counter({}, local.get("remediation_outcomes"))
    flapping = list(local.get("flapping") or [])
    cohorts: Dict[str, Dict] = {}
    weighted = [(local.get("series", 0), local)]
    for pid, pane in sorted(remotes.items()):
        if not pane:
            continue
        cohorts[pid] = {
            "agents": pane.get("agents", 0),
            "series": pane.get("series", 0),
            "records_total": pane.get("records_total", 0),
            "generation": pane.get("generation", 0),
        }
        _sum_into(merged, pane, (
            "agents", "series", "records_total", "duplicates_suppressed",
            "transitions_total", "failures_total", "unhealthy_series",
        ))
        _merge_counter(by_kind, pane.get("records_by_kind"))
        _merge_counter(outcomes, pane.get("remediation_outcomes"))
        flapping.extend(pane.get("flapping") or [])
        merged["max_outbox_lag_seconds"] = max(
            merged.get("max_outbox_lag_seconds", 0.0),
            pane.get("max_outbox_lag_seconds", 0.0),
        )
        weighted.append((pane.get("series", 0), pane))
    total_w = sum(max(w, 0) for w, _ in weighted)
    if total_w > 0:
        for key in ("availability", "mttr_seconds", "mtbf_seconds"):
            merged[key] = sum(
                max(w, 0) * float(p.get(key, 0.0)) for w, p in weighted
            ) / total_w
    merged["records_by_kind"] = dict(sorted(by_kind.items()))
    merged["remediation_outcomes"] = dict(sorted(outcomes.items()))
    flapping.sort(key=lambda f: (
        -f.get("flap_count", 0), f.get("agent", ""), f.get("component", ""),
    ))
    merged["flapping"] = flapping[:32]
    merged["cohorts"] = cohorts
    return merged


def merge_fabric(local: Dict, remotes: Dict[str, Dict]) -> Dict:
    merged = dict(local)
    by_state = _merge_counter({}, local.get("links_by_state"))
    degraded = list(local.get("degraded") or [])
    for pid, pane in sorted(remotes.items()):
        if not pane:
            continue
        _sum_into(merged, pane, (
            "agents", "links_total", "degraded_count", "links_truncated",
        ))
        _merge_counter(by_state, pane.get("links_by_state"))
        degraded.extend(pane.get("degraded") or [])
    rank = {"down": 3, "degraded": 2, "healthy": 1, "unknown": 0}
    degraded.sort(key=lambda r: (
        -rank.get(r.get("state", ""), 0),
        -r.get("last_degraded_ts", 0.0),
        r.get("agent", ""),
        r.get("link", ""),
    ))
    merged["links_by_state"] = dict(sorted(by_state.items()))
    merged["degraded"] = degraded[:256]
    return merged


def merge_predict(local: Dict, remotes: Dict[str, Dict]) -> Dict:
    merged = dict(local)
    buckets = _merge_counter({}, local.get("risk_buckets"))
    top = list(local.get("top") or [])
    lead = dict(local.get("lead") or {})
    lead_total = lead.get("mean_seconds", 0.0) * lead.get("count", 0)
    for pid, pane in sorted(remotes.items()):
        if not pane:
            continue
        _sum_into(merged, pane, (
            "agents", "series", "armed", "warns_total",
            "unknown_schema_records", "predict_truncated",
        ))
        _merge_counter(buckets, pane.get("risk_buckets"))
        top.extend(pane.get("top") or [])
        pl = pane.get("lead") or {}
        if pl.get("count"):
            if not lead.get("count") or pl["min_seconds"] < lead.get(
                "min_seconds", 0.0
            ):
                lead["min_seconds"] = pl["min_seconds"]
            lead["max_seconds"] = max(
                lead.get("max_seconds", 0.0), pl.get("max_seconds", 0.0)
            )
            lead["count"] = lead.get("count", 0) + pl["count"]
            lead_total += pl.get("mean_seconds", 0.0) * pl["count"]
    if lead.get("count"):
        lead["mean_seconds"] = lead_total / lead["count"]
    top.sort(key=lambda r: (
        -r.get("risk", 0.0), r.get("agent", ""), r.get("component", ""),
    ))
    merged["risk_buckets"] = buckets
    merged["lead"] = lead
    merged["top"] = top[: int(local.get("top_k", 20) or 20)]
    return merged


def merge_agents(
    local: Dict, remotes: Dict[str, Dict], limit: int, self_id: str = ""
) -> Dict:
    """Union of per-peer pages, re-sorted by agent id and capped at
    ``limit``. Federated pagination is approximate: ``offset`` applies
    per peer, not to the merged view (docs/fleet.md)."""
    rows = []
    for row in local.get("agents") or []:
        row = dict(row)
        if self_id:
            row.setdefault("peer", self_id)
        rows.append(row)
    merged = dict(local)
    more = local.get("next_offset") is not None
    for pid, page in sorted(remotes.items()):
        if not page:
            continue
        for row in page.get("agents") or []:
            row = dict(row)
            row.setdefault("peer", pid)
            rows.append(row)
        merged["total"] = merged.get("total", 0) + page.get("total", 0)
        more = more or page.get("next_offset") is not None
    rows.sort(key=lambda r: r.get("agent", ""))
    if len(rows) > limit:
        rows = rows[:limit]
        more = True
    merged["agents"] = rows
    merged["next_offset"] = (
        merged.get("offset", 0) + len(rows) if more else None
    )
    return merged


def merge_traces(local: Dict, remotes: Dict[str, Dict], limit: int) -> Dict:
    merged = dict(local)
    records = list(local.get("records") or [])
    seen = {
        (r.get("agent"), r.get("seq"), r.get("dedupe_key"))
        for r in records
    }
    for pid, pane in sorted(remotes.items()):
        if not pane:
            continue
        for r in pane.get("records") or []:
            key = (r.get("agent"), r.get("seq"), r.get("dedupe_key"))
            if key in seen:
                continue
            seen.add(key)
            records.append(r)
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", 0)))
    merged["records"] = records[:limit]
    merged["count"] = len(merged["records"])
    return merged


class FederationPlane:
    """One manager's view of the federated tier (module docstring)."""

    # counters share one lock; peers/replica/shipper guard themselves.
    # _adopt_mu serializes adopt() so a probe edge racing an explicit
    # adopt can't double-apply the prefix.
    GUARDED_BY = {
        "_scatter_ok": "_mu",
        "_scatter_err": "_mu",
        "_adopts": "_mu",
        "_last_fanout": "_mu",
    }

    PATHS = {
        "rollup": "/v1/fleet/rollup",
        "fabric": "/v1/fleet/fabric",
        "predict": "/v1/fleet/predict",
        "agents": "/v1/fleet/agents",
        "traces": "/v1/fleet/traces",
        "peers": "/v1/fleet/peers",
    }

    def __init__(
        self,
        peers: PeerSet,
        rollup,
        db,
        writer=None,
        session_token: Optional[str] = None,
        admin_token: Optional[str] = None,
        replication_interval: float = DEFAULT_REPLICATION_INTERVAL,
        probe_interval: float = DEFAULT_PROBE_INTERVAL,
        fanout_timeout: float = DEFAULT_FANOUT_TIMEOUT,
        auto_adopt: bool = True,
        ship_batch: int = DEFAULT_SHIP_BATCH,
        redeliver_after: float = DEFAULT_REDELIVER_AFTER,
    ) -> None:
        from concurrent.futures import ThreadPoolExecutor

        self.peers = peers
        self.rollup = rollup
        self.db = db
        self.writer = writer
        self.admin_token = admin_token
        self.replication_interval = max(0.05, float(replication_interval))
        self.probe_interval = max(0.1, float(probe_interval))
        self.fanout_timeout = max(0.1, float(fanout_timeout))
        self.auto_adopt = bool(auto_adopt)
        self._mu = threading.Lock()
        self._adopt_mu = threading.Lock()
        self._scatter_ok = 0
        self._scatter_err = 0
        self._adopts = 0
        self._last_fanout: Dict[str, Dict] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, min(8, len(peers.ring))),
            thread_name_prefix="tpud-mgr-fanout",
        )
        successor = peers.successor()
        self.shipper: Optional[JournalShipper] = None
        if successor is not None:
            self.shipper = JournalShipper(
                db, successor, peers.self_id,
                token=session_token or "",
                ship_batch=ship_batch,
                redeliver_after=redeliver_after,
            )
        self.replica = ReplicaStore(db, writer)

    # -- lifecycle ---------------------------------------------------------
    def start(self, scheduler) -> None:
        if self.shipper is not None:
            self.shipper.start()
            scheduler.add_job(
                "federation-replicate",
                self.replicate_once,
                interval=self.replication_interval,
                initial_delay=self.replication_interval,
            )
        scheduler.add_job(
            "federation-probe",
            self.probe_once,
            interval=self.probe_interval,
            initial_delay=self.probe_interval,
        )

    def stop(self) -> None:
        if self.shipper is not None:
            self.shipper.stop()
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- replication -------------------------------------------------------
    def replicate_once(self) -> int:
        if self.shipper is None:
            return 0
        return self.shipper.tick()

    def replica_sink(self, machine_id: str):
        """The ``on_records`` hook for a ``peer:`` transport handle."""
        source = machine_id[len(PEER_MACHINE_PREFIX):] or machine_id

        def sink(_mid: str, fresh) -> None:
            self.replica.replica_ingest(source, fresh)

        return sink

    # -- health + adopt ----------------------------------------------------
    def probe_once(self) -> None:
        now = time.time()
        for peer in self.peers.others():
            t0 = time.monotonic()
            err = ""
            ok = True
            try:
                self._fetch(
                    peer, self.PATHS["peers"], {"scope": "local"}
                )
            except Exception as e:  # noqa: BLE001 — any failure is "down"
                ok = False
                err = f"{type(e).__name__}: {e}"
            rtt = (time.monotonic() - t0) * 1000.0
            flipped = self.peers.mark_probe(
                peer.peer_id, ok, now, rtt_ms=rtt, error=err
            )
            if flipped:
                logger.warning(
                    "peer %s unreachable after %d probe(s): %s",
                    peer.peer_id, self.peers.dead_after_probes, err,
                )
            if (
                not ok
                and self.auto_adopt
                and not self.peers.is_reachable(peer.peer_id)
                and not self.peers.is_adopted(peer.peer_id)
            ):
                succ = self.peers.successor_of(peer.peer_id)
                if succ is not None and succ.peer_id == self.peers.self_id:
                    self.adopt(peer.peer_id)

    def adopt(self, peer_id: str) -> int:
        """Survivor rebuild: replay the dead peer's replicated journal
        prefix into the local rollup store. Idempotent — the rollup's
        per-agent dedupe + the journal's UNIQUE(agent, dedupe_key) make
        a second adopt (or an agent's post-failover redelivery of the
        same records) a no-op."""
        with self._adopt_mu:
            if self.peers.is_adopted(peer_id):
                return 0
            if self.writer is not None:
                self.writer.flush(timeout=10.0)
            rows = self.replica.rows(peer_id)
            groups: "OrderedDict[str, List[tuple]]" = OrderedDict()
            for (_rid, agent, seq, ts, _ing, kind, key, _cid,
                 payload, _shard) in rows:
                body = (
                    wire.unpack_obj(payload) if payload is not None else {}
                )
                groups.setdefault(agent, []).append(
                    (seq, ts, kind, key, body)
                )
            applied = 0
            for agent, recs in groups.items():
                applied += self.rollup.ingest(agent, recs)
            self.peers.mark_adopted(peer_id)
            with self._mu:
                self._adopts += 1
            logger.warning(
                "adopted cohort of dead peer %s: %d replicated row(s), "
                "%d applied fresh", peer_id, len(rows), applied,
            )
            return applied

    # -- scatter-gather ----------------------------------------------------
    def _fetch(self, peer: PeerDescriptor, path: str, params: Dict) -> Dict:
        qs = urllib.parse.urlencode({**params, "scope": "local"})
        req = urllib.request.Request(f"{peer.endpoint}{path}?{qs}")
        if self.admin_token:
            req.add_header("Authorization", f"Bearer {self.admin_token}")
        with urllib.request.urlopen(
            req, timeout=self.fanout_timeout
        ) as resp:
            return json.loads(resp.read().decode())

    def scatter(self, path: str, params: Dict) -> Dict[str, Dict]:
        """Fan one request out to every live remote peer with the
        per-peer timeout; returns ``{peer_id: {"data"|"error", ...}}``."""
        targets = self.peers.live_others()
        futures = {
            p.peer_id: self._pool.submit(self._fetch, p, path, params)
            for p in targets
        }
        out: Dict[str, Dict] = {}
        for pid, fut in futures.items():
            t0 = time.monotonic()
            try:
                data = fut.result(timeout=self.fanout_timeout + 0.5)
                out[pid] = {
                    "data": data,
                    "elapsed_ms": round((time.monotonic() - t0) * 1000, 2),
                }
                with self._mu:
                    self._scatter_ok += 1
            except Exception as e:  # noqa: BLE001 — a slow peer is a result
                out[pid] = {
                    "error": f"{type(e).__name__}: {e}",
                    "elapsed_ms": round((time.monotonic() - t0) * 1000, 2),
                }
                with self._mu:
                    self._scatter_err += 1
        with self._mu:
            self._last_fanout = {
                pid: {k: v for k, v in r.items() if k != "data"}
                for pid, r in out.items()
            }
        return out

    def federate(self, kind: str, local: Dict, params: Dict) -> Dict:
        """Merge the local pane with every live peer's ``scope=local``
        answer and stamp the ``peers`` health block on the envelope."""
        results = self.scatter(self.PATHS[kind], params)
        remotes = {
            pid: r.get("data") for pid, r in results.items() if "data" in r
        }
        if kind == "rollup":
            merged = merge_rollup(local, remotes)
        elif kind == "fabric":
            merged = merge_fabric(local, remotes)
        elif kind == "predict":
            merged = merge_predict(local, remotes)
        elif kind == "agents":
            merged = merge_agents(
                local, remotes, int(params.get("limit", 50) or 50),
                self_id=self.peers.self_id,
            )
        elif kind == "traces":
            merged = merge_traces(
                local, remotes, int(params.get("limit", 200) or 200)
            )
        else:
            merged = dict(local)
        merged["federated"] = True
        merged["peers"] = self.peers_block()
        merged["fanout"] = {
            pid: {k: v for k, v in r.items() if k != "data"}
            for pid, r in results.items()
        }
        return merged

    def federate_history(self, agent_id: str, local: Dict, params: Dict) -> Dict:
        """History is single-owner data: serve locally when the journal
        has the agent, otherwise ask the rendezvous owner (then any live
        peer) for its ``scope=local`` answer."""
        if local.get("total", 0) > 0:
            local = dict(local)
            local["peer"] = self.peers.self_id
            local["peers"] = self.peers_block()
            return local
        owner = self.peers.owner_of(agent_id)
        ranked = [owner] + [
            p for p in self.peers.live_others()
            if p.peer_id != owner.peer_id
        ]
        for peer in ranked:
            if peer.peer_id == self.peers.self_id:
                continue
            if not self.peers.is_reachable(peer.peer_id):
                continue
            try:
                data = self._fetch(
                    peer,
                    f"/v1/fleet/agents/{urllib.parse.quote(agent_id)}/history",
                    params,
                )
            except Exception:  # noqa: BLE001 — fall through to next peer
                continue
            if data.get("total", 0) > 0:
                data["peer"] = peer.peer_id
                data["peers"] = self.peers_block()
                return data
        local = dict(local)
        local["peer"] = self.peers.self_id
        local["peers"] = self.peers_block()
        return local

    # -- views -------------------------------------------------------------
    def peers_block(self) -> List[dict]:
        return self.peers.health_block(time.time())

    def peers_view(self) -> Dict:
        """``GET /v1/fleet/peers``: the peer map itself."""
        succ = self.peers.successor()
        pred = self.peers.predecessor()
        with self._mu:
            scatter = {
                "ok": self._scatter_ok,
                "errors": self._scatter_err,
                "adopts": self._adopts,
                "last_fanout": dict(self._last_fanout),
            }
        return {
            "federation": True,
            "instance_id": self.peers.self_id,
            "ring": list(self.peers.ring),
            "successor": succ.peer_id if succ else None,
            "predecessor": pred.peer_id if pred else None,
            "peers": self.peers_block(),
            "rendezvous": self.peers.cohort_counts(self.rollup.agent_ids()),
            "replication": (
                self.shipper.stats() if self.shipper is not None else None
            ),
            "replica": self.replica.stats(),
            "scatter": scatter,
        }

    def stats(self) -> Dict:
        """Flat numbers for the exposition layer (exposition.py)."""
        with self._mu:
            scatter_ok, scatter_err = self._scatter_ok, self._scatter_err
            adopts = self._adopts
        live = {p.peer_id for p in self.peers.live_others()}
        out = {
            "peers_total": len(self.peers.ring),
            "peers_live": len(live) + 1,  # self is always live
            "scatter_ok": scatter_ok,
            "scatter_errors": scatter_err,
            "adopts": adopts,
            "replica_accepted": self.replica.stats()["accepted"],
        }
        if self.shipper is not None:
            s = self.shipper.stats()
            out["replication_lag_rows"] = s["lag_rows"]
            out["replication_connected"] = 1 if s["connected"] else 0
        return out
