"""Federated Prometheus exposition for the manager's ``/metrics``.

One scrape of the manager covers the pod: the global registry's own
series (tpud_fleet_*, tpud_storage_*, session counters) plus hand-
rendered per-agent series derived from the fleet rollup store. The
per-agent block is the only place an ``agent`` label exists, and its
cardinality is bounded twice:

- at most ``max_agents`` agents are rendered (sorted ids, so the set
  is stable between scrapes); the remainder is surfaced as one
  ``tpud_fleet_exposition_truncated_agents`` gauge instead of being
  silently dropped;
- a fixed, small family set per agent (availability, flap count,
  outbox lag, transitions, unhealthy series) — per-(agent, component)
  series are deliberately NOT exposed; that cross-product is what
  blows up federation (docs/fleet.md).
"""

from __future__ import annotations

import time
from typing import List

from gpud_tpu.metrics.registry import (
    DEFAULT_REGISTRY,
    gauge,
    histogram,
)

DEFAULT_MAX_AGENTS = 1000

_h_scrape = histogram(
    "tpud_fleet_scrape_seconds",
    "wall time to render the manager's federated /metrics response",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
_g_exposed_series = gauge(
    "tpud_fleet_exposition_series",
    "per-agent series rendered in the last federated /metrics response",
)
_g_truncated = gauge(
    "tpud_fleet_exposition_truncated_agents",
    "agents omitted from the last federated /metrics response by the "
    "cardinality cap",
)

# the fixed per-agent family set: (suffix-free name, help, value extractor)
_AGENT_FAMILIES = (
    ("tpud_fleet_agent_availability_ratio",
     "healthy share of observed time across the agent's components",
     "availability"),
    ("tpud_fleet_agent_flap_count",
     "state transitions across the agent's components in the flap window",
     "flap_count"),
    ("tpud_fleet_agent_outbox_lag_seconds",
     "manager ingest wall clock minus the agent's newest record timestamp",
     "outbox_lag_seconds"),
    ("tpud_fleet_agent_transitions",
     "health-state transitions journaled for the agent, all components",
     "transitions"),
    ("tpud_fleet_agent_unhealthy_series",
     "the agent's components currently in a non-Healthy state",
     "unhealthy_series"),
    ("tpud_fleet_agent_predict_risk",
     "worst predicted-failure risk across the agent's components "
     "(decay anchored at the agent's newest record time)",
     "predict_risk"),
)

# fleet-level predictive gauges refreshed from the ranked pane at scrape
# time — fixed cardinality regardless of fleet size (docs/fleet.md)
_g_predict_armed = gauge(
    "tpud_fleet_predict_armed_series",
    "(agent, component) predictive series currently armed fleet-wide",
)
_g_predict_warns = gauge(
    "tpud_fleet_predict_warns",
    "predictive warnings journaled fleet-wide, all time",
)
_g_predict_risk_max = gauge(
    "tpud_fleet_predict_risk_max",
    "highest time-decayed predicted-failure risk in the fleet right now",
)
_g_predict_lead_mean = gauge(
    "tpud_fleet_predict_lead_mean_seconds",
    "mean measured lead time (predictive warning to reactive hard "
    "signal) across all journaled lead records",
)


# peer-federation gauges (fixed cardinality: one number each, refreshed
# from FederationPlane.stats() at scrape time; docs/fleet.md)
_g_peers_total = gauge(
    "tpud_fleet_peers",
    "managers in this manager's peer set (0 when not federated)",
)
_g_peers_live = gauge(
    "tpud_fleet_peers_live",
    "peers currently believed reachable, including self",
)
_g_replication_lag = gauge(
    "tpud_fleet_replication_lag_rows",
    "journal rows appended locally but not yet acked by the successor",
)
_g_replication_connected = gauge(
    "tpud_fleet_replication_connected",
    "1 when the replication stream to the successor is connected",
)
_g_adopts = gauge(
    "tpud_fleet_peer_adopts",
    "dead-peer cohorts this manager has adopted from its replica",
)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_fleet_metrics(
    rollup_store,
    max_agents: int = DEFAULT_MAX_AGENTS,
    ingest_executor=None,
    federation=None,
) -> str:
    """The manager's full /metrics body: global registry + bounded
    per-agent federation block."""
    t0 = time.monotonic()
    if federation is not None:
        fs = federation.stats()
        _g_peers_total.set(fs["peers_total"])
        _g_peers_live.set(fs["peers_live"])
        _g_adopts.set(fs["adopts"])
        _g_replication_lag.set(fs.get("replication_lag_rows", 0))
        _g_replication_connected.set(fs.get("replication_connected", 0))
    else:
        _g_peers_total.set(0)
        _g_peers_live.set(0)
    # refresh the per-shard gauges (cardinality bounded by shard count,
    # not fleet size) before the registry renders them
    from gpud_tpu.manager.shard import update_shard_gauges

    update_shard_gauges(rollup_store, ingest_executor)
    # fleet-level predictive rollup: one cached pane read feeds four
    # fixed-cardinality gauges (the ranked per-node detail stays behind
    # the paginated operator API, like everything agent-labelled)
    pane = rollup_store.fleet_predict(top=1)
    _g_predict_armed.set(pane["armed"])
    _g_predict_warns.set(pane["warns_total"])
    _g_predict_risk_max.set(
        pane["top"][0]["risk"] if pane["top"] else 0.0
    )
    _g_predict_lead_mean.set(pane["lead"]["mean_seconds"])
    parts: List[str] = [DEFAULT_REGISTRY.render_prometheus()]
    # walk the paginated view (cached + flush-barriered like any other
    # operator read) instead of a private fast path
    rows = []
    offset = 0
    total = None
    while len(rows) < max_agents:
        page = rollup_store.agents_page(
            offset, min(500, max_agents - len(rows))
        )
        total = page["total"]
        for a in page["agents"]:
            comps = list(a["components"].values())
            rows.append({
                "agent": a["agent"],
                "availability": (
                    sum(c["availability"] for c in comps) / len(comps)
                    if comps else 1.0
                ),
                "flap_count": sum(c["flap_count"] for c in comps),
                "outbox_lag_seconds": a["outbox_lag_seconds"],
                "transitions": sum(c["transitions"] for c in comps),
                "unhealthy_series": sum(
                    1 for c in comps if c["state"] and c["state"] != "Healthy"
                ),
                "predict_risk": a.get("predict_risk", 0.0),
            })
        if page["next_offset"] is None:
            break
        offset = page["next_offset"]
    _g_truncated.set(max(0, (total or 0) - len(rows)))
    series = 0
    if rows:
        for name, help_text, field in _AGENT_FAMILIES:
            lines = [f"# HELP {name} {help_text}", f"# TYPE {name} gauge"]
            for row in rows:
                lines.append(
                    f'{name}{{agent="{_escape(row["agent"])}"}} '
                    f'{_fmt(row[field])}'
                )
                series += 1
            parts.append("\n".join(lines) + "\n")
    _g_exposed_series.set(series)
    _h_scrape.observe(time.monotonic() - t0)
    return "".join(parts)


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)
