"""Standalone tpud control plane (dev/reference manager).

The reference agent talks to a proprietary SaaS control plane; its repo
ships only the agent side (reference: pkg/session/session.go:1-60,
pkg/session/v2/session.proto:16-60). This module closes that gap for
tpud: a runnable manager that real daemons enroll with and that
operators can drive — the server-side counterpart of
``gpud_tpu/session`` — speaking BOTH transports:

- v1: ``POST /api/v1/login`` + dual chunked ndjson streams on
  ``POST /api/v1/session`` (read = manager→agent requests, write =
  agent→manager responses), mirroring session/session.py's client.
- v2: gRPC bidi ``Connect`` with Hello/HelloAck revision negotiation;
  at rev 2 requests go out as typed ManagerPacket oneof arms
  (session/v2/typed.py dict_to_request) and responses come back as
  Result packets; rev-1 agents stay on JSON Frames.

Operator surface (same aiohttp app):

- ``GET  /v1/machines``                  — connected fleet
- ``POST /v1/machines/{id}/request``     — issue one method request and
  wait for the agent's response (body: ``{"method": ..., params...}``)
- ``POST /v1/drain``                     — notify v2 agents + close streams

Run: ``tpud manager serve`` (cli.py) or ``ControlPlane().start()``.
"""

from __future__ import annotations

import asyncio
import json
import os
import queue
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

DEFAULT_REQUEST_TIMEOUT = 30.0
MAX_REQUEST_TIMEOUT = 600.0
# rev 2: typed requests; rev 3: wire-codec framed payload bytes
# (session/wire.py) — negotiation clamps to the agent's max, so rev-2
# agents keep speaking bare JSON bytes
MAX_REVISION = 3


class AgentGone(Exception):
    """The agent disconnected (or was never connected)."""


class AgentHandle:
    """One connected agent: request/response plumbing + metadata."""

    def __init__(self, machine_id: str, transport: str, version: str = "") -> None:
        self.machine_id = machine_id
        self.transport = transport  # "v1" | "v2-rev1" | "v2-rev2"
        self.version = version
        self.connected_at = time.time()
        self.last_seen = self.connected_at
        self.outbound: "queue.Queue[Optional[dict]]" = queue.Queue()
        self.unsolicited: List[dict] = []  # responses with unknown req_id
        self._pending: Dict[str, "queue.Queue[dict]"] = {}
        self._lock = threading.Lock()
        self._gone = threading.Event()
        self.draining = threading.Event()  # v2: send DrainNotice on teardown
        self.drain_reason = "manager draining"
        self._seq = 0
        # store-and-forward outbox ingest (session/outbox.py): delivery is
        # at-least-once, so dedupe by key; the manager acks the highest
        # sequence seen (frames arrive in seq order on one stream, so the
        # max IS the contiguous watermark). All bounded — a week-long
        # backlog replaying through must not grow manager memory
        self.outbox_keys: "OrderedDict[str, None]" = OrderedDict()
        self.outbox_keys_max = 8192
        self.outbox_records: List[dict] = []  # delivered frames, newest last
        self.outbox_records_max = 2048
        self.outbox_acked = 0
        # fleet-plane hook: the ControlPlane points this at the rollup
        # store's ingest so every fresh decoded record is journaled +
        # rolled up; the handle itself stays transport-only
        self.on_records = None
        # when set (ControlPlane._register), outbox frames are handed to
        # the per-shard ingest executor instead of running inline on the
        # session reader thread — delta decode, dedupe, journal submit,
        # and the ack all happen on the agent's shard worker, in FIFO
        # order, so a slow BatchWriter flush can no longer stall the
        # next frame's read. Standalone handles (unit tests, chaos
        # harnesses) keep the inline path.
        self.ingest_executor = None
        self._ack_req_ids: "OrderedDict[str, bool]" = OrderedDict()
        # per-connection delta decoder for batched delivery frames: the
        # agent resets its encoder on reconnect, so a fresh handle always
        # starts on keyframes (session/wire.py)
        from gpud_tpu.session.wire import DeltaDecoder

        self._outbox_decoder = DeltaDecoder()

    # -- operator side -----------------------------------------------------
    def request(self, data: dict, timeout: float = DEFAULT_REQUEST_TIMEOUT) -> dict:
        """Send one method-dict request; block for the agent's response."""
        if self._gone.is_set():
            raise AgentGone(self.machine_id)
        with self._lock:
            self._seq += 1
            req_id = f"op-{self._seq}-{uuid.uuid4().hex[:8]}"
            q: "queue.Queue[dict]" = queue.Queue(maxsize=1)
            self._pending[req_id] = q
        self.outbound.put({"req_id": req_id, "data": data})
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"agent {self.machine_id}: no response to "
                f"{data.get('method')!r} within {timeout}s"
            ) from None
        finally:
            with self._lock:
                self._pending.pop(req_id, None)

    # -- transport side ----------------------------------------------------
    def resolve(self, req_id: str, payload: dict) -> None:
        self.last_seen = time.time()
        if req_id.startswith("outbox-") or (
            isinstance(payload, dict)
            and ("outbox_seq" in payload or "outbox_batch" in payload)
        ):
            ex = self.ingest_executor
            if ex is not None:
                # reader thread only enqueues; a saturated shard drops the
                # frame UN-acked (backpressure is counted) and the agent's
                # durable outbox redelivers it keyframe-anchored later
                ex.submit(
                    self.machine_id, lambda: self._ingest_outbox(payload)
                )
                return
            self._ingest_outbox(payload)
            return
        with self._lock:
            if self._ack_req_ids.pop(req_id, False):
                return  # agent's response to our outboxAck; nothing to do
            q = self._pending.get(req_id)
        if q is None:
            self.unsolicited.append({"req_id": req_id, "data": payload})
            del self.unsolicited[:-64]  # bounded
            return
        try:
            q.put_nowait(payload)
        except queue.Full:
            pass

    def _ingest_outbox(self, payload: dict) -> None:
        """One replayed outbox frame off the agent's write stream: dedupe
        by key, record if fresh, and push ONE cumulative ``outboxAck``
        request for the new watermark onto the read stream.

        Two shapes arrive here: the batched delta-encoded
        ``{"outbox_batch": {...}}`` frame (docs/session.md wire format)
        and the legacy per-record ``{"outbox_seq": ...}`` payload older
        agents still send. A batch that stops decoding mid-way (delta
        without a keyframe base) acks only the decoded prefix — the
        agent's ack-stall fallback redelivers the rest keyframe-anchored.
        """
        if not isinstance(payload, dict):
            return
        from gpud_tpu.session import wire

        batch = wire.parse_batch(payload)
        if batch is not None:
            decoded = []
            decode = self._outbox_decoder.decode_record
            for rec in batch.get("records") or []:
                try:
                    decoded.append(decode(rec))
                except (wire.DeltaDecodeError, TypeError, ValueError) as e:
                    logger.warning(
                        "%s: outbox batch decode stopped, acking prefix: %s",
                        self.machine_id, e,
                    )
                    break
            if not decoded:
                return
            ack_to = decoded[-1][0]
        else:
            try:
                seq = int(payload.get("outbox_seq", 0))
            except (TypeError, ValueError):
                return
            decoded = [(
                seq,
                payload.get("ts") or 0.0,
                payload.get("kind") or "",
                str(payload.get("dedupe_key") or ""),
                payload.get("payload"),
            )]
            ack_to = seq
        with self._lock:
            fresh = []
            for tup in decoded:
                key = tup[3]
                if key not in self.outbox_keys:
                    self.outbox_keys[key] = None
                    fresh.append(tup)
            while len(self.outbox_keys) > self.outbox_keys_max:
                self.outbox_keys.popitem(last=False)
            # only the tail of a big frame survives the record-buffer
            # trim; don't materialize dicts the trim would drop anyway
            for seq, ts, kind, key, body in fresh[-self.outbox_records_max:]:
                self.outbox_records.append({
                    "outbox_seq": seq,
                    "ts": ts,
                    "kind": kind,
                    "dedupe_key": key,
                    "payload": body,
                })
            del self.outbox_records[:-self.outbox_records_max]
            if ack_to > self.outbox_acked:
                self.outbox_acked = ack_to
            ack_seq = self.outbox_acked
            self._seq += 1
            ack_req_id = f"op-{self._seq}-ack"
            self._ack_req_ids[ack_req_id] = True
            # one ack per delivery frame; keep only recent ids so a
            # slow agent's late responses age into `unsolicited` (bounded)
            while len(self._ack_req_ids) > 512:
                self._ack_req_ids.popitem(last=False)
        # journal BEFORE queuing the ack: once the ack lands the agent
        # prunes these records and can never replay them, so the only
        # acceptable loss after this point is the BatchWriter's bounded
        # durability window (docs/fleet.md), not a whole unjournaled
        # batch. submit_many only buffers — the ack is not gated on a
        # commit — and a rollup failure must not kill the transport, so
        # ingest errors are logged and the ack still goes out (the
        # cumulative ack would cover these seqs on the next frame anyway).
        cb = self.on_records
        if cb is not None and fresh:
            try:
                cb(self.machine_id, fresh)
            except Exception:  # noqa: BLE001 — observability is best-effort
                logger.exception(
                    "%s: fleet rollup ingest failed", self.machine_id
                )
        if not self._gone.is_set():
            self.outbound.put(
                {"req_id": ack_req_id,
                 "data": {"method": "outboxAck", "seq": ack_seq}}
            )

    def mark_gone(self) -> None:
        self._gone.set()
        self.outbound.put(None)
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for q in pending:
            try:
                q.put_nowait({"error": "agent disconnected"})
            except queue.Full:
                pass

    @property
    def gone(self) -> bool:
        return self._gone.is_set()

    def to_dict(self) -> dict:
        return {
            "machine_id": self.machine_id,
            "transport": self.transport,
            "version": self.version,
            "connected_at": self.connected_at,
            "last_seen": self.last_seen,
        }


class ControlPlane:
    """Runnable manager process: v1 HTTP + v2 gRPC + operator API."""

    def __init__(
        self,
        port: int = 0,
        grpc_port: int = 0,
        *,
        session_token: Optional[str] = None,
        admin_token: Optional[str] = None,
        instance_id: Optional[str] = None,
        data_dir: Optional[str] = None,
        rollup_cache_ttl: float = 2.0,
        shards: Optional[int] = None,
        max_v2_agents: int = 64,
        predict_decay_seconds: Optional[float] = None,
    ) -> None:
        self.port = port
        self.grpc_port = grpc_port
        # session_token=None → accept any enrollment and issue a fresh
        # token per machine (dev mode); set → exact Bearer match required
        self.session_token = session_token
        self.admin_token = admin_token
        self.instance_id = instance_id or f"tpud-manager-{uuid.uuid4().hex[:8]}"
        self.agents: Dict[str, AgentHandle] = {}
        self._issued_tokens: Dict[str, str] = {}  # machine_id → token
        # machine_id → MachineInfo dict from the last login/gossip (the
        # reference control plane records the machine tree at enrollment).
        # Bounded: dev mode accepts logins from anyone and a restart-
        # looping agent with empty machine_id mints a fresh id per login
        self.machine_infos: Dict[str, dict] = {}
        self.machine_infos_max = 512
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._grpc_server = None
        self.logins: List[dict] = []
        self._stopped = False
        self._start_called = False
        # reentrant: start()'s failure paths call stop() while holding it
        self._lifecycle = threading.RLock()
        # separate pools for the two blocking workloads so they can't
        # starve each other (and the aiohttp loop's small default
        # executor stays free): every v1 read stream pins one stream
        # worker for its lifetime; every in-flight operator request pins
        # one op worker for up to its (clamped) timeout
        from concurrent.futures import ThreadPoolExecutor

        self.max_v1_agents = 64
        self.max_v2_agents = max(1, int(max_v2_agents))
        self._stream_pool = ThreadPoolExecutor(
            max_workers=self.max_v1_agents, thread_name_prefix="tpud-mgr-stream"
        )
        self._op_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="tpud-mgr-op"
        )
        # fleet observability plane: journal + rollups behind the shared
        # write-behind layer. data_dir=None keeps everything in memory
        # (tests, dev) — same code path, no durability
        from gpud_tpu.manager.rollup import FleetRollupStore
        from gpud_tpu.manager.shard import (
            DEFAULT_SHARD_COUNT,
            ShardIngestExecutor,
        )
        from gpud_tpu.sqlite import DB
        from gpud_tpu.storage.writer import BatchWriter

        self.data_dir = data_dir
        db_path = ":memory:"
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            db_path = os.path.join(data_dir, "fleet.db")
        self.db = DB(db_path)
        self.writer = BatchWriter(self.db)
        self.shards = int(shards) if shards else DEFAULT_SHARD_COUNT
        rollup_kwargs = {}
        if predict_decay_seconds is not None:
            rollup_kwargs["predict_decay_seconds"] = predict_decay_seconds
        self.rollup = FleetRollupStore(
            self.db, self.writer, cache_ttl_seconds=rollup_cache_ttl,
            shard_count=self.shards, **rollup_kwargs,
        )
        # lock-striped offload for wire decode + rollup ingest: session
        # reader threads enqueue, shard workers journal + ack
        self.ingest_executor = ShardIngestExecutor(self.shards)
        self._scheduler = None
        # HA tier (manager/federation.py): None until attach_peers().
        # Specs can't be known at construction when ports are dynamic,
        # so federation always binds late
        self.federation = None
        # rate limiter for the mid-batch-abandon warning, per machine
        self._abandon_warn_ts: Dict[str, float] = {}

    # -- federation --------------------------------------------------------
    def attach_peers(
        self,
        peer_id: str,
        peer_specs: List[str],
        *,
        replication_interval: Optional[float] = None,
        probe_interval: Optional[float] = None,
        fanout_timeout: Optional[float] = None,
        dead_after_probes: Optional[int] = None,
        auto_adopt: bool = True,
        ship_batch: Optional[int] = None,
        redeliver_after: Optional[float] = None,
    ):
        """Join a peer set (manager/federation.py). ``peer_specs`` must
        include this manager's own ``peer_id=endpoint[|grpc]`` entry.
        Call after start() — peer addresses usually aren't known until
        every manager has bound its ports."""
        from gpud_tpu.manager.federation import FederationPlane
        from gpud_tpu.manager.peers import PeerSet, parse_peer_spec

        if self.federation is not None:
            raise RuntimeError("peers already attached")
        if self._scheduler is None:
            raise RuntimeError("attach_peers() requires a started manager")
        descriptors = [parse_peer_spec(s) for s in peer_specs]
        kwargs = {}
        if dead_after_probes is not None:
            kwargs["dead_after_probes"] = dead_after_probes
        peerset = PeerSet(peer_id, descriptors, **kwargs)
        fed_kwargs = {"auto_adopt": auto_adopt}
        for name, val in (
            ("replication_interval", replication_interval),
            ("probe_interval", probe_interval),
            ("fanout_timeout", fanout_timeout),
            ("ship_batch", ship_batch),
            ("redeliver_after", redeliver_after),
        ):
            if val is not None:
                fed_kwargs[name] = val
        self.federation = FederationPlane(
            peerset, self.rollup, self.db, self.writer,
            session_token=self.session_token,
            admin_token=self.admin_token,
            **fed_kwargs,
        )
        self.federation.start(self._scheduler)
        logger.info(
            "federation up: self=%s ring=%s", peer_id, peerset.ring
        )
        return self.federation

    # -- registry ----------------------------------------------------------
    def _register(self, handle: AgentHandle) -> None:
        # point the transport's outbox hook at the rollup store before
        # the handle is visible, so the very first frame is journaled.
        # Peer replication streams (machine_id "peer:<id>") journal into
        # the replica store instead — a live peer's cohort must never
        # leak into this manager's own pane
        from gpud_tpu.manager.federation import PEER_MACHINE_PREFIX

        fed = self.federation
        if handle.machine_id.startswith(PEER_MACHINE_PREFIX) and fed is not None:
            handle.on_records = fed.replica_sink(handle.machine_id)
        else:
            handle.on_records = self.rollup.ingest
        handle.ingest_executor = self.ingest_executor
        with self._lock:
            old = self.agents.get(handle.machine_id)
            if old is not None:
                old.mark_gone()
            self.agents[handle.machine_id] = handle
        logger.info(
            "agent %s connected (%s)", handle.machine_id, handle.transport
        )

    def _unregister(self, handle: AgentHandle) -> None:
        # sample BEFORE mark_gone(): it enqueues a None wake sentinel,
        # so qsize afterwards can't distinguish abandonment from drain
        leftover = handle.outbound.qsize()
        handle.mark_gone()
        with self._lock:
            if self.agents.get(handle.machine_id) is handle:
                del self.agents[handle.machine_id]
        if leftover > 0 and not handle.draining.is_set():
            # the agent walked away mid-batch: frames (usually cumulative
            # acks) it never read are dropped with the stream. Warn —
            # silently eating these is how "why did the agent redeliver
            # a whole batch" hunts start — but rate-limit per machine,
            # because a flapping agent would otherwise log every cycle
            now = time.monotonic()
            last = self._abandon_warn_ts.get(handle.machine_id, 0.0)
            if now - last >= 30.0:
                if len(self._abandon_warn_ts) >= 1024:
                    self._abandon_warn_ts.clear()
                self._abandon_warn_ts[handle.machine_id] = now
                logger.warning(
                    "agent %s abandoned its %s stream mid-batch: %d "
                    "undelivered frame(s) dropped (acked watermark %d); "
                    "the agent will redeliver above its last acked seq",
                    handle.machine_id, handle.transport, leftover,
                    handle.outbox_acked,
                )
        logger.info("agent %s disconnected", handle.machine_id)

    def agent(self, machine_id: str) -> AgentHandle:
        with self._lock:
            h = self.agents.get(machine_id)
        if h is None or h.gone:
            raise AgentGone(machine_id)
        return h

    def machines(self) -> List[dict]:
        with self._lock:
            return [h.to_dict() for h in self.agents.values()]

    # -- auth --------------------------------------------------------------
    def _check_session_auth(self, machine_id: str, auth_header: str) -> bool:
        token = auth_header.removeprefix("Bearer ").strip()
        if self.session_token is not None:
            return token == self.session_token
        issued = self._issued_tokens.get(machine_id)
        return issued is None or token == issued

    def _check_admin(self, request) -> bool:  # noqa: ANN001 - aiohttp
        if not self.admin_token:
            return True
        got = request.headers.get("Authorization", "")
        return got.removeprefix("Bearer ").strip() == self.admin_token

    # -- v1 HTTP app -------------------------------------------------------
    async def _login(self, request):  # noqa: ANN001
        from aiohttp import web

        from gpud_tpu.api.v1.types import LoginRequest, LoginResponse

        body = await request.json()
        # decode through the shared wire type: the manager consumes
        # exactly what login.py's agent side encodes (api/v1/types.py),
        # including the nested MachineInfo tree. The body is UNTRUSTED —
        # a malformed tree must degrade to "no machine info", not fail
        # the enrollment itself
        if not isinstance(body, dict):
            body = {}
        try:
            req = LoginRequest.from_dict(body)
        except Exception:  # noqa: BLE001 — hostile/garbled machine_info
            req = LoginRequest(
                token=str(body.get("token", "") or ""),
                machine_id=str(body.get("machine_id", "") or ""),
            )
        self.logins.append(body)
        del self.logins[:-64]  # bounded like AgentHandle.unsolicited
        # fixed-token fleets must present the secret to enroll; otherwise
        # login would hand the session token to any caller
        if self.session_token is not None and req.token != self.session_token:
            return web.Response(status=401, text="bad join token")
        machine_id = req.machine_id or f"m-{uuid.uuid4().hex[:12]}"
        token = self.session_token or f"tok-{uuid.uuid4().hex}"
        self._issued_tokens[machine_id] = token
        self._record_machine_info(
            machine_id, req.machine_info.to_dict() if req.machine_info else {}
        )
        return web.json_response(
            LoginResponse(
                machine_id=machine_id,
                token=token,
                machine_proof=f"proof-{machine_id}",
            ).to_dict()
        )

    async def _session(self, request):  # noqa: ANN001
        from aiohttp import web

        stype = request.headers.get("X-TPUD-Session-Type", "")
        machine = request.headers.get("X-TPUD-Machine-ID", "")
        version = request.headers.get("X-TPUD-Version", "")
        auth = request.headers.get("Authorization", "")
        if not machine:
            return web.Response(status=400, text="missing machine id")
        if not self._check_session_auth(machine, auth):
            return web.Response(status=401, text="unauthorized")

        if stype == "read":
            # manager → agent: stream requests as ndjson for as long as
            # the agent stays connected
            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "application/x-ndjson"
            await resp.prepare(request)
            handle = AgentHandle(machine, "v1", version)
            self._register(handle)
            try:
                while not handle.gone:
                    # block with no timeout: mark_gone()'s None sentinel
                    # guarantees wakeup, so idle agents cost zero churn
                    item = await asyncio.get_event_loop().run_in_executor(
                        self._stream_pool, handle.outbound.get
                    )
                    if item is None:
                        if handle.gone:
                            break
                        continue
                    line = json.dumps(item) + "\n"
                    await resp.write(line.encode())
            except (ConnectionResetError, asyncio.CancelledError):
                pass
            finally:
                self._unregister(handle)
            return resp

        if stype == "write":
            # agent → manager: chunked ndjson responses
            while True:
                line = await request.content.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue  # keep-alive blank
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                with self._lock:
                    handle = self.agents.get(machine)
                if handle is not None:
                    handle.resolve(str(d.get("req_id", "")), d.get("data") or {})
            return web.Response(text="ok")

        return web.Response(status=400, text=f"bad session type {stype!r}")

    # -- operator API ------------------------------------------------------
    async def _machines_route(self, request):  # noqa: ANN001
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        return web.json_response({"machines": self.machines()})

    # per-entry serialized-size cap: dev mode accepts unauthenticated
    # logins, so without it a caller could pin machine_infos_max ×
    # multi-MB trees in memory (entry *count* alone doesn't bound memory)
    MACHINE_INFO_MAX_BYTES = 256 * 1024

    def _record_machine_info(self, machine_id: str, tree: dict) -> None:
        """Insertion-ordered overwrite with FIFO eviction past the cap —
        login-derived state stays bounded (same convention as the logins
        list above). Oversized trees are dropped, not truncated: a
        partial tree would present as authoritative machine state."""
        try:
            size = len(json.dumps(tree))
        except (TypeError, ValueError):
            logger.warning("unserializable machine_info from %s; not recorded",
                           machine_id)
            return
        if size > self.MACHINE_INFO_MAX_BYTES:
            logger.warning(
                "machine_info from %s is %d bytes (cap %d); not recorded",
                machine_id, size, self.MACHINE_INFO_MAX_BYTES,
            )
            return
        with self._lock:
            self.machine_infos.pop(machine_id, None)  # re-insert = newest
            self.machine_infos[machine_id] = tree
            while len(self.machine_infos) > self.machine_infos_max:
                self.machine_infos.pop(next(iter(self.machine_infos)))

    async def _machine_info_route(self, request):  # noqa: ANN001
        """The MachineInfo tree recorded at the machine's last login
        (reference: control plane machine view fed by LoginRequest)."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        machine_id = request.match_info["machine_id"]
        missing = object()
        with self._lock:  # racing FIFO eviction in _record_machine_info
            tree = self.machine_infos.get(machine_id, missing)
        if tree is missing:
            return web.Response(status=404, text=f"unknown machine {machine_id}")
        return web.json_response(
            {"machine_id": machine_id, "machine_info": tree}
        )

    async def _request_route(self, request):  # noqa: ANN001
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        machine_id = request.match_info["machine_id"]
        try:
            body = await request.json()
        except ValueError:
            return web.Response(status=400, text="body must be JSON")
        if not isinstance(body, dict) or not body.get("method"):
            return web.Response(status=400, text='body needs a "method"')
        try:
            timeout = float(
                request.query.get("timeout", DEFAULT_REQUEST_TIMEOUT)
            )
        except ValueError:
            return web.Response(status=400, text="timeout must be a number")
        # each in-flight request pins a pool worker for its duration
        timeout = min(max(timeout, 0.1), MAX_REQUEST_TIMEOUT)
        try:
            handle = self.agent(machine_id)
        except AgentGone:
            return web.Response(status=404, text=f"no agent {machine_id!r}")
        try:
            payload = await asyncio.get_event_loop().run_in_executor(
                self._op_pool, lambda: handle.request(body, timeout=timeout)
            )
        except (TimeoutError, AgentGone) as e:
            return web.Response(status=504, text=str(e))
        if body["method"] == "gossip" and isinstance(payload, dict) and payload.get("machine_info"):
            # refresh the recorded tree from the agent's gossip answer,
            # normalized through the shared wire type. The answer already
            # reached us successfully — a malformed tree skips the
            # recording, it must not 500 the response the agent gave
            from gpud_tpu.api.v1.types import GossipRequest

            try:
                g = GossipRequest.from_dict(
                    {"machine_id": machine_id,
                     "machine_info": payload["machine_info"]}
                )
                if g.machine_info is not None:
                    self._record_machine_info(
                        machine_id, g.machine_info.to_dict()
                    )
            except Exception:  # noqa: BLE001 — agent sent a garbled tree
                logger.warning(
                    "unparseable gossip machine_info from %s; not recorded",
                    machine_id,
                )
        return web.json_response({"machine_id": machine_id, "response": payload})

    async def _drain_route(self, request):  # noqa: ANN001
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        self.drain("operator drain")
        return web.json_response({"drained": True})

    # -- fleet observability API -------------------------------------------
    @staticmethod
    def _q_num(request, name: str, default, caster):  # noqa: ANN001
        raw = request.query.get(name)
        if raw is None or raw == "":
            return default
        return caster(raw)

    def _fleet_pane(self, kind: str, local_fn, params: dict, scope: str):
        """Run one local pane read and, when federated and the caller
        didn't pin ``?scope=local``, widen it across live peers. Every
        inter-peer fan-out pins ``scope=local`` so depth stops at one."""
        local = local_fn()
        fed = self.federation
        if fed is None or scope == "local":
            return local
        return fed.federate(kind, local, params)

    async def _fleet_rollup_route(self, request):  # noqa: ANN001
        """Fleet-wide rollup aggregates (availability, MTTR/MTBF,
        flapping, remediation outcomes); one pane across all peers
        unless ``?scope=local``."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        scope = request.query.get("scope", "")
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: self._fleet_pane(
                "rollup", self.rollup.fleet_rollup, {}, scope
            ),
        )
        return web.json_response(data)

    async def _fleet_fabric_route(self, request):  # noqa: ANN001
        """Fleet-wide ICI fabric matrix rollup: per-agent link aggregates
        from journaled ``ici_link`` sweep records — "which links degraded
        since ts" across every agent (``?since=``)."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        try:
            since = self._q_num(request, "since", 0.0, float)
        except ValueError:
            return web.Response(status=400, text="since must be a number")
        scope = request.query.get("scope", "")
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: self._fleet_pane(
                "fabric", lambda: self.rollup.fleet_fabric(since),
                {"since": since}, scope,
            ),
        )
        return web.json_response(data)

    async def _fleet_predict_route(self, request):  # noqa: ANN001
        """Fleet-ranked prediction pane: top-K (agent, component) rows
        by time-decayed predicted-failure risk from journaled
        ``predict_score`` records, with per-feature breakdown and
        fleet-wide lead-time aggregates (``?top=``, docs/fleet.md)."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        try:
            top = self._q_num(request, "top", 20, int)
        except ValueError:
            return web.Response(status=400, text="top must be an integer")
        scope = request.query.get("scope", "")
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: self._fleet_pane(
                "predict", lambda: self.rollup.fleet_predict(top),
                {"top": top}, scope,
            ),
        )
        return web.json_response(data)

    async def _fleet_agents_route(self, request):  # noqa: ANN001
        """One page of per-agent rollups (``?offset=&limit=``)."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        try:
            offset = self._q_num(request, "offset", 0, int)
            limit = self._q_num(request, "limit", 50, int)
        except ValueError:
            return web.Response(status=400, text="offset/limit must be integers")
        scope = request.query.get("scope", "")
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: self._fleet_pane(
                "agents", lambda: self.rollup.agents_page(offset, limit),
                {"offset": offset, "limit": limit}, scope,
            ),
        )
        return web.json_response(data)

    async def _fleet_history_route(self, request):  # noqa: ANN001
        """Journaled record timeline for one agent
        (``?since=&limit=&offset=``), newest first."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        agent_id = request.match_info["agent_id"]
        try:
            since = self._q_num(request, "since", 0.0, float)
            limit = self._q_num(request, "limit", 100, int)
            offset = self._q_num(request, "offset", 0, int)
        except ValueError:
            return web.Response(status=400, text="since/limit/offset must be numbers")
        scope = request.query.get("scope", "")

        def read():
            local = self.rollup.history(agent_id, since, limit, offset)
            fed = self.federation
            if fed is None or scope == "local":
                return local
            # history is single-owner data: proxy to the rendezvous
            # owner when the journal doesn't know the agent locally
            return fed.federate_history(
                agent_id, local,
                {"since": since, "limit": limit, "offset": offset},
            )

        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool, read
        )
        return web.json_response(data)

    async def _fleet_traces_route(self, request):  # noqa: ANN001
        """Fleet records stitched to one agent-side check trace
        (``?correlation_id=``)."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        cid = request.query.get("correlation_id", "")
        if not cid:
            return web.Response(status=400, text="correlation_id is required")
        try:
            limit = self._q_num(request, "limit", 200, int)
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        scope = request.query.get("scope", "")
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: self._fleet_pane(
                "traces", lambda: self.rollup.traces(cid, limit),
                {"correlation_id": cid, "limit": limit}, scope,
            ),
        )
        return web.json_response(data)

    async def _fleet_peers_route(self, request):  # noqa: ANN001
        """The peer map itself: ring order, rendezvous cohort counts,
        replication + replica watermarks, per-peer health. Standalone
        managers answer ``federation: false`` (200, not 404) so probes
        and the CLI work unchanged against either shape."""
        from aiohttp import web

        if not self._check_admin(request):
            return web.Response(status=401, text="unauthorized")
        fed = self.federation
        if fed is None:
            return web.json_response({
                "federation": False,
                "instance_id": self.instance_id,
                "peers": [],
            })
        data = await asyncio.get_event_loop().run_in_executor(
            self._op_pool, fed.peers_view
        )
        return web.json_response(data)

    async def _metrics_route(self, request):  # noqa: ANN001
        """Federated Prometheus exposition: manager registry + bounded
        per-agent fleet series. Unauthenticated, like the node /metrics."""
        from aiohttp import web

        from gpud_tpu.manager.exposition import render_fleet_metrics

        body = await asyncio.get_event_loop().run_in_executor(
            self._op_pool,
            lambda: render_fleet_metrics(
                self.rollup,
                ingest_executor=self.ingest_executor,
                federation=self.federation,
            ),
        )
        return web.Response(
            text=body, content_type="text/plain", charset="utf-8"
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """One-shot: after stop() (including the internal cleanup stop on
        a failed start) the pools are shut down — build a new ControlPlane
        instead of restarting this one."""
        with self._lifecycle:  # serializes whole-start vs whole-stop
            self._start_locked()

    def _start_locked(self) -> None:
        if self._stopped:
            raise RuntimeError(
                "ControlPlane cannot be restarted; create a new instance"
            )
        if self._start_called:
            raise RuntimeError("ControlPlane already started")
        # set synchronously under the lifecycle lock — _started is only
        # set by the HTTP thread later, so it can't guard concurrency
        self._start_called = True
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/api/v1/login", self._login)
        app.router.add_post("/api/v1/session", self._session)
        app.router.add_get("/v1/machines", self._machines_route)
        app.router.add_get(
            "/v1/machines/{machine_id}/machine-info", self._machine_info_route
        )
        app.router.add_post(
            "/v1/machines/{machine_id}/request", self._request_route
        )
        app.router.add_post("/v1/drain", self._drain_route)
        app.router.add_get("/v1/fleet/rollup", self._fleet_rollup_route)
        app.router.add_get("/v1/fleet/fabric", self._fleet_fabric_route)
        app.router.add_get("/v1/fleet/predict", self._fleet_predict_route)
        app.router.add_get("/v1/fleet/agents", self._fleet_agents_route)
        app.router.add_get(
            "/v1/fleet/agents/{agent_id}/history", self._fleet_history_route
        )
        app.router.add_get("/v1/fleet/traces", self._fleet_traces_route)
        app.router.add_get("/v1/fleet/peers", self._fleet_peers_route)
        app.router.add_get("/metrics", self._metrics_route)

        # the writer needs a periodic drain job (threshold pokes are
        # no-ops without one); the manager owns a one-worker scheduler
        from gpud_tpu.scheduler.core import Scheduler

        self._scheduler = Scheduler(workers=1)
        self.writer.start(self._scheduler)
        # enforce the journal row cap: without this job purge() has no
        # caller and a --data-dir manager's fleet.db grows without bound
        self._scheduler.add_job(
            "fleet-journal-purge",
            self.rollup.purge,
            interval=60.0,
            initial_delay=60.0,
        )
        self._scheduler.start()

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", self.port)
            loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
            self._started.set()
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(runner.cleanup())
                loop.close()

        self._thread = threading.Thread(
            target=run, name="tpud-manager-http", daemon=True
        )
        self._thread.start()
        if not self._started.wait(10.0):
            self.stop()
            raise RuntimeError("manager HTTP server failed to start")
        try:
            self._start_grpc()
        except Exception:
            # start() is atomic: a gRPC bind failure must not leak the
            # already-listening HTTP thread/socket
            self.stop()
            raise
        logger.info(
            "control plane up: http=127.0.0.1:%d grpc=127.0.0.1:%d",
            self.port,
            self.grpc_port,
        )

    def _start_grpc(self) -> None:
        try:
            import grpc
        except ImportError:
            logger.warning("grpc unavailable; v2 transport disabled")
            self.grpc_port = -1
            return
        from concurrent import futures

        from gpud_tpu.session.v2 import session_pb2 as pb

        handler = grpc.stream_stream_rpc_method_handler(
            self._connect_v2,
            request_deserializer=pb.AgentPacket.FromString,
            response_serializer=pb.ManagerPacket.SerializeToString,
        )
        service = grpc.method_handlers_generic_handler(
            "tpud.session.v2.Session", {"Connect": handler}
        )
        # each v2 Connect stream pins one handler thread for its lifetime
        # — this is the v2 fleet-size cap (constructor `max_v2_agents`;
        # raise it to hold a multi-thousand-agent fleet of persistent
        # streams, each costing one mostly-idle pool thread)
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_v2_agents),
            # without this, Linux SO_REUSEPORT lets a second manager bind
            # the same port and silently split the agent fleet
            options=[("grpc.so_reuseport", 0)],
        )
        self._grpc_server.add_generic_rpc_handlers((service,))
        requested = self.grpc_port
        self.grpc_port = self._grpc_server.add_insecure_port(
            f"127.0.0.1:{self.grpc_port}"
        )
        if self.grpc_port == 0:
            # grpc reports a failed bind as port 0 — surface it instead of
            # silently serving v1-only
            self._grpc_server = None
            raise RuntimeError(
                f"gRPC bind failed on 127.0.0.1:{requested} (port in use?)"
            )
        self._grpc_server.start()

    def _connect_v2(self, request_iterator, context):  # noqa: ANN001
        from gpud_tpu.session.v2 import session_pb2 as pb
        from gpud_tpu.session.v2 import typed

        try:
            first = next(request_iterator)
        except StopIteration:
            # stream opened and closed without a Hello (probe/scanner) —
            # returning here must not trip PEP 479 inside the generator
            return
        if first.WhichOneof("payload") != "hello":
            return  # protocol violation: close the stream
        hello = first.hello
        ack = pb.ManagerPacket()
        if not self._check_session_auth(hello.machine_id, f"Bearer {hello.token}"):
            ack.hello_ack.accepted = False
            ack.hello_ack.reason = "bad token"
            yield ack
            return
        # negotiate: the highest revision both sides speak (agent range
        # [min,max]; rev-1 agents leave max at 0 and set `revision`)
        agent_max = hello.max_revision or hello.revision or 1
        revision = min(agent_max, MAX_REVISION)
        if hello.min_revision and revision < hello.min_revision:
            # a future agent whose floor exceeds what this manager speaks
            # must be rejected, not driven at a revision it disclaimed
            ack.hello_ack.accepted = False
            ack.hello_ack.reason = (
                f"no common revision: agent [{hello.min_revision},"
                f"{hello.max_revision}] vs manager max {MAX_REVISION}"
            )
            yield ack
            return
        ack.hello_ack.accepted = True
        ack.hello_ack.revision = revision
        ack.hello_ack.manager_instance_id = self.instance_id
        yield ack

        handle = AgentHandle(
            hello.machine_id, f"v2-rev{revision}", hello.tpud_version
        )
        self._register(handle)
        stop = threading.Event()

        def decode_bytes(raw: bytes):
            # rev >= 3: wire-codec framed (prefix + optional zlib);
            # below: bare JSON bytes (ValueError either way on garbage)
            if revision >= 3:
                from gpud_tpu.session import wire

                return wire.decode_payload(raw)
            return json.loads(raw.decode())

        def drain_responses() -> None:
            try:
                for pkt in request_iterator:
                    kind = pkt.WhichOneof("payload")
                    if kind == "frame":
                        try:
                            data = decode_bytes(pkt.frame.data)
                        except ValueError:
                            continue
                        handle.resolve(pkt.frame.req_id, data)
                    elif kind == "result":
                        try:
                            data = decode_bytes(pkt.result.payload_json)
                        except ValueError:
                            continue
                        handle.resolve(pkt.result.request_id, data)
            except Exception:  # noqa: BLE001 - client cancel mid-read
                pass
            finally:
                stop.set()
                # wake the response generator NOW: it polls outbound with a
                # 0.2s timeout, and that linger holds a gRPC pool slot per
                # closed stream — at fleet churn rates (thousands of short
                # sessions) the idle tail, not real work, becomes the cap
                handle.outbound.put(None)

        threading.Thread(
            target=drain_responses,
            name=f"tpud-manager-v2-{hello.machine_id}",
            daemon=True,
        ).start()

        try:
            while not stop.is_set() and context.is_active():
                if handle.draining.is_set():
                    d = pb.ManagerPacket()
                    d.drain_notice.reason = handle.drain_reason
                    yield d
                    return
                item = _q_get(handle.outbound, timeout=0.2)
                if item is None:
                    # drain's mark_gone() sentinel can land while we wait:
                    # the notice must still go out before the stream ends
                    if handle.draining.is_set():
                        d = pb.ManagerPacket()
                        d.drain_notice.reason = handle.drain_reason
                        yield d
                        return
                    if handle.gone:
                        return
                    continue
                req_id, data = item["req_id"], item["data"]
                if revision >= 2:
                    try:
                        mpkt = typed.dict_to_request(data, req_id)
                        yield mpkt
                        continue
                    except Exception:  # noqa: BLE001
                        # method outside the typed set, or params the
                        # encoder chokes on (e.g. since="abc") — fall back
                        # to the Frame tunnel so one bad operator request
                        # can't tear down a healthy agent's stream; the
                        # agent dispatcher answers a structured error
                        pass
                m = pb.ManagerPacket()
                m.frame.req_id = req_id
                if revision >= 3:
                    from gpud_tpu.session import wire

                    m.frame.data = wire.encode_payload(data)
                else:
                    m.frame.data = json.dumps(data).encode()
                yield m
        finally:
            self._unregister(handle)

    def drain(self, reason: str = "shutdown") -> None:
        """Notify currently-connected v2 agents (DrainNotice) and end v1
        read streams. Drain is a point-in-time action: agents that
        reconnect afterwards are served normally."""
        with self._lock:
            handles = list(self.agents.values())
        for h in handles:
            h.drain_reason = reason
            h.draining.set()
            h.mark_gone()

    def stop(self) -> None:
        with self._lifecycle:  # a stop racing an in-flight start waits
            self._stop_locked()

    def _stop_locked(self) -> None:
        self._stopped = True
        # federation first: the shipper's session threads reconnect-loop
        # against the successor, and the fan-out pool must stop taking
        # work before the op pool beneath it does
        if self.federation is not None:
            self.federation.stop()
            self.federation = None
        self.drain("manager stopping")
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1.0)
            self._grpc_server = None
        if self._loop is not None and not self._loop.is_closed():
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError:
                pass  # loop closed between the check and the call
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._stream_pool.shutdown(wait=False, cancel_futures=True)
        self._op_pool.shutdown(wait=False, cancel_futures=True)
        # drain the shard workers before storage teardown: anything a
        # reader enqueued before its stream died still journals + acks
        self.ingest_executor.stop()
        # storage last: the final writer.close() barrier commits whatever
        # the torn-down transports journaled on their way out
        if self._scheduler is not None:
            self._scheduler.close()
            self._scheduler = None
        try:
            self.writer.close()
        finally:
            self.db.close()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def _q_get(q: "queue.Queue", timeout: float = 0.5):  # noqa: ANN001
    try:
        return q.get(timeout=timeout)
    except queue.Empty:
        return None
