"""Lock-striped sharding for the manager's fleet ingest plane.

PR 12's ``FleetRollupStore`` serialized every ingest and every rollup
walk behind one ``threading.Lock``, replayed the journal single-threaded
at boot, and ran decode + rollup ingest inline on each session reader
thread — one hot agent (or one slow BatchWriter flush) stalled the whole
plane. This module provides the two primitives that fix that:

- **Stable slot hashing.** Agents hash to one of ``SHARD_SLOTS`` virtual
  slots via crc32 (the same stable-hash idiom the scheduler uses for
  jitter). The *slot* — not the shard index — is what the journal's
  ``shard`` column records, so a restart with a different shard count
  still partitions the journal correctly: shard ``i`` of ``N`` owns
  every slot with ``slot % N == i``. Per-agent ordering (the only
  ordering ingest ever relied on) is preserved because an agent maps to
  exactly one slot and therefore exactly one shard.
- **RollupShard.** The striped unit of in-memory state: its own lock,
  its own per-agent dedupe LRUs, its own aggregates. Rollup *logic*
  stays in ``FleetRollupStore``; the shard is deliberately dumb so the
  store's tuning knobs (``dedupe_keys_max`` etc.) keep working when
  mutated after construction.
- **ShardIngestExecutor.** A bounded per-shard worker pool that takes
  wire-decoded batches off the session reader threads. The reader only
  enqueues (O(µs), never blocks); decode of the delta stream, dedupe,
  journal submit, and the ack all happen on the shard worker, which
  preserves the PR-12 ack-vs-durability contract (ack enqueued only
  after the shard journals) and per-agent FIFO ordering (same agent →
  same shard queue). A saturated shard *drops* the batch without
  acking — backpressure is accounted, and the agent's at-least-once
  outbox replays the un-acked frames later.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge

logger = get_logger(__name__)

# Virtual slots decouple the journal's persisted partition key from the
# runtime shard count: 256 slots re-partition evenly for any shard
# count that divides into them, and "evenly enough" for any other.
SHARD_SLOTS = 256
DEFAULT_SHARD_COUNT = 8
DEFAULT_SHARD_QUEUE_MAX = 1024

_g_shard_records = gauge(
    "tpud_fleet_shard_records",
    "journaled records applied to each rollup shard's in-memory aggregates",
)
_g_shard_queue_depth = gauge(
    "tpud_fleet_shard_queue_depth",
    "decoded outbox batches waiting on each shard's ingest queue",
)
_g_shard_dedupe = gauge(
    "tpud_fleet_shard_dedupe_keys",
    "replay-suppression LRU keys held by each rollup shard",
)
_g_shard_ingest_lag = gauge(
    "tpud_fleet_shard_ingest_lag_seconds",
    "age of each shard's most recently ingested record "
    "(manager wall clock minus record timestamp)",
)
_c_shard_backpressure = counter(
    "tpud_fleet_shard_backpressure_total",
    "outbox batches dropped un-acked because a shard ingest queue was full "
    "(the agent's outbox replays them)",
)


def slot_of(agent_id: str) -> int:
    """Stable virtual slot for an agent — what the journal persists."""
    return zlib.crc32(agent_id.encode("utf-8", "replace")) % SHARD_SLOTS


def shard_index(agent_id: str, shard_count: int) -> int:
    """Which of ``shard_count`` shards owns this agent right now."""
    return slot_of(agent_id) % shard_count


def shard_slots(index: int, shard_count: int) -> List[int]:
    """The virtual slots shard ``index`` owns under ``shard_count``."""
    return list(range(index, SHARD_SLOTS, shard_count))


class RollupShard:
    """One stripe of the fleet rollup store's in-memory state.

    Pure data holder: ``FleetRollupStore`` owns all mutation logic and
    takes ``lock`` around it. Counters are plain ints read without the
    lock on cheap paths (``records_total()``) — torn reads are
    impossible for ints and staleness is acceptable there.
    """

    __slots__ = (
        "index", "lock", "agents", "dedupe",
        "records_total", "duplicates_total", "series_total", "ingest_lag",
        "predict_total", "predict_unknown_total",
    )

    # counters (records_total etc.) are deliberately unguarded: plain
    # ints, torn-read-free, read lock-free on observability paths
    GUARDED_BY = {"agents": "lock", "dedupe": "lock"}

    def __init__(self, index: int) -> None:
        self.index = index
        self.lock = threading.Lock()
        self.agents: Dict[str, object] = {}
        self.dedupe: Dict[str, OrderedDict] = {}
        self.records_total = 0
        self.duplicates_total = 0
        self.series_total = 0
        self.ingest_lag = 0.0
        self.predict_total = 0
        self.predict_unknown_total = 0

    def dedupe_keys(self) -> int:
        with self.lock:
            return sum(len(d) for d in self.dedupe.values())


class ShardIngestExecutor:
    """Bounded per-shard workers that run ingest off the reader threads.

    ``submit`` routes by the same stable hash the rollup store shards
    by, so all work for one agent lands on one queue and runs in FIFO
    order. The queue bound is the backpressure contract: a full shard
    rejects the batch (counted, dropped, *not* acked) instead of
    blocking the session reader — the agent's durable outbox replays
    un-acked frames, so a drop costs redelivery, never data.
    """

    # _errors / _submit_ns are GIL-atomic (int += races lose one count
    # at worst on an error path; the deque is bounded and append-only)
    GUARDED_BY = {
        "_queues": "_conds",
        "_busy": "_conds",
        "_accepted": "_conds",
        "_dropped": "_conds",
        "_stopped": "_conds",
    }
    _LOCK_FREE = {
        "queue_depths": "len() snapshot of a fixed-size deque list; "
                        "torn reads tolerated on the observability path",
        "stats": "unlocked counter snapshot for observability; values "
                 "may lag one increment, never corrupt",
    }

    def __init__(
        self,
        shard_count: int = DEFAULT_SHARD_COUNT,
        max_queue_per_shard: int = DEFAULT_SHARD_QUEUE_MAX,
    ) -> None:
        self.shard_count = max(1, min(int(shard_count), SHARD_SLOTS))
        self.max_queue = max(1, int(max_queue_per_shard))
        self._conds = [threading.Condition() for _ in range(self.shard_count)]
        self._queues: List[deque] = [deque() for _ in range(self.shard_count)]
        self._busy = [0] * self.shard_count
        self._accepted = [0] * self.shard_count
        self._dropped = [0] * self.shard_count
        self._errors = 0
        # reader-side enqueue latency ring: the "reader-thread stall"
        # signal the bench gates — if enqueueing ever blocks, the
        # offload regressed to the inline behaviour it replaced
        self._submit_ns: deque = deque(maxlen=4096)
        self._stopped = False
        self._threads: List[threading.Thread] = []
        for i in range(self.shard_count):
            t = threading.Thread(
                target=self._worker, args=(i,),
                name=f"tpud-fleet-ingest-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    # -- reader side -------------------------------------------------------
    def submit(self, agent_id: str, fn: Callable[[], None]) -> bool:
        """Enqueue one decoded batch's ingest; never blocks the caller.

        Returns False (and counts backpressure) if the shard queue is
        full or the executor is stopped — the caller must NOT ack."""
        t0 = time.monotonic_ns()
        i = shard_index(agent_id, self.shard_count)
        cond = self._conds[i]
        with cond:
            if self._stopped or len(self._queues[i]) >= self.max_queue:
                self._dropped[i] += 1
                accepted = False
            else:
                self._queues[i].append(fn)
                self._accepted[i] += 1
                accepted = True
                cond.notify()
        self._submit_ns.append(time.monotonic_ns() - t0)
        if not accepted:
            _c_shard_backpressure.inc(labels={"shard": str(i)})
        return accepted

    # -- worker side -------------------------------------------------------
    def _worker(self, i: int) -> None:
        cond = self._conds[i]
        while True:
            with cond:
                q = self._queues[i]
                while not q and not self._stopped:
                    cond.wait(timeout=0.5)
                if not q:
                    if self._stopped:
                        cond.notify_all()  # wake any flush() waiter
                        return
                    continue
                fn = q.popleft()
                self._busy[i] += 1
            try:
                fn()
            except Exception:
                self._errors += 1
                logger.exception("shard %d ingest task failed", i)
            finally:
                with cond:
                    self._busy[i] -= 1
                    if not self._queues[i] and not self._busy[i]:
                        cond.notify_all()  # flush() barrier

    # -- lifecycle / barriers ----------------------------------------------
    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every shard queue is drained and idle."""
        deadline = time.monotonic() + timeout
        for i, cond in enumerate(self._conds):
            with cond:
                while self._queues[i] or self._busy[i]:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    cond.wait(timeout=min(remaining, 0.25))
        return True

    def stop(self, timeout: float = 5.0) -> None:
        """Drain queued work, then stop the workers."""
        self.flush(timeout=timeout)
        for cond in self._conds:
            with cond:
                self._stopped = True
                cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- observability -----------------------------------------------------
    def queue_depths(self) -> List[int]:
        return [len(q) for q in self._queues]

    def submit_latency_p95_ms(self) -> float:
        lat = sorted(self._submit_ns)
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(len(lat) * 0.95))] / 1e6

    def stats(self) -> Dict:
        return {
            "shards": self.shard_count,
            "max_queue_per_shard": self.max_queue,
            "queue_depths": self.queue_depths(),
            "accepted": list(self._accepted),
            "dropped": list(self._dropped),
            "errors": self._errors,
            "submit_p95_ms": self.submit_latency_p95_ms(),
        }


def update_shard_gauges(store, executor: Optional[ShardIngestExecutor] = None) -> None:
    """Refresh the ``tpud_fleet_shard_*`` gauges at scrape time.

    Cardinality is bounded by the shard count (≤ SHARD_SLOTS, 8 by
    default), never by fleet size — the per-agent detail stays behind
    the paginated operator API, matching the federation contract in
    docs/fleet.md."""
    depths = executor.queue_depths() if executor is not None else None
    for shard in store.shards():
        lbl = {"shard": str(shard.index)}
        _g_shard_records.set(float(shard.records_total), labels=lbl)
        _g_shard_dedupe.set(float(shard.dedupe_keys()), labels=lbl)
        _g_shard_ingest_lag.set(float(shard.ingest_lag), labels=lbl)
        if depths is not None and shard.index < len(depths):
            _g_shard_queue_depth.set(float(depths[shard.index]), labels=lbl)
