"""Manager peer set: rendezvous routing + per-peer health state.

The HA manager tier (docs/fleet.md "Federation & failover") runs N
manager processes as a peer set. Two questions every peer must answer
identically, with no coordination:

- **Which peer owns agent X?** Highest-random-weight (rendezvous)
  hashing over the agent's stable crc32 slot (manager/shard.py) crossed
  with each peer id: every peer computes the same owner from nothing but
  the shared peer list, and removing one peer only remaps that peer's
  cohort (the property plain modulo hashing lacks).
- **Which peer replicates my journal?** The ring successor by sorted
  peer id — each manager ships its rollup-journal appends to exactly one
  other peer (federation.py), so any single death leaves a complete
  replicated prefix on one survivor.

``PeerSet`` also carries the mutable per-peer health state the probe
loop and scatter-gather fan-out update; everything mutable is guarded by
one lock (GUARDED_BY, tools/guard_lint.py).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional

from gpud_tpu.manager.shard import slot_of

__all__ = [
    "PeerDescriptor",
    "PeerSet",
    "PeerSpecError",
    "owner_of",
    "parse_peer_spec",
    "rendezvous_rank",
    "rendezvous_score",
]


class PeerSpecError(ValueError):
    """A malformed ``peer_id=endpoint[|grpc_target]`` spec string."""


class PeerDescriptor:
    """One manager in the peer set (immutable identity + addresses)."""

    __slots__ = ("peer_id", "endpoint", "grpc_target")

    def __init__(
        self, peer_id: str, endpoint: str, grpc_target: str = ""
    ) -> None:
        self.peer_id = peer_id
        self.endpoint = endpoint.rstrip("/")
        self.grpc_target = grpc_target

    def to_dict(self) -> dict:
        return {
            "peer_id": self.peer_id,
            "endpoint": self.endpoint,
            "grpc_target": self.grpc_target,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"PeerDescriptor({self.peer_id!r}, {self.endpoint!r})"


def parse_peer_spec(spec: str) -> PeerDescriptor:
    """Parse ``peer_id=http://host:port[|grpc_host:grpc_port]``.

    The gRPC target is optional: federation replication falls back to
    the v1 HTTP session streams when the peer doesn't advertise one.
    """
    spec = (spec or "").strip()
    if "=" not in spec:
        raise PeerSpecError(
            f"peer spec {spec!r} must be peer_id=endpoint[|grpc_target]"
        )
    peer_id, _, addr = spec.partition("=")
    peer_id = peer_id.strip()
    addr, _, grpc_target = addr.partition("|")
    addr = addr.strip()
    if not peer_id or not addr:
        raise PeerSpecError(f"peer spec {spec!r} has an empty id or endpoint")
    if not addr.startswith(("http://", "https://")):
        raise PeerSpecError(
            f"peer spec {spec!r}: endpoint must be an http(s) URL"
        )
    return PeerDescriptor(peer_id, addr, grpc_target.strip())


def rendezvous_score(agent_id: str, peer_id: str) -> int:
    """HRW weight of ``peer_id`` for ``agent_id``.

    Hashes the agent's stable slot (not the raw id) crossed with the
    peer id, reusing the crc32 slot discipline from manager/shard.py:
    the slot column already journaled with every record is the same
    value routing decisions are made from, so a rebuilt store and the
    rendezvous map can never disagree about cohort membership.
    """
    slot = slot_of(agent_id)
    return zlib.crc32(f"{slot}:{peer_id}".encode("utf-8", "replace"))


def rendezvous_rank(agent_id: str, peer_ids: List[str]) -> List[str]:
    """Peer ids ranked best-first for ``agent_id`` (deterministic:
    score desc, then peer id as the tiebreak)."""
    return sorted(
        peer_ids, key=lambda p: (-rendezvous_score(agent_id, p), p)
    )


def owner_of(agent_id: str, peer_ids: List[str]) -> Optional[str]:
    """The owning peer for ``agent_id`` (None for an empty set)."""
    ranked = rendezvous_rank(agent_id, list(peer_ids))
    return ranked[0] if ranked else None


class PeerSet:
    """The full peer map from one manager's point of view.

    Identity (the descriptor list, which peer is *self*) is frozen at
    construction; per-peer health is the mutable part, updated by the
    federation probe loop and read by every scatter-gather envelope.
    """

    # all mutable per-peer health state shares one lock; the descriptor
    # map and ring order are construction-frozen and read lock-free
    GUARDED_BY = {
        "_failures": "_mu",
        "_reachable": "_mu",
        "_last_seen": "_mu",
        "_last_error": "_mu",
        "_rtt_ms": "_mu",
        "_adopted": "_mu",
    }

    def __init__(
        self,
        self_id: str,
        peers: List[PeerDescriptor],
        dead_after_probes: int = 3,
    ) -> None:
        by_id: Dict[str, PeerDescriptor] = {}
        for p in peers:
            if p.peer_id in by_id:
                raise PeerSpecError(f"duplicate peer id {p.peer_id!r}")
            by_id[p.peer_id] = p
        if self_id not in by_id:
            raise PeerSpecError(
                f"self peer id {self_id!r} missing from the peer list"
            )
        self.self_id = self_id
        self.peers = by_id
        self.ring = sorted(by_id)  # successor order: sorted peer ids
        self.dead_after_probes = max(1, int(dead_after_probes))
        self._mu = threading.Lock()
        self._failures: Dict[str, int] = {p: 0 for p in by_id}
        self._reachable: Dict[str, bool] = {p: True for p in by_id}
        self._last_seen: Dict[str, float] = {p: 0.0 for p in by_id}
        self._last_error: Dict[str, str] = {p: "" for p in by_id}
        self._rtt_ms: Dict[str, float] = {p: 0.0 for p in by_id}
        self._adopted: Dict[str, bool] = {p: False for p in by_id}

    # -- routing (construction-frozen, lock-free) --------------------------
    def owner_of(self, agent_id: str) -> PeerDescriptor:
        return self.peers[owner_of(agent_id, self.ring)]

    def owns(self, agent_id: str) -> bool:
        return owner_of(agent_id, self.ring) == self.self_id

    def successor_of(self, peer_id: str) -> Optional[PeerDescriptor]:
        """Ring successor (sorted-id order); None for a 1-peer set."""
        if len(self.ring) < 2 or peer_id not in self.peers:
            return None
        i = self.ring.index(peer_id)
        return self.peers[self.ring[(i + 1) % len(self.ring)]]

    def successor(self) -> Optional[PeerDescriptor]:
        """This manager's replication target."""
        return self.successor_of(self.self_id)

    def predecessor(self) -> Optional[PeerDescriptor]:
        """The peer whose journal this manager holds the replica of."""
        if len(self.ring) < 2:
            return None
        i = self.ring.index(self.self_id)
        return self.peers[self.ring[(i - 1) % len(self.ring)]]

    def others(self) -> List[PeerDescriptor]:
        return [self.peers[p] for p in self.ring if p != self.self_id]

    def cohort_counts(self, agent_ids: List[str]) -> Dict[str, int]:
        """How many of ``agent_ids`` each peer owns (the rendezvous map
        surfaced by ``GET /v1/fleet/peers``)."""
        counts = {p: 0 for p in self.ring}
        for aid in agent_ids:
            counts[owner_of(aid, self.ring)] += 1
        return counts

    # -- health ------------------------------------------------------------
    def mark_probe(
        self,
        peer_id: str,
        ok: bool,
        now: float,
        rtt_ms: float = 0.0,
        error: str = "",
    ) -> bool:
        """Record one probe outcome; returns True when this probe flips
        the peer to unreachable (the adopt trigger edge)."""
        with self._mu:
            if peer_id not in self._failures:
                return False
            was = self._reachable[peer_id]
            if ok:
                self._failures[peer_id] = 0
                self._reachable[peer_id] = True
                self._last_seen[peer_id] = now
                self._last_error[peer_id] = ""
                self._rtt_ms[peer_id] = rtt_ms
                if not was:
                    self._adopted[peer_id] = False  # peer came back
                return False
            self._failures[peer_id] += 1
            self._last_error[peer_id] = error
            if self._failures[peer_id] >= self.dead_after_probes:
                self._reachable[peer_id] = False
                return was  # edge only on the reachable→dead flip
            return False

    def mark_adopted(self, peer_id: str) -> None:
        with self._mu:
            if peer_id in self._adopted:
                self._adopted[peer_id] = True

    def is_adopted(self, peer_id: str) -> bool:
        with self._mu:
            return self._adopted.get(peer_id, False)

    def is_reachable(self, peer_id: str) -> bool:
        with self._mu:
            return self._reachable.get(peer_id, False)

    def live_others(self) -> List[PeerDescriptor]:
        """Remote peers currently believed reachable (fan-out targets)."""
        with self._mu:
            return [
                self.peers[p]
                for p in self.ring
                if p != self.self_id and self._reachable[p]
            ]

    def health_block(self, now: float) -> List[dict]:
        """The ``peers`` envelope block: one row per peer, self first."""
        rows = []
        with self._mu:
            for pid in sorted(
                self.ring, key=lambda p: (p != self.self_id, p)
            ):
                d = self.peers[pid].to_dict()
                d["self"] = pid == self.self_id
                d["reachable"] = (
                    True if pid == self.self_id else self._reachable[pid]
                )
                d["consecutive_failures"] = self._failures[pid]
                d["last_seen"] = self._last_seen[pid]
                d["age_seconds"] = (
                    round(now - self._last_seen[pid], 3)
                    if self._last_seen[pid] > 0
                    else None
                )
                d["last_error"] = self._last_error[pid]
                d["rtt_ms"] = round(self._rtt_ms[pid], 3)
                d["adopted"] = self._adopted[pid]
                rows.append(d)
        return rows
