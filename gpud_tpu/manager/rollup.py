"""Fleet rollup store: the manager-side aggregation layer.

Every observability surface below this file is per-node (ledger,
remediation audit, trace ring, outbox). The manager ingests all of it
but — before this store — only kept a bounded in-memory record buffer
per agent, so it could not answer a single fleet-level question
("which nodes flapped this week", "fleet availability", "MTTR across
the pod"). PAPERS.md ("Host-Side Telemetry", "When GPUs Fail Quietly")
argues diagnosis lives at the aggregation layer: the signals that
matter are cross-node patterns invisible to any one agent.

Design:

- **Durable journal, derived rollups.** Every ingested outbox record
  lands in one append-only journal table via the PR-7 ``BatchWriter``
  (group commit; ``INSERT OR IGNORE`` on ``UNIQUE(agent, dedupe_key)``
  makes replay after reconnect idempotent at the storage layer). The
  per-agent/per-component rollups (availability, MTTR/MTBF, flap
  counts, transition cadence, remediation outcomes, outbox lag) are
  *derived* state: incrementally updated in memory on ingest and
  rebuilt from the journal at construction — a SIGKILL can lose at
  most the writer's durability window and can never tear an aggregate,
  because aggregates are never persisted, only recomputed.
- **Lock-striped shards.** In-memory state is partitioned into
  ``shard_count`` stripes keyed by a stable crc32 slot hash of the
  agent id (``gpud_tpu/manager/shard.py``). Each shard has its own
  lock, per-agent dedupe LRUs, and aggregates, so ingest for agent A
  never contends with ingest for agent B on another shard, and the
  fleet rollup walk takes one shard lock at a time instead of freezing
  the whole plane. The journal persists the *slot* (``shard`` column),
  not the shard index, so a restart with a different shard count still
  partitions the journal correctly and ``_rebuild()`` replays shards
  in parallel — per-agent ordering (the only ordering ingest relies
  on) is preserved because an agent lives in exactly one slot.
- **Read-your-own-writes.** Every read path runs the writer's
  ``flush()`` barrier before touching SQLite, so batching is invisible
  to operators.
- **TTL + generation cache.** Rollup/pagination responses are cached
  per query-shape. An entry is served only while its TTL holds AND no
  ingest has advanced the store generation — writes invalidate
  immediately (read-after-write), the TTL bounds entry lifetime when
  the fleet is quiet.
- **Correlation stitching.** Records whose payload carries a
  ``correlation_id`` (minted by the agent's check wrapper and stamped
  on its trace span) are indexed by it, so one id resolves to every
  fleet event the originating check produced.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import Counter as _Counter
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Dict, Iterable, List, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.manager.shard import (
    DEFAULT_SHARD_COUNT,
    SHARD_SLOTS,
    RollupShard,
    shard_slots,
    slot_of,
)
from gpud_tpu.metrics.registry import counter, gauge, histogram
from gpud_tpu.session import wire

logger = get_logger(__name__)

TABLE = "tpud_fleet_journal_v0_1"

# storage_lint contract: these methods route their hot-path persistence
# through the BatchWriter (sync DB fallback only under a writer guard)
HOT_WRITE_METHODS = ("ingest",)

DEFAULT_CACHE_TTL = 2.0          # seconds a cached read stays servable
DEFAULT_DEDUPE_KEYS = 8192       # per-agent in-memory replay suppression
DEFAULT_RECENT_TRANSITIONS = 64  # per-series window for flap/cadence
DEFAULT_FLAP_WINDOW = 3600.0     # seconds a transition counts as a flap
DEFAULT_MAX_JOURNAL_ROWS = 500_000

_INSERT_SQL = (
    f"INSERT OR IGNORE INTO {TABLE} "
    "(agent, seq, ts, ingested, kind, dedupe_key, correlation_id, payload, "
    "shard) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)"
)

_c_records = counter(
    "tpud_fleet_ingest_records_total",
    "outbox records accepted into the fleet journal, by kind",
)
_c_duplicates = counter(
    "tpud_fleet_ingest_duplicates_total",
    "replayed outbox records suppressed by fleet ingest dedupe",
)
_g_ingest_lag = gauge(
    "tpud_fleet_ingest_lag_seconds",
    "age of the most recently ingested outbox record "
    "(manager wall clock minus record timestamp)",
)
_h_refresh = histogram(
    "tpud_fleet_rollup_refresh_seconds",
    "wall time to materialize one fleet rollup response (cache miss path)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5),
)
_c_cache_hits = counter(
    "tpud_fleet_cache_hits_total",
    "fleet operator-API reads served from the TTL cache",
)
_c_cache_misses = counter(
    "tpud_fleet_cache_misses_total",
    "fleet operator-API reads that had to materialize (barrier + compute)",
)
_g_agents = gauge(
    "tpud_fleet_agents",
    "agents with at least one journaled record in the fleet rollup store",
)
_g_series = gauge(
    "tpud_fleet_agent_series",
    "distinct (agent, component) rollup series held in memory",
)
_g_predict_series = gauge(
    "tpud_fleet_predict_series",
    "distinct (agent, component) predictive rollup series held in memory",
)
_g_predict_unknown = gauge(
    "tpud_fleet_predict_unknown_schema_records",
    "journaled predict_score records whose payload schema is newer than "
    "this manager understands (counted per record, never dropped)",
)


class _SeriesRollup:
    """Incremental per-(agent, component) health rollup."""

    __slots__ = (
        "state", "since", "first_ts", "last_ts", "transitions",
        "healthy_seconds", "unhealthy_seconds",
        "repair_total", "repair_count",
        "tbf_total", "tbf_count", "last_failure_ts", "failures",
        "recent",
    )

    def __init__(self) -> None:
        self.state = ""
        self.since = 0.0
        self.first_ts = 0.0
        self.last_ts = 0.0
        self.transitions = 0
        self.healthy_seconds = 0.0
        self.unhealthy_seconds = 0.0
        self.repair_total = 0.0     # completed unhealthy-episode downtime
        self.repair_count = 0
        self.tbf_total = 0.0        # gaps between consecutive failures
        self.tbf_count = 0
        self.last_failure_ts = 0.0
        self.failures = 0
        self.recent: deque = deque(maxlen=DEFAULT_RECENT_TRANSITIONS)

    def apply(self, from_state: str, to_state: str, ts: float) -> None:
        if not self.first_ts:
            self.first_ts = ts
        # close the open interval in the previous state
        if self.state and ts > self.since:
            dt = ts - self.since
            if self.state == "Healthy":
                self.healthy_seconds += dt
            else:
                self.unhealthy_seconds += dt
        prev = self.state or from_state
        self.transitions += 1
        self.recent.append(ts)
        if to_state != "Healthy" and (not prev or prev == "Healthy"):
            self.failures += 1
            if self.last_failure_ts:
                self.tbf_total += ts - self.last_failure_ts
                self.tbf_count += 1
            self.last_failure_ts = ts
        if to_state == "Healthy" and prev and prev != "Healthy" and self.since:
            self.repair_total += max(0.0, ts - self.since)
            self.repair_count += 1
        self.state = to_state
        self.since = ts
        if ts > self.last_ts:
            self.last_ts = ts

    def snapshot(self, as_of: float) -> Dict:
        healthy = self.healthy_seconds
        unhealthy = self.unhealthy_seconds
        # count the open interval up to the newest timestamp we trust
        if self.state and as_of > self.since:
            if self.state == "Healthy":
                healthy += as_of - self.since
            else:
                unhealthy += as_of - self.since
        total = healthy + unhealthy
        flap_cutoff = as_of - DEFAULT_FLAP_WINDOW
        flaps = sum(1 for t in self.recent if t >= flap_cutoff)
        cadence = 0.0
        if len(self.recent) >= 2:
            span = self.recent[-1] - self.recent[0]
            if span > 0:
                cadence = span / (len(self.recent) - 1)
        return {
            "state": self.state,
            "since": self.since,
            "transitions": self.transitions,
            "availability": (healthy / total) if total > 0 else 1.0,
            "healthy_seconds": healthy,
            "unhealthy_seconds": unhealthy,
            "mttr_seconds": (
                self.repair_total / self.repair_count if self.repair_count else 0.0
            ),
            "mtbf_seconds": (
                self.tbf_total / self.tbf_count if self.tbf_count else 0.0
            ),
            "failures": self.failures,
            "flap_count": flaps,
            "transition_cadence_seconds": cadence,
        }


# worst-state ordering for ICI link aggregates (fabric plane states)
_LINK_STATE_RANK = {"up": 0, "": 0, "degraded": 1, "down": 2}

# per-agent cap on distinct link aggregates — a garbled agent shipping
# unbounded link names degrades to truncation accounting, not OOM
MAX_LINKS_PER_AGENT = 1024

# per-link bound on retained degraded-record timestamps: the windowed
# 1h/24h/7d counters saturate here instead of growing with history
MAX_LINK_WINDOW_SAMPLES = 512

# windowed degradation buckets served by /v1/fleet/fabric
LINK_WINDOWS = (("1h", 3600.0), ("24h", 86400.0), ("7d", 604800.0))


class _LinkRollup:
    """Per-(agent, ici link) aggregate over shipped fabric sweep records."""

    __slots__ = (
        "src_chip", "dst_chip", "axis", "last_state", "worst_state",
        "records", "deviations", "downs", "last_ts", "last_degraded_ts",
        "max_deviation", "degraded_recent",
    )

    def __init__(self) -> None:
        self.src_chip = -1
        self.dst_chip = -1
        self.axis = ""
        self.last_state = ""
        self.worst_state = ""
        self.records = 0
        self.deviations = 0       # records that arrived flagged degraded
        self.downs = 0            # records that arrived hard-down
        self.last_ts = 0.0
        self.last_degraded_ts = 0.0  # newest not-up record ts
        self.max_deviation = 0.0
        # bounded not-up record timestamps behind the windowed counters
        self.degraded_recent: deque = deque(maxlen=MAX_LINK_WINDOW_SAMPLES)

    def apply(self, body: Dict, ts: float) -> None:
        state = str(body.get("state", "") or "")
        self.src_chip = int(body.get("src_chip", self.src_chip) or -1)
        self.dst_chip = int(body.get("dst_chip", self.dst_chip) or -1)
        self.axis = str(body.get("axis", self.axis) or "")
        when = float(body.get("ts", ts) or ts)
        self.records += 1
        self.last_state = state
        if _LINK_STATE_RANK.get(state, 0) > _LINK_STATE_RANK.get(
            self.worst_state, 0
        ):
            self.worst_state = state
        if state == "degraded":
            self.deviations += 1
        elif state == "down":
            self.downs += 1
        if state in ("degraded", "down"):
            self.degraded_recent.append(when)
            if when > self.last_degraded_ts:
                self.last_degraded_ts = when
        if when > self.last_ts:
            self.last_ts = when
        try:
            dev = float(body.get("deviation", 0.0) or 0.0)
        except (TypeError, ValueError):
            dev = 0.0
        if dev > self.max_deviation:
            self.max_deviation = dev

    def snapshot(self, as_of: Optional[float] = None) -> Dict:
        """``as_of`` anchors the windowed counters; ``None`` falls back
        to the link's own newest record time, which makes the snapshot a
        pure function of the journal (rebuild-parity tests lean on it —
        wall-clock anchoring is the *caller's* choice)."""
        anchor = self.last_ts if as_of is None else as_of
        windows = {
            label: sum(
                1 for t in self.degraded_recent if t > anchor - span
            )
            for label, span in LINK_WINDOWS
        }
        return {
            "src_chip": self.src_chip,
            "dst_chip": self.dst_chip,
            "axis": self.axis,
            "state": self.last_state,
            "worst_state": self.worst_state,
            "records": self.records,
            "deviations": self.deviations,
            "downs": self.downs,
            "last_ts": self.last_ts,
            "last_degraded_ts": self.last_degraded_ts,
            "max_deviation": self.max_deviation,
            "degraded_windows": windows,
        }


# newest predict_score payload schema this manager understands: records
# with a higher schema are journaled + counted, never applied (a newer
# agent in a mixed fleet degrades to accounting, not silent data loss)
PREDICT_SCHEMA_MAX = 1

# per-agent cap on distinct predictive series (same OOM guard as links)
MAX_PREDICT_PER_AGENT = 512

# per-series bound on retained lead-time measurements (p50 source)
MAX_PREDICT_LEADS = 64

# default e-folding time for stale-score down-ranking in the fleet pane:
# an armed component republishes every publish-interval (60s default),
# so a score 15 minutes old is either a dead agent or a cleared story —
# rank it down smoothly rather than serving it as fresh
DEFAULT_PREDICT_DECAY = 900.0


class _PredictRollup:
    """Per-(agent, component) predictive aggregate over journaled
    ``predict_score`` outbox records (warn/clear/lead/snapshot).

    Pure function of the agent's record sequence — no wall-clock reads —
    so a journal replay rebuilds it byte-identically for any shard
    count. Staleness decay is applied at *read* time in
    :meth:`FleetRollupStore._compute_fleet_predict`."""

    __slots__ = (
        "component_class", "schema", "score", "armed", "warned_at",
        "threshold", "last_event", "last_ts", "features",
        "warn_count", "clear_count", "snapshot_count",
        "lead_count", "lead_total", "lead_min", "lead_max", "leads",
    )

    def __init__(self) -> None:
        self.component_class = ""
        self.schema = 0
        self.score = 0.0
        self.armed = False
        self.warned_at: Optional[float] = None
        self.threshold = 0.0
        self.last_event = ""
        self.last_ts = 0.0
        self.features: Dict[str, float] = {}
        self.warn_count = 0
        self.clear_count = 0
        self.snapshot_count = 0
        self.lead_count = 0
        self.lead_total = 0.0
        self.lead_min = 0.0
        self.lead_max = 0.0
        self.leads: deque = deque(maxlen=MAX_PREDICT_LEADS)

    def apply(self, body: Dict, ts: float) -> None:
        event = str(body.get("event", "") or "")
        when = float(body.get("ts", ts) or ts)
        try:
            score = float(body.get("score", 0.0) or 0.0)
        except (TypeError, ValueError):
            score = 0.0
        score = 0.0 if score < 0.0 else (1.0 if score > 1.0 else score)
        if when >= self.last_ts:
            # latest-wins fields follow record time: per-agent replay
            # order is (ts, seq), so this is deterministic on rebuild
            self.last_ts = when
            self.last_event = event
            self.score = score
            self.armed = bool(body.get("armed"))
            self.schema = int(body.get("schema", 0) or 0)
            self.component_class = str(
                body.get("component_class", self.component_class) or ""
            )
            wa = body.get("warned_at")
            self.warned_at = float(wa) if wa is not None else None
            try:
                self.threshold = float(body.get("threshold", 0.0) or 0.0)
            except (TypeError, ValueError):
                self.threshold = 0.0
            feats = body.get("features")
            if isinstance(feats, dict):
                clean: Dict[str, float] = {}
                for k, v in feats.items():
                    try:
                        clean[str(k)] = float(v)
                    except (TypeError, ValueError):
                        continue
                self.features = clean
        if event == "warn":
            self.warn_count += 1
        elif event == "clear":
            self.clear_count += 1
        elif event == "snapshot":
            self.snapshot_count += 1
        elif event == "lead":
            lead = body.get("lead_seconds")
            try:
                lead = None if lead is None else float(lead)
            except (TypeError, ValueError):
                lead = None
            if lead is not None and lead >= 0.0:
                self.lead_count += 1
                self.lead_total += lead
                if self.lead_count == 1 or lead < self.lead_min:
                    self.lead_min = lead
                if lead > self.lead_max:
                    self.lead_max = lead
                self.leads.append(lead)

    def risk(self, now: float, decay_tau: float) -> float:
        """Predicted-failure likelihood at ``now``: the noisy-OR of the
        last fused score, an armed bonus, and repeat-warning evidence,
        all down-ranked by exponential staleness decay. Bounded [0, 1],
        monotone in freshness — a node that stopped reporting sinks."""
        age = max(0.0, now - self.last_ts)
        decay = math.exp(-age / decay_tau) if decay_tau > 0 else 1.0
        armed_term = 0.25 if self.armed else 0.0
        warn_term = 0.15 * min(self.warn_count, 4) / 4.0
        base = 1.0 - (1.0 - self.score) * (1.0 - armed_term) * (
            1.0 - warn_term
        )
        r = base * decay
        return 0.0 if r < 0.0 else (1.0 if r > 1.0 else r)

    def snapshot(self, now: float, decay_tau: float) -> Dict:
        leads = sorted(self.leads)
        return {
            "component_class": self.component_class,
            "schema": self.schema,
            "score": self.score,
            "risk": self.risk(now, decay_tau),
            "age_seconds": max(0.0, now - self.last_ts),
            "armed": self.armed,
            "warned_at": self.warned_at,
            "threshold": self.threshold,
            "last_event": self.last_event,
            "last_ts": self.last_ts,
            "features": dict(self.features),
            "warn_count": self.warn_count,
            "clear_count": self.clear_count,
            "snapshot_count": self.snapshot_count,
            "lead": {
                "count": self.lead_count,
                "mean_seconds": (
                    self.lead_total / self.lead_count
                    if self.lead_count else 0.0
                ),
                "min_seconds": self.lead_min,
                "max_seconds": self.lead_max,
                "p50_seconds": (
                    leads[(len(leads) - 1) // 2] if leads else 0.0
                ),
            },
        }


class _AgentRollup:
    """Per-agent aggregate over everything that agent's outbox shipped."""

    __slots__ = (
        "records_by_kind", "last_seq", "last_ts", "last_ingest",
        "outbox_lag_seconds", "remediation_outcomes", "series",
        "links", "links_truncated",
        "predict", "predict_truncated", "predict_unknown_schema",
    )

    def __init__(self) -> None:
        self.records_by_kind: _Counter = _Counter()
        self.last_seq = 0
        self.last_ts = 0.0
        self.last_ingest = 0.0
        self.outbox_lag_seconds = 0.0
        self.remediation_outcomes: _Counter = _Counter()
        self.series: Dict[str, _SeriesRollup] = {}
        self.links: Dict[str, _LinkRollup] = {}
        self.links_truncated = 0
        self.predict: Dict[str, _PredictRollup] = {}
        self.predict_truncated = 0
        self.predict_unknown_schema = 0


class FleetRollupStore:
    """Manager-side fleet journal + materialized rollups (module docstring).

    Thread-safe: ``ingest`` may be called from any shard-executor worker
    (or reader thread when no executor is wired); reads run on the
    operator pool. In-memory state is striped across ``shard_count``
    locks keyed by a stable hash of the agent id; cache/generation
    bookkeeping sits under a separate meta lock; SQLite work happens
    outside all of them.
    """

    # _shards is a fixed list built in __init__ and only indexed after —
    # the per-shard state behind it is guarded by each shard's own lock
    # (RollupShard.GUARDED_BY), taken via `with shard.lock`
    GUARDED_BY = {
        "_generation": "_meta",
        "_cache": "_meta",
        "_cache_hits": "_meta",
        "_cache_misses": "_meta",
    }

    def __init__(
        self,
        db,
        writer=None,
        cache_ttl_seconds: float = DEFAULT_CACHE_TTL,
        dedupe_keys_max: int = DEFAULT_DEDUPE_KEYS,
        max_journal_rows: int = DEFAULT_MAX_JOURNAL_ROWS,
        shard_count: int = DEFAULT_SHARD_COUNT,
        rebuild_parallel: bool = True,
        predict_decay_seconds: float = DEFAULT_PREDICT_DECAY,
    ) -> None:
        self.db = db
        self.writer = writer
        self.cache_ttl = float(cache_ttl_seconds)
        self.dedupe_keys_max = int(dedupe_keys_max)
        self.max_journal_rows = int(max_journal_rows)
        self.predict_decay = float(predict_decay_seconds)
        self.shard_count = max(1, min(int(shard_count), SHARD_SLOTS))
        self.rebuild_parallel = bool(rebuild_parallel)
        self._shards: List[RollupShard] = [
            RollupShard(i) for i in range(self.shard_count)
        ]
        # meta lock: generation + response cache + cache counters only —
        # never held while a shard lock is held
        self._meta = threading.Lock()
        self._generation = 0
        # cache key -> (generation, monotonic deadline, value)
        self._cache: Dict[tuple, tuple] = {}
        self._cache_hits = 0
        self._cache_misses = 0
        self.last_rebuild_seconds = 0.0
        self._ensure_schema()
        self._rebuild()

    def _shard_for(self, agent_id: str) -> RollupShard:
        return self._shards[slot_of(agent_id) % self.shard_count]

    def shards(self) -> List[RollupShard]:
        return self._shards

    # -- schema / rebuild --------------------------------------------------
    def _ensure_schema(self) -> None:
        self.db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                agent          TEXT NOT NULL,
                seq            INTEGER NOT NULL,
                ts             REAL NOT NULL,
                ingested       REAL NOT NULL,
                kind           TEXT NOT NULL,
                dedupe_key     TEXT NOT NULL,
                correlation_id TEXT NOT NULL DEFAULT '',
                payload        BLOB,
                shard          INTEGER NOT NULL DEFAULT -1,
                UNIQUE (agent, dedupe_key)
            )"""
        )
        cols = {r[1] for r in self.db.query(f"PRAGMA table_info({TABLE})")}
        if "shard" not in cols:
            # pre-sharding journal: widen, then backfill below
            self.db.execute(
                f"ALTER TABLE {TABLE} "
                f"ADD COLUMN shard INTEGER NOT NULL DEFAULT -1"
            )
        # backfill the derived slot for legacy rows (one-time migration;
        # slot_of is a pure function of the agent id, so this is safe to
        # re-run and converges immediately)
        stale = self.db.query(
            f"SELECT DISTINCT agent FROM {TABLE} WHERE shard < 0"
        )
        if stale:
            self.db.executemany(
                f"UPDATE {TABLE} SET shard = ? WHERE agent = ? AND shard < 0",
                [(slot_of(agent), agent) for (agent,) in stale],
            )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_agent_ts "
            f"ON {TABLE} (agent, ts)"
        )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_correlation "
            f"ON {TABLE} (correlation_id) WHERE correlation_id != ''"
        )
        # covering order for per-shard replay: each rebuild worker walks
        # its slots in index order, no sort step
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_shard "
            f"ON {TABLE} (shard, agent, ts, seq)"
        )

    def _rebuild(self) -> None:
        """Recompute every rollup from the journal (boot / crash recovery).

        The journal is the only durable state; aggregates are a pure
        function of it, so a SIGKILL between group commits can shorten
        the journal but never tear a rollup. Each shard replays only
        its own slots (the persisted ``shard`` column), so replay runs
        one worker per shard — per-agent ordering holds because an
        agent's rows all live in one slot.

        The fetch pool is capped at the host's usable core count: on a
        single-core host extra fetch threads only convoy on the GIL, so
        replay degrades to the plain serial loop there rather than
        paying thread overhead for no concurrency."""
        t0 = time.monotonic()
        try:
            cores = max(1, len(os.sched_getaffinity(0)))
        except AttributeError:
            cores = max(1, os.cpu_count() or 1)
        workers = min(self.shard_count, cores)
        if self.rebuild_parallel and workers > 1:
            # fetch/apply pipeline: one FETCH worker per shard (SQLite
            # index walk + msgpack unpack — the C-heavy part, which runs
            # with the GIL dropped during VDBE steps), while the calling
            # thread APPLIES each shard the moment its rows land. Running
            # the Python apply loops on N threads instead would convoy on
            # the GIL and come out *slower* than serial.
            counts = []
            with ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="tpud-fleet-rebuild",
            ) as ex:
                futs = {
                    ex.submit(self._fetch_shard_rows, s): s
                    for s in self._shards
                }
                for fut in as_completed(futs):
                    counts.append(
                        self._apply_shard_rows(futs[fut], fut.result())
                    )
        else:
            counts = [
                self._apply_shard_rows(s, self._fetch_shard_rows(s))
                for s in self._shards
            ]
        with self._meta:
            self._generation += 1
            self._cache.clear()
        self._update_gauges()
        self.last_rebuild_seconds = time.monotonic() - t0
        total = sum(counts)
        if total:
            logger.info(
                "fleet rollup store rebuilt from journal: %d records, "
                "%d agents, %d shards, %.3fs (%s)",
                total, sum(len(s.agents) for s in self._shards),
                self.shard_count, self.last_rebuild_seconds,
                "parallel" if self.rebuild_parallel and workers > 1
                else "serial",
            )

    def _fetch_shard_rows(self, shard: RollupShard) -> list:
        """Pull + decode one shard's journal slice (no shard state touched,
        safe on any thread). Returns ``(agent, seq, ts, ingested, kind,
        key, body)`` rows in replay order."""
        slots = shard_slots(shard.index, self.shard_count)
        placeholders = ",".join("?" * len(slots))
        # ORDER BY walks idx_fleet_shard — per-slot, per-agent (ts, seq)
        # order with no sort pass; cross-agent order is irrelevant
        rows = self.db.query(
            f"SELECT agent, seq, ts, ingested, kind, dedupe_key, payload "
            f"FROM {TABLE} WHERE shard IN ({placeholders}) "
            f"ORDER BY shard, agent, ts, seq",
            tuple(slots),
        )
        unpack = wire.unpack_obj
        return [
            (agent, seq, ts, ingested, kind, key,
             unpack(payload) if payload is not None else {})
            for agent, seq, ts, ingested, kind, key, payload in rows
        ]

    def _apply_shard_rows(self, shard: RollupShard, rows: list) -> int:
        apply_one = self._apply_shard_locked
        keys_max = self.dedupe_keys_max
        with shard.lock:
            shard.agents.clear()
            shard.dedupe.clear()
            shard.records_total = 0
            shard.duplicates_total = 0
            shard.series_total = 0
            shard.predict_total = 0
            shard.predict_unknown_total = 0
            dedupe = shard.dedupe
            run_agent = None
            run_keys: List[str] = []
            for agent, seq, ts, ingested, kind, key, body in rows:
                if agent != run_agent:
                    # reseed the replay-suppression LRU: after a restart
                    # agents replay journaled-but-unacked records, and the
                    # DB's INSERT OR IGNORE alone would let them double-
                    # count the in-memory aggregates. Keys are UNIQUE per
                    # agent and arrive oldest-first, so "insert each,
                    # evict past the cap" reduces to keeping the newest
                    # `keys_max` in order — seeded per agent run below.
                    if run_agent is not None:
                        dedupe[run_agent] = OrderedDict.fromkeys(
                            run_keys[-keys_max:]
                        )
                    run_agent = agent
                    run_keys = []
                run_keys.append(key)
                apply_one(shard, agent, seq, ts, ingested, kind, key, body)
            if run_agent is not None:
                dedupe[run_agent] = OrderedDict.fromkeys(run_keys[-keys_max:])
            return shard.records_total

    # -- ingest ------------------------------------------------------------
    def ingest(
        self,
        agent_id: str,
        records: Iterable[Tuple[int, float, str, str, object]],
        now: Optional[float] = None,
    ) -> int:
        """Journal + roll up a batch of decoded outbox records.

        ``records`` is the decoder's output shape: ``(seq, ts, kind,
        dedupe_key, payload)`` tuples. Replays are suppressed twice —
        a bounded per-agent key LRU here (protects the in-memory
        aggregates) and ``INSERT OR IGNORE`` in the journal (protects
        durable state even past the LRU window). Returns the number of
        fresh records applied."""
        wall = time.time() if now is None else now
        slot = slot_of(agent_id)
        shard = self._shards[slot % self.shard_count]
        rows: List[tuple] = []
        fresh: List[tuple] = []
        dup = 0
        pack = wire.pack_obj
        with shard.lock:
            seen = shard.dedupe.get(agent_id)
            if seen is None:
                seen = shard.dedupe[agent_id] = OrderedDict()
            for seq, ts, kind, key, payload in records:
                key = key or f"seq:{seq}"
                if key in seen:
                    seen.move_to_end(key)
                    dup += 1
                    continue
                seen[key] = None
                while len(seen) > self.dedupe_keys_max:
                    seen.popitem(last=False)
                body = payload if isinstance(payload, dict) else {}
                cid = str(body.get("correlation_id", "") or "")
                rows.append(
                    (agent_id, seq, ts, wall, kind, key, cid,
                     pack(payload), slot)
                )
                fresh.append((seq, ts, kind, key, body))
            for seq, ts, kind, key, body in fresh:
                self._apply_shard_locked(
                    shard, agent_id, seq, ts, wall, kind, key, body
                )
            if dup:
                shard.duplicates_total += dup
            if fresh:
                shard.ingest_lag = max(0.0, wall - fresh[-1][1])
        if dup:
            _c_duplicates.inc(dup)
        if not rows:
            return 0
        # generation bumps before the journal submit, exactly as the
        # single-lock store did: readers invalidate immediately, the
        # barrier on the miss path makes the rows visible to SQL reads
        with self._meta:
            self._generation += 1
        self._update_gauges()
        if self.writer is not None:
            self.writer.submit_many("fleet", _INSERT_SQL, rows)
        else:
            self.db.executemany(_INSERT_SQL, rows)
        kind_counts: Dict[str, int] = {}
        for _, _, kind, _, _ in fresh:
            kind_counts[kind] = kind_counts.get(kind, 0) + 1
        for kind, n in kind_counts.items():
            _c_records.inc(n, labels={"kind": kind})
        _g_ingest_lag.set(max(0.0, wall - fresh[-1][1]))
        return len(fresh)

    def _apply_shard_locked(
        self, shard: RollupShard, agent_id: str, seq: int, ts: float,
        ingested: float, kind: str, key: str, body: Dict,
    ) -> None:
        ar = shard.agents.get(agent_id)
        if ar is None:
            ar = shard.agents[agent_id] = _AgentRollup()
        ar.records_by_kind[kind] += 1
        shard.records_total += 1
        if seq > ar.last_seq:
            ar.last_seq = seq
        if ts >= ar.last_ts:
            # lag is anchored to the newest record by *record* time, so a
            # replayed old record can't make a caught-up agent look laggy
            ar.last_ts = ts
            ar.outbox_lag_seconds = max(0.0, ingested - ts)
        if ingested > ar.last_ingest:
            ar.last_ingest = ingested
        if kind == "transition":
            comp = str(body.get("component", "") or "_unknown")
            sr = ar.series.get(comp)
            if sr is None:
                sr = ar.series[comp] = _SeriesRollup()
                shard.series_total += 1
            sr.apply(
                str(body.get("from", "") or ""),
                str(body.get("to", "") or ""),
                float(body.get("ts", ts) or ts),
            )
        elif kind == "remediation_audit":
            ar.remediation_outcomes[str(body.get("outcome", "") or "unknown")] += 1
        elif kind == "ici_link":
            link = str(body.get("link", "") or "")
            if not link:
                return
            lr = ar.links.get(link)
            if lr is None:
                if len(ar.links) >= MAX_LINKS_PER_AGENT:
                    ar.links_truncated += 1
                    return
                lr = ar.links[link] = _LinkRollup()
            lr.apply(body, ts)
        elif kind == "predict_score":
            try:
                schema = int(body.get("schema", 0) or 0)
            except (TypeError, ValueError):
                schema = 0
            if schema > PREDICT_SCHEMA_MAX:
                # newer-agent record: already journaled above (a future
                # manager can replay it), counted here, never applied
                ar.predict_unknown_schema += 1
                shard.predict_unknown_total += 1
                return
            comp = str(body.get("component", "") or "_unknown")
            pr = ar.predict.get(comp)
            if pr is None:
                if len(ar.predict) >= MAX_PREDICT_PER_AGENT:
                    ar.predict_truncated += 1
                    return
                pr = ar.predict[comp] = _PredictRollup()
                shard.predict_total += 1
            pr.apply(body, ts)

    def _update_gauges(self) -> None:
        # per-shard counters are plain ints; summing without the shard
        # locks reads a consistent-enough snapshot for gauges
        _g_agents.set(sum(len(s.agents) for s in self._shards))
        _g_series.set(sum(s.series_total for s in self._shards))
        _g_predict_series.set(sum(s.predict_total for s in self._shards))
        _g_predict_unknown.set(
            sum(s.predict_unknown_total for s in self._shards)
        )

    # -- cache plumbing ----------------------------------------------------
    def _barrier(self) -> None:
        if self.writer is not None:
            self.writer.flush()

    def _cached(self, key: tuple, compute, sql: bool = True) -> object:
        now = time.monotonic()
        with self._meta:
            ent = self._cache.get(key)
            if ent is not None and ent[0] == self._generation and now < ent[1]:
                self._cache_hits += 1
                _c_cache_hits.inc()
                return ent[2]
            gen = self._generation
            self._cache_misses += 1
        _c_cache_misses.inc()
        # miss path: barrier first so SQLite-backed computations see every
        # record journaled before this read began. Pure in-memory computes
        # (``sql=False``) skip it — shard state is applied BEFORE the
        # journal submit, so memory is always at least as new as the DB,
        # and waiting out the write-behind backlog would put the whole
        # ingest burst in the operator's read latency for nothing.
        if sql:
            self._barrier()
        with _h_refresh.time():
            value = compute()
        with self._meta:
            # only cache what was computed against the still-current
            # generation — an ingest racing the compute wins
            if gen == self._generation:
                self._cache[key] = (gen, time.monotonic() + self.cache_ttl, value)
        return value

    def invalidate_cache(self) -> None:
        with self._meta:
            self._cache.clear()
            self._generation += 1

    def cache_stats(self) -> Dict:
        with self._meta:
            return {
                "hits": self._cache_hits,
                "misses": self._cache_misses,
                "entries": len(self._cache),
                "generation": self._generation,
            }

    # -- read paths --------------------------------------------------------
    def fleet_rollup(self) -> Dict:
        """Fleet-wide aggregates (``GET /v1/fleet/rollup``)."""
        return self._cached(("rollup",), self._compute_fleet_rollup, sql=False)

    def _compute_fleet_rollup(self) -> Dict:
        by_kind: _Counter = _Counter()
        remediation: _Counter = _Counter()
        agent_count = 0
        records_total = 0
        duplicates = 0
        max_lag = 0.0
        # one shard lock at a time: snapshot each stripe, then merge.
        # Accumulation runs over a globally sorted series list so the
        # float sums are identical for any shard count (byte-identical
        # rollups across N=1 / N=8 / rebuild-with-new-N).
        snaps: List[tuple] = []
        with self._meta:
            gen = self._generation
        for shard in self._shards:
            with shard.lock:
                records_total += shard.records_total
                duplicates += shard.duplicates_total
                agent_count += len(shard.agents)
                for aid, ar in shard.agents.items():
                    by_kind.update(ar.records_by_kind)
                    remediation.update(ar.remediation_outcomes)
                    if ar.outbox_lag_seconds > max_lag:
                        max_lag = ar.outbox_lag_seconds
                    as_of = ar.last_ts
                    for comp, sr in ar.series.items():
                        snaps.append((
                            aid, comp, sr.snapshot(as_of), sr.transitions,
                            sr.failures, sr.repair_total, sr.repair_count,
                            sr.tbf_total, sr.tbf_count,
                        ))
        snaps.sort(key=lambda s: (s[0], s[1]))
        transitions = 0
        failures = 0
        repair_total = 0.0
        repair_count = 0
        tbf_total = 0.0
        tbf_count = 0
        healthy = 0.0
        unhealthy = 0.0
        unhealthy_now = 0
        flapping: List[Dict] = []
        for aid, comp, snap, s_tr, s_fail, s_rt, s_rc, s_tt, s_tc in snaps:
            transitions += s_tr
            failures += s_fail
            repair_total += s_rt
            repair_count += s_rc
            tbf_total += s_tt
            tbf_count += s_tc
            healthy += snap["healthy_seconds"]
            unhealthy += snap["unhealthy_seconds"]
            if snap["state"] and snap["state"] != "Healthy":
                unhealthy_now += 1
            if snap["flap_count"] >= 3:
                flapping.append(
                    {"agent": aid, "component": comp,
                     "flap_count": snap["flap_count"]}
                )
        flapping.sort(key=lambda f: (-f["flap_count"], f["agent"], f["component"]))
        observed = healthy + unhealthy
        return {
            "generation": gen,
            "agents": agent_count,
            "series": len(snaps),
            "records_total": records_total,
            "records_by_kind": dict(sorted(by_kind.items())),
            "duplicates_suppressed": duplicates,
            "transitions_total": transitions,
            "failures_total": failures,
            "unhealthy_series": unhealthy_now,
            "availability": (healthy / observed) if observed > 0 else 1.0,
            "mttr_seconds": (repair_total / repair_count) if repair_count else 0.0,
            "mtbf_seconds": (tbf_total / tbf_count) if tbf_count else 0.0,
            "remediation_outcomes": dict(sorted(remediation.items())),
            "flapping": flapping[:32],
            "max_outbox_lag_seconds": max_lag,
        }

    def fleet_fabric(
        self, since: float = 0.0, now: Optional[float] = None
    ) -> Dict:
        """Fleet-wide ICI link matrix rollup (``GET /v1/fleet/fabric``):
        per-agent link aggregates from journaled ``ici_link`` fabric
        sweep records, answering "which links degraded since ts" — and,
        via the windowed 1h/24h/7d counters, "which links degraded this
        week" — across the whole fleet from one query. ``now`` anchors
        the window buckets; passing it explicitly (tests, parity
        comparisons) makes the response a pure function of the journal
        and bypasses the TTL cache."""
        since = float(since)
        if now is not None:
            return self._compute_fleet_fabric(since, float(now))
        return self._cached(
            ("fabric", since),
            lambda: self._compute_fleet_fabric(since, None),
            sql=False,
        )

    def _compute_fleet_fabric(
        self, since: float, now: Optional[float]
    ) -> Dict:
        with self._meta:
            gen = self._generation
        agents_with_links = 0
        links_total = 0
        truncated = 0
        by_state: _Counter = _Counter()
        degraded: List[Dict] = []
        for shard in self._shards:
            with shard.lock:
                for aid, ar in shard.agents.items():
                    if not ar.links:
                        continue
                    agents_with_links += 1
                    links_total += len(ar.links)
                    truncated += ar.links_truncated
                    for name, lr in ar.links.items():
                        by_state[lr.last_state or "unknown"] += 1
                        if (
                            lr.last_state in ("degraded", "down")
                            or (lr.last_degraded_ts > 0
                                and lr.last_degraded_ts >= since)
                        ):
                            row = lr.snapshot(as_of=now)
                            row["agent"] = aid
                            row["link"] = name
                            degraded.append(row)
        degraded.sort(
            key=lambda r: (
                -_LINK_STATE_RANK.get(r["state"], 0),
                -r["last_degraded_ts"],
                r["agent"],
                r["link"],
            )
        )
        return {
            "generation": gen,
            "since": since,
            "agents": agents_with_links,
            "links_total": links_total,
            "links_by_state": dict(sorted(by_state.items())),
            "degraded_count": len(degraded),
            "degraded": degraded[:256],
            "links_truncated": truncated,
        }

    def fleet_predict(
        self, top: int = 20, now: Optional[float] = None
    ) -> Dict:
        """Fleet-ranked prediction pane (``GET /v1/fleet/predict``):
        "which K of my N nodes fail next", from journaled
        ``predict_score`` records. Rows are (agent, component) predictive
        aggregates ranked by time-decayed risk — the last fused score
        plus armed/repeat-warning evidence, down-ranked exponentially as
        the score goes stale (``predict_decay_seconds`` e-folding).
        ``now`` anchors the decay; passing it explicitly (tests, parity
        comparisons) makes the response a pure function of the journal
        and bypasses the TTL cache."""
        top = max(1, min(500, int(top)))
        if now is not None:
            return self._compute_fleet_predict(top, float(now))
        return self._cached(
            ("predict", top),
            lambda: self._compute_fleet_predict(top, time.time()),
            sql=False,
        )

    def _compute_fleet_predict(self, top: int, now: float) -> Dict:
        with self._meta:
            gen = self._generation
        decay_tau = self.predict_decay
        agents_with_predict = 0
        unknown_schema = 0
        truncated = 0
        armed = 0
        warns_total = 0
        # (agent, component, snapshot) collected one shard lock at a
        # time, then globally sorted — identical output for any shard
        # count (the fleet lead-time aggregation below also walks the
        # sorted list so float sums are order-stable)
        rows: List[tuple] = []
        for shard in self._shards:
            with shard.lock:
                for aid, ar in shard.agents.items():
                    if not ar.predict and not ar.predict_unknown_schema:
                        continue
                    if ar.predict:
                        agents_with_predict += 1
                    unknown_schema += ar.predict_unknown_schema
                    truncated += ar.predict_truncated
                    for comp, pr in ar.predict.items():
                        snap = pr.snapshot(now, decay_tau)
                        if snap["armed"]:
                            armed += 1
                        warns_total += snap["warn_count"]
                        rows.append((aid, comp, snap))
        rows.sort(key=lambda r: (-r[2]["risk"], r[0], r[1]))
        lead_count = 0
        lead_total = 0.0
        lead_min = 0.0
        lead_max = 0.0
        for aid, comp, snap in sorted(rows, key=lambda r: (r[0], r[1])):
            lead = snap["lead"]
            if lead["count"]:
                if lead_count == 0 or lead["min_seconds"] < lead_min:
                    lead_min = lead["min_seconds"]
                if lead["max_seconds"] > lead_max:
                    lead_max = lead["max_seconds"]
                lead_count += lead["count"]
                lead_total += lead["mean_seconds"] * lead["count"]
        ranked = []
        for aid, comp, snap in rows[:top]:
            row = dict(snap)
            row["agent"] = aid
            row["component"] = comp
            ranked.append(row)
        buckets = {"low": 0, "moderate": 0, "elevated": 0, "critical": 0}
        for _aid, _comp, snap in rows:
            r = snap["risk"]
            if r < 0.25:
                buckets["low"] += 1
            elif r < 0.5:
                buckets["moderate"] += 1
            elif r < 0.75:
                buckets["elevated"] += 1
            else:
                buckets["critical"] += 1
        return {
            "generation": gen,
            "now": now,
            "decay_tau_seconds": decay_tau,
            "agents": agents_with_predict,
            "series": len(rows),
            "armed": armed,
            "warns_total": warns_total,
            "risk_buckets": buckets,
            "lead": {
                "count": lead_count,
                "mean_seconds": (
                    lead_total / lead_count if lead_count else 0.0
                ),
                "min_seconds": lead_min,
                "max_seconds": lead_max,
            },
            "unknown_schema_records": unknown_schema,
            "predict_truncated": truncated,
            "top_k": top,
            "top": ranked,
        }

    def agents_page(self, offset: int = 0, limit: int = 50) -> Dict:
        """One page of per-agent rollups (``GET /v1/fleet/agents``)."""
        offset = max(0, int(offset))
        limit = max(1, min(500, int(limit)))
        return self._cached(
            ("agents", offset, limit),
            lambda: self._compute_agents_page(offset, limit),
            sql=False,
        )

    def _compute_agents_page(self, offset: int, limit: int) -> Dict:
        ids = self.agent_ids()
        page_ids = ids[offset:offset + limit]
        rollups = []
        for aid in page_ids:
            shard = self._shard_for(aid)
            with shard.lock:
                ar = shard.agents.get(aid)
                if ar is None:
                    continue  # raced a rebuild; agents are never removed
                as_of = ar.last_ts
                # predict risk anchored at the agent's own newest record
                # time (a pure function of the journal — pagination stays
                # rebuild-deterministic); the wall-clock staleness decay
                # lives in the fleet_predict ranking pane
                predict = {
                    comp: pr.snapshot(as_of, self.predict_decay)
                    for comp, pr in sorted(ar.predict.items())
                }
                rollups.append({
                    "agent": aid,
                    "last_seq": ar.last_seq,
                    "last_record_ts": ar.last_ts,
                    "last_ingest": ar.last_ingest,
                    "outbox_lag_seconds": ar.outbox_lag_seconds,
                    "records_by_kind": dict(ar.records_by_kind),
                    "remediation_outcomes": dict(ar.remediation_outcomes),
                    "components": {
                        comp: sr.snapshot(as_of)
                        for comp, sr in sorted(ar.series.items())
                    },
                    "predict": predict,
                    "predict_risk": max(
                        (p["risk"] for p in predict.values()), default=0.0
                    ),
                    "predict_unknown_schema": ar.predict_unknown_schema,
                })
        total = len(ids)
        next_offset = offset + len(rollups)
        return {
            "agents": rollups,
            "total": total,
            "offset": offset,
            "limit": limit,
            "next_offset": next_offset if next_offset < total else None,
        }

    def agent_snapshot(self, agent_id: str) -> Optional[Dict]:
        """Uncached single-agent rollup (expectation checks, tests)."""
        shard = self._shard_for(agent_id)
        with shard.lock:
            ar = shard.agents.get(agent_id)
            if ar is None:
                return None
            as_of = ar.last_ts
            return {
                "agent": agent_id,
                "last_seq": ar.last_seq,
                "records_by_kind": dict(ar.records_by_kind),
                "remediation_outcomes": dict(ar.remediation_outcomes),
                "components": {
                    comp: sr.snapshot(as_of)
                    for comp, sr in sorted(ar.series.items())
                },
                "predict": {
                    comp: pr.snapshot(as_of, self.predict_decay)
                    for comp, pr in sorted(ar.predict.items())
                },
                "predict_unknown_schema": ar.predict_unknown_schema,
            }

    def dedupe_snapshot(self, agent_id: str) -> List[str]:
        """The agent's replay-suppression LRU keys, oldest first (tests)."""
        shard = self._shard_for(agent_id)
        with shard.lock:
            seen = shard.dedupe.get(agent_id)
            return list(seen) if seen else []

    def shard_stats(self) -> List[Dict]:
        """Per-shard occupancy/lag snapshot (metrics + bench)."""
        out = []
        for shard in self._shards:
            with shard.lock:
                out.append({
                    "index": shard.index,
                    "agents": len(shard.agents),
                    "series": shard.series_total,
                    "records_total": shard.records_total,
                    "duplicates_total": shard.duplicates_total,
                    "dedupe_keys": sum(len(d) for d in shard.dedupe.values()),
                    "ingest_lag_seconds": shard.ingest_lag,
                    "predict_series": shard.predict_total,
                    "predict_unknown_schema": shard.predict_unknown_total,
                })
        return out

    def history(
        self,
        agent_id: str,
        since: float = 0.0,
        limit: int = 100,
        offset: int = 0,
    ) -> Dict:
        """Journaled record timeline for one agent
        (``GET /v1/fleet/agents/{id}/history``), newest first."""
        since = float(since)
        limit = max(1, min(1000, int(limit)))
        offset = max(0, int(offset))
        return self._cached(
            ("history", agent_id, since, limit, offset),
            lambda: self._compute_history(agent_id, since, limit, offset),
        )

    def _compute_history(
        self, agent_id: str, since: float, limit: int, offset: int
    ) -> Dict:
        total_row = self.db.query_one(
            f"SELECT COUNT(*) FROM {TABLE} WHERE agent = ? AND ts >= ?",
            (agent_id, since),
        )
        rows = self.db.query(
            f"SELECT seq, ts, ingested, kind, dedupe_key, correlation_id, "
            f"payload FROM {TABLE} WHERE agent = ? AND ts >= ? "
            f"ORDER BY ts DESC, seq DESC LIMIT ? OFFSET ?",
            (agent_id, since, limit, offset),
        )
        records = [_record_dict(r) for r in rows]
        total = int(total_row[0]) if total_row else 0
        next_offset = offset + len(records)
        return {
            "agent": agent_id,
            "records": records,
            "total": total,
            "offset": offset,
            "limit": limit,
            "next_offset": next_offset if next_offset < total else None,
        }

    def traces(self, correlation_id: str, limit: int = 200) -> Dict:
        """Every journaled fleet record stitched to one agent-side check
        trace (``GET /v1/fleet/traces?correlation_id=``)."""
        correlation_id = str(correlation_id)
        limit = max(1, min(1000, int(limit)))
        return self._cached(
            ("traces", correlation_id, limit),
            lambda: self._compute_traces(correlation_id, limit),
        )

    def _compute_traces(self, correlation_id: str, limit: int) -> Dict:
        rows = self.db.query(
            f"SELECT agent, seq, ts, ingested, kind, dedupe_key, "
            f"correlation_id, payload FROM {TABLE} "
            f"WHERE correlation_id = ? ORDER BY ts, seq LIMIT ?",
            (correlation_id, limit),
        )
        records = []
        for r in rows:
            d = _record_dict(r[1:])
            d["agent"] = r[0]
            records.append(d)
        return {
            "correlation_id": correlation_id,
            "records": records,
            "count": len(records),
        }

    # -- maintenance -------------------------------------------------------
    def purge(self) -> int:
        """Bound the journal: delete the oldest rows past
        ``max_journal_rows``. Rollups are NOT rebuilt — they summarize
        all history ever ingested; the journal bound only caps what a
        rebuild can recover (documented in docs/fleet.md)."""
        self._barrier()
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        total = int(row[0]) if row else 0
        excess = total - self.max_journal_rows
        if excess <= 0:
            return 0
        self.db.execute(
            f"DELETE FROM {TABLE} WHERE rowid IN "
            f"(SELECT rowid FROM {TABLE} ORDER BY ts, seq LIMIT ?)",
            (excess,),
        )
        logger.info("fleet journal purged %d rows (cap %d)",
                    excess, self.max_journal_rows)
        return excess

    def journal_count(self) -> int:
        self._barrier()
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        return int(row[0]) if row else 0

    def records_total(self) -> int:
        return sum(s.records_total for s in self._shards)

    def duplicates_total(self) -> int:
        return sum(s.duplicates_total for s in self._shards)

    def agent_ids(self) -> List[str]:
        ids: List[str] = []
        for shard in self._shards:
            with shard.lock:
                ids.extend(shard.agents)
        ids.sort()
        return ids


def _record_dict(row) -> Dict:
    seq, ts, ingested, kind, key, cid, payload = row
    return {
        "seq": seq,
        "ts": ts,
        "ingested": ingested,
        "kind": kind,
        "dedupe_key": key,
        "correlation_id": cid,
        "payload": wire.unpack_obj(payload) if payload is not None else None,
    }
