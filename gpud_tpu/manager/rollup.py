"""Fleet rollup store: the manager-side aggregation layer.

Every observability surface below this file is per-node (ledger,
remediation audit, trace ring, outbox). The manager ingests all of it
but — before this store — only kept a bounded in-memory record buffer
per agent, so it could not answer a single fleet-level question
("which nodes flapped this week", "fleet availability", "MTTR across
the pod"). PAPERS.md ("Host-Side Telemetry", "When GPUs Fail Quietly")
argues diagnosis lives at the aggregation layer: the signals that
matter are cross-node patterns invisible to any one agent.

Design:

- **Durable journal, derived rollups.** Every ingested outbox record
  lands in one append-only journal table via the PR-7 ``BatchWriter``
  (group commit; ``INSERT OR IGNORE`` on ``UNIQUE(agent, dedupe_key)``
  makes replay after reconnect idempotent at the storage layer). The
  per-agent/per-component rollups (availability, MTTR/MTBF, flap
  counts, transition cadence, remediation outcomes, outbox lag) are
  *derived* state: incrementally updated in memory on ingest and
  rebuilt from the journal at construction — a SIGKILL can lose at
  most the writer's durability window and can never tear an aggregate,
  because aggregates are never persisted, only recomputed.
- **Read-your-own-writes.** Every read path runs the writer's
  ``flush()`` barrier before touching SQLite, so batching is invisible
  to operators.
- **TTL + generation cache.** Rollup/pagination responses are cached
  per query-shape. An entry is served only while its TTL holds AND no
  ingest has advanced the store generation — writes invalidate
  immediately (read-after-write), the TTL bounds entry lifetime when
  the fleet is quiet.
- **Correlation stitching.** Records whose payload carries a
  ``correlation_id`` (minted by the agent's check wrapper and stamped
  on its trace span) are indexed by it, so one id resolves to every
  fleet event the originating check produced.
"""

from __future__ import annotations

import threading
import time
from collections import Counter as _Counter
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram
from gpud_tpu.session import wire

logger = get_logger(__name__)

TABLE = "tpud_fleet_journal_v0_1"

# storage_lint contract: these methods route their hot-path persistence
# through the BatchWriter (sync DB fallback only under a writer guard)
HOT_WRITE_METHODS = ("ingest",)

DEFAULT_CACHE_TTL = 2.0          # seconds a cached read stays servable
DEFAULT_DEDUPE_KEYS = 8192       # per-agent in-memory replay suppression
DEFAULT_RECENT_TRANSITIONS = 64  # per-series window for flap/cadence
DEFAULT_FLAP_WINDOW = 3600.0     # seconds a transition counts as a flap
DEFAULT_MAX_JOURNAL_ROWS = 500_000

_INSERT_SQL = (
    f"INSERT OR IGNORE INTO {TABLE} "
    "(agent, seq, ts, ingested, kind, dedupe_key, correlation_id, payload) "
    "VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
)

_c_records = counter(
    "tpud_fleet_ingest_records_total",
    "outbox records accepted into the fleet journal, by kind",
)
_c_duplicates = counter(
    "tpud_fleet_ingest_duplicates_total",
    "replayed outbox records suppressed by fleet ingest dedupe",
)
_g_ingest_lag = gauge(
    "tpud_fleet_ingest_lag_seconds",
    "age of the most recently ingested outbox record "
    "(manager wall clock minus record timestamp)",
)
_h_refresh = histogram(
    "tpud_fleet_rollup_refresh_seconds",
    "wall time to materialize one fleet rollup response (cache miss path)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5),
)
_c_cache_hits = counter(
    "tpud_fleet_cache_hits_total",
    "fleet operator-API reads served from the TTL cache",
)
_c_cache_misses = counter(
    "tpud_fleet_cache_misses_total",
    "fleet operator-API reads that had to materialize (barrier + compute)",
)
_g_agents = gauge(
    "tpud_fleet_agents",
    "agents with at least one journaled record in the fleet rollup store",
)
_g_series = gauge(
    "tpud_fleet_agent_series",
    "distinct (agent, component) rollup series held in memory",
)


class _SeriesRollup:
    """Incremental per-(agent, component) health rollup."""

    __slots__ = (
        "state", "since", "first_ts", "last_ts", "transitions",
        "healthy_seconds", "unhealthy_seconds",
        "repair_total", "repair_count",
        "tbf_total", "tbf_count", "last_failure_ts", "failures",
        "recent",
    )

    def __init__(self) -> None:
        self.state = ""
        self.since = 0.0
        self.first_ts = 0.0
        self.last_ts = 0.0
        self.transitions = 0
        self.healthy_seconds = 0.0
        self.unhealthy_seconds = 0.0
        self.repair_total = 0.0     # completed unhealthy-episode downtime
        self.repair_count = 0
        self.tbf_total = 0.0        # gaps between consecutive failures
        self.tbf_count = 0
        self.last_failure_ts = 0.0
        self.failures = 0
        self.recent: deque = deque(maxlen=DEFAULT_RECENT_TRANSITIONS)

    def apply(self, from_state: str, to_state: str, ts: float) -> None:
        if not self.first_ts:
            self.first_ts = ts
        # close the open interval in the previous state
        if self.state and ts > self.since:
            dt = ts - self.since
            if self.state == "Healthy":
                self.healthy_seconds += dt
            else:
                self.unhealthy_seconds += dt
        prev = self.state or from_state
        self.transitions += 1
        self.recent.append(ts)
        if to_state != "Healthy" and (not prev or prev == "Healthy"):
            self.failures += 1
            if self.last_failure_ts:
                self.tbf_total += ts - self.last_failure_ts
                self.tbf_count += 1
            self.last_failure_ts = ts
        if to_state == "Healthy" and prev and prev != "Healthy" and self.since:
            self.repair_total += max(0.0, ts - self.since)
            self.repair_count += 1
        self.state = to_state
        self.since = ts
        if ts > self.last_ts:
            self.last_ts = ts

    def snapshot(self, as_of: float) -> Dict:
        healthy = self.healthy_seconds
        unhealthy = self.unhealthy_seconds
        # count the open interval up to the newest timestamp we trust
        if self.state and as_of > self.since:
            if self.state == "Healthy":
                healthy += as_of - self.since
            else:
                unhealthy += as_of - self.since
        total = healthy + unhealthy
        flap_cutoff = as_of - DEFAULT_FLAP_WINDOW
        flaps = sum(1 for t in self.recent if t >= flap_cutoff)
        cadence = 0.0
        if len(self.recent) >= 2:
            span = self.recent[-1] - self.recent[0]
            if span > 0:
                cadence = span / (len(self.recent) - 1)
        return {
            "state": self.state,
            "since": self.since,
            "transitions": self.transitions,
            "availability": (healthy / total) if total > 0 else 1.0,
            "healthy_seconds": healthy,
            "unhealthy_seconds": unhealthy,
            "mttr_seconds": (
                self.repair_total / self.repair_count if self.repair_count else 0.0
            ),
            "mtbf_seconds": (
                self.tbf_total / self.tbf_count if self.tbf_count else 0.0
            ),
            "failures": self.failures,
            "flap_count": flaps,
            "transition_cadence_seconds": cadence,
        }


class _AgentRollup:
    """Per-agent aggregate over everything that agent's outbox shipped."""

    __slots__ = (
        "records_by_kind", "last_seq", "last_ts", "last_ingest",
        "outbox_lag_seconds", "remediation_outcomes", "series",
    )

    def __init__(self) -> None:
        self.records_by_kind: _Counter = _Counter()
        self.last_seq = 0
        self.last_ts = 0.0
        self.last_ingest = 0.0
        self.outbox_lag_seconds = 0.0
        self.remediation_outcomes: _Counter = _Counter()
        self.series: Dict[str, _SeriesRollup] = {}


class FleetRollupStore:
    """Manager-side fleet journal + materialized rollups (module docstring).

    Thread-safe: ``ingest`` may be called from any agent connection's
    reader thread; reads run on the operator pool. The in-memory state
    is guarded by one lock; SQLite work happens outside it.
    """

    def __init__(
        self,
        db,
        writer=None,
        cache_ttl_seconds: float = DEFAULT_CACHE_TTL,
        dedupe_keys_max: int = DEFAULT_DEDUPE_KEYS,
        max_journal_rows: int = DEFAULT_MAX_JOURNAL_ROWS,
    ) -> None:
        self.db = db
        self.writer = writer
        self.cache_ttl = float(cache_ttl_seconds)
        self.dedupe_keys_max = int(dedupe_keys_max)
        self.max_journal_rows = int(max_journal_rows)
        self._lock = threading.Lock()
        self._agents: Dict[str, _AgentRollup] = {}
        self._dedupe: Dict[str, OrderedDict] = {}
        self._generation = 0
        self._records_total = 0
        self._duplicates_total = 0
        # cache key -> (generation, monotonic deadline, value)
        self._cache: Dict[tuple, tuple] = {}
        self._cache_hits = 0
        self._cache_misses = 0
        self._ensure_schema()
        self._rebuild()

    # -- schema / rebuild --------------------------------------------------
    def _ensure_schema(self) -> None:
        self.db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                agent          TEXT NOT NULL,
                seq            INTEGER NOT NULL,
                ts             REAL NOT NULL,
                ingested       REAL NOT NULL,
                kind           TEXT NOT NULL,
                dedupe_key     TEXT NOT NULL,
                correlation_id TEXT NOT NULL DEFAULT '',
                payload        BLOB,
                UNIQUE (agent, dedupe_key)
            )"""
        )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_agent_ts "
            f"ON {TABLE} (agent, ts)"
        )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fleet_correlation "
            f"ON {TABLE} (correlation_id) WHERE correlation_id != ''"
        )

    def _rebuild(self) -> None:
        """Recompute every rollup from the journal (boot / crash recovery).

        The journal is the only durable state; aggregates are a pure
        function of it, so a SIGKILL between group commits can shorten
        the journal but never tear a rollup."""
        rows = self.db.query(
            f"SELECT agent, seq, ts, ingested, kind, dedupe_key, payload "
            f"FROM {TABLE} ORDER BY agent, ts, seq"
        )
        with self._lock:
            self._agents.clear()
            self._dedupe.clear()
            self._records_total = 0
            for agent, seq, ts, ingested, kind, key, payload in rows:
                # reseed the replay-suppression LRU: after a restart agents
                # replay journaled-but-unacked records, and the DB's INSERT
                # OR IGNORE alone would let them double-count the in-memory
                # aggregates. Rows arrive oldest-first per agent, so LRU
                # eviction keeps the newest keys — the ones replays carry.
                seen = self._dedupe.get(agent)
                if seen is None:
                    seen = self._dedupe[agent] = OrderedDict()
                seen[key] = None
                while len(seen) > self.dedupe_keys_max:
                    seen.popitem(last=False)
                body = wire.unpack_obj(payload) if payload is not None else {}
                self._apply_locked(agent, seq, ts, ingested, kind, key, body)
            self._generation += 1
            self._cache.clear()
            self._update_gauges_locked()
        if rows:
            logger.info(
                "fleet rollup store rebuilt from journal: %d records, "
                "%d agents", len(rows), len(self._agents),
            )

    # -- ingest ------------------------------------------------------------
    def ingest(
        self,
        agent_id: str,
        records: Iterable[Tuple[int, float, str, str, object]],
        now: Optional[float] = None,
    ) -> int:
        """Journal + roll up a batch of decoded outbox records.

        ``records`` is the decoder's output shape: ``(seq, ts, kind,
        dedupe_key, payload)`` tuples. Replays are suppressed twice —
        a bounded per-agent key LRU here (protects the in-memory
        aggregates) and ``INSERT OR IGNORE`` in the journal (protects
        durable state even past the LRU window). Returns the number of
        fresh records applied."""
        wall = time.time() if now is None else now
        rows: List[tuple] = []
        fresh: List[tuple] = []
        with self._lock:
            seen = self._dedupe.get(agent_id)
            if seen is None:
                seen = self._dedupe[agent_id] = OrderedDict()
            for seq, ts, kind, key, payload in records:
                key = key or f"seq:{seq}"
                if key in seen:
                    seen.move_to_end(key)
                    self._duplicates_total += 1
                    _c_duplicates.inc()
                    continue
                seen[key] = None
                while len(seen) > self.dedupe_keys_max:
                    seen.popitem(last=False)
                body = payload if isinstance(payload, dict) else {}
                cid = str(body.get("correlation_id", "") or "")
                rows.append(
                    (agent_id, seq, ts, wall, kind, key, cid,
                     wire.pack_obj(payload))
                )
                fresh.append((seq, ts, kind, key, body))
            for seq, ts, kind, key, body in fresh:
                self._apply_locked(agent_id, seq, ts, wall, kind, key, body)
            if fresh:
                self._generation += 1
                self._update_gauges_locked()
        if not rows:
            return 0
        if self.writer is not None:
            self.writer.submit_many("fleet", _INSERT_SQL, rows)
        else:
            self.db.executemany(_INSERT_SQL, rows)
        for _, ts, kind, _, _ in fresh:
            _c_records.inc(labels={"kind": kind})
        _g_ingest_lag.set(max(0.0, wall - fresh[-1][1]))
        return len(fresh)

    def _apply_locked(
        self, agent_id: str, seq: int, ts: float, ingested: float,
        kind: str, key: str, body: Dict,
    ) -> None:
        ar = self._agents.get(agent_id)
        if ar is None:
            ar = self._agents[agent_id] = _AgentRollup()
        ar.records_by_kind[kind] += 1
        self._records_total += 1
        if seq > ar.last_seq:
            ar.last_seq = seq
        if ts >= ar.last_ts:
            # lag is anchored to the newest record by *record* time, so a
            # replayed old record can't make a caught-up agent look laggy
            ar.last_ts = ts
            ar.outbox_lag_seconds = max(0.0, ingested - ts)
        if ingested > ar.last_ingest:
            ar.last_ingest = ingested
        if kind == "transition":
            comp = str(body.get("component", "") or "_unknown")
            sr = ar.series.get(comp)
            if sr is None:
                sr = ar.series[comp] = _SeriesRollup()
            sr.apply(
                str(body.get("from", "") or ""),
                str(body.get("to", "") or ""),
                float(body.get("ts", ts) or ts),
            )
        elif kind == "remediation_audit":
            ar.remediation_outcomes[str(body.get("outcome", "") or "unknown")] += 1

    def _update_gauges_locked(self) -> None:
        _g_agents.set(len(self._agents))
        _g_series.set(sum(len(a.series) for a in self._agents.values()))

    # -- cache plumbing ----------------------------------------------------
    def _barrier(self) -> None:
        if self.writer is not None:
            self.writer.flush()

    def _cached(self, key: tuple, compute) -> object:
        now = time.monotonic()
        with self._lock:
            ent = self._cache.get(key)
            if ent is not None and ent[0] == self._generation and now < ent[1]:
                self._cache_hits += 1
                _c_cache_hits.inc()
                return ent[2]
            gen = self._generation
            self._cache_misses += 1
        _c_cache_misses.inc()
        # miss path: barrier first so SQLite-backed computations see
        # every record journaled before this read began
        self._barrier()
        with _h_refresh.time():
            value = compute()
        with self._lock:
            # only cache what was computed against the still-current
            # generation — an ingest racing the compute wins
            if gen == self._generation:
                self._cache[key] = (gen, time.monotonic() + self.cache_ttl, value)
        return value

    def invalidate_cache(self) -> None:
        with self._lock:
            self._cache.clear()
            self._generation += 1

    def cache_stats(self) -> Dict:
        with self._lock:
            return {
                "hits": self._cache_hits,
                "misses": self._cache_misses,
                "entries": len(self._cache),
                "generation": self._generation,
            }

    # -- read paths --------------------------------------------------------
    def fleet_rollup(self) -> Dict:
        """Fleet-wide aggregates (``GET /v1/fleet/rollup``)."""
        return self._cached(("rollup",), self._compute_fleet_rollup)

    def _compute_fleet_rollup(self) -> Dict:
        by_kind: _Counter = _Counter()
        remediation: _Counter = _Counter()
        transitions = 0
        failures = 0
        repair_total = 0.0
        repair_count = 0
        tbf_total = 0.0
        tbf_count = 0
        healthy = 0.0
        unhealthy = 0.0
        series = 0
        unhealthy_now = 0
        flapping: List[Dict] = []
        max_lag = 0.0
        # hold the lock for the whole walk: per-series dicts and deques
        # mutate under it on ingest, so iterating a shallow snapshot
        # outside would race (RuntimeError mid-iteration, torn sums)
        with self._lock:
            gen = self._generation
            records_total = self._records_total
            duplicates = self._duplicates_total
            agent_count = len(self._agents)
            for aid, ar in sorted(self._agents.items()):
                by_kind.update(ar.records_by_kind)
                remediation.update(ar.remediation_outcomes)
                max_lag = max(max_lag, ar.outbox_lag_seconds)
                as_of = ar.last_ts
                for comp, sr in sorted(ar.series.items()):
                    series += 1
                    snap = sr.snapshot(as_of)
                    transitions += sr.transitions
                    failures += sr.failures
                    repair_total += sr.repair_total
                    repair_count += sr.repair_count
                    tbf_total += sr.tbf_total
                    tbf_count += sr.tbf_count
                    healthy += snap["healthy_seconds"]
                    unhealthy += snap["unhealthy_seconds"]
                    if snap["state"] and snap["state"] != "Healthy":
                        unhealthy_now += 1
                    if snap["flap_count"] >= 3:
                        flapping.append(
                            {"agent": aid, "component": comp,
                             "flap_count": snap["flap_count"]}
                        )
        flapping.sort(key=lambda f: -f["flap_count"])
        observed = healthy + unhealthy
        return {
            "generation": gen,
            "agents": agent_count,
            "series": series,
            "records_total": records_total,
            "records_by_kind": dict(by_kind),
            "duplicates_suppressed": duplicates,
            "transitions_total": transitions,
            "failures_total": failures,
            "unhealthy_series": unhealthy_now,
            "availability": (healthy / observed) if observed > 0 else 1.0,
            "mttr_seconds": (repair_total / repair_count) if repair_count else 0.0,
            "mtbf_seconds": (tbf_total / tbf_count) if tbf_count else 0.0,
            "remediation_outcomes": dict(remediation),
            "flapping": flapping[:32],
            "max_outbox_lag_seconds": max_lag,
        }

    def agents_page(self, offset: int = 0, limit: int = 50) -> Dict:
        """One page of per-agent rollups (``GET /v1/fleet/agents``)."""
        offset = max(0, int(offset))
        limit = max(1, min(500, int(limit)))
        return self._cached(
            ("agents", offset, limit),
            lambda: self._compute_agents_page(offset, limit),
        )

    def _compute_agents_page(self, offset: int, limit: int) -> Dict:
        with self._lock:
            ids = sorted(self._agents)
            page_ids = ids[offset:offset + limit]
            rollups = []
            for aid in page_ids:
                ar = self._agents[aid]
                as_of = ar.last_ts
                rollups.append({
                    "agent": aid,
                    "last_seq": ar.last_seq,
                    "last_record_ts": ar.last_ts,
                    "last_ingest": ar.last_ingest,
                    "outbox_lag_seconds": ar.outbox_lag_seconds,
                    "records_by_kind": dict(ar.records_by_kind),
                    "remediation_outcomes": dict(ar.remediation_outcomes),
                    "components": {
                        comp: sr.snapshot(as_of)
                        for comp, sr in sorted(ar.series.items())
                    },
                })
            total = len(ids)
        next_offset = offset + len(rollups)
        return {
            "agents": rollups,
            "total": total,
            "offset": offset,
            "limit": limit,
            "next_offset": next_offset if next_offset < total else None,
        }

    def agent_snapshot(self, agent_id: str) -> Optional[Dict]:
        """Uncached single-agent rollup (expectation checks, tests)."""
        with self._lock:
            ar = self._agents.get(agent_id)
            if ar is None:
                return None
            as_of = ar.last_ts
            return {
                "agent": agent_id,
                "last_seq": ar.last_seq,
                "records_by_kind": dict(ar.records_by_kind),
                "remediation_outcomes": dict(ar.remediation_outcomes),
                "components": {
                    comp: sr.snapshot(as_of)
                    for comp, sr in sorted(ar.series.items())
                },
            }

    def history(
        self,
        agent_id: str,
        since: float = 0.0,
        limit: int = 100,
        offset: int = 0,
    ) -> Dict:
        """Journaled record timeline for one agent
        (``GET /v1/fleet/agents/{id}/history``), newest first."""
        since = float(since)
        limit = max(1, min(1000, int(limit)))
        offset = max(0, int(offset))
        return self._cached(
            ("history", agent_id, since, limit, offset),
            lambda: self._compute_history(agent_id, since, limit, offset),
        )

    def _compute_history(
        self, agent_id: str, since: float, limit: int, offset: int
    ) -> Dict:
        total_row = self.db.query_one(
            f"SELECT COUNT(*) FROM {TABLE} WHERE agent = ? AND ts >= ?",
            (agent_id, since),
        )
        rows = self.db.query(
            f"SELECT seq, ts, ingested, kind, dedupe_key, correlation_id, "
            f"payload FROM {TABLE} WHERE agent = ? AND ts >= ? "
            f"ORDER BY ts DESC, seq DESC LIMIT ? OFFSET ?",
            (agent_id, since, limit, offset),
        )
        records = [_record_dict(r) for r in rows]
        total = int(total_row[0]) if total_row else 0
        next_offset = offset + len(records)
        return {
            "agent": agent_id,
            "records": records,
            "total": total,
            "offset": offset,
            "limit": limit,
            "next_offset": next_offset if next_offset < total else None,
        }

    def traces(self, correlation_id: str, limit: int = 200) -> Dict:
        """Every journaled fleet record stitched to one agent-side check
        trace (``GET /v1/fleet/traces?correlation_id=``)."""
        correlation_id = str(correlation_id)
        limit = max(1, min(1000, int(limit)))
        return self._cached(
            ("traces", correlation_id, limit),
            lambda: self._compute_traces(correlation_id, limit),
        )

    def _compute_traces(self, correlation_id: str, limit: int) -> Dict:
        rows = self.db.query(
            f"SELECT agent, seq, ts, ingested, kind, dedupe_key, "
            f"correlation_id, payload FROM {TABLE} "
            f"WHERE correlation_id = ? ORDER BY ts, seq LIMIT ?",
            (correlation_id, limit),
        )
        records = []
        for r in rows:
            d = _record_dict(r[1:])
            d["agent"] = r[0]
            records.append(d)
        return {
            "correlation_id": correlation_id,
            "records": records,
            "count": len(records),
        }

    # -- maintenance -------------------------------------------------------
    def purge(self) -> int:
        """Bound the journal: delete the oldest rows past
        ``max_journal_rows``. Rollups are NOT rebuilt — they summarize
        all history ever ingested; the journal bound only caps what a
        rebuild can recover (documented in docs/fleet.md)."""
        self._barrier()
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        total = int(row[0]) if row else 0
        excess = total - self.max_journal_rows
        if excess <= 0:
            return 0
        self.db.execute(
            f"DELETE FROM {TABLE} WHERE rowid IN "
            f"(SELECT rowid FROM {TABLE} ORDER BY ts, seq LIMIT ?)",
            (excess,),
        )
        logger.info("fleet journal purged %d rows (cap %d)",
                    excess, self.max_journal_rows)
        return excess

    def journal_count(self) -> int:
        self._barrier()
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        return int(row[0]) if row else 0

    def records_total(self) -> int:
        with self._lock:
            return self._records_total

    def agent_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._agents)


def _record_dict(row) -> Dict:
    seq, ts, ingested, kind, key, cid, payload = row
    return {
        "seq": seq,
        "ts": ts,
        "ingested": ingested,
        "kind": kind,
        "dedupe_key": key,
        "correlation_id": cid,
        "payload": wire.unpack_obj(payload) if payload is not None else None,
    }
