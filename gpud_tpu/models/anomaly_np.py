"""NumPy twin of :func:`gpud_tpu.models.anomaly.robust_scores`.

The daemon's anomaly component scores telemetry windows every poll. On a
monitoring host the window is tiny (a handful of chips × ≤3h of minutes),
and importing jax inflates the daemon RSS well past the <150 MB footprint
target (BASELINE.md) — so the product path scores with this twin by
default and switches to the JAX implementation only when jax is already
resident or explicitly requested (TPUD_ANALYTICS_BACKEND=jax), e.g. for
fleet-scale batched scoring on the accelerator (parallel/fleet.py).

Semantics are kept bit-comparable with the JAX version (float32 EWMA,
median/MAD normalization, mean of top-k residuals); tests assert parity.
"""

from __future__ import annotations

import numpy as np


def robust_scores_np(windows, alpha: float = 0.3) -> np.ndarray:
    """Per-chip anomaly score from telemetry windows.

    Args:
      windows: [C, T, F] float — per-chip, per-step feature matrix.
    Returns:
      [C] float32 — 0 ≈ nominal; >3 ≈ a feature is running away from its
      own recent behavior.
    """
    x = np.asarray(windows, dtype=np.float32)
    if x.ndim != 3:
        raise ValueError(f"windows must be [C, T, F], got shape {x.shape}")
    _, T, _ = x.shape
    if T < 2:
        return np.zeros((x.shape[0],), dtype=np.float32)

    # EWMA one-step forecast along time, initialized at the first sample
    ewma = np.empty_like(x)
    ewma[:, 0, :] = x[:, 0, :]
    for t in range(1, T):
        ewma[:, t, :] = (1.0 - alpha) * ewma[:, t - 1, :] + alpha * x[:, t, :]
    resid = x[:, 1:, :] - ewma[:, :-1, :]

    # robust scale per chip/feature: median absolute deviation, floored
    # relative to the signal magnitude so near-constant features (fixed
    # clock, HBM total) don't turn LSB jitter into huge z-scores
    med = np.median(resid, axis=1, keepdims=True)
    mad = np.median(np.abs(resid - med), axis=1, keepdims=True)
    xmag = np.median(np.abs(x), axis=1, keepdims=True)
    scale = 1.4826 * mad + 1e-3 * (1.0 + xmag)
    z = np.abs(resid - med) / scale

    # score: mean of the top-k residual steps per chip (persistent
    # deviation, not single spikes)
    k = max(1, resid.shape[1] // 8)
    worst = z.max(axis=2)  # [C, T-1]
    top = np.sort(worst, axis=1)[:, -k:]
    return top.mean(axis=1).astype(np.float32)
