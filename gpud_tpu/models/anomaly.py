"""Telemetry anomaly models (JAX) — the daemon's on-accelerator analytics.

Two models over per-chip telemetry windows ``[chips, T, F]`` (features:
temp, hbm_temp, power, hbm_used_frac, duty_cycle, util, clock, ...):

1. ``robust_scores`` — deterministic statistical scorer: EWMA forecast
   residuals normalized by a median/MAD robust scale, reduced to a per-chip
   anomaly score. No parameters, jittable, bfloat16-friendly.

2. ``TelemetryAutoencoder`` — a small MLP autoencoder whose reconstruction
   error flags multivariate anomalies. Written with pure jax (init/apply
   functions returning pytrees) so the training step can be pjit-sharded:
   batch axis → data parallelism, hidden axis → tensor parallelism (see
   gpud_tpu/parallel/fleet.py). Matmuls run in bfloat16 on the MXU with
   float32 accumulation.

This is the analytics slot of the daemon (fleet-side trend detection,
"which chip is drifting hot before it trips"), not its control path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

N_FEATURES = 8


# ---------------------------------------------------------------------------
# 1. Deterministic robust scorer
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("alpha",))
def robust_scores(windows: jax.Array, alpha: float = 0.3) -> jax.Array:
    """Per-chip anomaly score from telemetry windows.

    Args:
      windows: [C, T, F] float — per-chip, per-step feature matrix.
    Returns:
      [C] float32 — 0 ≈ nominal; >3 ≈ a feature is running away from its
      own recent behavior.
    """
    x = windows.astype(jnp.float32)

    # EWMA one-step forecast along time via an associative scan
    # (lax.associative_scan keeps it a single fused pass on device)
    def ewma_combine(a, b):
        # elements are (decay, value): compose affine maps
        da, va = a
        db, vb = b
        return da * db, vb + db * va

    T = x.shape[1]
    decays = jnp.full((T,), 1.0 - alpha, dtype=jnp.float32)
    contribs = alpha * x
    # initialize the filter at the first sample (decay_0=0, contrib_0=x_0):
    # without this every chip shows a huge startup residual from s_0=0
    decays = decays.at[0].set(0.0)
    contribs = contribs.at[:, 0, :].set(x[:, 0, :])
    d, sm = jax.lax.associative_scan(
        ewma_combine,
        (
            jnp.broadcast_to(decays[None, :, None], x.shape),
            contribs,
        ),
        axis=1,
    )
    ewma = sm  # [C, T, F]
    resid = x[:, 1:, :] - ewma[:, :-1, :]  # one-step-ahead residuals

    # robust scale per chip/feature: median absolute deviation, floored
    # relative to the signal magnitude so near-constant features (fixed
    # clock, HBM total) don't turn LSB jitter into huge z-scores
    med = jnp.median(resid, axis=1, keepdims=True)
    mad = jnp.median(jnp.abs(resid - med), axis=1, keepdims=True)
    xmag = jnp.median(jnp.abs(x), axis=1, keepdims=True)
    scale = 1.4826 * mad + 1e-3 * (1.0 + xmag)
    z = jnp.abs(resid - med) / scale

    # score: mean of the top-k residuals per chip (persistent deviation,
    # not single spikes)
    k = max(1, resid.shape[1] // 8)
    top = jax.lax.top_k(z.max(axis=2), k)[0]  # [C, k] worst steps
    return jnp.mean(top, axis=1)


# ---------------------------------------------------------------------------
# 2. MLP autoencoder (pure-jax, shardable)
# ---------------------------------------------------------------------------

class AEParams(NamedTuple):
    w_enc: jax.Array  # [F*T, H]
    b_enc: jax.Array  # [H]
    w_lat: jax.Array  # [H, Z]
    b_lat: jax.Array  # [Z]
    w_dec1: jax.Array  # [Z, H]
    b_dec1: jax.Array  # [H]
    w_dec2: jax.Array  # [H, F*T]
    b_dec2: jax.Array  # [F*T]


class AEConfig(NamedTuple):
    window: int = 16
    features: int = N_FEATURES
    hidden: int = 256
    latent: int = 32

    @property
    def input_dim(self) -> int:
        return self.window * self.features


def ae_init(key: jax.Array, cfg: AEConfig) -> AEParams:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    d, h, z = cfg.input_dim, cfg.hidden, cfg.latent

    def glorot(k, shape):
        fan_in, fan_out = shape
        s = jnp.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(k, shape, dtype=jnp.float32) * s

    return AEParams(
        w_enc=glorot(k1, (d, h)),
        b_enc=jnp.zeros((h,), jnp.float32),
        w_lat=glorot(k2, (h, z)),
        b_lat=jnp.zeros((z,), jnp.float32),
        w_dec1=glorot(k3, (z, h)),
        b_dec1=jnp.zeros((h,), jnp.float32),
        w_dec2=glorot(k4, (h, d)),
        b_dec2=jnp.zeros((d,), jnp.float32),
    )


def ae_apply(params: AEParams, x: jax.Array) -> jax.Array:
    """x: [B, F*T] → reconstruction [B, F*T]. Matmuls in bf16 on the MXU,
    accumulation in f32 (preferred_element_type)."""

    def mm(a, w):
        return jax.lax.dot_general(
            a.astype(jnp.bfloat16),
            w.astype(jnp.bfloat16),
            (((a.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    h = jax.nn.gelu(mm(x, params.w_enc) + params.b_enc)
    zl = mm(h, params.w_lat) + params.b_lat
    h2 = jax.nn.gelu(mm(zl, params.w_dec1) + params.b_dec1)
    out = mm(h2, params.w_dec2) + params.b_dec2
    return out


def ae_loss(params: AEParams, batch: jax.Array) -> jax.Array:
    recon = ae_apply(params, batch)
    return jnp.mean(jnp.square(recon - batch))


@jax.jit
def ae_scores(params: AEParams, batch: jax.Array) -> jax.Array:
    """Per-sample reconstruction error — the anomaly score."""
    recon = ae_apply(params, batch)
    return jnp.mean(jnp.square(recon - batch), axis=-1)


@functools.partial(jax.jit, static_argnames=("lr",))
def ae_train_step(
    params: AEParams, batch: jax.Array, lr: float = 1e-3
) -> Tuple[AEParams, jax.Array]:
    """One SGD step; grads are averaged implicitly when pjit shards the
    batch axis (XLA inserts the psum from the sharding annotations — we do
    not hand-write collectives, per the scaling-book recipe)."""
    loss, grads = jax.value_and_grad(ae_loss)(params, batch)
    new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return new_params, loss


def windows_to_batch(windows: jax.Array) -> jax.Array:
    """[C, T, F] → [C, T*F] flattened samples for the autoencoder."""
    c = windows.shape[0]
    return windows.reshape(c, -1).astype(jnp.float32)
