"""Daemon configuration.

Mirrors the reference's config surface (reference: pkg/config/config.go:17-130,
pkg/config/default.go:15-34,137-157): defaults of port 15132 (we keep the same
port so tooling carries over), data dir /var/lib/tpud (or ~/.tpud when not
root), metrics retention 3h, events retention 14d, compact disabled.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

DEFAULT_PORT = 15132                     # reference: pkg/config/default.go
DEFAULT_METRICS_RETENTION = 3 * 3600     # 3h  (reference: default.go:26)
DEFAULT_EVENTS_RETENTION = 14 * 86400    # 14d (reference: default.go:28)
DEFAULT_POLL_INTERVAL = 60               # 1m component cadence
DEFAULT_SCRAPE_INTERVAL = 60             # 1m metrics syncer
DEFAULT_RECORDER_INTERVAL = 15 * 60      # 15m self-metrics recorder
DEFAULT_SESSION_PIPE_INTERVAL = 3        # 3s (reference: server.go:616)
DEFAULT_HEALTH_FLAP_THRESHOLD = 5        # transitions within the flap window
DEFAULT_HEALTH_FLAP_WINDOW = 600         # 10m flap-detection window
DEFAULT_HEALTH_AVAILABILITY_WINDOW = 3600  # 1h rolling availability window
DEFAULT_REMEDIATION_INTERVAL = 30        # remediation scan cadence
DEFAULT_REMEDIATION_COOLDOWN = 300       # per-component attempt cooldown
DEFAULT_REMEDIATION_RATE_CAPACITY = 6    # token-bucket burst
DEFAULT_REMEDIATION_RATE_REFILL = 600    # one token back per 10m
DEFAULT_REMEDIATION_MAX_REBOOTS = 2      # reboots allowed inside the window
DEFAULT_REMEDIATION_REBOOT_WINDOW = 3600
DEFAULT_REMEDIATION_ESCALATION_THRESHOLD = 3  # failed soft repairs => escalate
DEFAULT_REMEDIATION_ESCALATION_WINDOW = 3600
# predictive health: online precursor scoring (docs/predict.md)
DEFAULT_PREDICT_INTERVAL = 15.0          # predict-scan cadence
DEFAULT_PREDICT_THRESHOLD = 0.6          # fused score that arms a warning
DEFAULT_PREDICT_HYSTERESIS = 0.15        # clear band below the threshold
DEFAULT_PREDICT_ARM_TICKS = 2            # consecutive ticks above to warn
DEFAULT_PREDICT_CLEAR_TICKS = 3          # consecutive ticks below to clear
DEFAULT_PREDICT_WINDOW = 600.0           # feature lookback window (s)
DEFAULT_PREDICT_HISTORY_LIMIT = 256      # in-memory score points / component
DEFAULT_PREDICT_WARN_COOLDOWN = 300.0    # predicted-warning audit-row cooldown
DEFAULT_PREDICT_PUBLISH_INTERVAL = 60.0  # armed-score outbox snapshot cadence
# threshold calibration: per-component-class thresholds/weights fitted
# by replaying the node's own ledger history (docs/predict.md)
DEFAULT_PREDICT_CALIBRATE_INTERVAL = 3600.0  # re-fit cadence (s)
DEFAULT_PREDICT_CALIBRATE_MIN_HISTORY = 8    # class samples below => defaults
DEFAULT_PREDICT_CALIBRATE_MIN_THRESHOLD = 0.35  # fitted-threshold floor
DEFAULT_PREDICT_CALIBRATE_MARGIN = 0.05      # gap above the benign maximum
DEFAULT_PREDICT_CALIBRATE_HORIZON = 900.0    # post-sample failure horizon (s)
# fabric observability plane (docs/fabric.md): mesh-wide all-links sweep
DEFAULT_FABRIC_SWEEP_INTERVAL = 60.0     # all-links sweep cadence (s)
DEFAULT_FABRIC_SWEEP_THRESHOLD_Z = 4.0   # EWMA z that flags Degraded
DEFAULT_FABRIC_SWEEP_EWMA_ALPHA = 0.3    # per-link baseline smoothing
DEFAULT_FABRIC_SWEEP_WARMUP = 3          # sweeps before deviation flags
DEFAULT_FABRIC_SWEEP_RETENTION = 7 * 86400.0  # matrix history window (s)
# unified check scheduler (docs/scheduler.md): bounded worker pool +
# deadline heap replacing per-component poller threads
DEFAULT_SCHEDULER_WORKERS = 4
DEFAULT_SCHEDULER_WATCHDOG = 120         # hang budget per check run (s)
DEFAULT_SCHEDULER_JITTER = 0.05          # ±5% deterministic cadence jitter
# chaos campaign runner (docs/chaos.md)
DEFAULT_CHAOS_MAX_CAMPAIGN_SECONDS = 300
DEFAULT_CHAOS_HISTORY_LIMIT = 32
# session-path fault injection rate limit (injectFault token bucket)
DEFAULT_INJECT_RATE_CAPACITY = 10
DEFAULT_INJECT_RATE_REFILL = 6.0         # one injection token back per 6s
# write-behind storage commit layer (docs/storage.md)
DEFAULT_STORAGE_BATCH_FLUSH_INTERVAL = 0.2   # group-commit cadence (s)
DEFAULT_STORAGE_BATCH_MAX_PENDING = 100_000  # buffered ops before backpressure
DEFAULT_STORAGE_BATCH_FLUSH_THRESHOLD = 5_000  # buffered ops that poke a drain
DEFAULT_STORAGE_BATCH_BACKPRESSURE = 0.05    # bounded wait for room (s)
DEFAULT_STORAGE_WAL_CHECKPOINT = 300         # wal_checkpoint(TRUNCATE) cadence (s)
# durable session outbox (docs/session.md): store-and-forward journal
# between producers and the control-plane session
DEFAULT_OUTBOX_MAX_ROWS = 100_000            # journal hard cap (rows)
DEFAULT_OUTBOX_MAX_AGE = 7 * 86400           # journal age cap: a week of partition
DEFAULT_OUTBOX_REPLAY_BATCH = 500            # frames per replay drain
DEFAULT_OUTBOX_REPLAY_INTERVAL = 1.0         # replay job cadence (s)
# control-plane circuit breaker (docs/session.md)
DEFAULT_SESSION_CIRCUIT_THRESHOLD = 5        # consecutive failures before open
DEFAULT_SESSION_CIRCUIT_OPEN_SECONDS = 30.0  # open-state cooldown before probe
# session wire path (docs/session.md wire format): batched delta-encoded
# delivery frames with cumulative acks, rev-3 payload compression
DEFAULT_WIRE_KEYFRAME_INTERVAL = 64          # full payload every K records/stream
DEFAULT_WIRE_COMPRESS_MIN_BYTES = 512        # zlib floor for rev-3 payloads
DEFAULT_OUTBOX_REDELIVER_SECONDS = 30.0      # ack-stall window before redelivery
DEFAULT_OUTBOX_REPLAY_JITTER = 2.0           # post-recovery replay stagger cap (s)

STATE_FILE = "tpud.state"                # reference: default.go:137-157 (gpud.state)
FIFO_FILE = "tpud.fifo"
PACKAGES_DIR = "packages"
TARGET_VERSION_FILE = "target_version"
PLUGIN_SPECS_FILE = "plugins.yaml"
LOG_FILE = "tpud.log"
AUDIT_LOG_FILE = "tpud.audit.log"


def resolve_data_dir(data_dir: str = "") -> str:
    """Reference: pkg/config ResolveDataDir — /var/lib/gpud for root,
    ~/.gpud otherwise."""
    if data_dir:
        return data_dir
    if os.environ.get("TPUD_DATA_DIR"):
        return os.environ["TPUD_DATA_DIR"]
    if hasattr(os, "geteuid") and os.geteuid() == 0:
        return "/var/lib/tpud"
    return os.path.expanduser("~/.tpud")


@dataclass
class Config:
    port: int = DEFAULT_PORT
    data_dir: str = ""
    db_in_memory: bool = False           # reference: pkg/server/server.go:132-154
    metrics_retention_seconds: int = DEFAULT_METRICS_RETENTION
    events_retention_seconds: int = DEFAULT_EVENTS_RETENTION
    # health-transition ledger tuning (docs/observability.md)
    health_flap_threshold: int = DEFAULT_HEALTH_FLAP_THRESHOLD
    health_flap_window_seconds: int = DEFAULT_HEALTH_FLAP_WINDOW
    health_availability_window_seconds: int = DEFAULT_HEALTH_AVAILABILITY_WINDOW
    # remediation engine (docs/remediation.md). Enabled by default but
    # deny-by-default: with an empty enforce list every suggested action is
    # decided dry_run and nothing mutates the host.
    remediation_enabled: bool = True
    remediation_interval_seconds: int = DEFAULT_REMEDIATION_INTERVAL
    remediation_enforce_actions: List[str] = field(default_factory=list)
    remediation_cooldown_seconds: int = DEFAULT_REMEDIATION_COOLDOWN
    remediation_rate_capacity: int = DEFAULT_REMEDIATION_RATE_CAPACITY
    remediation_rate_refill_seconds: int = DEFAULT_REMEDIATION_RATE_REFILL
    remediation_max_reboots: int = DEFAULT_REMEDIATION_MAX_REBOOTS
    remediation_reboot_window_seconds: int = DEFAULT_REMEDIATION_REBOOT_WINDOW
    remediation_escalation_threshold: int = (
        DEFAULT_REMEDIATION_ESCALATION_THRESHOLD
    )
    remediation_escalation_window_seconds: int = (
        DEFAULT_REMEDIATION_ESCALATION_WINDOW
    )
    remediation_runtime_unit: str = ""   # empty = tpu-runtime.service
    # predictive health (docs/predict.md): precursor scoring over
    # check-latency drift, transition cadence, state trajectory, and kmsg
    # error-class novelty. Warnings are advisory — annotation + dry-run
    # audit row + outbox publish — never an executed action.
    predict_enabled: bool = True
    predict_interval_seconds: float = DEFAULT_PREDICT_INTERVAL
    predict_threshold: float = DEFAULT_PREDICT_THRESHOLD
    predict_hysteresis: float = DEFAULT_PREDICT_HYSTERESIS
    predict_arm_ticks: int = DEFAULT_PREDICT_ARM_TICKS
    predict_clear_ticks: int = DEFAULT_PREDICT_CLEAR_TICKS
    predict_window_seconds: float = DEFAULT_PREDICT_WINDOW
    predict_history_limit: int = DEFAULT_PREDICT_HISTORY_LIMIT
    predict_warn_cooldown_seconds: float = DEFAULT_PREDICT_WARN_COOLDOWN
    predict_publish_interval_seconds: float = DEFAULT_PREDICT_PUBLISH_INTERVAL
    # ledger-history threshold calibration (docs/predict.md)
    predict_calibrate_enabled: bool = True
    predict_calibrate_interval_seconds: float = (
        DEFAULT_PREDICT_CALIBRATE_INTERVAL
    )
    predict_calibrate_min_history: int = DEFAULT_PREDICT_CALIBRATE_MIN_HISTORY
    predict_calibrate_min_threshold: float = (
        DEFAULT_PREDICT_CALIBRATE_MIN_THRESHOLD
    )
    predict_calibrate_margin: float = DEFAULT_PREDICT_CALIBRATE_MARGIN
    predict_calibrate_horizon_seconds: float = (
        DEFAULT_PREDICT_CALIBRATE_HORIZON
    )
    # fabric observability (docs/fabric.md): logical-mesh discovery + the
    # all-links sweep with per-link EWMA latency baselines. Hermetic by
    # construction: with no JAX devices and no ICI inventory the mesh
    # degrades to 1x1 and the sweep observes zero links.
    fabric_sweep_enabled: bool = True
    fabric_sweep_interval_seconds: float = DEFAULT_FABRIC_SWEEP_INTERVAL
    fabric_sweep_latency_threshold_z: float = DEFAULT_FABRIC_SWEEP_THRESHOLD_Z
    fabric_sweep_ewma_alpha: float = DEFAULT_FABRIC_SWEEP_EWMA_ALPHA
    fabric_sweep_warmup_sweeps: int = DEFAULT_FABRIC_SWEEP_WARMUP
    fabric_sweep_retention_seconds: float = DEFAULT_FABRIC_SWEEP_RETENTION
    # chaos campaign runner (docs/chaos.md): enabled by default — running
    # a campaign still takes an explicit API/CLI call, and every fault is
    # software-injected and undone on campaign exit
    chaos_enabled: bool = True
    chaos_max_campaign_seconds: int = DEFAULT_CHAOS_MAX_CAMPAIGN_SECONDS
    chaos_history_limit: int = DEFAULT_CHAOS_HISTORY_LIMIT
    # token bucket on the session injectFault path (a hostile/buggy
    # control plane must not be able to spam kmsg writes)
    inject_rate_capacity: int = DEFAULT_INJECT_RATE_CAPACITY
    inject_rate_refill_seconds: float = DEFAULT_INJECT_RATE_REFILL
    # write-behind storage commit layer (docs/storage.md): all four stores
    # buffer hot-path writes and group-commit on one scheduler job. Off =
    # the classic one-transaction-per-row synchronous path everywhere.
    storage_batch_enabled: bool = True
    storage_batch_flush_interval_seconds: float = (
        DEFAULT_STORAGE_BATCH_FLUSH_INTERVAL
    )
    storage_batch_max_pending: int = DEFAULT_STORAGE_BATCH_MAX_PENDING
    storage_batch_flush_threshold: int = DEFAULT_STORAGE_BATCH_FLUSH_THRESHOLD
    storage_batch_backpressure_seconds: float = DEFAULT_STORAGE_BATCH_BACKPRESSURE
    storage_batch_fsync: bool = False    # one fsync per group commit when True
    storage_wal_checkpoint_seconds: int = DEFAULT_STORAGE_WAL_CHECKPOINT
    # durable session outbox (docs/session.md): at-least-once delivery of
    # events/transitions/audit/chaos results across partitions + restarts.
    # Off = the classic fire-and-forget in-memory channels only.
    outbox_enabled: bool = True
    outbox_max_rows: int = DEFAULT_OUTBOX_MAX_ROWS
    outbox_max_age_seconds: int = DEFAULT_OUTBOX_MAX_AGE
    outbox_replay_batch: int = DEFAULT_OUTBOX_REPLAY_BATCH
    outbox_replay_interval_seconds: float = DEFAULT_OUTBOX_REPLAY_INTERVAL
    # session wire path (docs/session.md wire format): per-stream delta
    # keyframe cadence, rev-3 compression floor, ack-stall redelivery
    # window, and the post-recovery replay jitter cap that staggers a
    # reconnecting fleet's replay storm
    session_wire_keyframe_interval: int = DEFAULT_WIRE_KEYFRAME_INTERVAL
    session_wire_compress_min_bytes: int = DEFAULT_WIRE_COMPRESS_MIN_BYTES
    outbox_redeliver_seconds: float = DEFAULT_OUTBOX_REDELIVER_SECONDS
    outbox_replay_jitter_seconds: float = DEFAULT_OUTBOX_REPLAY_JITTER
    # control-plane circuit breaker: closed → open after N consecutive
    # connect failures → half-open probe after the cooldown
    session_circuit_failure_threshold: int = DEFAULT_SESSION_CIRCUIT_THRESHOLD
    session_circuit_open_seconds: float = DEFAULT_SESSION_CIRCUIT_OPEN_SECONDS
    # HA manager tier (docs/session.md "Peer failover"): standby manager
    # specs ("endpoint", "endpoint|grpc_target", or the full
    # "peer_id=endpoint[|grpc_target]" form) tried in order when the
    # breaker trips on the primary. Empty = classic single-manager
    # parking behavior
    session_peers: List[str] = field(default_factory=list)
    # manager-side federation knobs (gpud_tpu/manager/federation.py),
    # consumed by `tpud manager serve`: journal-replication tick cadence,
    # peer health probe cadence, per-peer scatter-gather budget, probes
    # before a peer is declared dead, and whether the ring successor
    # auto-adopts a dead peer's replicated cohort
    federation_replication_interval_seconds: float = 1.0
    federation_probe_interval_seconds: float = 5.0
    federation_fanout_timeout_seconds: float = 2.0
    federation_dead_after_probes: int = 3
    federation_auto_adopt: bool = True
    # unified check scheduler (docs/scheduler.md)
    scheduler_workers: int = DEFAULT_SCHEDULER_WORKERS
    scheduler_watchdog_seconds: int = DEFAULT_SCHEDULER_WATCHDOG
    scheduler_jitter_fraction: float = DEFAULT_SCHEDULER_JITTER
    poll_interval_seconds: int = DEFAULT_POLL_INTERVAL
    scrape_interval_seconds: int = DEFAULT_SCRAPE_INTERVAL
    compact_period_seconds: int = 0      # 0 = disabled (reference default)
    enable_auto_update: bool = True
    endpoint: str = ""                   # control-plane endpoint (or TPUD_ENDPOINT)
    token: str = ""                      # join/session token (or TPUD_TOKEN)
    machine_id: str = ""
    components_enabled: List[str] = field(default_factory=list)   # empty = all
    components_disabled: List[str] = field(default_factory=list)
    kernel_modules_to_check: List[str] = field(default_factory=list)
    nfs_group_dirs: List[str] = field(default_factory=list)
    mount_points: List[str] = field(default_factory=list)
    mount_targets: List[str] = field(default_factory=list)
    expected_chip_count: int = 0         # 0 = derive from accelerator type
    accelerator_type_override: str = ""
    kmsg_path: str = ""                  # empty = /dev/kmsg (or TPUD_KMSG_FILE_PATH)
    plugin_specs_file: str = ""
    pprof: bool = False
    log_level: str = "info"
    log_file: str = ""
    audit_log_file: str = ""
    tls: bool = True
    # failure injection (hidden flags in the reference, command.go:345-410)
    inject: Dict[str, str] = field(default_factory=dict)

    def resolved_data_dir(self) -> str:
        return resolve_data_dir(self.data_dir)

    def state_file(self) -> str:
        if self.db_in_memory:
            return ":memory:"
        return os.path.join(self.resolved_data_dir(), STATE_FILE)

    def fifo_file(self) -> str:
        return os.path.join(self.resolved_data_dir(), FIFO_FILE)

    def packages_dir(self) -> str:
        return os.path.join(self.resolved_data_dir(), PACKAGES_DIR)

    def target_version_file(self) -> str:
        return os.path.join(self.resolved_data_dir(), TARGET_VERSION_FILE)

    def resolved_plugin_specs_file(self) -> str:
        return self.plugin_specs_file or os.path.join(
            self.resolved_data_dir(), PLUGIN_SPECS_FILE
        )

    def validate(self) -> Optional[str]:
        # port 0 = ephemeral (tests)
        if not (0 <= self.port < 65536):
            return f"invalid port {self.port}"
        if self.metrics_retention_seconds < 60:
            return "metrics retention must be >= 60s"
        if self.events_retention_seconds < 60:
            return "events retention must be >= 60s"
        if self.health_flap_threshold < 2:
            return "health flap threshold must be >= 2"
        if self.health_flap_window_seconds < 60:
            return "health flap window must be >= 60s"
        if self.health_availability_window_seconds < 60:
            return "health availability window must be >= 60s"
        if self.remediation_interval_seconds < 1:
            return "remediation interval must be >= 1s"
        if self.remediation_cooldown_seconds < 0:
            return "remediation cooldown must be >= 0s"
        if self.remediation_rate_capacity < 1:
            return "remediation rate capacity must be >= 1"
        if self.remediation_rate_refill_seconds < 1:
            return "remediation rate refill must be >= 1s"
        if self.remediation_max_reboots < 1:
            return "remediation max reboots must be >= 1"
        if self.remediation_reboot_window_seconds < 60:
            return "remediation reboot window must be >= 60s"
        if self.remediation_escalation_threshold < 1:
            return "remediation escalation threshold must be >= 1"
        if self.remediation_escalation_window_seconds < 60:
            return "remediation escalation window must be >= 60s"
        if self.predict_interval_seconds <= 0:
            return "predict interval must be > 0s"
        if not 0.0 < self.predict_threshold <= 1.0:
            return "predict threshold must be in (0, 1]"
        if not 0.0 <= self.predict_hysteresis < self.predict_threshold:
            return "predict hysteresis must be in [0, threshold)"
        if self.predict_arm_ticks < 1:
            return "predict arm ticks must be >= 1"
        if self.predict_clear_ticks < 1:
            return "predict clear ticks must be >= 1"
        if self.predict_window_seconds < 1:
            return "predict window must be >= 1s"
        if self.predict_history_limit < 1:
            return "predict history limit must be >= 1"
        if self.predict_warn_cooldown_seconds < 0:
            return "predict warn cooldown must be >= 0s"
        if self.predict_publish_interval_seconds < 0:
            return "predict publish interval must be >= 0s"
        if self.predict_calibrate_interval_seconds <= 0:
            return "predict calibrate interval must be > 0s"
        if self.predict_calibrate_min_history < 1:
            return "predict calibrate min history must be >= 1"
        if not 0.0 < self.predict_calibrate_min_threshold <= 1.0:
            return "predict calibrate min threshold must be in (0, 1]"
        if not 0.0 <= self.predict_calibrate_margin < 0.5:
            return "predict calibrate margin must be in [0, 0.5)"
        if self.predict_calibrate_horizon_seconds < 1:
            return "predict calibrate horizon must be >= 1s"
        if self.fabric_sweep_interval_seconds <= 0:
            return "fabric sweep interval must be > 0s"
        if self.fabric_sweep_latency_threshold_z <= 0:
            return "fabric sweep latency threshold z must be > 0"
        if not 0.0 < self.fabric_sweep_ewma_alpha <= 1.0:
            return "fabric sweep ewma alpha must be in (0, 1]"
        if self.fabric_sweep_warmup_sweeps < 1:
            return "fabric sweep warmup sweeps must be >= 1"
        if self.fabric_sweep_retention_seconds < 60:
            return "fabric sweep retention must be >= 60s"
        if self.chaos_max_campaign_seconds < 1:
            return "chaos max campaign seconds must be >= 1"
        if self.chaos_history_limit < 1:
            return "chaos history limit must be >= 1"
        if self.inject_rate_capacity < 1:
            return "inject rate capacity must be >= 1"
        if self.inject_rate_refill_seconds <= 0:
            return "inject rate refill must be > 0s"
        if self.storage_batch_flush_interval_seconds <= 0:
            return "storage batch flush interval must be > 0s"
        if self.storage_batch_max_pending < 1000:
            return "storage batch max pending must be >= 1000"
        if self.storage_batch_flush_threshold < 1:
            return "storage batch flush threshold must be >= 1"
        if self.storage_batch_flush_threshold > self.storage_batch_max_pending:
            return "storage batch flush threshold must be <= max pending"
        if self.storage_batch_backpressure_seconds < 0:
            return "storage batch backpressure must be >= 0s"
        if self.storage_wal_checkpoint_seconds < 0:
            return "storage wal checkpoint cadence must be >= 0s (0 disables)"
        if self.outbox_max_rows < 1000:
            return "outbox max rows must be >= 1000"
        if self.outbox_max_age_seconds < 60:
            return "outbox max age must be >= 60s"
        if self.outbox_replay_batch < 1:
            return "outbox replay batch must be >= 1"
        if self.outbox_replay_interval_seconds <= 0:
            return "outbox replay interval must be > 0s"
        if self.session_circuit_failure_threshold < 1:
            return "session circuit failure threshold must be >= 1"
        if self.session_circuit_open_seconds <= 0:
            return "session circuit open seconds must be > 0s"
        for spec in self.session_peers:
            s = (spec or "").strip()
            if not s or "://" not in s:
                return (
                    f"session peer {spec!r} must be an http(s) endpoint "
                    "spec (endpoint, endpoint|grpc, or id=endpoint|grpc)"
                )
        if self.federation_replication_interval_seconds <= 0:
            return "federation replication interval must be > 0s"
        if self.federation_probe_interval_seconds <= 0:
            return "federation probe interval must be > 0s"
        if self.federation_fanout_timeout_seconds <= 0:
            return "federation fanout timeout must be > 0s"
        if self.federation_dead_after_probes < 1:
            return "federation dead-after-probes must be >= 1"
        if self.session_wire_keyframe_interval < 1:
            return "session wire keyframe interval must be >= 1"
        if self.session_wire_compress_min_bytes < 0:
            return "session wire compress min bytes must be >= 0"
        if self.outbox_redeliver_seconds <= 0:
            return "outbox redeliver window must be > 0s"
        if self.outbox_replay_jitter_seconds < 0:
            return "outbox replay jitter must be >= 0s"
        if self.scheduler_workers < 1:
            return "scheduler workers must be >= 1"
        if self.scheduler_watchdog_seconds < 0:
            return "scheduler watchdog must be >= 0s (0 disables)"
        if not (0.0 <= self.scheduler_jitter_fraction <= 0.5):
            return "scheduler jitter fraction must be in [0, 0.5]"
        if self.poll_interval_seconds < 1:
            return "poll interval must be >= 1s"
        if self.scrape_interval_seconds < 1:
            return "scrape interval must be >= 1s"
        if self.compact_period_seconds < 0:
            return "compact period must be >= 0s (0 disables)"
        if self.expected_chip_count < 0:
            return "expected chip count must be >= 0 (0 = derive)"
        from gpud_tpu.remediation.policy import EXECUTABLE_ACTIONS

        unknown = sorted(
            set(self.remediation_enforce_actions) - set(EXECUTABLE_ACTIONS)
        )
        if unknown:
            return (
                f"unknown remediation enforce action(s) {unknown}; "
                f"known: {list(EXECUTABLE_ACTIONS)}"
            )
        return None


def default_config(**overrides) -> Config:
    cfg = Config()
    # env-based enrollment for containerized deploys (the Helm chart
    # injects TPUD_TOKEN from a Secret and TPUD_ENDPOINT from values)
    cfg.endpoint = os.environ.get("TPUD_ENDPOINT", "")
    cfg.token = os.environ.get("TPUD_TOKEN", "")
    for k, v in overrides.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"unknown config field: {k}")
        setattr(cfg, k, v)
    return cfg
