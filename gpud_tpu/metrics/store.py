"""Metrics SQLite time-series store + scraper + syncer + recorder.

Reference: pkg/metrics/{scraper,store,syncer,recorder} — the three-stage
pipeline (SURVEY §5.5): components set gauges in the registry → the syncer
scrapes once a minute into SQLite with retention purge → /v1/metrics and
the session serve history from the store.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import Metric
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import Registry
from gpud_tpu.sqlite import DB
from gpud_tpu import sqlite as sqlite_mod

logger = get_logger(__name__)

TABLE = "tpud_metrics_v0_1"

DEFAULT_RETENTION = 3 * 3600  # 3h (reference: pkg/config/default.go:26)
SCRAPE_INTERVAL = 60.0        # 1m  (reference: pkg/server/server.go:231-239)
RECORDER_INTERVAL = 15 * 60.0 # 15m (reference: pkg/server/server.go:241)

# metric-name prefix → component attribution for /v1/metrics grouping
COMPONENT_LABEL = "component"


class MetricsStore:
    """SQLite time-series table with Record/Read/Purge
    (reference: pkg/metrics/store/sqlite.go:64)."""

    def __init__(self, db: DB, retention_seconds: int = DEFAULT_RETENTION) -> None:
        self.db = db
        self.retention_seconds = retention_seconds
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                unix_seconds INTEGER NOT NULL,
                name TEXT NOT NULL,
                labels TEXT NOT NULL DEFAULT '',
                value REAL NOT NULL
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (unix_seconds)"
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_name_ts ON {TABLE} (name, unix_seconds)"
        )

    def record(self, rows: List[tuple]) -> None:
        """rows: (unix_seconds, name, labels_dict, value) — batched insert
        (footprint discipline: one transaction per scrape)."""
        if not rows:
            return
        self.db.executemany(
            f"INSERT INTO {TABLE} (unix_seconds, name, labels, value) VALUES (?, ?, ?, ?)",
            [
                (ts, name, json.dumps(labels, sort_keys=True) if labels else "", value)
                for ts, name, labels, value in rows
            ],
        )

    def read(
        self,
        since: float,
        name: str = "",
        components: Optional[List[str]] = None,
    ) -> List[Metric]:
        sql = f"SELECT unix_seconds, name, labels, value FROM {TABLE} WHERE unix_seconds>=?"
        params: list = [int(since)]
        if name:
            sql += " AND name=?"
            params.append(name)
        sql += " ORDER BY unix_seconds ASC"
        out: List[Metric] = []
        comp_filter = set(components) if components else None
        for ts, nm, labels_json, value in self.db.query(sql, params):
            labels = json.loads(labels_json) if labels_json else {}
            if comp_filter is not None and labels.get(COMPONENT_LABEL) not in comp_filter:
                continue
            out.append(Metric(unix_seconds=ts, name=nm, labels=labels, value=value))
        return out

    def purge(self, before: float) -> int:
        return self.db.execute(
            f"DELETE FROM {TABLE} WHERE unix_seconds<?", (int(before),)
        ).rowcount


class Syncer:
    """Every minute: scrape registry → store, purge older than retention
    (reference: pkg/metrics/syncer/syncer.go:22-50, wired at
    pkg/server/server.go:231-239)."""

    def __init__(
        self,
        registry: Registry,
        store: MetricsStore,
        interval_seconds: float = SCRAPE_INTERVAL,
    ) -> None:
        self.registry = registry
        self.store = store
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None
        self.time_now_fn = time.time

    def sync_once(self) -> int:
        rows = self.registry.gather(self.time_now_fn())
        self.store.record(rows)
        self.store.purge(self.time_now_fn() - self.store.retention_seconds)
        return len(rows)

    def start(self, scheduler=None) -> None:
        """On the unified scheduler when given (the daemon path; zero
        threads), else the legacy dedicated thread."""
        if scheduler is not None:
            if self._job is None:
                self._job = scheduler.add_job(
                    "metrics-syncer",
                    self.sync_once,
                    interval=self.interval,
                    initial_delay=self.interval,  # scrape-at-boot is noise
                )
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tpud-metrics-syncer", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001
                logger.exception("metrics sync failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class SelfMetricsRecorder:
    """tpud self-metrics: fd usage, DB size, sqlite op timings, vacuum
    seconds, every 15m (reference: pkg/metrics/recorder/gpud_metrics.go:14-60)."""

    def __init__(
        self,
        registry: Registry,
        db: DB,
        interval_seconds: float = RECORDER_INTERVAL,
    ) -> None:
        self.db = db
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None
        self.g_db_size = registry.gauge(
            "tpud_sqlite_db_size_bytes", "state DB size in bytes"
        )
        self.g_fds = registry.gauge("tpud_file_descriptors", "open fd count of tpud")
        self.g_select_secs = registry.gauge(
            "tpud_sqlite_select_seconds_total", "cumulative sqlite select seconds"
        )
        self.g_write_secs = registry.gauge(
            "tpud_sqlite_insert_update_delete_seconds_total",
            "cumulative sqlite write seconds",
        )
        self.g_vacuum_secs = registry.gauge(
            "tpud_sqlite_vacuum_seconds_total", "cumulative sqlite vacuum seconds"
        )

    def record_once(self) -> None:
        try:
            self.g_db_size.set(self.db.size_bytes())
        except Exception:  # noqa: BLE001
            pass
        self.g_fds.set(_open_fd_count())
        s = sqlite_mod.stats()
        self.g_select_secs.set(s["select_seconds"])
        self.g_write_secs.set(s["insert_update_delete_seconds"])
        self.g_vacuum_secs.set(s["vacuum_seconds"])

    def start(self, scheduler=None) -> None:
        if scheduler is not None:
            if self._job is None:
                # first record runs on the pool (part of startup
                # readiness), then every 15m
                self._job = scheduler.add_job(
                    "self-metrics-recorder", self.record_once,
                    interval=self.interval,
                )
            return
        if self._thread is not None:
            return
        self.record_once()
        self._thread = threading.Thread(
            target=self._loop, name="tpud-self-metrics", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.record_once()
            except Exception:  # noqa: BLE001
                logger.exception("self-metrics record failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _open_fd_count() -> int:
    try:
        import os

        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1
