"""Metrics SQLite time-series store + scraper + syncer + recorder.

Reference: pkg/metrics/{scraper,store,syncer,recorder} — the three-stage
pipeline (SURVEY §5.5): components set gauges in the registry → the syncer
scrapes once a minute into SQLite with retention purge → /v1/metrics and
the session serve history from the store.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import Metric
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import Registry
from gpud_tpu.sqlite import DB
from gpud_tpu import sqlite as sqlite_mod

logger = get_logger(__name__)

TABLE = "tpud_metrics_v0_1"

DEFAULT_RETENTION = 3 * 3600  # 3h (reference: pkg/config/default.go:26)
SCRAPE_INTERVAL = 60.0        # 1m  (reference: pkg/server/server.go:231-239)
RECORDER_INTERVAL = 15 * 60.0 # 15m (reference: pkg/server/server.go:241)

# metric-name prefix → component attribution for /v1/metrics grouping
COMPONENT_LABEL = "component"

# write-behind contract (tools/storage_lint.py): these methods must route
# through the BatchWriter, never commit per-row via db.execute directly
HOT_WRITE_METHODS = ("record",)


class MetricsStore:
    """SQLite time-series table with Record/Read/Purge
    (reference: pkg/metrics/store/sqlite.go:64).

    With a ``writer`` (the write-behind BatchWriter), ``record`` buffers
    rows for the next group commit — same-(timestamp, name, labels)
    samples coalesce last-write-wins — and every read/purge runs the
    flush barrier first so history queries always see completed scrapes.
    Without one (tests, CLI tools) writes stay synchronous.
    """

    def __init__(
        self,
        db: DB,
        retention_seconds: int = DEFAULT_RETENTION,
        writer=None,
    ) -> None:
        self.db = db
        self.writer = writer
        self.retention_seconds = retention_seconds
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                unix_seconds INTEGER NOT NULL,
                name TEXT NOT NULL,
                labels TEXT NOT NULL DEFAULT '',
                value REAL NOT NULL
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (unix_seconds)"
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_name_ts ON {TABLE} (name, unix_seconds)"
        )

    def record(self, rows: List[tuple]) -> None:
        """rows: (unix_seconds, name, labels_dict, value) — batched insert
        (footprint discipline: one transaction per scrape). ``labels`` may
        also be a pre-encoded JSON string (the firehose fast path skips
        re-serializing identical labelsets per sample)."""
        if not rows:
            return
        sql = f"INSERT INTO {TABLE} (unix_seconds, name, labels, value) VALUES (?, ?, ?, ?)"
        encoded = [
            (
                ts,
                name,
                labels if isinstance(labels, str)
                else (json.dumps(labels, sort_keys=True) if labels else ""),
                value,
            )
            for ts, name, labels, value in rows
        ]
        if self.writer is not None:
            # gauge samples for the same (second, series) coalesce
            # last-write-wins: an ingest storm re-sampling a gauge within
            # one flush window commits one row, not thousands
            self.writer.submit_many(
                "metrics", sql, encoded,
                keys=[("m", ts, name, labels) for ts, name, labels, _v in encoded],
            )
        else:
            self.db.executemany(sql, encoded)

    def flush(self) -> None:
        """Read-after-write barrier (no-op without a writer)."""
        if self.writer is not None:
            self.writer.flush()

    def read(
        self,
        since: float,
        name: str = "",
        components: Optional[List[str]] = None,
    ) -> List[Metric]:
        self.flush()
        sql = f"SELECT unix_seconds, name, labels, value FROM {TABLE} WHERE unix_seconds>=?"
        params: list = [int(since)]
        if name:
            sql += " AND name=?"
            params.append(name)
        sql += " ORDER BY unix_seconds ASC"
        out: List[Metric] = []
        comp_filter = set(components) if components else None
        for ts, nm, labels_json, value in self.db.query(sql, params):
            labels = json.loads(labels_json) if labels_json else {}
            if comp_filter is not None and labels.get(COMPONENT_LABEL) not in comp_filter:
                continue
            out.append(Metric(unix_seconds=ts, name=nm, labels=labels, value=value))
        return out

    def purge(self, before: float) -> int:
        # barrier first: a purge racing buffered rows would let a sample
        # older than the cutoff commit right after the DELETE
        self.flush()
        return self.db.execute(
            f"DELETE FROM {TABLE} WHERE unix_seconds<?", (int(before),)
        ).rowcount


class Syncer:
    """Every minute: scrape registry → store, purge older than retention
    (reference: pkg/metrics/syncer/syncer.go:22-50, wired at
    pkg/server/server.go:231-239)."""

    def __init__(
        self,
        registry: Registry,
        store: MetricsStore,
        interval_seconds: float = SCRAPE_INTERVAL,
    ) -> None:
        self.registry = registry
        self.store = store
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None
        self.time_now_fn = time.time

    def sync_once(self) -> int:
        rows = self.registry.gather(self.time_now_fn())
        self.store.record(rows)
        self.store.purge(self.time_now_fn() - self.store.retention_seconds)
        return len(rows)

    def start(self, scheduler=None) -> None:
        """On the unified scheduler when given (the daemon path; zero
        threads), else the legacy dedicated thread."""
        if scheduler is not None:
            if self._job is None:
                self._job = scheduler.add_job(
                    "metrics-syncer",
                    self.sync_once,
                    interval=self.interval,
                    initial_delay=self.interval,  # scrape-at-boot is noise
                )
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tpud-metrics-syncer", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001
                logger.exception("metrics sync failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class SelfMetricsRecorder:
    """tpud self-metrics: fd usage, DB size, sqlite op timings, vacuum
    seconds, every 15m (reference: pkg/metrics/recorder/gpud_metrics.go:14-60)."""

    def __init__(
        self,
        registry: Registry,
        db: DB,
        interval_seconds: float = RECORDER_INTERVAL,
    ) -> None:
        self.db = db
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None
        self.g_db_size = registry.gauge(
            "tpud_sqlite_db_size_bytes", "state DB size in bytes"
        )
        self.g_fds = registry.gauge("tpud_file_descriptors", "open fd count of tpud")
        self.g_select_secs = registry.gauge(
            "tpud_sqlite_select_seconds_total", "cumulative sqlite select seconds"
        )
        self.g_write_secs = registry.gauge(
            "tpud_sqlite_insert_update_delete_seconds_total",
            "cumulative sqlite write seconds",
        )
        self.g_vacuum_secs = registry.gauge(
            "tpud_sqlite_vacuum_seconds_total", "cumulative sqlite vacuum seconds"
        )

    def record_once(self) -> None:
        try:
            self.g_db_size.set(self.db.size_bytes())
        except Exception:  # noqa: BLE001
            pass
        self.g_fds.set(_open_fd_count())
        s = sqlite_mod.stats()
        self.g_select_secs.set(s["select_seconds"])
        self.g_write_secs.set(s["insert_update_delete_seconds"])
        self.g_vacuum_secs.set(s["vacuum_seconds"])

    def start(self, scheduler=None) -> None:
        if scheduler is not None:
            if self._job is None:
                # first record runs on the pool (part of startup
                # readiness), then every 15m
                self._job = scheduler.add_job(
                    "self-metrics-recorder", self.record_once,
                    interval=self.interval,
                )
            return
        if self._thread is not None:
            return
        self.record_once()
        self._thread = threading.Thread(
            target=self._loop, name="tpud-self-metrics", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.record_once()
            except Exception:  # noqa: BLE001
                logger.exception("self-metrics record failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _open_fd_count() -> int:
    try:
        import os

        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1
