"""Metrics registry: Gauges/Counters all components register into.

Reference: pkg/metrics/registry.go:5-23 — a package-global Prometheus
registry. Here a small dependency-free implementation that renders the
Prometheus text exposition format for the /metrics endpoint and feeds the
scraper → SQLite pipeline.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple, Type, TypeVar

LabelKey = Tuple[Tuple[str, str], ...]
Sample = Tuple[str, LabelKey, float]  # (exposition name, labels, value)


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _render_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    TYPE = "gauge"

    # Gauge and Counter inherit this map (guard_lint merges base-class
    # GUARDED_BY down through in-module subclasses)
    GUARDED_BY = {"_values": "_mu"}

    def __init__(
        self, name: str, help_text: str, registry: Optional["Registry"] = None
    ) -> None:
        self.name = name
        self.help_text = help_text
        self._mu = threading.Lock()
        self._values: Dict[LabelKey, float] = {}
        # registry=None lets Registry construct the metric while already
        # holding its own lock (atomic get-or-create) without re-entry
        if registry is not None:
            registry._register(self)

    def labels_values(self) -> List[Tuple[LabelKey, float]]:
        with self._mu:
            return list(self._values.items())

    def samples(self) -> List[Sample]:
        """Exposition/gather view: one sample per labelset, sorted for
        deterministic output. Histograms expand to multiple series here."""
        return [(self.name, key, value) for key, value in sorted(self.labels_values())]

    def clear(self) -> None:
        with self._mu:
            self._values.clear()

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._values.pop(_label_key(labels), None)


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._values[_label_key(labels)] = float(value)

    def get(self, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        with self._mu:
            return self._values.get(_label_key(labels))


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        k = _label_key(labels)
        with self._mu:
            self._values[k] = self._values.get(k, 0.0) + amount

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._mu:
            return self._values.get(_label_key(labels), 0.0)


# latency-oriented default buckets: the daemon's hot paths (checks, HTTP
# handlers, sqlite queries, dispatch) live between ~1ms and the 60s poll
# cadence (reference: prometheus client_golang DefBuckets, widened upward)
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class _HistogramTimer:
    """``with h.time(labels):`` — observes wall duration on exit, including
    the exception path (failure latency is still latency)."""

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: "Histogram", labels: Optional[Dict[str, str]]) -> None:
        self._hist = hist
        self._labels = labels

    def __enter__(self) -> "_HistogramTimer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._hist.observe(time.monotonic() - self._t0, self._labels)


class Histogram(_Metric):
    """Cumulative-bucket histogram with the standard Prometheus exposition
    (``name_bucket{le=...}``/``name_sum``/``name_count``). Bucket bounds are
    fixed at creation; per-labelset state is (per-bucket counts, sum, count).
    """

    TYPE = "histogram"

    GUARDED_BY = {"_series": "_mu"}  # plus _Metric's inherited _values

    def __init__(
        self,
        name: str,
        help_text: str,
        registry: Optional["Registry"] = None,
        buckets: Optional[Iterable[float]] = None,
    ) -> None:
        bounds = sorted(
            {float(b) for b in (DEFAULT_BUCKETS if buckets is None else buckets)}
        )
        # the +Inf bucket is implicit (it always equals _count)
        bounds = [b for b in bounds if not math.isinf(b)]
        if not bounds or any(math.isnan(b) for b in bounds):
            raise ValueError(f"histogram {name}: invalid buckets {bounds!r}")
        self.buckets: Tuple[float, ...] = tuple(bounds)
        # bucket bounds never change after creation: render the ``le``
        # label values once here instead of per-sample on every scrape
        # (the exposition path runs while check threads are observing)
        self._le_strs: Tuple[str, ...] = tuple(
            _format_value(b) for b in self.buckets
        )
        super().__init__(name, help_text, registry)
        # LabelKey -> [bucket_counts, sum, count]
        self._series: Dict[LabelKey, list] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        v = float(value)
        k = _label_key(labels)
        with self._mu:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = [[0] * len(self.buckets), 0.0, 0]
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s[0][i] += 1
                    break
            s[1] += v
            s[2] += 1

    def time(self, labels: Optional[Dict[str, str]] = None) -> _HistogramTimer:
        return _HistogramTimer(self, labels)

    def get_count(self, labels: Optional[Dict[str, str]] = None) -> int:
        with self._mu:
            s = self._series.get(_label_key(labels))
            return s[2] if s else 0

    def get_sum(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._mu:
            s = self._series.get(_label_key(labels))
            return s[1] if s else 0.0

    def labels_values(self) -> List[Tuple[LabelKey, float]]:
        """Observation count per labelset (the scalar view of a histogram)."""
        with self._mu:
            return [(k, float(s[2])) for k, s in self._series.items()]

    def samples(self) -> List[Sample]:
        # hold the lock ONLY for the raw state copy; sorting and series
        # expansion run outside it so observe() on the check/HTTP hot
        # paths is never blocked behind exposition formatting
        with self._mu:
            snap = [
                (k, list(s[0]), s[1], s[2]) for k, s in self._series.items()
            ]
        snap.sort(key=lambda item: item[0])
        out: List[Sample] = []
        for key, counts, total, n in snap:
            cum = 0
            for le, c in zip(self._le_strs, counts):
                cum += c
                out.append(
                    (self.name + "_bucket", key + (("le", le),), float(cum))
                )
            out.append((self.name + "_bucket", key + (("le", "+Inf"),), float(n)))
            out.append((self.name + "_sum", key, float(total)))
            out.append((self.name + "_count", key, float(n)))
        return out

    def clear(self) -> None:
        with self._mu:
            self._series.clear()

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._series.pop(_label_key(labels), None)


MetricT = TypeVar("MetricT", bound=_Metric)


class Registry:
    GUARDED_BY = {"_metrics": "_mu"}

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, m: _Metric) -> None:
        with self._mu:
            if m.name in self._metrics:
                raise ValueError(f"metric already registered: {m.name}")
            self._metrics[m.name] = m

    def _get_or_create(
        self, name: str, cls: Type[MetricT], help_text: str, **kwargs
    ) -> MetricT:
        """Atomic check-then-create: two threads racing on the same name
        must both get the one metric, never a 'metric already registered'
        ValueError. The metric is constructed unregistered (registry=None)
        and inserted under the same lock acquisition as the lookup."""
        with self._mu:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(f"{name} is not a {cls.TYPE}: {existing.TYPE}")
                return existing
            m = cls(name, help_text, None, **kwargs)
            self._metrics[name] = m
            return m

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help_text)

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, Counter, help_text)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: Optional[Iterable[float]] = None,
    ) -> Histogram:
        """Get-or-create; an existing histogram keeps its original buckets
        (bucket bounds are part of the series' identity once scraped)."""
        return self._get_or_create(name, Histogram, help_text, buckets=buckets)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._metrics.pop(name, None)

    def all_metrics(self) -> List[_Metric]:
        with self._mu:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # -- exposition --------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text format for /metrics
        (reference: pkg/server/server.go:415-418)."""
        lines: List[str] = []
        for m in self.all_metrics():
            if m.help_text:
                # exposition format: HELP text escapes backslash + newline
                escaped = m.help_text.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {m.name} {escaped}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            for name, key, value in m.samples():
                lines.append(f"{name}{_render_labels(key)} {_format_value(value)}")
        return "\n".join(lines) + "\n"

    def gather(self, now: Optional[float] = None) -> List[Tuple[int, str, Dict[str, str], float]]:
        """Snapshot for the scraper: (unix_seconds, name, labels, value).
        Histograms flow through as their bucket/sum/count series (the ``le``
        bound rides in the labels), so the SQLite store needs no schema
        change to hold them."""
        ts = int(now if now is not None else time.time())
        out = []
        for m in self.all_metrics():
            for name, key, value in m.samples():
                out.append((ts, name, dict(key), value))
        return out


def _format_value(v: float) -> str:
    # non-finite values per the exposition format — one inf/NaN gauge
    # (e.g. a stray division) must not 500 the whole /metrics endpoint
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


# package-global default registry (reference: pkg/metrics/registry.go:5)
DEFAULT_REGISTRY = Registry()


def gauge(name: str, help_text: str = "") -> Gauge:
    return DEFAULT_REGISTRY.gauge(name, help_text)


def counter(name: str, help_text: str = "") -> Counter:
    return DEFAULT_REGISTRY.counter(name, help_text)


def histogram(
    name: str, help_text: str = "", buckets: Optional[Iterable[float]] = None
) -> Histogram:
    return DEFAULT_REGISTRY.histogram(name, help_text, buckets=buckets)
