"""Metrics registry: Gauges/Counters all components register into.

Reference: pkg/metrics/registry.go:5-23 — a package-global Prometheus
registry. Here a small dependency-free implementation that renders the
Prometheus text exposition format for the /metrics endpoint and feeds the
scraper → SQLite pipeline.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _render_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    TYPE = "gauge"

    def __init__(self, name: str, help_text: str, registry: "Registry") -> None:
        self.name = name
        self.help_text = help_text
        self._mu = threading.Lock()
        self._values: Dict[LabelKey, float] = {}
        registry._register(self)

    def labels_values(self) -> List[Tuple[LabelKey, float]]:
        with self._mu:
            return list(self._values.items())

    def clear(self) -> None:
        with self._mu:
            self._values.clear()

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._values.pop(_label_key(labels), None)


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._values[_label_key(labels)] = float(value)

    def get(self, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        with self._mu:
            return self._values.get(_label_key(labels))


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        k = _label_key(labels)
        with self._mu:
            self._values[k] = self._values.get(k, 0.0) + amount

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._mu:
            return self._values.get(_label_key(labels), 0.0)


class Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, m: _Metric) -> None:
        with self._mu:
            if m.name in self._metrics:
                raise ValueError(f"metric already registered: {m.name}")
            self._metrics[m.name] = m

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        with self._mu:
            existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, Gauge):
                raise TypeError(f"{name} is not a gauge")
            return existing
        return Gauge(name, help_text, self)

    def counter(self, name: str, help_text: str = "") -> Counter:
        with self._mu:
            existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, Counter):
                raise TypeError(f"{name} is not a counter")
            return existing
        return Counter(name, help_text, self)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._metrics.pop(name, None)

    def all_metrics(self) -> List[_Metric]:
        with self._mu:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # -- exposition --------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text format for /metrics
        (reference: pkg/server/server.go:415-418)."""
        lines: List[str] = []
        for m in self.all_metrics():
            if m.help_text:
                # exposition format: HELP text escapes backslash + newline
                escaped = m.help_text.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {m.name} {escaped}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            for key, value in sorted(m.labels_values()):
                lines.append(f"{m.name}{_render_labels(key)} {_format_value(value)}")
        return "\n".join(lines) + "\n"

    def gather(self, now: Optional[float] = None) -> List[Tuple[int, str, Dict[str, str], float]]:
        """Snapshot for the scraper: (unix_seconds, name, labels, value)."""
        ts = int(now if now is not None else time.time())
        out = []
        for m in self.all_metrics():
            for key, value in m.labels_values():
                out.append((ts, m.name, dict(key), value))
        return out


def _format_value(v: float) -> str:
    # non-finite values per the exposition format — one inf/NaN gauge
    # (e.g. a stray division) must not 500 the whole /metrics endpoint
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


# package-global default registry (reference: pkg/metrics/registry.go:5)
DEFAULT_REGISTRY = Registry()


def gauge(name: str, help_text: str = "") -> Gauge:
    return DEFAULT_REGISTRY.gauge(name, help_text)


def counter(name: str, help_text: str = "") -> Counter:
    return DEFAULT_REGISTRY.counter(name, help_text)
