"""Subprocess/script runner.

Reference: pkg/process/process.go:21-431 (Process with Start/Wait/combined
output) and pkg/process/runner.go:14-21 + runner_exclusive.go
(Runner/ExclusiveRunner for serialized bash-script execution — plugins must
never run concurrently with each other).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

DEFAULT_TIMEOUT = 60.0


@dataclass
class RunResult:
    exit_code: int = 0
    output: str = ""         # combined stdout+stderr (reference semantics)
    error: str = ""          # runner-level error (timeout, spawn failure)
    duration_seconds: float = 0.0
    timed_out: bool = False

    @property
    def ok(self) -> bool:
        return self.exit_code == 0 and not self.error


def run_command(
    argv: List[str],
    timeout: float = DEFAULT_TIMEOUT,
    env: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Run an argv command, returning combined output (never raises)."""
    t0 = time.monotonic()
    try:
        cp = subprocess.run(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            timeout=timeout,
            env={**os.environ, **(env or {})},
            check=False,
        )
        return RunResult(
            exit_code=cp.returncode,
            output=cp.stdout.decode("utf-8", "replace"),
            duration_seconds=time.monotonic() - t0,
        )
    except subprocess.TimeoutExpired as e:
        out = (e.output or b"").decode("utf-8", "replace") if e.output else ""
        return RunResult(
            exit_code=-1,
            output=out,
            error=f"timed out after {timeout}s",
            duration_seconds=time.monotonic() - t0,
            timed_out=True,
        )
    except (OSError, ValueError) as e:
        return RunResult(
            exit_code=-1,
            error=str(e),
            duration_seconds=time.monotonic() - t0,
        )


def run_shell(
    command: str,
    timeout: float = DEFAULT_TIMEOUT,
    env: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Run a shell command string (for nsenter-style overrides where the
    whole command line is configured, reference: components/registry.go:46-64)."""
    return run_command(["bash", "-c", command], timeout=timeout, env=env)


def run_bash_script(
    script: str,
    timeout: float = DEFAULT_TIMEOUT,
    env: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Write a multi-line bash script to a temp file and execute it — the
    custom-plugin step contract (reference: pkg/custom-plugins/types.go:108-130)."""
    with tempfile.NamedTemporaryFile(
        "w", suffix=".sh", prefix="tpud-", delete=False
    ) as f:
        f.write(script)
        path = f.name
    try:
        os.chmod(path, 0o700)
        return run_command(["bash", path], timeout=timeout, env=env)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def split_command(command: str) -> List[str]:
    return shlex.split(command)


class ExclusiveRunner:
    """Serializes script execution across plugin components
    (reference: pkg/process/runner_exclusive.go)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.last_run: Dict[str, float] = {}

    def run_script(
        self,
        name: str,
        script: str,
        timeout: float = DEFAULT_TIMEOUT,
        env: Optional[Dict[str, str]] = None,
    ) -> RunResult:
        with self._mu:
            self.last_run[name] = time.time()
            return run_bash_script(script, timeout=timeout, env=env)
