"""Remediation: policy-gated auto-repair of suggested actions.

The daemon's components *diagnose* — checks emit
``HealthState.suggested_actions`` (REBOOT_SYSTEM, HARDWARE_INSPECTION, …)
and the health ledger records every flip — but nothing local *acts* on a
diagnosis. This package closes the detect → repair loop on-node:

- ``policy``  — what is allowed to run (allowlist, cooldowns, rate limit,
  reboot-window guard, escalation thresholds); default: everything dry-run.
- ``audit``   — every attempt persisted to SQLite (action, trigger state,
  policy decision, outcome, duration), retention via ``RetentionPurger``.
- ``actions`` — the executors: soft tier (re-trigger check, set-healthy,
  restart the TPU runtime unit) and hard tier (guarded host reboot).
- ``engine``  — the scan loop tying them together.

See docs/remediation.md for the operator-facing contract.
"""

from gpud_tpu.remediation.audit import AuditStore
from gpud_tpu.remediation.engine import RemediationEngine
from gpud_tpu.remediation.policy import Policy

__all__ = ["AuditStore", "Policy", "RemediationEngine"]
