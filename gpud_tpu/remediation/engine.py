"""Remediation engine: the detect → decide → repair → audit scan loop.

Every scan walks the component registry, collects pending
``SuggestedActions`` from the latest health states, and runs each through
the policy ladder:

  escalated?  → stop retrying (HARDWARE_INSPECTION marker already filed)
  cooldown    → one attempt per component per cooldown window (derived
                from the audit ledger, so it survives restarts)
  allowlist   → action not enforced ⇒ ``dry_run`` audit row, host untouched
  rate limit  → global token bucket across all enforced repairs
  reboot gate → completed reboots (reboot event store) + engine-executed
                reboots (audit ledger) inside the window cap hard repairs
  execute     → soft/hard executor; N failed soft repairs in the
                escalation window ⇒ escalate REBOOT_SYSTEM →
                HARDWARE_INSPECTION and stop

Every decision lands in the SQLite audit ledger and the
``tpud_remediation_attempts_total{action,outcome}`` counter; decision
latency is histogrammed. The loop mirrors ``PollingComponent`` (own daemon
thread, pokeable, injectable clock) and the whole subsystem is wired like
the health ledger: constructed in ``server.Server``, started in the
assembly block, closed on stop.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
)
from gpud_tpu.log import audit as audit_log
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, histogram
from gpud_tpu.remediation.actions import Executors
from gpud_tpu.remediation.audit import DEFAULT_RETENTION, AuditStore
from gpud_tpu.remediation.policy import (
    ACTION_INSPECTION,
    ACTION_PREDICTED,
    ACTION_REBOOT,
    ACTION_RESTART_RUNTIME,
    DECISION_BLOCKED_RATE_LIMIT,
    DECISION_BLOCKED_REBOOT_WINDOW,
    DECISION_DRY_RUN,
    DECISION_ESCALATE,
    DECISION_EXECUTE,
    DECISION_MANUAL,
    OUTCOME_BLOCKED_RATE_LIMIT,
    OUTCOME_BLOCKED_REBOOT_WINDOW,
    OUTCOME_DRY_RUN,
    OUTCOME_ESCALATED,
    OUTCOME_EXECUTED,
    OUTCOME_FAILED,
    OUTCOME_MANUAL,
    Policy,
    TokenBucket,
    map_suggested_action,
)
from gpud_tpu.sqlite import DB

logger = get_logger(__name__)

DEFAULT_INTERVAL = 30.0

# components whose REBOOT_SYSTEM suggestion has a cheaper soft repair the
# engine tries (and escalates from) before ever considering the host
DEFAULT_SOFT_REPAIRS: Dict[str, str] = {
    "accelerator-tpu-runtime": ACTION_RESTART_RUNTIME,
}

_c_attempts = counter(
    "tpud_remediation_attempts_total",
    "remediation attempts by action and outcome "
    "(dry_run|executed|failed|blocked_*|escalated|manual)",
)
_h_decision = histogram(
    "tpud_remediation_decision_duration_seconds",
    "policy decision + execution latency per remediation attempt, by action",
)


class RemediationEngine:
    """One engine per daemon. ``scan_once`` is synchronous and injectable-
    clock deterministic; ``start`` runs it on its own cadence thread."""

    def __init__(
        self,
        registry,
        db: DB,
        policy: Optional[Policy] = None,
        event_store=None,
        reboot_event_store=None,
        interval_seconds: float = DEFAULT_INTERVAL,
        audit_retention_seconds: int = DEFAULT_RETENTION,
        soft_repairs: Optional[Dict[str, str]] = None,
        runtime_unit: str = "",
        run_command_fn=None,
        reboot_fn=None,
        writer=None,
    ) -> None:
        self.registry = registry
        self.policy = policy or Policy()
        self.event_store = event_store
        self.reboot_event_store = reboot_event_store
        self.interval = interval_seconds
        self.audit = AuditStore(
            db, retention_seconds=audit_retention_seconds, writer=writer
        )
        self.soft_repairs = (
            dict(DEFAULT_SOFT_REPAIRS) if soft_repairs is None else dict(soft_repairs)
        )
        self.executors = Executors(
            registry=registry,
            runtime_unit=runtime_unit,
            run_command_fn=run_command_fn,
            reboot_fn=reboot_fn,
        )
        self.time_now_fn = time.time
        self.bucket = TokenBucket(self.policy)
        # components escalated to HARDWARE_INSPECTION: no more retries
        # until the component is observed Healthy again
        self._escalated: Set[str] = set()
        self._mu = threading.Lock()
        self._last_scan: Optional[float] = None
        self._stop = threading.Event()
        self._poke = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None  # scheduler Job when scheduler-driven

    # -- scan loop ---------------------------------------------------------
    def start(self, scheduler=None) -> None:
        """With a scheduler (the daemon path), the scan cadence is a heap
        job and the audit purger rides the server's consolidated
        ``retention-purge`` job — zero engine-owned threads. Without one,
        the legacy dedicated thread + per-store purger thread."""
        if scheduler is not None:
            if self._job is None and self._thread is None:
                # first scan waits out one interval like the legacy loop:
                # component first-checks must land before acting on states
                self._job = scheduler.add_job(
                    "remediation-scan",
                    self.scan_once,
                    interval=self.interval,
                    initial_delay=self.interval,
                )
            return
        if self._thread is not None:
            return
        self.audit.start_purger()
        self._thread = threading.Thread(
            target=self._loop, name="tpud-remediation", daemon=True
        )
        self._thread.start()

    def poke(self) -> None:
        if self._job is not None:
            self._job.poke()
            return
        self._poke.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._poke.wait(self.interval)
            self._poke.clear()
            if self._stop.is_set():
                return
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — one bad scan must not end repair
                logger.exception("remediation scan failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        self._poke.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.audit.close()

    # -- one scan ----------------------------------------------------------
    def scan_once(self) -> List[Dict]:
        """Walk the registry once; returns the audit rows written (newest
        view of what this scan did — tests and the status view use it)."""
        now = self.time_now_fn()
        written: List[Dict] = []
        with self._mu:
            self._last_scan = now
            for comp in self.registry.all():
                # a component may be deregistered / mid-close while this
                # scan holds a reference to it (chaos campaigns, dynamic
                # registries): any failure is that component's problem,
                # recorded as a Warning event — the scan itself never dies
                name = ""
                try:
                    name = comp.name()
                    states = comp.last_health_states()
                    row = self._scan_component(name, states, now)
                except Exception as e:  # noqa: BLE001
                    name = name or comp.__class__.__name__
                    logger.exception("remediation scan of %s failed", name)
                    self._emit_scan_warning(name, e, now)
                    continue
                if row is not None:
                    written.append(row)
        return written

    def _emit_scan_warning(self, name: str, exc: Exception, now: float) -> None:
        es = self.event_store
        if es is None:
            return
        try:
            es.bucket(name).insert(
                Event(
                    component=name,
                    time=now,
                    name="remediation_scan_error",
                    type=EventType.WARNING,
                    message=(
                        f"component unavailable during remediation scan: "
                        f"{type(exc).__name__}: {exc}"
                    ),
                )
            )
        except Exception:  # noqa: BLE001 — accounting must not kill the scan
            logger.exception("scan-warning event emit failed for %s", name)

    def _scan_component(
        self, name: str, states, now: float
    ) -> Optional[Dict]:
        # a Healthy observation clears the stop-retrying latch: the fault
        # is gone (repaired out-of-band or self-cleared), so a future
        # diagnosis is a NEW episode that deserves fresh attempts
        if all(s.health == HealthStateType.HEALTHY for s in states):
            self._escalated.discard(name)
            return None
        for state in states:
            sa = state.suggested_actions
            if sa is None or state.health == HealthStateType.HEALTHY:
                continue
            for suggested in sa.repair_actions:
                action = map_suggested_action(
                    suggested, self.soft_repairs.get(name)
                )
                if action is None:
                    continue
                # one attempt per component per scan: the first actionable
                # suggestion wins (states arrive severity-ordered from the
                # component's own check)
                return self._attempt(name, state, suggested, action, now)
        return None

    def _attempt(
        self, name: str, state, suggested: str, action: str, now: float
    ) -> Optional[Dict]:
        if name in self._escalated:
            return None  # escalated: stop retrying until Healthy
        last = self.audit.last_attempt_time(
            name, exclude_action=ACTION_PREDICTED
        )
        if last is not None and now - last < self.policy.cooldown_seconds:
            return None  # in cooldown — not a new attempt, no audit noise
        t0 = time.monotonic()
        decision, outcome, detail, duration = self._decide_and_run(
            name, suggested, action, now
        )
        _h_decision.observe(time.monotonic() - t0, {"action": action})
        row = {
            "time": now,
            "component": name,
            "action": action,
            "suggested": suggested,
            "trigger_health": state.health,
            "trigger_reason": state.reason,
            "decision": decision,
            "outcome": outcome,
            "detail": detail,
            "duration_seconds": duration,
        }
        self.audit.record(
            component=name,
            action=action,
            suggested=suggested,
            trigger_health=state.health,
            trigger_reason=state.reason,
            decision=decision,
            outcome=outcome,
            detail=detail,
            duration_seconds=duration,
            ts=now,
        )
        _c_attempts.inc(labels={"action": action, "outcome": outcome})
        if outcome in (OUTCOME_EXECUTED, OUTCOME_FAILED, OUTCOME_ESCALATED):
            audit_log(
                "remediation_attempt",
                component=name,
                repair=action,
                outcome=outcome,
            )
            self._emit_event(name, action, outcome, detail, now)
        return row

    def _decide_and_run(self, name: str, suggested: str, action: str, now: float):
        """Returns (decision, outcome, detail, duration_seconds)."""
        if action == ACTION_INSPECTION:
            return (
                DECISION_MANUAL,
                OUTCOME_MANUAL,
                "hardware inspection required; no automated repair",
                0.0,
            )
        if not self.policy.is_enforced(action):
            return (
                DECISION_DRY_RUN,
                OUTCOME_DRY_RUN,
                f"{action} not in the enforce allowlist; no host mutation",
                0.0,
            )
        if not self.bucket.take(now):
            return (
                DECISION_BLOCKED_RATE_LIMIT,
                OUTCOME_BLOCKED_RATE_LIMIT,
                "global repair rate limit exhausted",
                0.0,
            )
        if action == ACTION_REBOOT:
            n = self.reboots_in_window(now)
            if n >= self.policy.max_reboots:
                return (
                    DECISION_BLOCKED_REBOOT_WINDOW,
                    OUTCOME_BLOCKED_REBOOT_WINDOW,
                    f"{n} reboot(s) already inside the "
                    f"{self.policy.reboot_window_seconds:g}s window "
                    f"(max {self.policy.max_reboots})",
                    0.0,
                )
        t0 = time.monotonic()
        ok, detail = self._execute(name, action)
        duration = time.monotonic() - t0
        if ok:
            return DECISION_EXECUTE, OUTCOME_EXECUTED, detail, duration
        # a soft repair standing in for REBOOT_SYSTEM that keeps failing
        # escalates to HARDWARE_INSPECTION instead of retrying forever
        if (
            suggested == RepairActionType.REBOOT_SYSTEM
            and action != ACTION_REBOOT
            and self._failed_attempts(name, now) + 1
            >= self.policy.escalation_threshold
        ):
            self._escalated.add(name)
            return (
                DECISION_ESCALATE,
                OUTCOME_ESCALATED,
                f"{self.policy.escalation_threshold} failed soft repairs "
                f"inside {self.policy.escalation_window_seconds:g}s; "
                f"escalating to hardware inspection (last: {detail})",
                duration,
            )
        return DECISION_EXECUTE, OUTCOME_FAILED, detail, duration

    def _execute(self, name: str, action: str):
        fn = getattr(self.executors, action, None)
        if fn is None:
            return False, f"no executor for action {action!r}"
        return fn(name)

    def _failed_attempts(self, name: str, now: float) -> int:
        return self.audit.count(
            component=name,
            outcomes=[OUTCOME_FAILED],
            since=now - self.policy.escalation_window_seconds,
        )

    def reboots_in_window(self, now: Optional[float] = None) -> int:
        """Completed reboots (event store) + engine-executed reboots
        (audit). Deliberately conservative: an executed reboot usually
        also produces a boot event next boot, and double-counting errs on
        the side of NOT reboot-cycling a node."""
        ts = self.time_now_fn() if now is None else now
        since = ts - self.policy.reboot_window_seconds
        n = 0
        if self.reboot_event_store is not None:
            try:
                n += len(self.reboot_event_store.get_reboot_events(since))
            except Exception:  # noqa: BLE001
                logger.exception("reboot event lookup failed")
        n += self.audit.count(
            action=ACTION_REBOOT, outcomes=[OUTCOME_EXECUTED], since=since
        )
        return n

    def _emit_event(
        self, name: str, action: str, outcome: str, detail: str, now: float
    ) -> None:
        es = self.event_store
        if es is None:
            return
        try:
            es.bucket(name).insert(
                Event(
                    component=name,
                    time=now,
                    name="remediation",
                    type=(
                        EventType.WARNING
                        if outcome != OUTCOME_EXECUTED
                        else EventType.INFO
                    ),
                    message=f"remediation {action}: {outcome} ({detail})",
                    extra_info={"action": action, "outcome": outcome},
                )
            )
        except Exception:  # noqa: BLE001 — accounting must not kill the scan
            logger.exception("remediation event emit failed for %s", name)

    # -- status ------------------------------------------------------------
    def status(self) -> Dict:
        """Policy + guard state rollup for HTTP/session/CLI views."""
        now = self.time_now_fn()
        return {
            "policy": self.policy.to_dict(),
            "escalated": sorted(self._escalated),
            "rate_tokens_available": round(self.bucket.available(now), 3),
            "reboots_in_window": self.reboots_in_window(now),
            "last_scan": self._last_scan,
            "interval_seconds": self.interval,
            "soft_repairs": dict(self.soft_repairs),
            "audit": self.audit.summary(),
        }
