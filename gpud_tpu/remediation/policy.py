"""Remediation policy: what may run, how often, and when to give up.

The policy is deliberately *deny-by-default*: with an empty
``enforce_actions`` allowlist every suggested action is decided ``dry_run``
— the full detect → decide → audit pipeline runs, nothing mutates the
host. Operators graduate one action type at a time by allowlisting it
(``POST /v1/remediation/policy``), watching the audit ledger the whole
way (docs/remediation.md).

Guardrails the engine enforces on top of the allowlist:

- per-component cooldown — one attempt per component per window;
- global token bucket — a burst of simultaneous diagnoses cannot fan out
  into a burst of repairs;
- max-reboots-per-window — counts completed reboots (the reboot event
  store) plus reboots this engine executed (the audit ledger), so a
  repair loop can never reboot-cycle a node;
- escalation — N failed soft repairs inside a window escalate
  REBOOT_SYSTEM → HARDWARE_INSPECTION and stop retrying.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# internal action vocabulary: these land in audit rows and metric labels
ACTION_RETRIGGER_CHECK = "retrigger_check"
ACTION_SET_HEALTHY = "set_healthy"
ACTION_RESTART_RUNTIME = "restart_runtime"
ACTION_REBOOT = "reboot_system"
ACTION_INSPECTION = "hardware_inspection"
# advisory marker written by the predict engine's early warnings; never
# executable, and deliberately its own cooldown lane — a prediction must
# never defer the reactive repair of the fault it predicted
ACTION_PREDICTED = "predicted_warning"

# actions an operator can allowlist; INSPECTION is a manual marker and
# never executes, so allowlisting it would be meaningless
EXECUTABLE_ACTIONS = (
    ACTION_RETRIGGER_CHECK,
    ACTION_SET_HEALTHY,
    ACTION_RESTART_RUNTIME,
    ACTION_REBOOT,
)

# policy decisions / audit outcomes
DECISION_DRY_RUN = "dry_run"
DECISION_EXECUTE = "execute"
DECISION_BLOCKED_RATE_LIMIT = "blocked_rate_limit"
DECISION_BLOCKED_REBOOT_WINDOW = "blocked_reboot_window"
DECISION_ESCALATE = "escalate"
DECISION_MANUAL = "manual"

OUTCOME_DRY_RUN = "dry_run"
OUTCOME_EXECUTED = "executed"
OUTCOME_FAILED = "failed"
OUTCOME_BLOCKED_RATE_LIMIT = "blocked_rate_limit"
OUTCOME_BLOCKED_REBOOT_WINDOW = "blocked_reboot_window"
OUTCOME_ESCALATED = "escalated"
OUTCOME_MANUAL = "manual"

DEFAULT_COOLDOWN = 300.0
DEFAULT_RATE_CAPACITY = 6
DEFAULT_RATE_REFILL_SECONDS = 600.0  # one token back per 10 minutes
DEFAULT_MAX_REBOOTS = 2
DEFAULT_REBOOT_WINDOW = 3600.0
DEFAULT_ESCALATION_THRESHOLD = 3
DEFAULT_ESCALATION_WINDOW = 3600.0


@dataclass
class Policy:
    """Runtime-updatable policy knobs. ``update`` applies a partial dict
    key-by-key (one invalid value must not block the rest — the
    updateConfig contract) and returns (updated_keys, errors)."""

    enforce_actions: List[str] = field(default_factory=list)
    cooldown_seconds: float = DEFAULT_COOLDOWN
    rate_capacity: int = DEFAULT_RATE_CAPACITY
    rate_refill_seconds: float = DEFAULT_RATE_REFILL_SECONDS
    max_reboots: int = DEFAULT_MAX_REBOOTS
    reboot_window_seconds: float = DEFAULT_REBOOT_WINDOW
    escalation_threshold: int = DEFAULT_ESCALATION_THRESHOLD
    escalation_window_seconds: float = DEFAULT_ESCALATION_WINDOW

    def is_enforced(self, action: str) -> bool:
        return action in self.enforce_actions

    def to_dict(self) -> Dict:
        return {
            "enforce_actions": sorted(self.enforce_actions),
            "cooldown_seconds": self.cooldown_seconds,
            "rate_capacity": self.rate_capacity,
            "rate_refill_seconds": self.rate_refill_seconds,
            "max_reboots": self.max_reboots,
            "reboot_window_seconds": self.reboot_window_seconds,
            "escalation_threshold": self.escalation_threshold,
            "escalation_window_seconds": self.escalation_window_seconds,
        }

    # (attr, coerce, floor) — `not >= floor` also rejects NaN, which
    # json.loads happily produces from a bare NaN token
    _NUMERIC: Tuple = (
        ("cooldown_seconds", float, 0.0),
        ("rate_capacity", int, 1),
        ("rate_refill_seconds", float, 1.0),
        ("max_reboots", int, 1),
        ("reboot_window_seconds", float, 60.0),
        ("escalation_threshold", int, 1),
        ("escalation_window_seconds", float, 60.0),
    )

    def update(self, cfg: Dict) -> Tuple[List[str], List[str]]:
        updated: List[str] = []
        errors: List[str] = []
        if not isinstance(cfg, dict):
            return updated, ["policy update must be an object"]
        if "enforce_actions" in cfg:
            v = cfg["enforce_actions"]
            if not isinstance(v, list) or any(
                not isinstance(a, str) for a in v
            ):
                errors.append("enforce_actions: must be a list of action names")
            else:
                unknown = sorted(set(v) - set(EXECUTABLE_ACTIONS))
                if unknown:
                    errors.append(
                        f"enforce_actions: unknown action(s) {unknown}; "
                        f"known: {list(EXECUTABLE_ACTIONS)}"
                    )
                else:
                    self.enforce_actions = sorted(set(v))
                    updated.append("enforce_actions")
        for key, coerce, floor in self._NUMERIC:
            if key not in cfg:
                continue
            try:
                val = coerce(cfg[key])
                if not val >= floor:
                    raise ValueError(f"must be >= {floor}")
            except (TypeError, ValueError) as e:
                errors.append(f"{key}: {e}")
                continue
            setattr(self, key, val)
            updated.append(key)
        return updated, errors


class TokenBucket:
    """Global repair rate limit. Reads capacity/refill from the policy on
    every ``take`` so runtime policy pushes apply without a rebuild."""

    def __init__(self, policy: Policy) -> None:
        self.policy = policy
        self._tokens = float(policy.rate_capacity)
        self._last: Optional[float] = None

    def _refill(self, now: float) -> None:
        cap = float(self.policy.rate_capacity)
        if self._last is not None and now > self._last:
            self._tokens += (now - self._last) / self.policy.rate_refill_seconds
        self._tokens = min(cap, self._tokens)
        self._last = now

    def take(self, now: float) -> bool:
        self._refill(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def available(self, now: float) -> float:
        self._refill(now)
        return self._tokens


def map_suggested_action(
    repair_action: str, soft_repair: Optional[str]
) -> Optional[str]:
    """Map a wire ``RepairActionType`` to the engine's action vocabulary.

    ``soft_repair`` is the component's configured soft alternative for a
    REBOOT_SYSTEM suggestion (e.g. restart the runtime unit first); the
    escalation guard is what eventually stops a soft repair that never
    sticks. Returns None for IGNORE / unknown actions."""
    from gpud_tpu.api.v1.types import RepairActionType

    if repair_action == RepairActionType.IGNORE_NO_ACTION_REQUIRED:
        return None
    if repair_action == RepairActionType.PREDICTED_DEGRADATION:
        # the predict engine's own warning path audits these as dry_run;
        # a component echoing the suggestion must still never execute
        return None
    if repair_action == RepairActionType.CHECK_USER_APP_AND_TPU:
        return ACTION_RETRIGGER_CHECK
    if repair_action == RepairActionType.REBOOT_SYSTEM:
        return soft_repair or ACTION_REBOOT
    if repair_action == RepairActionType.HARDWARE_INSPECTION:
        return ACTION_INSPECTION
    return None
