"""Remediation action executors.

Soft tier first, hard tier last — the same ladder an operator walks by
hand: re-run the check (the fault may have cleared), clear a sticky state,
restart the TPU runtime unit, and only then reboot the host. Every
executor returns ``(ok, detail)`` and never raises: the engine records the
outcome in the audit ledger either way, and one misbehaving executor must
not kill the scan loop.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

from gpud_tpu import host as pkghost
from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.log import get_logger
from gpud_tpu.process import run_command

logger = get_logger(__name__)

# default systemd unit the restart_runtime executor bounces; mirrors the
# runtime component's unit list (components/tpu/runtime.py RUNTIME_UNITS)
DEFAULT_RUNTIME_UNIT = "tpu-runtime.service"

RESTART_TIMEOUT = 60.0


class Executors:
    """Executor set with injectable process/reboot functions (tests swap
    ``run_command_fn``/``reboot_fn`` exactly like the dispatcher's
    ``reboot_fn``)."""

    def __init__(
        self,
        registry=None,
        runtime_unit: str = "",
        run_command_fn: Optional[Callable] = None,
        reboot_fn: Optional[Callable] = None,
    ) -> None:
        self.registry = registry
        self.runtime_unit = runtime_unit or DEFAULT_RUNTIME_UNIT
        self.run_command_fn = run_command_fn or run_command
        # the same privileged path the session's reboot dispatch uses
        self.reboot_fn = reboot_fn or pkghost.reboot

    # -- soft tier ---------------------------------------------------------
    def retrigger_check(self, component: str) -> Tuple[bool, str]:
        """Re-run the component's check; success = it came back Healthy."""
        comp = self.registry.get(component) if self.registry else None
        if comp is None:
            return False, f"component {component!r} not found"
        try:
            cr = comp.check()
        except Exception as e:  # noqa: BLE001 — executor must not raise
            return False, f"check raised: {e}"
        health = cr.health_state_type()
        ok = health == HealthStateType.HEALTHY
        return ok, f"re-check came back {health}"

    def set_healthy(self, component: str) -> Tuple[bool, str]:
        """Clear a sticky state (only components exposing set_healthy)."""
        comp = self.registry.get(component) if self.registry else None
        if comp is None:
            return False, f"component {component!r} not found"
        fn = getattr(comp, "set_healthy", None)
        if fn is None:
            return False, f"component {component!r} is not health-settable"
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            return False, f"set_healthy raised: {e}"
        return True, "sticky state cleared"

    def restart_runtime(self, component: str) -> Tuple[bool, str]:
        """Bounce the TPU runtime systemd unit via the process runner."""
        unit = self.runtime_unit
        r = self.run_command_fn(
            ["systemctl", "restart", unit], timeout=RESTART_TIMEOUT
        )
        if r.exit_code == 0 and not r.error:
            return True, f"restarted {unit}"
        detail = r.error or r.output.strip() or f"exit {r.exit_code}"
        return False, f"systemctl restart {unit} failed: {detail}"

    # -- hard tier ---------------------------------------------------------
    def reboot_system(self, component: str) -> Tuple[bool, str]:
        """Guarded host reboot (the engine applies the reboot-window guard
        before this runs)."""
        try:
            err = self.reboot_fn()
        except Exception as e:  # noqa: BLE001
            return False, f"reboot raised: {e}"
        if err:
            return False, err
        return True, "reboot initiated"
