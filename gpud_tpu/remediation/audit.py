"""Remediation audit ledger: every repair attempt, persisted.

Auto-repair is only operable when every decision leaves a durable trail:
what the trigger was, what the policy decided, what actually ran, and how
it went. One append-only SQLite table (schema versioned like the
eventstore/health-ledger tables), purged past retention by the shared
``RetentionPurger``; the CLI opens a second store over the same state file
(daemon running or not, WAL mode) for the offline ``tpud remediation``
view.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter
from gpud_tpu.retention import RetentionPurger
from gpud_tpu.sqlite import DB

logger = get_logger(__name__)

TABLE = "tpud_remediation_audit_v0_1"

DEFAULT_RETENTION = 14 * 86400  # matches the eventstore window

_c_purged = counter(
    "tpud_remediation_audit_purged_total",
    "remediation audit rows deleted by the retention purger",
)

# write-behind contract (tools/storage_lint.py): these methods must route
# through the BatchWriter, never commit per-row via db.execute directly
HOT_WRITE_METHODS = ("record",)


class AuditStore:
    """Append-only remediation attempt ledger over the shared state DB.

    With a ``writer`` (write-behind BatchWriter), ``record`` appends into
    the shared group-commit buffer and every read runs the flush barrier
    first — mandatory here, because reads are decision inputs: the
    cooldown anchor (``last_attempt_time``) and the rate/escalation
    counters must see the attempt recorded microseconds ago or the engine
    would double-fire.
    """

    def __init__(
        self,
        db: DB,
        retention_seconds: int = DEFAULT_RETENTION,
        writer=None,
    ) -> None:
        self.db = db
        self.writer = writer
        self.retention_seconds = retention_seconds
        self.time_now_fn = time.time
        # optional post-record observer (the server wires the session
        # outbox here); must never fail the record path
        self.on_record = None
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                timestamp REAL NOT NULL,
                component TEXT NOT NULL,
                action TEXT NOT NULL,
                suggested TEXT NOT NULL,
                trigger_health TEXT NOT NULL,
                trigger_reason TEXT,
                decision TEXT NOT NULL,
                outcome TEXT NOT NULL,
                detail TEXT,
                duration_seconds REAL NOT NULL DEFAULT 0
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_comp_ts "
            f"ON {TABLE} (component, timestamp)"
        )
        self._purger = RetentionPurger(
            "tpud-remediation-audit-purger",
            retention_seconds / 5.0,
            self._purge_tick,
        )

    # -- write path --------------------------------------------------------
    def record(
        self,
        component: str,
        action: str,
        suggested: str,
        trigger_health: str,
        trigger_reason: str,
        decision: str,
        outcome: str,
        detail: str = "",
        duration_seconds: float = 0.0,
        ts: Optional[float] = None,
    ) -> None:
        sql = (
            f"INSERT INTO {TABLE} (timestamp, component, action, suggested, "
            "trigger_health, trigger_reason, decision, outcome, detail, "
            "duration_seconds) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
        )
        params = (
            self.time_now_fn() if ts is None else ts,
            component,
            action,
            suggested,
            trigger_health,
            trigger_reason or "",
            decision,
            outcome,
            detail or "",
            duration_seconds,
        )
        if self.writer is not None:
            self.writer.submit("audit", sql, params)
        else:
            self.db.execute(sql, params)
        hook = self.on_record
        if hook is not None:
            try:
                hook(
                    {
                        "ts": params[0],
                        "component": component,
                        "action": action,
                        "suggested": suggested,
                        "trigger_health": trigger_health,
                        "trigger_reason": trigger_reason or "",
                        "decision": decision,
                        "outcome": outcome,
                        "detail": detail or "",
                        "duration_seconds": duration_seconds,
                    }
                )
            except Exception:  # noqa: BLE001
                logger.exception("audit on_record hook failed")

    def flush(self) -> None:
        """Read-after-write barrier (no-op without a writer)."""
        if self.writer is not None:
            self.writer.flush()

    # -- read path ---------------------------------------------------------
    def read(
        self,
        component: Optional[str] = None,
        action: Optional[str] = None,
        outcome: Optional[str] = None,
        since: float = 0.0,
        limit: int = 0,
    ) -> List[Dict]:
        """Attempt rows, newest first."""
        self.flush()
        sql = (
            f"SELECT timestamp, component, action, suggested, trigger_health, "
            f"trigger_reason, decision, outcome, detail, duration_seconds "
            f"FROM {TABLE} WHERE timestamp>=?"
        )
        params: list = [since]
        for col, val in (
            ("component", component), ("action", action), ("outcome", outcome)
        ):
            if val:
                sql += f" AND {col}=?"
                params.append(val)
        sql += " ORDER BY timestamp DESC, id DESC"
        if limit:
            sql += " LIMIT ?"
            params.append(limit)
        return [
            {
                "time": r[0],
                "component": r[1],
                "action": r[2],
                "suggested": r[3],
                "trigger_health": r[4],
                "trigger_reason": r[5] or "",
                "decision": r[6],
                "outcome": r[7],
                "detail": r[8] or "",
                "duration_seconds": r[9],
            }
            for r in self.db.query(sql, params)
        ]

    def last_attempt_time(
        self, component: str, action: Optional[str] = None,
        exclude_action: Optional[str] = None,
    ) -> Optional[float]:
        """Newest audit row for the component — the cooldown anchor.

        ``action`` narrows to one action's lane (the predict engine
        anchors its warning cooldown on its own rows); ``exclude_action``
        carves a lane out (the reactive engine excludes predicted rows so
        an early warning never defers the repair it predicted)."""
        self.flush()
        sql = f"SELECT MAX(timestamp) FROM {TABLE} WHERE component=?"
        params: list = [component]
        if action:
            sql += " AND action=?"
            params.append(action)
        if exclude_action:
            sql += " AND action<>?"
            params.append(exclude_action)
        row = self.db.query_one(sql, params)
        return row[0] if row and row[0] is not None else None

    def count(
        self,
        component: Optional[str] = None,
        action: Optional[str] = None,
        outcomes: Optional[List[str]] = None,
        since: float = 0.0,
    ) -> int:
        self.flush()
        sql = f"SELECT COUNT(*) FROM {TABLE} WHERE timestamp>=?"
        params: list = [since]
        if component:
            sql += " AND component=?"
            params.append(component)
        if action:
            sql += " AND action=?"
            params.append(action)
        if outcomes:
            sql += f" AND outcome IN ({','.join('?' * len(outcomes))})"
            params.extend(outcomes)
        row = self.db.query_one(sql, params)
        return int(row[0]) if row else 0

    def summary(self) -> Dict:
        """Rollup for status views: total rows + per-outcome counts."""
        self.flush()
        rows = self.db.query(
            f"SELECT outcome, COUNT(*) FROM {TABLE} GROUP BY outcome"
        )
        by_outcome = {r[0]: int(r[1]) for r in rows}
        return {
            "attempts_total": sum(by_outcome.values()),
            "by_outcome": by_outcome,
        }

    # -- retention ---------------------------------------------------------
    def start_purger(self, scheduler=None) -> None:
        self._purger.start(scheduler)

    def purge_once(self) -> None:
        """One retention pass now (consolidated scheduler job hook)."""
        self._purge_tick()

    def _purge_tick(self) -> None:
        self.flush()  # never let a buffered row dodge (or outlive) the purge
        cutoff = self.time_now_fn() - self.retention_seconds
        n = self.db.execute(
            f"DELETE FROM {TABLE} WHERE timestamp<?", (cutoff,)
        ).rowcount
        if n:
            _c_purged.inc(n)
            logger.info("remediation audit purged %d rows", n)

    def close(self) -> None:
        self._purger.close()
