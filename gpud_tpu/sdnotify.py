"""systemd readiness notification (sd_notify protocol).

Reference: the daemon runs as Type=notify with sd_notify READY/STOPPING
calls (pkg/gpud-manager/systemd/gpud.service:1-37, cmd/gpud/run —
pkgsystemd.NotifyReady / server HandleSignals). The protocol is a single
datagram to the unix socket in ``NOTIFY_SOCKET``; a leading '@' means a
Linux abstract socket. No-op when systemd isn't supervising us.
"""

from __future__ import annotations

import os
import socket

from gpud_tpu.log import get_logger

logger = get_logger(__name__)


def notify(state: str) -> bool:
    """Send one sd_notify state string; returns True when delivered."""
    addr = os.environ.get("NOTIFY_SOCKET", "")
    if not addr:
        return False
    if addr.startswith("@"):
        addr = "\0" + addr[1:]
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM) as s:
            s.connect(addr)
            s.send(state.encode())
        return True
    except OSError as e:
        logger.warning("sd_notify(%s) failed: %s", state, e)
        return False


def ready() -> bool:
    return notify("READY=1")


def stopping() -> bool:
    return notify("STOPPING=1")


def status(text: str) -> bool:
    return notify(f"STATUS={text}")
