"""Minimal CRI (Container Runtime Interface) gRPC client.

Reference: components/containerd/cri.go — the reference lists pods and
containers over the containerd CRI socket using k8s.io/cri-api. Vendoring
the full CRI proto tree is ~10k lines for the three RPCs we need, so this
module carries a small protobuf wire-format codec and hand-written message
shapes for exactly:

- ``runtime.v1.RuntimeService/Version``
- ``runtime.v1.RuntimeService/ListContainers``
- ``runtime.v1.RuntimeService/ListPodSandbox``

(with a ``runtime.v1alpha2`` fallback for older containerd). gRPC framing
comes from grpcio with identity serializers; only the protobuf payloads
are hand-coded. Field numbers follow k8s.io/cri-api/pkg/apis/runtime/v1.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

DEFAULT_SOCKET = "/run/containerd/containerd.sock"
DEFAULT_TIMEOUT = 5.0

CONTAINER_STATES = {
    0: "created",
    1: "running",
    2: "exited",
    3: "unknown",
}
SANDBOX_STATES = {0: "ready", 1: "notready"}


# ---------------------------------------------------------------------------
# protobuf wire-format codec (encode used for requests and test fixtures,
# decode for responses)
# ---------------------------------------------------------------------------

def encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_field_varint(field: int, v: int) -> bytes:
    return encode_varint(field << 3 | 0) + encode_varint(v)


def encode_field_bytes(field: int, data: bytes) -> bytes:
    return encode_varint(field << 3 | 2) + encode_varint(len(data)) + data


def encode_field_str(field: int, s: str) -> bytes:
    return encode_field_bytes(field, s.encode("utf-8"))


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def parse_message(data: bytes) -> Dict[int, List]:
    """Parse one protobuf message into {field_number: [raw values]} —
    ints for varint/fixed fields, bytes for length-delimited ones."""
    fields: Dict[int, List] = {}
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 0x7
        if wire == 0:  # varint
            v, i = _read_varint(data, i)
        elif wire == 1:  # 64-bit
            if i + 8 > len(data):
                raise ValueError("truncated fixed64")
            v = struct.unpack_from("<q", data, i)[0]
            i += 8
        elif wire == 2:  # length-delimited
            ln, i = _read_varint(data, i)
            if i + ln > len(data):
                raise ValueError("truncated bytes field")
            v = data[i : i + ln]
            i += ln
        elif wire == 5:  # 32-bit
            if i + 4 > len(data):
                raise ValueError("truncated fixed32")
            v = struct.unpack_from("<i", data, i)[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def _first_str(fields: Dict[int, List], n: int) -> str:
    v = fields.get(n, [b""])[0]
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)


def _first_int(fields: Dict[int, List], n: int) -> int:
    v = fields.get(n, [0])[0]
    return v if isinstance(v, int) else 0


def _parse_map_entry(data: bytes) -> Tuple[str, str]:
    f = parse_message(data)
    return _first_str(f, 1), _first_str(f, 2)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class CRIClient:
    """Talks CRI over a unix socket. All methods raise ``CRIError`` on
    transport/decode failure so callers can classify 'socket present but
    runtime unresponsive'."""

    def __init__(
        self,
        socket_path: str = DEFAULT_SOCKET,
        timeout: float = DEFAULT_TIMEOUT,
        target: str = "",
    ) -> None:
        # `target` overrides the unix socket (tests use localhost tcp)
        self.target = target or f"unix://{socket_path}"
        self.timeout = timeout
        self._channel = None
        self._api_version = "v1"

    def _chan(self):
        if self._channel is None:
            import grpc

            self._channel = grpc.insecure_channel(self.target)
        return self._channel

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None

    def _call(self, method: str, request: bytes) -> bytes:
        import grpc

        full = f"/runtime.{self._api_version}.RuntimeService/{method}"
        fn = self._chan().unary_unary(
            full,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        try:
            return fn(request, timeout=self.timeout)
        except grpc.RpcError as e:
            # older containerd serves only v1alpha2 — same wire shapes
            if (
                self._api_version == "v1"
                and e.code() == grpc.StatusCode.UNIMPLEMENTED
            ):
                self._api_version = "v1alpha2"
                return self._call(method, request)
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                # neither API served: CRI plugin disabled, not a failure
                raise CRIUnservedError(f"{method}: CRI not served") from e
            raise CRIError(f"{method}: {e.code().name}: {e.details()}") from e

    def snapshot(self) -> Dict:
        """Version + container/sandbox listing in one call set; raises
        CRIUnservedError when the runtime deliberately doesn't serve CRI,
        CRIError/RpcError on real failures."""
        return {
            "version": self.version(),
            "containers": self.list_containers(),
            "sandboxes": self.list_pod_sandboxes(),
        }

    # -- RPCs -------------------------------------------------------------
    def version(self) -> Dict[str, str]:
        raw = self._call("Version", encode_field_str(1, "v1"))
        f = parse_message(raw)
        return {
            "version": _first_str(f, 1),
            "runtime_name": _first_str(f, 2),
            "runtime_version": _first_str(f, 3),
            "runtime_api_version": _first_str(f, 4),
        }

    def list_containers(self) -> List[Dict]:
        raw = self._call("ListContainers", b"")
        out = []
        for c in parse_message(raw).get(1, []):
            f = parse_message(c)
            meta = parse_message(f.get(3, [b""])[0])
            labels = dict(
                _parse_map_entry(e) for e in f.get(8, [])
            )
            out.append(
                {
                    "id": _first_str(f, 1),
                    "pod_sandbox_id": _first_str(f, 2),
                    "name": _first_str(meta, 1),
                    "image": _first_str(parse_message(f.get(4, [b""])[0]), 1),
                    "state": CONTAINER_STATES.get(_first_int(f, 6), "unknown"),
                    "created_at": _first_int(f, 7),
                    "labels": labels,
                }
            )
        return out

    def list_pod_sandboxes(self) -> List[Dict]:
        raw = self._call("ListPodSandbox", b"")
        out = []
        for p in parse_message(raw).get(1, []):
            f = parse_message(p)
            meta = parse_message(f.get(2, [b""])[0])
            out.append(
                {
                    "id": _first_str(f, 1),
                    "name": _first_str(meta, 1),
                    "namespace": _first_str(meta, 3),
                    "state": SANDBOX_STATES.get(_first_int(f, 3), "unknown"),
                    "created_at": _first_int(f, 4),
                }
            )
        return out


class CRIError(Exception):
    pass


class CRIUnservedError(CRIError):
    """The runtime answered, but with UNIMPLEMENTED on every CRI API —
    the CRI plugin is disabled (e.g. containerd as Docker's backend), which
    is a configuration, not a health failure."""


def grpc_available() -> bool:
    """grpcio is an optional extra; callers must not read its absence as a
    runtime failure."""
    try:
        import grpc  # noqa: F401

        return True
    except ImportError:
        return False


def probe(socket_path: str = DEFAULT_SOCKET, timeout: float = DEFAULT_TIMEOUT,
          target: str = "") -> Optional[Dict]:
    """One-shot snapshot; ``{"unserved": True}`` when CRI is deliberately
    not served, None on transport failure."""
    client = CRIClient(socket_path, timeout, target=target)
    try:
        return client.snapshot()
    except CRIUnservedError:
        return {"unserved": True}
    except Exception as e:  # noqa: BLE001 — callers treat None as unresponsive
        logger.debug("CRI probe failed: %s", e)
        return None
    finally:
        client.close()
