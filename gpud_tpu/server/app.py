"""HTTP API routes.

Reference: pkg/server routes (handlers_components.go:20-31,
handlers_plugins.go:14-17, handlers_healthz.go:10,
handlers_machine_info.go:13, handlers_inject_fault.go:13,
server.go:402-434):

  GET  /healthz
  GET  /v1/components            DELETE /v1/components?componentName=
  GET  /v1/components/trigger-check?componentName=|tagName=
  POST /v1/components/set-healthy?componentName=
  GET  /v1/states[?components=]
  GET  /v1/events[?startTime=&endTime=]
  GET  /v1/metrics[?since=]
  GET  /v1/info
  GET  /metrics                  (Prometheus text)
  GET  /machine-info
  POST /inject-fault
  GET  /admin/config
  GET  /admin/packages
"""

from __future__ import annotations

import json
import time
from typing import TYPE_CHECKING

from aiohttp import web

from gpud_tpu import machine_info as machineinfo
from gpud_tpu.api.v1.types import (
    ComponentEvents,
    ComponentHealthStates,
    ComponentInfo,
    ComponentMetrics,
    HealthState,
)
from gpud_tpu.fault_injector import Request as InjectRequest
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, histogram
from gpud_tpu.tracing import DEFAULT_TRACER

if TYPE_CHECKING:
    from gpud_tpu.server.server import Server

logger = get_logger(__name__)

DEFAULT_EVENTS_LOOKBACK = 3 * 3600  # /v1/events default window
DEFAULT_METRICS_LOOKBACK = 3 * 3600
DEFAULT_HISTORY_LOOKBACK = 24 * 3600  # /v1/states/history default window
DEFAULT_HISTORY_LIMIT = 256

# Prometheus text exposition content type (the scraper negotiates on the
# version parameter; a bare text/plain is accepted but non-conformant)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

DEFAULT_TRACES_LIMIT = 256

_h_http = histogram(
    "tpud_http_request_duration_seconds",
    "HTTP API request latency by route and method",
)
_c_http = counter(
    "tpud_http_requests_total",
    "HTTP API requests by route, method and status code",
)


@web.middleware
async def observe_middleware(request: web.Request, handler):
    """Per-request latency + trace recording. Route label is the matched
    route template (bounded cardinality); unmatched requests — hostile
    paths, 404 probes — collapse into one 'unmatched' label rather than
    minting a metric series per probed URL."""
    t0 = time.monotonic()
    start_unix = time.time()
    status = 500
    try:
        resp = await handler(request)
        status = resp.status
        return resp
    except web.HTTPException as e:
        status = e.status
        raise
    finally:
        duration = time.monotonic() - t0
        resource = request.match_info.route.resource
        route = resource.canonical if resource is not None else "unmatched"
        _h_http.observe(duration, {"route": route, "method": request.method})
        _c_http.inc(
            labels={"route": route, "method": request.method, "status": str(status)}
        )
        # flat record (not the thread-local span stack): concurrent requests
        # interleave on the one event-loop thread
        DEFAULT_TRACER.record(
            "http.request",
            duration,
            component="http",
            start_unix=start_unix,
            status="ok" if status < 500 else "error",
            attrs={"route": route, "method": request.method, "status": status},
        )


def _json(data, status: int = 200) -> web.Response:
    return web.Response(
        text=json.dumps(data),
        status=status,
        content_type="application/json",
    )


def _components_filter(request: web.Request):
    raw = request.query.get("components", "")
    return [c for c in raw.split(",") if c] or None


def _qfloat(req: web.Request, key: str, default: float) -> float:
    """Numeric query param; malformed input is a 400, not an unhandled 500
    (reference returns 400 on bad query input)."""
    raw = req.query.get(key)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"invalid {key}: {raw!r}"}),
            content_type="application/json",
        )


def build_app(srv: "Server") -> web.Application:
    app = web.Application(middlewares=[observe_middleware])
    r = app.router

    async def healthz(_req: web.Request) -> web.Response:
        return _json({"status": "ok", "version": srv.version})

    async def list_components(_req: web.Request) -> web.Response:
        return _json(srv.registry.names())

    async def deregister_component(req: web.Request) -> web.Response:
        name = req.query.get("componentName", "")
        comp = srv.registry.get(name)
        if comp is None:
            return _json({"error": f"component {name!r} not found"}, 404)
        if not comp.can_deregister():
            return _json({"error": f"component {name!r} is not deregisterable"}, 400)
        comp = srv.registry.deregister(name)
        if comp is not None:
            comp.close()
        return _json({"deregistered": name})

    async def trigger_check(req: web.Request) -> web.Response:
        name = req.query.get("componentName", "")
        tag = req.query.get("tagName", "")
        comps = []
        if name:
            c = srv.registry.get(name)
            if c is None:
                return _json({"error": f"component {name!r} not found"}, 404)
            comps = [c]
        elif tag:
            comps = [c for c in srv.registry.all() if tag in c.tags()]
            if not comps:
                return _json({"error": f"no components with tag {tag!r}"}, 404)
        else:
            return _json({"error": "componentName or tagName required"}, 400)
        out = []
        for c in comps:
            cr = await _run_blocking(srv, c.check)
            out.append(
                ComponentHealthStates(
                    component=c.name(), states=cr.health_states()
                ).to_dict()
            )
        return _json(out)

    async def set_healthy(req: web.Request) -> web.Response:
        name = req.query.get("componentName", "")
        c = srv.registry.get(name)
        if c is None:
            return _json({"error": f"component {name!r} not found"}, 404)
        fn = getattr(c, "set_healthy", None)
        if fn is None:
            return _json({"error": f"component {name!r} is not health-settable"}, 400)
        await _run_blocking(srv, fn)
        return _json({"set_healthy": name})

    async def states(req: web.Request) -> web.Response:
        comps = _components_filter(req)
        out = []
        for c in srv.registry.all():
            if comps and c.name() not in comps:
                continue
            if not comps and c.name() not in srv.supported_names:
                continue  # unsupported components are skipped unless asked for
            out.append(
                ComponentHealthStates(
                    component=c.name(), states=c.last_health_states()
                ).to_dict()
            )
        return _json(out)

    async def events(req: web.Request) -> web.Response:
        now = time.time()
        start = _qfloat(req, "startTime", now - DEFAULT_EVENTS_LOOKBACK)
        end = _qfloat(req, "endTime", now)
        comps = _components_filter(req)
        out = []
        for c in srv.registry.all():
            if comps and c.name() not in comps:
                continue
            if not comps and c.name() not in srv.supported_names:
                continue
            evs = [e for e in c.events(start) if e.time <= end]
            out.append(
                ComponentEvents(
                    component=c.name(), start_time=start, end_time=end, events=evs
                ).to_dict()
            )
        return _json(out)

    async def metrics_v1(req: web.Request) -> web.Response:
        now = time.time()
        since = _qfloat(req, "since", now - DEFAULT_METRICS_LOOKBACK)
        comps = _components_filter(req)
        ms = srv.metrics_store.read(since, components=comps)
        by_comp = {}
        for m in ms:
            comp = m.labels.get("component", "")
            by_comp.setdefault(comp, []).append(m)
        return _json(
            [
                ComponentMetrics(component=k, metrics=v).to_dict()
                for k, v in sorted(by_comp.items())
            ]
        )

    async def info(req: web.Request) -> web.Response:
        now = time.time()
        start = _qfloat(req, "startTime", now - DEFAULT_EVENTS_LOOKBACK)
        comps = _components_filter(req)
        ms = srv.metrics_store.read(start, components=comps)
        metrics_by_comp = {}
        for m in ms:
            metrics_by_comp.setdefault(m.labels.get("component", ""), []).append(m)
        out = []
        for c in srv.registry.all():
            if comps and c.name() not in comps:
                continue
            if not comps and c.name() not in srv.supported_names:
                continue
            out.append(
                ComponentInfo(
                    component=c.name(),
                    start_time=start,
                    end_time=now,
                    states=c.last_health_states(),
                    events=c.events(start),
                    metrics=metrics_by_comp.get(c.name(), []),
                ).to_dict()
            )
        if not comps:
            # self-observability summary rides along as a pseudo-component
            # entry so existing list-shaped consumers keep parsing
            out.append(_self_info_entry(srv, start, now))
        return _json(out)

    async def states_history(req: web.Request) -> web.Response:
        """Persisted health-transition timeline from the ledger
        (?component=&since=&limit=&correlationSeconds=); each transition
        carries the eventstore events within ±correlation window."""
        ledger = srv.health_ledger
        component = req.query.get("component", "") or None
        since = _qfloat(
            req, "since", time.time() - DEFAULT_HISTORY_LOOKBACK
        )
        limit = int(_qfloat(req, "limit", DEFAULT_HISTORY_LIMIT))
        if limit < 0:
            limit = DEFAULT_HISTORY_LIMIT
        corr = _qfloat(req, "correlationSeconds", ledger.correlation_window)
        transitions = ledger.history(
            component=component, since=since, limit=limit
        )
        ledger.annotate_with_events(transitions, window=corr)
        out = {
            "transitions": transitions,
            "count": len(transitions),
            "flapping": ledger.flapping_components(),
        }
        if component:
            av = ledger.availability(component)
            if av is not None:
                out["availability"] = av
        return _json(out)

    async def remediation_audit(req: web.Request) -> web.Response:
        """Remediation audit ledger: every policy decision and repair
        attempt (?component=&action=&outcome=&since=&limit=), newest
        first, plus the engine's guard-state rollup."""
        eng = srv.remediation
        if eng is None:
            return _json({"error": "remediation engine disabled"}, 404)
        component = req.query.get("component", "") or None
        action = req.query.get("action", "") or None
        outcome = req.query.get("outcome", "") or None
        since = _qfloat(req, "since", 0.0)
        limit = int(_qfloat(req, "limit", DEFAULT_HISTORY_LIMIT))
        if limit < 0:
            limit = DEFAULT_HISTORY_LIMIT
        attempts = eng.audit.read(
            component=component, action=action, outcome=outcome,
            since=since, limit=limit,
        )
        return _json(
            {
                "attempts": attempts,
                "count": len(attempts),
                "status": eng.status(),
            }
        )

    async def predict_scores(req: web.Request) -> web.Response:
        """Precursor scores (docs/predict.md): per-component fused score,
        feature breakdown, armed/warned state, and measured lead times
        (?component= narrows; ?history=N appends the last N in-memory
        score points per component)."""
        eng = srv.predictor
        if eng is None:
            return _json({"error": "predict engine disabled"}, 404)
        component = req.query.get("component", "")
        history = int(_qfloat(req, "history", 0.0))
        if history < 0:
            history = 0
        out = eng.scores(component=component, history_limit=history)
        out["status"] = eng.status()
        return _json(out)

    async def predict_calibration(req: web.Request) -> web.Response:
        """Threshold calibration state (docs/predict.md): per-class
        fitted thresholds/weights replayed from the node's own ledger
        history, with provenance (calibrated vs thin-history default).
        ?refit=1 re-fits synchronously before answering."""
        eng = srv.predictor
        if eng is None:
            return _json({"error": "predict engine disabled"}, 404)
        if req.query.get("refit", "") in ("1", "true"):
            await _run_blocking(srv, eng.calibrate_now)
        return _json(eng.calibration())

    async def fabric_matrix(req: web.Request) -> web.Response:
        """Fabric observability (docs/fabric.md): discovered mesh, sweep
        status, and the current per-link (src_chip, dst_chip, axis,
        latency, state) matrix. ?link=, ?since=, or ?limit= appends
        matrix history rows from the durable store (newest first)."""
        plane = getattr(srv, "fabric", None)
        if plane is None:
            return _json({"error": "fabric plane disabled"}, 404)
        link = req.query.get("link", "")
        since = _qfloat(req, "since", 0.0)
        limit = int(_qfloat(req, "limit", 0.0))
        out = {"status": plane.status(), "matrix": plane.matrix()}
        if link or since > 0 or limit > 0:
            out["history"] = await _run_blocking(
                srv,
                lambda: plane.history(
                    link=link, since=since, limit=limit if limit > 0 else 256
                ),
            )
        return _json(out)

    async def remediation_policy_get(_req: web.Request) -> web.Response:
        """Current remediation policy and guard state (allowlist,
        cooldown, rate limit, reboot-window, escalation)."""
        eng = srv.remediation
        if eng is None:
            return _json({"error": "remediation engine disabled"}, 404)
        return _json(eng.status())

    async def remediation_policy_post(req: web.Request) -> web.Response:
        """Update the remediation policy at runtime: partial JSON object of
        policy fields (enforce_actions graduates an action out of
        dry-run). Audited; invalid keys are rejected field-by-field."""
        eng = srv.remediation
        if eng is None:
            return _json({"error": "remediation engine disabled"}, 404)
        try:
            body = await req.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _json({"error": "invalid JSON body"}, 400)
        if not isinstance(body, dict):
            return _json({"error": "body must be a JSON object"}, 400)
        from gpud_tpu.log import audit as audit_log

        updated, errors = eng.policy.update(body)
        if updated:
            audit_log("remediation_policy_update", updated=",".join(updated))
        out = {"status": "ok" if not errors else "partial", "updated": updated}
        if errors:
            out["errors"] = errors
        return _json(out, 200 if updated or not errors else 400)

    async def prometheus(_req: web.Request) -> web.Response:
        return web.Response(
            body=srv.metrics_registry.render_prometheus().encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    async def debug_traces(req: web.Request) -> web.Response:
        """Recent spans from the in-process trace ring, newest first
        (?component= filters, ?since= unix-ts floor, ?limit= caps,
        ?correlation_id= matches the id a check run stamped on its root
        span; see docs/observability.md). Malformed numeric params are a
        400."""
        component = req.query.get("component", "") or None
        correlation_id = req.query.get("correlation_id", "") or None
        limit = int(_qfloat(req, "limit", DEFAULT_TRACES_LIMIT))
        if limit < 0:
            limit = DEFAULT_TRACES_LIMIT
        since = _qfloat(req, "since", 0.0)
        stats = srv.tracer.stats()
        return _json(
            {
                "spans": srv.tracer.snapshot(
                    component=component, limit=limit, since=since,
                    correlation_id=correlation_id,
                ),
                "stats": stats,
                # surfaced at the envelope level: a consumer paging the ring
                # must see at a glance whether spans fell out under it
                "dropped_total": stats["dropped_total"],
            }
        )

    async def machine_info_handler(_req: web.Request) -> web.Response:
        mi = await _run_blocking(
            srv,
            lambda: machineinfo.get_machine_info(
                tpu=srv.tpu_instance, machine_id=srv.machine_id
            ),
        )
        return _json(mi.to_dict())

    async def inject_fault(req: web.Request) -> web.Response:
        try:
            body = await req.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            # non-UTF8 bytes raise UnicodeDecodeError before JSON parsing
            return _json({"error": "invalid JSON body"}, 400)
        if not isinstance(body, dict):
            return _json({"error": "body must be a JSON object"}, 400)
        try:
            ir = InjectRequest.from_dict(body)
        except (TypeError, ValueError) as e:
            return _json({"error": f"invalid inject request: {e}"}, 400)
        res = await _run_blocking(srv, lambda: srv.fault_injector.inject(ir))
        if not res.ok:
            return _json({"error": res.error, **res.to_dict()}, 400)
        return _json({"injected": True, **res.to_dict()})

    async def chaos_run(req: web.Request) -> web.Response:
        """Run a chaos campaign (body: scenario name or inline mapping;
        wait=false launches it on the pool and returns immediately)."""
        if srv.chaos is None:
            return _json({"error": "chaos is disabled (chaos_enabled)"}, 400)
        try:
            body = await req.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _json({"error": "invalid JSON body"}, 400)
        if not isinstance(body, dict):
            return _json({"error": "body must be a JSON object"}, 400)
        spec = body.get("scenario")
        wait = bool(body.get("wait", True))
        out, err = await _run_blocking(
            srv, lambda: srv.chaos.run_campaign(spec, wait=wait)
        )
        if err:
            return _json({"error": err}, 400)
        return _json(out)

    async def chaos_campaigns(req: web.Request) -> web.Response:
        """Chaos campaign results (newest first) + available scenarios
        (?limit= caps the history returned)."""
        if srv.chaos is None:
            return _json({"error": "chaos is disabled (chaos_enabled)"}, 400)
        limit = int(_qfloat(req, "limit", 0.0))
        return _json(srv.chaos.campaigns(limit=max(0, limit)))

    async def session_status(_req: web.Request) -> web.Response:
        """Control-plane session health: connection + auth state, circuit
        breaker, and the store-and-forward outbox backlog/watermark."""

        def collect() -> dict:
            out: dict = {
                "configured": srv.session is not None,
                "degraded": _session_degraded(srv),
            }
            session = srv.session
            if session is not None:
                out["session"] = {
                    "endpoint": session.endpoint,
                    "connected": session.connected,
                    "auth_failed": session.auth_failed,
                    "connect_attempts": getattr(session, "connect_attempts", 0),
                    "last_connect_error": session.last_connect_error,
                }
            circuit = getattr(srv, "session_circuit", None)
            if circuit is not None:
                out["circuit"] = circuit.stats()
            outbox = getattr(srv, "outbox", None)
            if outbox is not None:
                out["outbox"] = outbox.stats()
            from gpud_tpu.session import wire

            out["wire"] = wire.codec_stats()
            jitter = getattr(srv, "last_replay_jitter_seconds", None)
            if jitter is not None:
                out["last_replay_jitter_seconds"] = round(jitter, 3)
            return out

        return _json(await _run_blocking(srv, collect))

    async def admin_config(_req: web.Request) -> web.Response:
        cfg = srv.config
        # the local API is unauthenticated — never serve credentials
        redacted = {"token", "machine_proof"}
        return _json(
            {
                k: ("<redacted>" if k in redacted and v else v)
                for k, v in vars(cfg).items()
                if isinstance(v, (str, int, float, bool, list))
            }
        )

    async def admin_packages(_req: web.Request) -> web.Response:
        if srv.package_manager is None:
            return _json([])
        sts = await _run_blocking(srv, srv.package_manager.status)
        return _json([s.to_dict() for s in sts])

    async def plugins(_req: web.Request) -> web.Response:
        specs = srv.plugin_specs or []
        return _json([s.to_dict() for s in specs])

    # -- debug/profiling, gated by --pprof (reference: pkg/server
    #    /admin/pprof/{profile,heap,trace}, server.go:425-434) ------------
    async def pprof_profile(req: web.Request) -> web.Response:
        """Wall-clock sampling profiler over ALL threads (cProfile is
        per-thread and would only see this handler sleeping; Go pprof — the
        reference — samples every goroutine, so sample _current_frames)."""
        seconds = min(60.0, _qfloat(req, "seconds", 5.0))
        interval = 0.01

        def run():
            import collections
            import sys as _sys
            import time as _t

            counts: collections.Counter = collections.Counter()
            deadline = _t.monotonic() + seconds
            samples = 0
            while _t.monotonic() < deadline:
                for frame in _sys._current_frames().values():  # noqa: SLF001
                    co = frame.f_code
                    counts[f"{co.co_filename}:{frame.f_lineno} {co.co_name}"] += 1
                samples += 1
                _t.sleep(interval)
            lines = [f"# {samples} samples over {seconds}s ({interval * 1e3:.0f}ms interval)"]
            for loc, n in counts.most_common(60):
                lines.append(f"{n:6d}  {loc}")
            return "\n".join(lines) + "\n"

        text = await _run_blocking(srv, run)
        return web.Response(text=text, content_type="text/plain")

    async def pprof_heap(_req: web.Request) -> web.Response:
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return web.Response(
                text="tracemalloc started; re-request for a snapshot\n",
                content_type="text/plain",
            )
        snap = tracemalloc.take_snapshot()
        # stop after the snapshot: per-allocation tracing must not keep
        # taxing a long-lived monitoring daemon after one debug request
        tracemalloc.stop()
        lines = [str(s) for s in snap.statistics("lineno")[:50]]
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def pprof_threads(_req: web.Request) -> web.Response:
        import sys as _sys
        import threading as _threading
        import traceback as _traceback

        names = {t.ident: t.name for t in _threading.enumerate()}
        parts = []
        for tid, frame in _sys._current_frames().items():  # noqa: SLF001
            parts.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
            parts.append("".join(_traceback.format_stack(frame)))
        return web.Response(text="\n".join(parts), content_type="text/plain")

    if srv.config.pprof:
        r.add_get("/admin/pprof/profile", pprof_profile)
        r.add_get("/admin/pprof/heap", pprof_heap)
        r.add_get("/admin/pprof/threads", pprof_threads)

    async def openapi(_req: web.Request) -> web.Response:
        """Machine-readable API description (reference: the swagger route,
        server.go:420) — generated from the live route table so it can
        never drift from what is actually served."""
        paths: dict = {}
        for route in app.router.routes():
            info = route.resource.get_info() if route.resource else {}
            path = info.get("path") or info.get("formatter") or ""
            if not path or path == "/openapi.json":
                continue
            method = route.method.lower()
            if method == "head":
                continue
            doc = (route.handler.__doc__ or "").strip().split("\n")[0]
            paths.setdefault(path, {})[method] = {
                "summary": doc or route.handler.__name__,
                "responses": {"200": {"description": "OK"}},
            }
        return _json(
            {
                "openapi": "3.0.3",
                "info": {
                    "title": "tpud local API",
                    "version": srv.version,
                    "description": "TPU fleet-health daemon node API",
                },
                "paths": dict(sorted(paths.items())),
            }
        )

    r.add_get("/openapi.json", openapi)
    r.add_get("/healthz", healthz)
    r.add_get("/v1/components", list_components)
    r.add_delete("/v1/components", deregister_component)
    r.add_get("/v1/components/trigger-check", trigger_check)
    # reference parity: a dedicated trigger-tag route exists alongside
    # trigger-check (pkg/server/handlers_components.go:20-31); both land
    # on the same handler here, which dispatches on the query params
    r.add_get("/v1/components/trigger-tag", trigger_check)
    r.add_post("/v1/components/set-healthy", set_healthy)
    r.add_get("/v1/states", states)
    r.add_get("/v1/states/history", states_history)
    r.add_get("/v1/predict/scores", predict_scores)
    r.add_get("/v1/predict/calibration", predict_calibration)
    r.add_get("/v1/fabric", fabric_matrix)
    r.add_get("/v1/remediation/audit", remediation_audit)
    r.add_get("/v1/remediation/policy", remediation_policy_get)
    r.add_post("/v1/remediation/policy", remediation_policy_post)
    r.add_post("/v1/chaos/run", chaos_run)
    r.add_get("/v1/chaos/campaigns", chaos_campaigns)
    r.add_get("/v1/session/status", session_status)
    r.add_get("/v1/events", events)
    r.add_get("/v1/metrics", metrics_v1)
    r.add_get("/v1/info", info)
    r.add_get("/v1/plugins", plugins)
    r.add_get("/v1/debug/traces", debug_traces)
    r.add_get("/metrics", prometheus)
    r.add_get("/machine-info", machine_info_handler)
    r.add_post("/inject-fault", inject_fault)
    r.add_get("/admin/config", admin_config)
    r.add_get("/admin/packages", admin_packages)
    return app


SELF_COMPONENT = "tpud-self"


def _session_degraded(srv: "Server") -> bool:
    """True when a control-plane session exists but delivery is impaired:
    disconnected, parked on an auth failure, or circuit not closed. New
    records still land in the outbox journal, so nothing is lost — but
    the manager's view of this node is stale until the path recovers."""
    session = srv.session
    if session is None:
        return False
    if not session.connected or session.auth_failed:
        return True
    circuit = getattr(srv, "session_circuit", None)
    from gpud_tpu.session.outbox import CIRCUIT_CLOSED

    return circuit is not None and circuit.state != CIRCUIT_CLOSED


def _self_info_entry(srv: "Server", start: float, now: float) -> dict:
    """Daemon self-observability summary for /v1/info: trace-ring stats and
    sqlite op totals, flattened to the ComponentInfo shape (extra_info is a
    string map on the wire)."""
    from gpud_tpu import sqlite as sqlite_mod

    tstats = srv.tracer.stats()
    extra = {
        "trace_ring_capacity": str(tstats["capacity"]),
        "trace_ring_size": str(tstats["size"]),
        "trace_spans_recorded_total": str(tstats["recorded_total"]),
        "trace_spans_dropped_total": str(tstats["dropped_total"]),
    }
    slowest = tstats.get("slowest")
    if slowest:
        extra["trace_slowest_name"] = slowest["name"]
        extra["trace_slowest_duration_seconds"] = (
            f"{slowest['duration_seconds']:.6f}"
        )
    for k, v in sqlite_mod.stats().items():
        extra[f"sqlite_{k}"] = f"{v:.6f}" if isinstance(v, float) else str(v)
    ledger = getattr(srv, "health_ledger", None)
    if ledger is not None:
        summary = ledger.summary()
        extra["health_transitions_total"] = str(summary["transitions_total"])
        extra["health_components_tracked"] = str(summary["components_tracked"])
        extra["health_flapping_components"] = ",".join(summary["flapping"])
    # SessionDegraded: the manager-facing warning flag — set whenever a
    # configured control-plane session cannot currently deliver (records
    # keep journaling to the outbox; nothing is lost, only delayed)
    if srv.session is not None:
        extra["SessionDegraded"] = str(_session_degraded(srv)).lower()
        circuit = getattr(srv, "session_circuit", None)
        if circuit is not None:
            extra["session_circuit_state"] = circuit.state
    outbox = getattr(srv, "outbox", None)
    if outbox is not None:
        extra["outbox_backlog"] = str(outbox.stats()["backlog"])
    return ComponentInfo(
        component=SELF_COMPONENT,
        start_time=start,
        end_time=now,
        states=[
            HealthState(
                time=now,
                component=SELF_COMPONENT,
                name=SELF_COMPONENT,
                reason="daemon self-observability summary",
                extra_info=extra,
            )
        ],
        events=[],
        metrics=[],
    ).to_dict()


async def _run_blocking(srv: "Server", fn):
    """Run a blocking check in the loop's default executor so slow checks
    don't stall the API (reference rationale:
    session_process_request.go:108-125 triggerComponent is async)."""
    import asyncio

    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, fn)
