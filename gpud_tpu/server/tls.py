"""Self-signed TLS for the local API.

Reference: pkg/server/server.go:507-547 — a self-signed ECDSA cert is
generated at boot so the local API is always HTTPS (clients connect with
verification disabled; the value is wire privacy on shared hosts, not
identity).
"""

from __future__ import annotations

import datetime
import os
import ssl
import tempfile
from typing import Tuple


def generate_self_signed(common_name: str = "tpud.local") -> Tuple[str, str]:
    """Returns (cert_pem_path, key_pem_path) in a private temp dir."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName("localhost"), x509.DNSName(common_name)]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    d = tempfile.mkdtemp(prefix="tpud-tls-")
    cert_path = os.path.join(d, "cert.pem")
    key_path = os.path.join(d, "key.pem")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    os.chmod(key_path, 0o600)
    return cert_path, key_path


def server_ssl_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx
