"""Daemon composition root.

Reference: pkg/server/server.go:117 ``server.New`` (call stack in SURVEY
§3.1): open DBs → metadata → eventstore + reboot store → metrics pipeline
→ fault injector → TPU instance → TpudInstance DI → registry (all
components) → component Start() → TLS → routes → listener; plus the
session/token loop and the auto-update watcher (wired in later stages).
"""

from __future__ import annotations

import asyncio
import os
import stat
import threading
import time
from typing import List, Optional

from aiohttp import web

from gpud_tpu import host as pkghost
from gpud_tpu.components.all import all_components
from gpud_tpu.components.base import FailureInjector, Registry, TpudInstance
from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent
from gpud_tpu.config import Config, default_config
from gpud_tpu.eventstore import EventStore
from gpud_tpu.fault_injector import Injector
from gpud_tpu.kmsg.syncer import SharedWatcher, Syncer
from gpud_tpu.kmsg.watcher import kmsg_path
from gpud_tpu.log import get_logger
from gpud_tpu.metadata import Metadata
from gpud_tpu.metrics.registry import DEFAULT_REGISTRY, Registry as MetricsRegistry
from gpud_tpu.metrics.store import MetricsStore, SelfMetricsRecorder, Syncer as MetricsSyncer
from gpud_tpu.server.app import build_app
from gpud_tpu.server.tls import generate_self_signed, server_ssl_context
from gpud_tpu.sqlite import open_rw_ro
from gpud_tpu.tpu.instance import new_instance
from gpud_tpu.version import __version__

logger = get_logger(__name__)


class Server:
    def __init__(
        self,
        config: Optional[Config] = None,
        failure_injector: Optional[FailureInjector] = None,
        metrics_registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.config = config or default_config()
        self.version = __version__
        err = self.config.validate()
        if err:
            raise ValueError(err)

        # persistence (reference: server.go:132-221)
        self.db_rw, self.db_ro = open_rw_ro(self.config.state_file())
        self.metadata = Metadata(self.db_rw)
        # write-behind commit layer (docs/storage.md): ONE group-commit
        # path all four stores share; constructed before any store so
        # every store takes it at construction
        self.storage_writer = None
        if self.config.storage_batch_enabled:
            from gpud_tpu.storage import BatchWriter

            self.storage_writer = BatchWriter(
                self.db_rw,
                flush_interval_seconds=(
                    self.config.storage_batch_flush_interval_seconds
                ),
                max_pending=self.config.storage_batch_max_pending,
                flush_threshold=self.config.storage_batch_flush_threshold,
                backpressure_seconds=(
                    self.config.storage_batch_backpressure_seconds
                ),
                fsync=self.config.storage_batch_fsync,
            )
        self.event_store = EventStore(
            self.db_rw,
            retention_seconds=self.config.events_retention_seconds,
            writer=self.storage_writer,
        )
        self.reboot_event_store = pkghost.RebootEventStore(self.event_store)
        self.reboot_event_store.record_reboot()
        # health-transition ledger: the persistent state timeline every
        # component check writes through (gpud_tpu/health_history.py)
        from gpud_tpu.health_history import HealthLedger

        self.health_ledger = HealthLedger(
            self.db_rw,
            event_store=self.event_store,
            retention_seconds=self.config.events_retention_seconds,
            flap_threshold=self.config.health_flap_threshold,
            flap_window_seconds=self.config.health_flap_window_seconds,
            availability_window_seconds=(
                self.config.health_availability_window_seconds
            ),
            writer=self.storage_writer,
        )
        self.machine_id = (
            self.config.machine_id
            or self.metadata.machine_id()
            or pkghost.machine_id()
        )
        # remediation engine: acts (under policy) on the suggested actions
        # the components diagnose (gpud_tpu/remediation/, docs/remediation.md)
        from gpud_tpu.remediation.engine import RemediationEngine
        from gpud_tpu.remediation.policy import Policy as RemediationPolicy

        self.remediation: Optional[RemediationEngine] = None
        if self.config.remediation_enabled:
            self.remediation = RemediationEngine(
                registry=None,  # attached below once the registry exists
                db=self.db_rw,
                policy=RemediationPolicy(
                    enforce_actions=list(self.config.remediation_enforce_actions),
                    cooldown_seconds=float(self.config.remediation_cooldown_seconds),
                    rate_capacity=self.config.remediation_rate_capacity,
                    rate_refill_seconds=float(
                        self.config.remediation_rate_refill_seconds
                    ),
                    max_reboots=self.config.remediation_max_reboots,
                    reboot_window_seconds=float(
                        self.config.remediation_reboot_window_seconds
                    ),
                    escalation_threshold=(
                        self.config.remediation_escalation_threshold
                    ),
                    escalation_window_seconds=float(
                        self.config.remediation_escalation_window_seconds
                    ),
                ),
                event_store=self.event_store,
                reboot_event_store=self.reboot_event_store,
                interval_seconds=float(self.config.remediation_interval_seconds),
                audit_retention_seconds=self.config.events_retention_seconds,
                runtime_unit=self.config.remediation_runtime_unit,
                writer=self.storage_writer,
            )

        # predictive health engine: online precursor scoring that warns
        # before hard faults (gpud_tpu/predict/, docs/predict.md).
        # Advisory only — warnings annotate states, write dry-run audit
        # rows, and publish to the outbox; nothing executes.
        from gpud_tpu.predict import PredictEngine

        self.predictor: Optional[PredictEngine] = None
        if self.config.predict_enabled:
            self.predictor = PredictEngine(
                registry=None,  # attached below once the registry exists
                ledger=self.health_ledger,
                event_store=self.event_store,
                remediation=self.remediation,
                interval_seconds=float(self.config.predict_interval_seconds),
                threshold=float(self.config.predict_threshold),
                hysteresis=float(self.config.predict_hysteresis),
                arm_ticks=self.config.predict_arm_ticks,
                clear_ticks=self.config.predict_clear_ticks,
                window_seconds=float(self.config.predict_window_seconds),
                history_limit=self.config.predict_history_limit,
                warn_cooldown_seconds=float(
                    self.config.predict_warn_cooldown_seconds
                ),
                publish_interval_seconds=float(
                    self.config.predict_publish_interval_seconds
                ),
                calibrate_enabled=bool(
                    self.config.predict_calibrate_enabled
                ),
                calibrate_interval_seconds=float(
                    self.config.predict_calibrate_interval_seconds
                ),
                calibrate_min_history=self.config.predict_calibrate_min_history,
                calibrate_min_threshold=float(
                    self.config.predict_calibrate_min_threshold
                ),
                calibrate_margin=float(self.config.predict_calibrate_margin),
                calibrate_horizon_seconds=float(
                    self.config.predict_calibrate_horizon_seconds
                ),
            )

        # metrics pipeline (reference: server.go:223-242)
        self.metrics_registry = metrics_registry or DEFAULT_REGISTRY
        # in-process trace ring (served at /v1/debug/traces)
        from gpud_tpu.tracing import DEFAULT_TRACER

        self.tracer = DEFAULT_TRACER
        self.metrics_store = MetricsStore(
            self.db_rw,
            retention_seconds=self.config.metrics_retention_seconds,
            writer=self.storage_writer,
        )
        self.metrics_syncer = MetricsSyncer(
            self.metrics_registry,
            self.metrics_store,
            interval_seconds=self.config.scrape_interval_seconds,
        )
        self.self_metrics = SelfMetricsRecorder(self.metrics_registry, self.db_rw)

        # fault injection + accelerator (reference: server.go:274-296)
        self._kmsg_path = kmsg_path(self.config.kmsg_path)
        self.fault_injector = Injector(kmsg_path=self._kmsg_path)
        self.tpu_instance = new_instance(
            failure_injector=failure_injector,
            accelerator_type=self.config.accelerator_type_override,
        )

        # chaos campaign runner (docs/chaos.md): loads declarative
        # scenarios and executes them against this live daemon; running
        # one always takes an explicit API/CLI call
        self.chaos = None
        if self.config.chaos_enabled:
            from gpud_tpu.chaos import ChaosManager

            self.chaos = ChaosManager(
                self,
                history_limit=self.config.chaos_history_limit,
                max_campaign_seconds=float(
                    self.config.chaos_max_campaign_seconds
                ),
            )

        # fabric observability plane (docs/fabric.md): logical mesh
        # discovery + the all-links sweep with per-link EWMA baselines;
        # constructed before the outbox so the ici_link producer below
        # can hook it
        self.fabric = None
        if self.config.fabric_sweep_enabled:
            from gpud_tpu.fabric import FabricPlane

            self.fabric = FabricPlane(
                self.db_rw,
                tpu=self.tpu_instance,
                writer=self.storage_writer,
                interval_seconds=float(
                    self.config.fabric_sweep_interval_seconds
                ),
                latency_threshold_z=float(
                    self.config.fabric_sweep_latency_threshold_z
                ),
                ewma_alpha=float(self.config.fabric_sweep_ewma_alpha),
                warmup_sweeps=int(self.config.fabric_sweep_warmup_sweeps),
                retention_seconds=float(
                    self.config.fabric_sweep_retention_seconds
                ),
            )

        # durable session outbox + control-plane circuit breaker
        # (docs/session.md): producers journal here; a replay job drains
        # everything above the manager-acked watermark into the session
        # as batched delta-encoded delivery frames (docs/session.md wire
        # format)
        self.outbox = None
        self._outbox_replay_job = None
        # jitter applied to the last post-recovery replay poke (None =
        # never connected; 0.0 = immediate, unjittered poke) — chaos
        # expectations read this to prove replay pacing engaged
        self.last_replay_jitter_seconds = None
        from gpud_tpu.session import wire as session_wire
        from gpud_tpu.session.outbox import CircuitBreaker, SessionOutbox

        session_wire.configure(
            compress_min_bytes=self.config.session_wire_compress_min_bytes
        )
        self.session_circuit = CircuitBreaker(
            failure_threshold=self.config.session_circuit_failure_threshold,
            open_seconds=float(self.config.session_circuit_open_seconds),
        )
        if self.config.outbox_enabled:
            self.outbox = SessionOutbox(
                self.db_rw,
                writer=self.storage_writer,
                max_rows=self.config.outbox_max_rows,
                max_age_seconds=float(self.config.outbox_max_age_seconds),
                replay_batch=self.config.outbox_replay_batch,
                keyframe_interval=self.config.session_wire_keyframe_interval,
                redeliver_after_seconds=float(
                    self.config.outbox_redeliver_seconds
                ),
            )
            self._wire_outbox_producers()

        # unified check scheduler: one deadline heap + bounded worker pool
        # owns every periodic job (docs/scheduler.md) — components, metrics
        # scrape/record, retention, remediation scan, update watcher
        from gpud_tpu.scheduler import Scheduler

        self.scheduler = Scheduler(
            workers=self.config.scheduler_workers,
            hang_timeout=float(self.config.scheduler_watchdog_seconds),
            jitter_fraction=self.config.scheduler_jitter_fraction,
        )

        # DI + registry (reference: server.go:298-340)
        self.tpud_instance = TpudInstance(
            machine_id=self.machine_id,
            tpu_instance=self.tpu_instance,
            db_rw=self.db_rw,
            db_ro=self.db_ro,
            event_store=self.event_store,
            reboot_event_store=self.reboot_event_store,
            mount_points=list(self.config.mount_points),
            mount_targets=list(self.config.mount_targets),
            kernel_modules_to_check=list(self.config.kernel_modules_to_check),
            kmsg_path=self._kmsg_path,
            failure_injector=failure_injector,
            config=self.config,
            health_ledger=self.health_ledger,
            scheduler=self.scheduler,
        )
        self.registry = Registry(self.tpud_instance)
        enabled = set(self.config.components_enabled)
        disabled = set(self.config.components_disabled)
        for init_func in all_components():
            name = getattr(init_func, "NAME", "")
            if enabled and name not in enabled:
                continue
            if name in disabled:
                continue
            self.registry.must_register(init_func)

        if self.remediation is not None:
            # the engine scans (and its soft executors act through) the
            # fully-populated registry
            self.remediation.registry = self.registry
            self.remediation.executors.registry = self.registry
        if self.predictor is not None:
            self.predictor.registry = self.registry
            # fabric deviations corroborate the ICI component's precursor
            # score (neighbor co-occurrence feature; docs/fabric.md)
            if self.fabric is not None:
                self.predictor.fabric = self.fabric

        # shared kmsg watcher: one reader feeding every kmsg-consuming
        # component (reference hot-loop #2, SURVEY §3.1)
        self.kmsg_watcher = SharedWatcher(path=self._kmsg_path, from_now=True)
        self._wire_kmsg_syncers()

        # plugins (reference: server.go:343-387 init + component registries)
        self.plugin_specs = []
        specs_file = self.config.resolved_plugin_specs_file()
        if os.path.isfile(specs_file):
            from gpud_tpu.plugins.component import (
                build_components,
                run_init_plugins,
            )
            from gpud_tpu.plugins.spec import load_specs

            # boot-time leniency: one bad spec in a hand-edited or legacy
            # plugins.yaml degrades that plugin (skip+log), never
            # crash-loops the daemon; dispatch stays strict at push time
            self.plugin_specs = load_specs(specs_file, on_invalid="skip")
            init_err = run_init_plugins(self.tpud_instance, self.plugin_specs)
            if init_err:
                raise RuntimeError(init_err)  # fail boot (reference: 343-387)
            for comp in build_components(self.tpud_instance, self.plugin_specs):
                # a name clash with a built-in must not crash-loop the boot;
                # skip and log (dispatch rejects such specs upfront, but an
                # older or hand-edited plugins.yaml can still contain one)
                _, reg_err = self.registry.register(lambda _inst, c=comp: c)
                if reg_err is not None:
                    logger.error(
                        "skipping plugin %r: %s", comp.name(), reg_err
                    )

        # package manager (reference: gpudmanager.New + Start)
        from gpud_tpu.manager.packages import PackageManager

        self.package_manager = PackageManager(self.config.packages_dir())

        # auto-update watcher (reference: server.go:814-832)
        from gpud_tpu.update import VersionFileWatcher

        self.update_watcher = (
            VersionFileWatcher(self.config.target_version_file())
            if self.config.enable_auto_update
            else None
        )

        # control-plane session (reference: server.go:448 updateToken loop)
        self.session = None
        self.dispatcher = None
        self.last_gossip = None
        self._session_mu = threading.Lock()
        # serializes credential-pair metadata writes (rotations vs the
        # success-gated on_connected persist) WITHOUT touching
        # _session_mu — on_connected runs on the session's keepalive
        # thread, which session.stop() joins while _session_mu is held
        self._cred_mu = threading.Lock()
        self._closed = False

        # supportedness is evaluated once off the event loop: probes like
        # docker/kubelet shell out or open sockets, which must never run
        # inside async handlers
        self.supported_names = {
            c.name() for c in self.registry.all() if c.is_supported()
        }

        # http plumbing
        self._app = build_app(self)
        self._runner: Optional[web.AppRunner] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        self.port = self.config.port

    def _wire_kmsg_syncers(self) -> None:
        from gpud_tpu.components.cpu import match_cpu_lockup
        from gpud_tpu.components.disk import match_disk_error
        from gpud_tpu.components.memory import match_oom
        from gpud_tpu.components.os_comp import match_kernel_panic

        for comp_name, match_fn in (
            ("cpu", match_cpu_lockup),
            ("disk", match_disk_error),
            ("memory", match_oom),
            ("os", match_kernel_panic),
        ):
            self.kmsg_watcher.register(
                Syncer(match_fn, self.event_store.bucket(comp_name))
            )
        err_comp = self.registry.get(TPUErrorKmsgComponent.NAME)
        if err_comp is not None and err_comp.syncer is not None:
            self.kmsg_watcher.register(err_comp.syncer)

    # -- durable outbox wiring (docs/session.md) ---------------------------
    def _wire_outbox_producers(self) -> None:
        """Hook every control-plane-relevant producer into the outbox
        journal: events, health transitions, remediation audit rows, and
        chaos campaign results (gossip publishes from its dispatch
        worker). Dedupe keys are derived from each record's natural
        identity so the manager can collapse at-least-once redeliveries.

        Event/transition hooks fire synchronously on the check thread
        that produced them, so the check wrapper's correlation id is
        readable from the tracing thread-local — it rides the record to
        the manager, which serves it back at /v1/fleet/traces."""
        from gpud_tpu.tracing import current_correlation_id

        outbox = self.outbox

        def on_event(component: str, ev) -> None:
            body = {
                "component": component,
                "time": ev.time,
                "name": ev.name,
                "type": ev.type,
                "message": ev.message,
            }
            cid = current_correlation_id()
            if cid:
                body["correlation_id"] = cid
            outbox.publish(
                "event",
                body,
                dedupe_key=f"event:{component}:{ev.time}:{ev.name}",
            )

        def on_transition(
            component: str, from_state: str, to_state: str,
            ts: float, reason: str,
        ) -> None:
            body = {
                "component": component,
                "from": from_state,
                "to": to_state,
                "ts": ts,
                "reason": reason,
            }
            cid = current_correlation_id()
            if cid:
                body["correlation_id"] = cid
            outbox.publish(
                "transition",
                body,
                dedupe_key=f"transition:{component}:{ts}:{to_state}",
            )

        def on_audit(row: dict) -> None:
            outbox.publish(
                "remediation_audit",
                row,
                dedupe_key=(
                    f"audit:{row.get('component')}:{row.get('ts')}:"
                    f"{row.get('action')}"
                ),
            )

        def on_chaos_result(result: dict) -> None:
            outbox.publish(
                "chaos_result",
                {
                    "id": result.get("id"),
                    "scenario": result.get("scenario"),
                    "passed": result.get("passed"),
                    "error": result.get("error", ""),
                },
                dedupe_key=f"chaos:{result.get('scenario')}:{result.get('id')}",
            )

        def on_predict(body: dict) -> None:
            outbox.publish(
                "predict_score",
                body,
                dedupe_key=(
                    f"predict:{body.get('component')}:{body.get('event')}:"
                    f"{body.get('ts')}"
                ),
            )

        def on_ici_link(body: dict) -> None:
            outbox.publish(
                "ici_link",
                body,
                dedupe_key=f"ici_link:{body.get('link')}:{body.get('ts')}",
            )

        self.event_store.on_insert = on_event
        self.health_ledger.on_transition = on_transition
        if self.remediation is not None:
            self.remediation.audit.on_record = on_audit
        if self.chaos is not None:
            self.chaos.on_result = on_chaos_result
        if self.predictor is not None:
            self.predictor.on_publish = on_predict
        if self.fabric is not None:
            self.fabric.on_publish = on_ici_link

    def _outbox_replay_tick(self) -> int:
        """Scheduler job "session-outbox-replay": drain one batch of
        unacked records into the session; no-op while disconnected, auth-
        parked, or caught up."""
        outbox = self.outbox
        if outbox is None:
            return 0
        return outbox.replay_once(self.session)

    def _session_frame_drop_event(self, direction: str, detail: str) -> None:
        """Rate-limited (session-side) Warning event for dropped session
        frames — overflow must be visible in the event timeline, not just
        a counter."""
        from gpud_tpu.api.v1.types import Event, EventType

        self.event_store.bucket("session").insert(
            Event(
                component="session",
                time=time.time(),
                name="session_frame_dropped",
                type=EventType.WARNING,
                message=f"{direction} channel overflow: {detail}",
            )
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start pollers + API listener (non-blocking; reference spawns
        goroutines at server.go:390-450). Idempotent: a second start on a
        running server is a no-op — re-running the assembly would leak a
        duplicate fifo watcher and crash a second serve loop against the
        already-bound port."""
        if self._thread is not None and self._thread.is_alive():
            return
        # retry-after-failed-start: clear the stale listener verdict so a
        # successful rebind isn't condemned by the previous error, and
        # never re-run the component/watcher assembly (their own start()
        # methods are idempotent, but the fifo watcher's is a thread)
        self._started.clear()
        self._start_error = None
        if not getattr(self, "_assembled", False):
            self._assembled = True
            # register every periodic job BEFORE scheduler.start(): jobs
            # known at start form the startup-readiness set, and their
            # first checks run in parallel on the pool instead of
            # serially on this (boot) thread
            for comp in self.registry.all():
                if comp.name() in self.supported_names:
                    comp.start()
            self.kmsg_watcher.start()
            # consolidated retention: the three purger threads
            # (eventstore, health ledger, remediation audit) collapse
            # into ONE scheduler job on a shared cadence — each store's
            # pass is independent, one failing table must not starve
            # the others
            self._retention_targets = [
                ("events", self.event_store.purge_once),
                ("health", self.health_ledger.purge_once),
            ]
            if self.remediation is not None:
                self._retention_targets.append(
                    ("remediation-audit", self.remediation.audit.purge_once)
                )
            if self.outbox is not None:
                # size/age bounds on the delivery journal: a week-long
                # partition degrades telemetry, never fills the disk
                self._retention_targets.append(
                    ("session-outbox", self.outbox.purge_once)
                )
            if self.fabric is not None:
                self._retention_targets.append(
                    ("fabric-matrix", self.fabric.purge_once)
                )
            retention_interval = max(
                60.0, self.config.events_retention_seconds / 5.0
            )
            self.scheduler.add_job(
                "retention-purge",
                self._purge_retention,
                interval=retention_interval,
                initial_delay=retention_interval,
            )
            if self.storage_writer is not None:
                # the periodic group-commit drain ("storage-writer-flush")
                self.storage_writer.start(self.scheduler)
                if (
                    not self.config.db_in_memory
                    and self.config.storage_wal_checkpoint_seconds > 0
                ):
                    # low-cadence WAL maintenance: flush, sample
                    # tpud_sqlite_wal_bytes, wal_checkpoint(TRUNCATE) so
                    # the WAL stays bounded under sustained batched ingest
                    from gpud_tpu.storage import checkpoint_wal

                    interval = float(self.config.storage_wal_checkpoint_seconds)
                    self.scheduler.add_job(
                        "wal-checkpoint",
                        lambda: checkpoint_wal(self.db_rw, self.storage_writer),
                        interval=interval,
                        initial_delay=interval,
                    )
            if self.outbox is not None:
                # replay drains above the acked watermark whenever the
                # session is connected; on_connected pokes it for an
                # immediate post-reconnect drain
                interval = float(self.config.outbox_replay_interval_seconds)
                self._outbox_replay_job = self.scheduler.add_job(
                    "session-outbox-replay",
                    self._outbox_replay_tick,
                    interval=interval,
                    initial_delay=interval,
                )
            if self.remediation is not None:
                self.remediation.start(self.scheduler)
            if self.predictor is not None:
                self.predictor.start(self.scheduler)
            if self.fabric is not None:
                self.fabric.start(self.scheduler)
            self.metrics_syncer.start(self.scheduler)
            self.self_metrics.start(self.scheduler)
            self.package_manager.start()
            if self.update_watcher is not None:
                self.update_watcher.start(self.scheduler)
            self.scheduler.start()
            self._reapply_config_overrides()
            self._maybe_start_session()
            self._start_token_fifo()

        self._thread = threading.Thread(
            target=self._serve, name="tpud-http", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=15.0):
            raise RuntimeError("API listener failed to start in time")
        if self._start_error is not None:
            raise RuntimeError(f"API listener failed: {self._start_error}")
        # Type=notify readiness: systemd holds dependents until the API is
        # actually listening (reference: pkgsystemd.NotifyReady)
        from gpud_tpu import sdnotify

        sdnotify.ready()

    def _purge_retention(self) -> None:
        """One consolidated retention pass over every store (scheduler
        job "retention-purge"); per-store isolation so one failing table
        doesn't starve the others."""
        for name, purge in self._retention_targets:
            try:
                purge()
            except Exception:  # noqa: BLE001
                logger.exception("retention purge failed for %s", name)

    def _serve(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def _run():
            runner = web.AppRunner(self._app)
            await runner.setup()
            ssl_ctx = None
            if self.config.tls:
                cert, key = generate_self_signed()
                ssl_ctx = server_ssl_context(cert, key)
            site = web.TCPSite(runner, "0.0.0.0", self.config.port, ssl_context=ssl_ctx)
            await site.start()
            # pick up the ephemeral port if 0 was requested (tests)
            for s in site._server.sockets:  # noqa: SLF001
                self.port = s.getsockname()[1]
                break
            self._runner = runner
            self._started.set()

        try:
            loop.run_until_complete(_run())
            loop.run_forever()
        except BaseException as e:  # noqa: BLE001
            self._start_error = e
            self._started.set()
        finally:
            try:
                if self._runner is not None:
                    loop.run_until_complete(self._runner.cleanup())
            except Exception:  # noqa: BLE001
                pass
            loop.close()

    def stop(self) -> None:
        logger.info("stopping tpud server")
        from gpud_tpu import sdnotify

        sdnotify.stopping()
        with self._session_mu:
            self._closed = True  # bars the fifo watcher from new sessions
        if getattr(self, "_fifo_stop", None) is not None:
            self._fifo_stop.set()
            # unblock the fifo reader's blocking open
            err = self.write_token("", self.config.fifo_file())
            del err
            if getattr(self, "_fifo_thread", None) is not None:
                self._fifo_thread.join(timeout=3.0)
        with self._session_mu:
            if self.session is not None:
                self.session.stop()
                self.session = None
        self.package_manager.close()
        if self.update_watcher is not None:
            self.update_watcher.close()
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.metrics_syncer.close()
        self.self_metrics.close()
        self.kmsg_watcher.close()
        for comp in self.registry.all():
            try:
                comp.close()
            except Exception:  # noqa: BLE001
                logger.exception("component %s close failed", comp.name())
        if self.remediation is not None:
            self.remediation.close()
        if self.predictor is not None:
            self.predictor.close()
        if self.fabric is not None:
            self.fabric.close()
        if self.chaos is not None:
            # aborts any in-flight campaign's sleeps before the pool the
            # campaign runs on is drained
            self.chaos.close()
        # after every job owner cancelled its jobs; before the stores the
        # retention job writes through are closed
        self.scheduler.close()
        self.health_ledger.close()
        self.event_store.close()
        if self.storage_writer is not None:
            # graceful-shutdown barrier: commit everything still buffered
            # (last of all — every writer above may emit final rows)
            self.storage_writer.close()

    def _reapply_config_overrides(self) -> None:
        """Control-plane config overrides survive restarts (reference:
        persistMetadataOverrides in cmd/gpud/run). Best-effort: a corrupt
        row must never abort boot (systemd would crash-loop us)."""
        try:
            import json as _json

            from gpud_tpu import metadata as md

            raw = self.metadata.get(md.KEY_CONFIG_OVERRIDES)
            if not raw:
                return
            cfgs = _json.loads(raw)
            if not isinstance(cfgs, dict):
                logger.warning("ignoring malformed persisted overrides: %r", cfgs)
                return
            from gpud_tpu.session.dispatch import Dispatcher

            updated, _applied, errors = Dispatcher(self).apply_config_overrides(cfgs)
            if updated:
                logger.info("re-applied persisted config overrides: %s", updated)
            if errors:
                logger.warning("persisted override errors: %s", errors)
        except Exception:  # noqa: BLE001
            logger.exception("re-applying persisted overrides failed; continuing boot")

    # -- session wiring ----------------------------------------------------
    def _maybe_start_session(self) -> None:
        """Create the control-plane session when an endpoint + token exist
        (reference: server.updateToken → session.NewSession, server.go:590)."""
        from gpud_tpu import metadata as md

        with self._session_mu:
            if self._closed:
                return
            # credentials must stay PAIRED with the endpoint they were
            # issued for. Rotations (login/FIFO/updateToken) persist the
            # endpoint+token pair to metadata together, so:
            #   1. a complete --endpoint/--token flag pair wins ONLY when
            #      it points at a DIFFERENT control plane than the
            #      enrollment — that's an operator re-point. Flags aimed
            #      at the SAME endpoint (the systemd unit re-supplying
            #      bootstrap args every restart) defer to the metadata
            #      pair, whose token is the freshest credential for that
            #      endpoint — otherwise every restart would resurrect the
            #      revoked bootstrap token;
            #   2. else a complete metadata pair wins as a unit;
            #   3. else piecewise fallback.
            # raw reads captured ONCE: they drive both the credential
            # decision and the rotation-staleness snapshot below — a
            # second read for the snapshot would open a window where a
            # concurrent rotation lands between the two and the snapshot
            # wrongly matches it
            raw_md_endpoint = self.metadata.get(md.KEY_ENDPOINT)
            md_token = self.metadata.get(md.KEY_TOKEN)
            md_endpoint = md.normalize_endpoint(raw_md_endpoint)
            cfg_endpoint = md.normalize_endpoint(self.config.endpoint)
            if md_token and not md_endpoint and cfg_endpoint:
                # migration: older rotation code persisted only KEY_TOKEN,
                # so which endpoint that token belongs to is unrecorded.
                # Assume the flag endpoint (the control plane the daemon
                # was enrolled with) — otherwise the first restart after
                # upgrade would resurrect the revoked bootstrap flag
                # token. The guess is NOT persisted here: pairs are only
                # recorded on a successful connect (on_connected), and if
                # the guess is wrong auth fails and the flag-credential
                # fallback below recovers.
                md_endpoint = cfg_endpoint
            if (
                cfg_endpoint
                and self.config.token
                and (not (md_endpoint and md_token) or cfg_endpoint != md_endpoint)
            ):
                endpoint, token = cfg_endpoint, self.config.token
                if md_endpoint and md_endpoint != cfg_endpoint:
                    logger.warning(
                        "boot flags re-point the daemon: enrolled %s -> %s",
                        md_endpoint, cfg_endpoint,
                    )
            elif md_endpoint and md_token:
                if self.config.token and self.config.token != md_token:
                    # same-endpoint flag token loses to the rotated
                    # credential; say so, or an operator pushing a fresh
                    # token via the unit file has no trail to follow. (If
                    # the rotated credential is the dead one, the auth
                    # fallback below promotes the flag token.)
                    logger.warning(
                        "--token flag for %s deferred to the rotated "
                        "metadata credential (auth-failure fallback will "
                        "promote the flag token if the rotation is stale)",
                        md_endpoint,
                    )
                if cfg_endpoint and cfg_endpoint != md_endpoint:
                    logger.warning(
                        "enrolled metadata endpoint %s overrides --endpoint "
                        "%s (no --token given; supply both flags to "
                        "re-point)", md_endpoint, cfg_endpoint,
                    )
                endpoint, token = md_endpoint, md_token
            else:
                endpoint = cfg_endpoint or md_endpoint
                token = md_token or self.config.token
            if not endpoint or not token:
                return
            from gpud_tpu.session.dispatch import Dispatcher
            from gpud_tpu.session.session import Session

            self.dispatcher = Dispatcher(self)
            self.session = Session(
                endpoint=endpoint,
                machine_id=self.machine_id,
                token=token,
                machine_proof=self.metadata.get(md.KEY_MACHINE_PROOF),
                dispatch_fn=self.dispatcher,
            )
            session = self.session
            # pairs are persisted only once the control plane ACCEPTS the
            # credential — a guessed or stale pair can then never become
            # durable state that outranks fresh boot flags. The persist is
            # skipped if a rotation changed metadata since this session
            # was decided (the rotation is newer and owns the pair).
            snapshot = (raw_md_endpoint, md_token)

            def persist_on_connect() -> None:
                nonlocal snapshot
                with self._cred_mu:
                    pair = (
                        md.normalize_endpoint(session.endpoint),
                        session.token,
                    )
                    cur = (
                        self.metadata.get(md.KEY_ENDPOINT),
                        self.metadata.get(md.KEY_TOKEN),
                    )
                    if cur == pair:
                        snapshot = pair  # already recorded; reconnects no-op
                        return
                    if cur != snapshot:
                        return  # superseded by a rotation; don't clobber
                    self.metadata.set_credential_pair(*pair)
                    # refresh: a credential promoted LATER in this
                    # session's life (mid-stream revocation + flag
                    # fallback) must still be persistable
                    snapshot = pair

            def on_connected() -> None:
                persist_on_connect()
                # reconnect: in-flight frames from the old connection may
                # be lost and the manager's delta decoder is fresh — fall
                # back to the durable watermark, keyframe-anchored
                if self.outbox is not None:
                    self.outbox.reset_delivery()
                # drain the outbox backlog immediately instead of waiting
                # out the replay interval — reconnect is exactly when the
                # store-and-forward journal has work. EXCEPT straight
                # after a circuit-breaker recovery: then every agent in
                # the fleet is reconnecting at once (the manager was
                # down), and a synchronized replay burst would DDoS it —
                # stagger the poke by a random jitter instead
                job = self._outbox_replay_job
                if job is None:
                    return
                jitter_cap = float(self.config.outbox_replay_jitter_seconds)
                age = self.session_circuit.recovery_age()
                recovering = age is not None and age <= max(
                    5.0, 2.0 * jitter_cap
                )
                if recovering and jitter_cap > 0:
                    import random

                    jitter = random.uniform(0.1 * jitter_cap, jitter_cap)
                    self.last_replay_jitter_seconds = jitter
                    t = threading.Timer(jitter, job.poke)
                    t.daemon = True
                    t.start()
                else:
                    self.last_replay_jitter_seconds = 0.0
                    job.poke()

            # HA manager tier (docs/session.md "Peer failover"): the
            # breaker owns failover order — the endpoint we enrolled
            # with first, then the configured standby peers (minus any
            # duplicate spelling of the primary). Set before start();
            # with no session_peers the list stays empty and the breaker
            # behaves exactly as before
            peer_specs = [
                p.strip() for p in (self.config.session_peers or [])
                if p and p.strip()
            ]
            if peer_specs:
                def _spec_endpoint(spec: str) -> str:
                    return md.normalize_endpoint(
                        spec.split("=", 1)[-1].split("|", 1)[0]
                    )

                self.session_circuit.peers = [endpoint] + [
                    p for p in peer_specs if _spec_endpoint(p) != endpoint
                ]
            session.circuit = self.session_circuit
            session.on_frame_dropped = self._session_frame_drop_event
            session.on_connected = on_connected
            self.session.on_auth_failure = self._make_auth_failure_handler(
                session
            )
            self.session.start()
            logger.info("control-plane session started to %s", endpoint)

    def persist_credential_pair(self, endpoint: str, token: str) -> None:
        """Rotation writers (FIFO, updateToken) record the pair through
        here so they serialize with the success-gated connect persist."""
        with self._cred_mu:
            self.metadata.set_credential_pair(endpoint, token)

    def persist_token(self, token: str) -> None:
        """Token-only rotation (no live session to name the endpoint) —
        still serialized under _cred_mu so a dying session's late
        persist_on_connect can't interleave and clobber the rotation."""
        from gpud_tpu import metadata as md

        with self._cred_mu:
            self.metadata.set(md.KEY_TOKEN, token)

    def _make_auth_failure_handler(self, session):
        """Persist auth failures so operators can distinguish "control
        plane revoked us" from network flakiness across restarts; and if
        the boot flags carry a DIFFERENT token for the endpoint the
        session is talking to, promote it once — the metadata credential
        just proved dead, and the flag pair is the operator's standing
        instruction (recovery path for a stale rotation or a re-point
        attempted while only a token-only migration pair existed)."""
        from gpud_tpu import metadata as md

        def on_auth_failure(reason: str) -> None:
            self.metadata.set(
                md.KEY_LAST_AUTH_FAILURE, f"{int(time.time())}|{reason[:200]}"
            )
            cfg_endpoint = md.normalize_endpoint(self.config.endpoint)
            if (
                self.config.token
                and self.config.token != session.token
                and (not cfg_endpoint or cfg_endpoint == session.endpoint)
                and not session.flag_token_tried
            ):
                session.flag_token_tried = True  # one shot: no ping-pong
                logger.warning(
                    "auth failed with the stored credential; retrying with "
                    "the --token flag credential"
                )
                # un-parks the session's auth wait (it watches .token)
                session.token = self.config.token

        return on_auth_failure

    def _start_token_fifo(self) -> None:
        """FIFO so `tpud up`'s login can hand a fresh token to a running
        daemon (reference: server.go:638-713 gpud.fifo + WriteToken
        727-756)."""
        if self.config.db_in_memory:
            return
        fifo_path = self.config.fifo_file()
        try:
            if os.path.exists(fifo_path):
                # a leftover regular file would make open() return instantly
                # and the watch loop busy-spin — recreate it as a FIFO
                if not stat.S_ISFIFO(os.stat(fifo_path).st_mode):
                    logger.warning(
                        "token fifo path %s is not a FIFO; recreating", fifo_path
                    )
                    os.remove(fifo_path)
                    os.mkfifo(fifo_path)
            else:
                os.mkfifo(fifo_path)
        except OSError as e:
            logger.warning("token fifo unavailable: %s", e)
            return

        def watch():
            import select as _select

            from gpud_tpu import metadata as md

            def apply(token: str) -> None:
                # persist the PAIR: the rotated token belongs to the
                # endpoint the session is (about to be) talking to, and
                # the pair must survive a process restart that re-supplies
                # stale boot flags
                with self._session_mu:
                    active = (
                        self.session.endpoint
                        if self.session is not None
                        else md.normalize_endpoint(self.config.endpoint)
                        or md.normalize_endpoint(
                            self.metadata.get(md.KEY_ENDPOINT)
                        )
                    )
                if active:
                    self.persist_credential_pair(active, token)
                else:
                    self.persist_token(token)
                logger.info("received new token via fifo; (re)starting session")
                with self._session_mu:
                    if self.session is not None:
                        self.session.stop()
                        self.session = None
                self._maybe_start_session()

            # the watcher holds the FIFO open O_RDWR for the daemon's
            # whole life: a reader always exists, so write_token never
            # ENXIOs after boot AND — unlike an open/EOF/close loop — an
            # ACKED write can never be discarded in the window where the
            # last reader closes (Linux drops FIFO buffers at zero
            # readers). A transient open failure (fd pressure) retries —
            # one bad moment at boot must not disable rotation for the
            # daemon's whole life.
            fd = -1
            while fd < 0:
                try:
                    fd = os.open(fifo_path, os.O_RDWR)
                except OSError as e:
                    logger.warning("token fifo unavailable: %s; retrying", e)
                    if self._fifo_stop.wait(1.0):
                        return
            poller = _select.poll()  # no FD_SETSIZE limit, unlike select()
            poller.register(fd, _select.POLLIN)
            buf = b""
            try:
                while not self._fifo_stop.is_set():
                    if buf and b"\n" not in buf:
                        # a writer sent bytes with no newline (raw
                        # `printf > fifo` rotation). The old EOF-framed
                        # reader accepted those; emulate it: if the
                        # writer goes quiet, the buffer IS the delivery.
                        # (A write arriving inside the window doesn't
                        # merge either — the read path below frames a
                        # surviving raw partial before appending.)
                        # 1s quiet window: a writer pausing mid-token
                        # >250ms could get its token torn in two; real
                        # tokens arrive in one atomic pipe write, so the
                        # longer window only delays the raw-printf path.
                        if not poller.poll(1000):
                            if len(buf) >= 1024:
                                # same bound as the pre-append framing
                                # below: a kilobyte+ newline-less blob is
                                # not a credential token — persisting it
                                # would evict a valid stored credential
                                logger.warning(
                                    "discarding %d-byte newline-less fifo "
                                    "delivery (exceeds token bound)",
                                    len(buf),
                                )
                                buf = b""
                                continue
                            token = buf.decode("utf-8", "replace").strip()
                            buf = b""
                            if token:
                                apply(token)
                            continue
                    try:
                        chunk = os.read(fd, 4096)  # blocks until a write
                    except OSError:
                        if self._fifo_stop.wait(1.0):
                            return
                        continue
                    if self._fifo_stop.is_set():
                        return
                    if buf and b"\n" not in buf:
                        # the previous read left a newline-less raw
                        # delivery (tokens fit one atomic pipe write, so
                        # a small survivor is complete, not a fragment):
                        # frame it BEFORE appending, or a tooling write
                        # arriving in the quiet window would merge with
                        # it. An over-bound survivor is garbage — discard
                        # it here too, or it would merge with this chunk
                        # and ride through the split below as one huge
                        # "delivery" (bypassing the quiet-window bound).
                        if len(buf) < 1024:
                            token = buf.decode("utf-8", "replace").strip()
                            if token:
                                apply(token)
                        else:
                            logger.warning(
                                "discarding %d-byte newline-less fifo "
                                "delivery (exceeds token bound)", len(buf),
                            )
                        buf = b""
                    buf += chunk
                    if b"\n" not in buf:
                        continue  # partial delivery; newline or quiet next
                    *lines, buf = buf.split(b"\n")  # tail = pending partial
                    # rapid successive write_token calls coalesce into ONE
                    # read; each newline-delimited line is a separate
                    # delivery and the LATEST rotation wins — joining them
                    # would persist a corrupt multi-line token that then
                    # rides an Authorization header. The same 1024-byte
                    # token bound applies per line: a newline-terminated
                    # blob must not become the credential either.
                    deliveries = []
                    for ln in lines:
                        if len(ln) >= 1024:
                            logger.warning(
                                "discarding %d-byte fifo line (exceeds "
                                "token bound)", len(ln),
                            )
                            continue
                        d = ln.decode("utf-8", "replace").strip()
                        if d:
                            deliveries.append(d)
                    if deliveries:
                        apply(deliveries[-1])
            finally:
                try:
                    os.close(fd)
                except OSError:
                    pass

        self._fifo_stop = threading.Event()
        self._fifo_thread = threading.Thread(
            target=watch, name="tpud-token-fifo", daemon=True
        )
        self._fifo_thread.start()

    @staticmethod
    def write_token(token: str, fifo_path: str) -> Optional[str]:
        """Reference: server.WriteToken (server.go:727-756)."""
        try:
            fd = os.open(fifo_path, os.O_WRONLY | os.O_NONBLOCK)
            try:
                os.write(fd, (token + "\n").encode())
            finally:
                os.close(fd)
            return None
        except OSError as e:
            return str(e)

    # -- conveniences ------------------------------------------------------
    def base_url(self) -> str:
        scheme = "https" if self.config.tls else "http"
        return f"{scheme}://localhost:{self.port}"
