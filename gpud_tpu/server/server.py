"""Daemon composition root.

Reference: pkg/server/server.go:117 ``server.New`` (call stack in SURVEY
§3.1): open DBs → metadata → eventstore + reboot store → metrics pipeline
→ fault injector → TPU instance → TpudInstance DI → registry (all
components) → component Start() → TLS → routes → listener; plus the
session/token loop and the auto-update watcher (wired in later stages).
"""

from __future__ import annotations

import asyncio
import threading
from typing import List, Optional

from aiohttp import web

from gpud_tpu import host as pkghost
from gpud_tpu.components.all import all_components
from gpud_tpu.components.base import FailureInjector, Registry, TpudInstance
from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent
from gpud_tpu.config import Config, default_config
from gpud_tpu.eventstore import EventStore
from gpud_tpu.fault_injector import Injector
from gpud_tpu.kmsg.syncer import SharedWatcher, Syncer
from gpud_tpu.kmsg.watcher import kmsg_path
from gpud_tpu.log import get_logger
from gpud_tpu.metadata import Metadata
from gpud_tpu.metrics.registry import DEFAULT_REGISTRY, Registry as MetricsRegistry
from gpud_tpu.metrics.store import MetricsStore, SelfMetricsRecorder, Syncer as MetricsSyncer
from gpud_tpu.server.app import build_app
from gpud_tpu.server.tls import generate_self_signed, server_ssl_context
from gpud_tpu.sqlite import open_rw_ro
from gpud_tpu.tpu.instance import new_instance
from gpud_tpu.version import __version__

logger = get_logger(__name__)


class Server:
    def __init__(
        self,
        config: Optional[Config] = None,
        failure_injector: Optional[FailureInjector] = None,
        metrics_registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.config = config or default_config()
        self.version = __version__
        err = self.config.validate()
        if err:
            raise ValueError(err)

        # persistence (reference: server.go:132-221)
        self.db_rw, self.db_ro = open_rw_ro(self.config.state_file())
        self.metadata = Metadata(self.db_rw)
        self.event_store = EventStore(
            self.db_rw, retention_seconds=self.config.events_retention_seconds
        )
        self.reboot_event_store = pkghost.RebootEventStore(self.event_store)
        self.reboot_event_store.record_reboot()
        self.machine_id = (
            self.config.machine_id
            or self.metadata.machine_id()
            or pkghost.machine_id()
        )

        # metrics pipeline (reference: server.go:223-242)
        self.metrics_registry = metrics_registry or DEFAULT_REGISTRY
        self.metrics_store = MetricsStore(
            self.db_rw, retention_seconds=self.config.metrics_retention_seconds
        )
        self.metrics_syncer = MetricsSyncer(
            self.metrics_registry,
            self.metrics_store,
            interval_seconds=self.config.scrape_interval_seconds,
        )
        self.self_metrics = SelfMetricsRecorder(self.metrics_registry, self.db_rw)

        # fault injection + accelerator (reference: server.go:274-296)
        self._kmsg_path = kmsg_path(self.config.kmsg_path)
        self.fault_injector = Injector(kmsg_path=self._kmsg_path)
        self.tpu_instance = new_instance(
            failure_injector=failure_injector,
            accelerator_type=self.config.accelerator_type_override,
        )

        # DI + registry (reference: server.go:298-340)
        self.tpud_instance = TpudInstance(
            machine_id=self.machine_id,
            tpu_instance=self.tpu_instance,
            db_rw=self.db_rw,
            db_ro=self.db_ro,
            event_store=self.event_store,
            reboot_event_store=self.reboot_event_store,
            mount_points=list(self.config.mount_points),
            mount_targets=list(self.config.mount_targets),
            kernel_modules_to_check=list(self.config.kernel_modules_to_check),
            kmsg_path=self._kmsg_path,
            failure_injector=failure_injector,
            config=self.config,
        )
        self.registry = Registry(self.tpud_instance)
        enabled = set(self.config.components_enabled)
        disabled = set(self.config.components_disabled)
        for init_func in all_components():
            name = getattr(init_func, "NAME", "")
            if enabled and name not in enabled:
                continue
            if name in disabled:
                continue
            self.registry.must_register(init_func)

        # shared kmsg watcher: one reader feeding every kmsg-consuming
        # component (reference hot-loop #2, SURVEY §3.1)
        self.kmsg_watcher = SharedWatcher(path=self._kmsg_path, from_now=True)
        self._wire_kmsg_syncers()

        # plugins/packages placeholders (stage 8 wires them)
        self.plugin_specs = None
        self.package_manager = None
        self.session = None

        # http plumbing
        self._app = build_app(self)
        self._runner: Optional[web.AppRunner] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        self.port = self.config.port

    def _wire_kmsg_syncers(self) -> None:
        from gpud_tpu.components.cpu import match_cpu_lockup
        from gpud_tpu.components.memory import match_oom
        from gpud_tpu.components.os_comp import match_kernel_panic

        for comp_name, match_fn in (
            ("cpu", match_cpu_lockup),
            ("memory", match_oom),
            ("os", match_kernel_panic),
        ):
            self.kmsg_watcher.register(
                Syncer(match_fn, self.event_store.bucket(comp_name))
            )
        err_comp = self.registry.get(TPUErrorKmsgComponent.NAME)
        if err_comp is not None and err_comp.syncer is not None:
            self.kmsg_watcher.register(err_comp.syncer)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start pollers + API listener (non-blocking; reference spawns
        goroutines at server.go:390-450)."""
        for comp in self.registry.all():
            if comp.is_supported():
                comp.start()
        self.kmsg_watcher.start()
        self.event_store.start_purger()
        self.metrics_syncer.start()
        self.self_metrics.start()

        self._thread = threading.Thread(
            target=self._serve, name="tpud-http", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=15.0):
            raise RuntimeError("API listener failed to start in time")
        if self._start_error is not None:
            raise RuntimeError(f"API listener failed: {self._start_error}")

    def _serve(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def _run():
            runner = web.AppRunner(self._app)
            await runner.setup()
            ssl_ctx = None
            if self.config.tls:
                cert, key = generate_self_signed()
                ssl_ctx = server_ssl_context(cert, key)
            site = web.TCPSite(runner, "0.0.0.0", self.config.port, ssl_context=ssl_ctx)
            await site.start()
            # pick up the ephemeral port if 0 was requested (tests)
            for s in site._server.sockets:  # noqa: SLF001
                self.port = s.getsockname()[1]
                break
            self._runner = runner
            self._started.set()

        try:
            loop.run_until_complete(_run())
            loop.run_forever()
        except BaseException as e:  # noqa: BLE001
            self._start_error = e
            self._started.set()
        finally:
            try:
                if self._runner is not None:
                    loop.run_until_complete(self._runner.cleanup())
            except Exception:  # noqa: BLE001
                pass
            loop.close()

    def stop(self) -> None:
        logger.info("stopping tpud server")
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.metrics_syncer.close()
        self.self_metrics.close()
        self.kmsg_watcher.close()
        for comp in self.registry.all():
            try:
                comp.close()
            except Exception:  # noqa: BLE001
                logger.exception("component %s close failed", comp.name())
        self.event_store.close()

    # -- conveniences ------------------------------------------------------
    def base_url(self) -> str:
        scheme = "https" if self.config.tls else "http"
        return f"{scheme}://localhost:{self.port}"
