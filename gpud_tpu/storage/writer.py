"""Write-behind commit layer: one batched, group-commit SQLite writer.

Before this layer every metric scrape row, event, health transition, and
remediation audit row was its own SQLite transaction (`DB.execute`
commits per call) — four stores × per-row commits is the dominant cost
of sustained ingest and the footprint papers' first complaint about
monitors (PAPERS.md: the monitor's own cost *is* the product). The
``BatchWriter`` turns that into:

- an in-memory append buffer any thread can ``submit()`` to, with
  per-store delta aggregation: append-only rows (events, transitions,
  audit, metric samples) accumulate; keyed ops (the ledger's last-state
  upsert, same-timestamp gauge samples) coalesce last-write-wins so an
  ingest storm commits one row per key per flush window instead of one
  per observation;
- one drain path that executes the whole buffer inside a SINGLE SQLite
  transaction (group commit: one WAL append — and, with ``fsync=True``,
  one fsync — per batch instead of per row), grouped by statement so
  ``executemany`` does the per-row work in C;
- a scheduler job (``storage-writer-flush``, reusing gpud_tpu/scheduler/)
  draining every ``flush_interval_seconds``, poked early when the buffer
  crosses ``flush_threshold`` ops;
- a bounded queue: past ``max_pending`` ops, ``submit`` applies
  backpressure (bounded wait for a drain) and then drops with per-store
  accounting (``tpud_storage_dropped_total``) — ingest overload degrades
  telemetry, never daemon memory;
- an explicit ``flush()`` barrier: returns once every op submitted
  before the call is committed. Every read-after-write path (HTTP
  history queries, the remediation engine's cooldown/rate derivations,
  retention purges, eventstore dedupe finds) runs it first, so batching
  is invisible to readers — "read your own writes" holds at every API
  surface while the hot path stays append-only.

Durability window (docs/storage.md): a SIGKILL loses at most the ops
buffered since the last drain (≤ the flush interval, bounded tighter by
the threshold poke); a committed batch is atomic — SQLite's transaction
guarantees mean no torn rows, which ``tests/test_crash_consistency.py``
proves by killing a writer mid-stream.

The writer is optional everywhere: stores constructed without one (unit
tests, CLI tools reading a daemon's state file) keep the synchronous
per-call commit path unchanged.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram

logger = get_logger(__name__)

DEFAULT_FLUSH_INTERVAL = 0.2      # seconds between scheduled drains
DEFAULT_MAX_PENDING = 100_000     # ops buffered before backpressure/drop
DEFAULT_FLUSH_THRESHOLD = 5_000   # buffered ops that poke an early drain
DEFAULT_BACKPRESSURE_SECONDS = 0.05  # bounded wait for room before dropping
_FLUSH_SAMPLES = 512              # ring of recent flush durations for stats()

FLUSH_JOB_NAME = "storage-writer-flush"

_g_queue_depth = gauge(
    "tpud_storage_queue_depth",
    "ops buffered in the write-behind layer awaiting the next group commit",
)
_g_batch_size = gauge(
    "tpud_storage_batch_size",
    "ops committed by the most recent storage batch (one transaction)",
)
_h_flush = histogram(
    "tpud_storage_flush_seconds",
    "wall time of one storage batch drain (swap + group commit)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5),
)
_c_ops = counter(
    "tpud_storage_ops_total",
    "write ops accepted into the write-behind buffer, by store",
)
_c_coalesced = counter(
    "tpud_storage_coalesced_total",
    "keyed write ops absorbed by last-write-wins coalescing, by store",
)
_c_dropped = counter(
    "tpud_storage_dropped_total",
    "write ops dropped by the bounded queue (or a failed/crashed batch), "
    "by store",
)
_c_commits = counter(
    "tpud_storage_commits_total",
    "group commits executed by the write-behind writer",
)
_c_backpressure = counter(
    "tpud_storage_backpressure_waits_total",
    "submits that had to wait for queue room before being accepted",
)
_g_wal_bytes = gauge(
    "tpud_sqlite_wal_bytes",
    "size of the state DB's WAL file, sampled just before each periodic "
    "wal_checkpoint(TRUNCATE)",
)
_h_checkpoint = histogram(
    "tpud_storage_wal_checkpoint_seconds",
    "wall time of the periodic PRAGMA wal_checkpoint(TRUNCATE) pass",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)


class BatchWriter:
    """The shared write-behind commit path (module docstring).

    Thread-safe: ``submit``/``submit_many`` may be called from any thread
    (component checks, the kmsg watcher, session dispatch, the manager's
    future fleet-ingest path). Drains are serialized on ``_drain_mu`` and
    may run on the scheduler pool or inline on a barrier caller's thread
    — ``DB`` keeps per-thread connections, so either is safe.
    """

    # _flush_samples is a bounded deque (GIL-atomic appends, stats() reads
    # a sorted snapshot); _job is written once under start()/close() and
    # only poked afterwards — both deliberately unguarded
    GUARDED_BY = {
        "_appends": "_cv",
        "_coalesce": "_cv",
        "_pending": "_cv",
        "_seq": "_cv",
        "_flushed_seq": "_cv",
        "_stopped": "_cv",
        "_commits": "_cv",
        "_committed_ops": "_cv",
        "_dropped": "_cv",
        "_last_batch": "_cv",
    }

    def __init__(
        self,
        db,
        flush_interval_seconds: float = DEFAULT_FLUSH_INTERVAL,
        max_pending: int = DEFAULT_MAX_PENDING,
        flush_threshold: int = DEFAULT_FLUSH_THRESHOLD,
        backpressure_seconds: float = DEFAULT_BACKPRESSURE_SECONDS,
        fsync: bool = False,
    ) -> None:
        self.db = db
        self.flush_interval = float(flush_interval_seconds)
        self.max_pending = int(max_pending)
        self.flush_threshold = max(1, int(flush_threshold))
        self.backpressure_seconds = float(backpressure_seconds)
        self.fsync = bool(fsync)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # sql -> ordered params list (append-only rows); executemany per sql
        self._appends: Dict[str, List[tuple]] = {}
        # coalesce key -> [sql, params] (last-write-wins keyed ops)
        self._coalesce: Dict[tuple, list] = {}
        self._pending = 0
        self._seq = 0           # ops ever accepted (coalesced included)
        self._flushed_seq = 0   # highest seq durably committed (or dropped)
        self._stopped = False
        self._drain_mu = threading.Lock()
        self._flush_samples: deque = deque(maxlen=_FLUSH_SAMPLES)
        self._commits = 0
        self._committed_ops = 0
        self._dropped = 0
        self._last_batch = 0
        self._job = None

    # -- write path --------------------------------------------------------
    def submit(
        self,
        store: str,
        sql: str,
        params: tuple,
        key: Optional[tuple] = None,
    ) -> bool:
        """Buffer one write op. ``key`` ops coalesce last-write-wins
        (only the newest survives a flush window); ``key=None`` appends.
        Returns False only when the bounded queue dropped the op."""
        return self.submit_many(store, sql, (params,), key=key) == 1

    def submit_many(
        self,
        store: str,
        sql: str,
        params_seq: Iterable[tuple],
        key: Optional[tuple] = None,
        keys: Optional[List[tuple]] = None,
    ) -> int:
        """Buffer a batch of ops for one statement under one lock
        acquisition (the firehose path). ``keys`` gives a coalesce key per
        row; ``key`` applies one key to every row. Returns the number of
        ops accepted (appends + coalesce updates); the remainder was
        dropped by the bounded queue."""
        params_list = list(params_seq)
        if not params_list:
            return 0
        with self._cv:
            if self._stopped:
                # sync fallback: a writer that is closed (daemon shutdown,
                # tools) degrades to the classic one-commit-per-call path
                # so late writes are never silently lost
                pass
            else:
                return self._buffer_locked(store, sql, params_list, key, keys)
        # out of the lock: direct synchronous writes
        if len(params_list) == 1:
            self.db.execute(sql, params_list[0])
        else:
            self.db.executemany(sql, params_list)
        _c_ops.inc(len(params_list), {"store": store})
        return len(params_list)

    def _buffer_locked(
        self,
        store: str,
        sql: str,
        params_list: List[tuple],
        key: Optional[tuple],
        keys: Optional[List[tuple]],
    ) -> int:
        accepted = 0
        overflow = False
        for i, params in enumerate(params_list):
            k = keys[i] if keys is not None else key
            if k is not None:
                slot = self._coalesce.get(k)
                if slot is not None:
                    slot[0] = sql
                    slot[1] = params
                    self._seq += 1
                    accepted += 1
                    _c_coalesced.inc(labels={"store": store})
                    continue
            if self._pending >= self.max_pending:
                if not self._wait_for_room_locked():
                    overflow = True
                    dropped = len(params_list) - i
                    self._dropped += dropped
                    _c_dropped.inc(dropped, {"store": store})
                    break
            if k is not None:
                self._coalesce[k] = [sql, params]
            else:
                self._appends.setdefault(sql, []).append(params)
            self._pending += 1
            self._seq += 1
            accepted += 1
        _g_queue_depth.set(self._pending)
        if accepted:
            _c_ops.inc(accepted, {"store": store})
        if self._pending >= self.flush_threshold or overflow:
            self._wake_flusher_locked()
        return accepted

    def _wait_for_room_locked(self) -> bool:
        """Bounded backpressure: poke a drain and wait briefly for room.
        Returns True when there is room, False to drop."""
        _c_backpressure.inc()
        self._wake_flusher_locked()
        deadline = time.monotonic() + self.backpressure_seconds
        while self._pending >= self.max_pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._stopped:
                return False
            self._cv.wait(remaining)
        return True

    def _wake_flusher_locked(self) -> None:
        job = self._job
        if job is not None:
            job.poke()

    # -- drain / barrier ---------------------------------------------------
    def drain(self) -> int:
        """One swap + group commit; returns ops committed. Runs on the
        scheduler job, on barrier callers, and on close()."""
        with self._drain_mu:
            return self._drain_inner()

    def _drain_inner(self) -> int:
        t0 = time.monotonic()
        with self._cv:
            if not self._pending:
                return 0
            appends = self._appends
            coalesce = self._coalesce
            watermark = self._seq
            n = self._pending
            self._appends = {}
            self._coalesce = {}
            self._pending = 0
            _g_queue_depth.set(0)
            self._cv.notify_all()  # backpressure waiters: room exists
        groups: List[Tuple[str, List[tuple]]] = list(appends.items())
        by_sql: Dict[str, List[tuple]] = {}
        for sql, params in coalesce.values():
            by_sql.setdefault(sql, []).append(tuple(params))
        groups.extend(by_sql.items())
        committed = True
        try:
            self.db.run_batch(groups, fsync=self.fsync)
        except Exception:  # noqa: BLE001
            # a failed batch (disk full, I/O error) is dropped whole —
            # requeueing would reorder against newer ops and grow without
            # bound while the disk stays broken. The barrier still
            # advances: readers must never hang on storage that is down.
            logger.exception("storage batch commit failed; %d ops lost", n)
            committed = False
            _c_dropped.inc(n, {"store": "_commit_failed"})
        else:
            _c_commits.inc()
        dt = time.monotonic() - t0
        with self._cv:
            # counter updates ride the same acquisition as the watermark:
            # unlocked `self._dropped += n` here raced drop_pending() and
            # _buffer_locked() read-modify-writes (lost increments)
            if committed:
                self._commits += 1
                self._committed_ops += n
            else:
                self._dropped += n
            if self._flushed_seq < watermark:
                self._flushed_seq = watermark
            self._last_batch = n
            self._cv.notify_all()
        _g_batch_size.set(n)
        _h_flush.observe(dt)
        self._flush_samples.append(dt)
        return n

    def flush(self, timeout: float = 30.0) -> bool:
        """Barrier: returns once every op submitted before this call is
        committed (or dropped). The no-pending fast path is one lock
        acquisition, so read paths can call it unconditionally."""
        with self._cv:
            if self._flushed_seq >= self._seq:
                return True
            target = self._seq
        deadline = time.monotonic() + timeout
        while True:
            with self._cv:
                if self._flushed_seq >= target:
                    return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            # drive the drain from this thread instead of waiting on the
            # scheduler job — a barrier must make progress even when every
            # pool worker is blocked on this same barrier
            if self._drain_mu.acquire(timeout=min(remaining, 1.0)):
                try:
                    self._drain_inner()
                finally:
                    self._drain_mu.release()

    def drop_pending(self, reason: str = "crash") -> int:
        """Discard the whole in-memory buffer WITHOUT committing — the
        chaos ``storage_crash`` fault: exactly what a SIGKILL between
        drains loses. Barriers are released (the ops are gone; waiting
        for them would hang the daemon the drill is testing)."""
        with self._cv:
            n = self._pending
            self._appends = {}
            self._coalesce = {}
            self._pending = 0
            self._flushed_seq = self._seq
            self._dropped += n
            _g_queue_depth.set(0)
            self._cv.notify_all()
        if n:
            _c_dropped.inc(n, {"store": reason})
            logger.warning("storage writer dropped %d buffered ops (%s)", n, reason)
        return n

    # -- lifecycle ---------------------------------------------------------
    def start(self, scheduler=None) -> None:
        """Register the periodic drain job. Without a scheduler the writer
        still works: drains happen on threshold crossings and barriers."""
        if scheduler is None or self._job is not None:
            return
        self._job = scheduler.add_job(
            FLUSH_JOB_NAME,
            self.drain,
            interval=self.flush_interval,
            initial_delay=self.flush_interval,  # nothing to drain at boot
            jitter=False,  # the durability window is a contract, not a cadence
        )

    def close(self) -> None:
        """Final graceful-shutdown barrier: stop accepting buffered ops
        (submits fall back to synchronous writes) and commit everything
        still buffered."""
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self.drain()

    # -- introspection -----------------------------------------------------
    def pending_ops(self) -> int:
        with self._cv:
            return self._pending

    def stats(self) -> Dict:
        with self._cv:
            pending = self._pending
            commits = self._commits
            committed = self._committed_ops
            dropped = self._dropped
            last = self._last_batch
        samples = sorted(self._flush_samples)
        p50 = samples[len(samples) // 2] if samples else 0.0
        p95 = samples[int(0.95 * (len(samples) - 1))] if samples else 0.0
        return {
            "pending_ops": pending,
            "commits": commits,
            "committed_ops": committed,
            "dropped_ops": dropped,
            "last_batch_ops": last,
            "flush_p50_seconds": p50,
            "flush_p95_seconds": p95,
        }


def checkpoint_wal(db, writer: Optional[BatchWriter] = None) -> Dict:
    """One periodic WAL maintenance pass (scheduler job "wal-checkpoint"):
    barrier-flush the writer so the WAL holds everything buffered, sample
    the WAL size into ``tpud_sqlite_wal_bytes`` (its pre-truncate peak is
    the operator's signal), then ``PRAGMA wal_checkpoint(TRUNCATE)`` so
    the file stays bounded under sustained batched ingest."""
    if writer is not None:
        writer.flush()
    wal_bytes = db.wal_size_bytes()
    _g_wal_bytes.set(wal_bytes)
    t0 = time.monotonic()
    busy, log_pages, ckpt_pages = db.wal_checkpoint("TRUNCATE")
    dt = time.monotonic() - t0
    _h_checkpoint.observe(dt)
    return {
        "wal_bytes": wal_bytes,
        "busy": busy,
        "log_pages": log_pages,
        "checkpointed_pages": ckpt_pages,
        "seconds": dt,
    }
