"""Write-behind storage layer: batched, group-commit SQLite ingest.

``BatchWriter`` (gpud_tpu/storage/writer.py) is the single commit path
all four persistent stores (metrics time-series, eventstore, health
ledger, remediation audit) route their hot-path writes through when
``Config.storage_batch_enabled`` is on. See docs/storage.md.
"""

from gpud_tpu.storage.writer import BatchWriter, checkpoint_wal

__all__ = ["BatchWriter", "checkpoint_wal"]
