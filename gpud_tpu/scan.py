"""One-shot diagnostic scan.

Reference: pkg/scan/scan.go:33-118 — builds the accelerator instance and a
GPUdInstance *without* an event store, runs Check() on every supported
component and prints result tables. Check() implementations take their
"read everything now" path when no event store is present (e.g. the error
component reads the whole kmsg ring buffer).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, TextIO

from gpud_tpu.components.all import all_components
from gpud_tpu.components.base import (
    CheckResult,
    FailureInjector,
    Registry,
    TpudInstance,
)
from gpud_tpu import host as pkghost
from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.tpu.instance import new_instance


_HEALTH_GLYPH = {
    HealthStateType.HEALTHY: "✔",
    HealthStateType.DEGRADED: "◐",
    HealthStateType.UNHEALTHY: "✘",
    HealthStateType.INITIALIZING: "…",
}


def scan(
    accelerator_type: str = "",
    failure_injector: Optional[FailureInjector] = None,
    out: TextIO = sys.stdout,
    availability: Optional[Dict[str, Dict]] = None,
) -> List[CheckResult]:
    """Run every supported component's check once and print a table.
    ``availability`` (component -> availability dict from the health
    ledger) adds a rolling-availability column when the host has a state
    DB with history. Returns the check results (for tests / the CLI exit
    code)."""
    tpu = new_instance(
        failure_injector=failure_injector, accelerator_type=accelerator_type
    )
    inst = TpudInstance(
        machine_id=pkghost.machine_id(),
        tpu_instance=tpu,
        event_store=None,  # scan mode: no persistence (reference: scan.go:83-100)
        failure_injector=failure_injector,
    )
    registry = Registry(inst)
    for init_func in all_components():
        registry.must_register(init_func)

    out.write(f"machine-id : {inst.machine_id}\n")
    # machine summary + provider detect (reference: scan.go:62-73)
    try:
        import psutil

        from gpud_tpu import host as _host

        vm = psutil.virtual_memory()
        out.write(
            f"host       : {_host.os_name()}, kernel {_host.kernel_version()}, "
            f"{psutil.cpu_count(logical=True)} cpus, {vm.total >> 30} GiB ram\n"
        )
    except Exception:  # noqa: BLE001
        pass
    try:
        from gpud_tpu.providers.detect import detect

        prov = detect(timeout=2.0)
        if prov.provider != "unknown":
            out.write(
                f"provider   : {prov.provider} {prov.region} "
                f"{prov.instance_type}".rstrip() + "\n"
            )
    except Exception:  # noqa: BLE001
        pass
    out.write(f"tpu        : {'present' if tpu.tpu_lib_exists() else 'absent'}")
    if tpu.tpu_lib_exists():
        out.write(
            f" ({tpu.product_name()}, {tpu.accelerator_type() or 'type unknown'}, "
            f"{len(tpu.devices())} chips)"
        )
    out.write("\n\n")

    results: List[CheckResult] = []
    name_w = max(len(c.name()) for c in registry.all())
    for comp in registry.all():
        if not comp.is_supported():
            out.write(f"  {comp.name():<{name_w}}  -  not supported on this host\n")
            continue
        cr = comp.check()
        results.append(cr)
        glyph = _HEALTH_GLYPH.get(cr.health_state_type(), "?")
        av = (availability or {}).get(comp.name())
        av_col = f"  [avail {av['ratio'] * 100:5.1f}%]" if av else ""
        out.write(f"  {comp.name():<{name_w}}  {glyph}{av_col}  {cr.summary()}\n")
        for st in cr.health_states():
            if st.suggested_actions:
                out.write(
                    f"  {'':<{name_w}}     ↳ suggested: "
                    f"{st.suggested_actions.describe_actions()}\n"
                )
    out.write("\n")
    unhealthy = [
        r for r in results if r.health_state_type() != HealthStateType.HEALTHY
    ]
    out.write(
        f"{len(results)} checks, {len(results) - len(unhealthy)} healthy, "
        f"{len(unhealthy)} not healthy\n"
    )
    return results
