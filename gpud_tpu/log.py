"""Logging for tpud.

Mirrors the reference's zap + lumberjack + audit logger setup
(reference: pkg/log/log.go:27-70) with stdlib logging: a rotating file
handler when a log file is configured, and a separate append-only audit
logger for privileged actions (reboot, bootstrap script exec, fault
injection — reference: pkg/log/audit*).
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import os
import threading
import time
from typing import Any, Dict, Optional

_configured = False
_audit_logger: Optional["AuditLogger"] = None
_mu = threading.Lock()


def setup(level: str = "info", log_file: str = "") -> None:
    """Configure the root tpud logger. Safe to call multiple times."""
    global _configured
    with _mu:
        lvl = getattr(logging, level.upper(), logging.INFO)
        root = logging.getLogger("tpud")
        root.setLevel(lvl)
        if _configured:
            return
        fmt = logging.Formatter(
            "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        )
        handler: logging.Handler
        if log_file:
            os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
            # lumberjack-style rotation (reference: pkg/log/log.go)
            handler = logging.handlers.RotatingFileHandler(
                log_file, maxBytes=100 * 1024 * 1024, backupCount=3
            )
        else:
            handler = logging.StreamHandler()
        handler.setFormatter(fmt)
        root.addHandler(handler)
        _configured = True


def get_logger(name: str) -> logging.Logger:
    short = name.replace("gpud_tpu.", "")
    return logging.getLogger(f"tpud.{short}")


class AuditLogger:
    """Append-only JSONL audit records of privileged actions
    (reference: pkg/log/audit*, wired at cmd/gpud/run/command.go:366-370).

    A nop instance (no path) swallows records.
    """

    def __init__(self, path: str = "") -> None:
        self.path = path
        self._mu = threading.Lock()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def log(self, action: str, **fields: Any) -> None:
        if not self.path:
            return
        rec: Dict[str, Any] = {"ts": time.time(), "action": action}
        rec.update(fields)
        line = json.dumps(rec, sort_keys=True)
        try:
            with self._mu:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
        except OSError as e:
            # an unwritable audit file (perms, ENOSPC) must degrade to
            # unaudited — never crash the privileged action being audited
            logging.getLogger("tpud.audit").warning(
                "audit write failed (%s); record dropped: %s", e, line
            )


def set_audit_logger(a: AuditLogger) -> None:
    global _audit_logger
    _audit_logger = a


def audit(action: str, **fields: Any) -> None:
    if _audit_logger is not None:
        _audit_logger.log(action, **fields)
