"""Shared inotify wrapper (ctypes; Linux-only).

Event-driven wakeups for file tails (kmsg fixture mode) and directory
informers (package manager) — no busy polling, near-zero
change-to-wakeup latency. Absence (non-Linux, restricted sandbox) is
fine: every consumer has a polling fallback.
"""

from __future__ import annotations

import errno
import os
import select
import time
from typing import Optional


class InotifyWatch:
    """Minimal inotify wrapper (ctypes; Linux-only) for event-driven file
    tails and directory informers — no busy polling, near-zero
    change-to-wakeup latency. Also consumed by the package manager's file
    informer (gpud_tpu/manager/packages.py)."""

    IN_MODIFY = 0x00000002
    # directory-informer mask: create/modify/delete/move inside a dir
    TREE_MASK = 0x00000002 | 0x00000100 | 0x00000200 | 0x00000040 | 0x00000080

    def __init__(self, ifd: int, libc, mask: int) -> None:
        self.ifd = ifd
        self._libc = libc
        self._mask = mask
        self._closed = False
        self._poller = select.poll()
        self._poller.register(ifd, select.POLLIN)

    @classmethod
    def create(cls, path: str, mask: int = IN_MODIFY) -> Optional["InotifyWatch"]:
        try:
            import ctypes

            libc = ctypes.CDLL(None, use_errno=True)
            # CLOEXEC so spawned subprocesses don't inherit (and pin) the
            # inotify instance; on Linux IN_NONBLOCK/IN_CLOEXEC share the
            # O_* flag values
            ifd = libc.inotify_init1(os.O_NONBLOCK | os.O_CLOEXEC)
            if ifd < 0:
                return None
            wd = libc.inotify_add_watch(ifd, path.encode(), mask)
            if wd < 0:
                os.close(ifd)
                return None
            return cls(ifd, libc, mask)
        except Exception:  # noqa: BLE001 — non-Linux / restricted sandbox
            return None

    def add_path(self, path: str) -> bool:
        """Watch an additional path on the same instance (informer trees)."""
        if self._closed:
            return False
        try:
            return self._libc.inotify_add_watch(self.ifd, path.encode(), self._mask) >= 0
        except Exception:  # noqa: BLE001
            return False

    def wait(self, timeout_ms: int) -> bool:
        """Block until the file is modified (or timeout); drains the event
        queue. Returns True when an event arrived.

        Threading contract: ``close()`` must be called from the thread
        that waits (both consumers — the kmsg tail and the package
        informer — do exactly that). The ``_closed`` guard below is a
        misuse backstop, NOT cross-thread synchronization: a truly
        concurrent close-mid-wait cannot be made safe at this layer (the
        kernel may recycle the fd number between check and read). The
        backstop sleeps out the timeout so a violated contract degrades
        to latency, never to an EBADF crash or a 100% busy-spin of the
        consumer loop."""
        if self._closed:
            time.sleep(timeout_ms / 1000.0)
            return False
        events = self._poller.poll(timeout_ms)
        if not events:
            return False
        if self._closed:
            time.sleep(timeout_ms / 1000.0)
            return False
        try:
            while True:
                if not os.read(self.ifd, 4096):
                    break
        except OSError as e:
            if e.errno == errno.EBADF:
                self._closed = True  # fd gone: every later wait sleeps
                return False
            if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK):
                raise
        return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            os.close(self.ifd)
        except OSError:
            pass


