"""In-process trace ring: why was that check/request/query slow?

A dependency-free tracer for the daemon's own hot paths. Spans carry a
monotonic-clock duration plus a wall-clock start, nest via a per-thread
stack (a sqlite query inside a component check becomes a child span), and
land in a bounded ring buffer — fixed memory, newest-wins, no I/O on the
hot path. ``GET /v1/debug/traces`` serves the ring; ``/v1/info`` carries a
summary. The design follows the host-side-telemetry argument (arxiv
2510.16946) that the monitor's own latency must be observable after the
fact, and eACGM's (arxiv 2506.02007) non-instrusive in-process collection:
no external collector, no sampling daemon, bounded overhead.

Async code (the aiohttp handlers) records flat spans via ``Tracer.record``
instead of the context manager: every request shares the loop thread, so a
thread-local parent stack would mis-attribute concurrent requests.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

DEFAULT_RING_CAPACITY = 2048

STATUS_OK = "ok"
STATUS_ERROR = "error"


class Span:
    """One finished (or in-flight) operation. Plain attributes + to_dict —
    mirrors the repo's dataclass-with-to_dict idiom without paying dataclass
    overhead on the hot path."""

    __slots__ = (
        "span_id", "parent_id", "name", "component", "start_unix",
        "duration_seconds", "status", "error", "attrs", "thread",
    )

    def __init__(
        self,
        span_id: int,
        parent_id: int,
        name: str,
        component: str,
        start_unix: float,
        thread: str = "",
    ) -> None:
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.component = component
        self.start_unix = start_unix
        self.duration_seconds = 0.0
        self.status = STATUS_OK
        self.error = ""
        self.attrs: Dict[str, str] = {}
        self.thread = thread

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = str(value)

    def to_dict(self) -> Dict:
        d: Dict = {
            "span_id": self.span_id,
            "name": self.name,
            "start_unix": self.start_unix,
            "duration_seconds": self.duration_seconds,
            "status": self.status,
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.component:
            d["component"] = self.component
        if self.error:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.thread:
            d["thread"] = self.thread
        return d


class Tracer:
    def __init__(self, capacity: int = DEFAULT_RING_CAPACITY) -> None:
        if capacity <= 0:
            raise ValueError("tracer capacity must be positive")
        self.capacity = capacity
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._recorded = 0
        self._dropped = 0
        self.time_now_fn = time.time

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span_id(self) -> int:
        st = self._stack()
        return st[-1].span_id if st else 0

    @contextmanager
    def span(self, name: str, component: str = "", attrs: Optional[Dict] = None):
        """Nested span over a sync code block. Exceptions mark the span
        ``error`` and re-raise; the span is recorded either way."""
        st = self._stack()
        sp = Span(
            span_id=next(self._ids),
            parent_id=st[-1].span_id if st else 0,
            name=name,
            component=component,
            start_unix=self.time_now_fn(),
            thread=threading.current_thread().name,
        )
        if attrs:
            for k, v in attrs.items():
                sp.set_attr(k, v)
        st.append(sp)
        t0 = time.monotonic()
        try:
            yield sp
        except BaseException as e:
            sp.status = STATUS_ERROR
            sp.error = f"{type(e).__name__}: {e}"[:500]
            raise
        finally:
            sp.duration_seconds = time.monotonic() - t0
            st.pop()
            self._append(sp)

    def record(
        self,
        name: str,
        duration_seconds: float,
        component: str = "",
        start_unix: Optional[float] = None,
        status: str = STATUS_OK,
        error: str = "",
        attrs: Optional[Dict] = None,
        parent_required: bool = False,
    ) -> Optional[Span]:
        """Flat recording for already-measured operations. With
        ``parent_required`` the span is only kept when a span is active on
        this thread — used for high-frequency leaves (sqlite ops) that are
        only interesting as children of a slow check/dispatch."""
        st = self._stack()
        if parent_required and not st:
            return None
        sp = Span(
            span_id=next(self._ids),
            parent_id=st[-1].span_id if st else 0,
            name=name,
            component=component,
            start_unix=(
                start_unix
                if start_unix is not None
                else self.time_now_fn() - duration_seconds
            ),
            thread=threading.current_thread().name,
        )
        sp.duration_seconds = float(duration_seconds)
        sp.status = status
        sp.error = error[:500]
        if attrs:
            for k, v in attrs.items():
                sp.set_attr(k, v)
        self._append(sp)
        return sp

    def _append(self, sp: Span) -> None:
        with self._mu:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(sp)
            self._recorded += 1

    # -- reading -----------------------------------------------------------
    def snapshot(
        self,
        component: Optional[str] = None,
        limit: int = 0,
        since: float = 0.0,
        correlation_id: Optional[str] = None,
    ) -> List[Dict]:
        """Newest-first span dicts, optionally filtered by component, a
        unix-timestamp floor on span start, and/or the ``correlation_id``
        attribute the check wrapper stamps on its root span."""
        with self._mu:
            spans = list(self._ring)
        spans.reverse()
        out = []
        for sp in spans:
            if component and sp.component != component:
                continue
            if since and sp.start_unix < since:
                continue
            if correlation_id and sp.attrs.get("correlation_id") != correlation_id:
                continue
            out.append(sp.to_dict())
            if limit and len(out) >= limit:
                break
        return out

    def stats(self) -> Dict:
        with self._mu:
            size = len(self._ring)
            recorded = self._recorded
            dropped = self._dropped
            slowest: Optional[Span] = None
            for sp in self._ring:
                if slowest is None or sp.duration_seconds > slowest.duration_seconds:
                    slowest = sp
        out = {
            "capacity": self.capacity,
            "size": size,
            "recorded_total": recorded,
            "dropped_total": dropped,
        }
        if slowest is not None:
            out["slowest"] = slowest.to_dict()
        return out

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()


# package-global tracer, mirroring metrics.registry.DEFAULT_REGISTRY
DEFAULT_TRACER = Tracer()


def span(name: str, component: str = "", attrs: Optional[Dict] = None):
    return DEFAULT_TRACER.span(name, component=component, attrs=attrs)


# -- cross-node correlation --------------------------------------------------
# The check wrapper (components/base.py) mints one id per check run,
# stamps it on the root span, and holds it in this thread-local for the
# whole run — including the ledger observe() that fires transition hooks
# AFTER the span closes. The server's outbox producers read it to stamp
# outgoing fleet records, so the manager can stitch a fleet event back
# to the exact agent-side trace that produced it (docs/fleet.md).

_correlation = threading.local()
_cid_counter = itertools.count(1)
# per-process random component: timestamp+counter alone collide when two
# agents boot in the same millisecond, and the fleet correlation index
# would stitch their unrelated records together
_cid_nonce = os.urandom(4).hex()


def new_correlation_id() -> str:
    """Fleet-unique, cheap, and grep-able: ``c<nonce>-<unix-ms>-<seq>``."""
    return f"c{_cid_nonce}-{int(time.time() * 1000):x}-{next(_cid_counter):x}"


def set_correlation_id(cid: str) -> None:
    _correlation.cid = cid


def current_correlation_id() -> str:
    return getattr(_correlation, "cid", "")


def clear_correlation_id() -> None:
    _correlation.cid = ""
