"""pstore crash-log reader.

Reference: pkg/pstore/pstore.go:19-50 — reads kernel crash dumps that
systemd-pstore moved to /var/lib/systemd/pstore after a reboot, records
them into a SQLite history table (schema v0_7_0 there) so the os component
can attribute a reboot to a kernel panic.
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass
from typing import List, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.sqlite import DB

logger = get_logger(__name__)

DEFAULT_PSTORE_DIR = "/var/lib/systemd/pstore"
ENV_PSTORE_DIR = "TPUD_PSTORE_DIR"
TABLE = "tpud_pstore_v0_1"

# dmesg-style crash files written by the kernel's pstore backend
_CRASH_FILE_RE = re.compile(r"(dmesg|console)-.*", re.IGNORECASE)
_PANIC_RE = re.compile(
    r"(Kernel panic|BUG:|Oops:|general protection fault|watchdog: hard LOCKUP)",
    re.IGNORECASE,
)


@dataclass
class CrashRecord:
    path: str
    mtime: float
    kind: str        # panic | oops | unknown
    excerpt: str     # first matching lines


def pstore_dir(override: str = "") -> str:
    return override or os.environ.get(ENV_PSTORE_DIR, "") or DEFAULT_PSTORE_DIR


def read_crash_files(dir_path: str = "", max_bytes: int = 1 << 20) -> List[CrashRecord]:
    """Scan the pstore dir for crash dumps (reference: pstore.go:19-50)."""
    d = pstore_dir(dir_path)
    out: List[CrashRecord] = []
    if not os.path.isdir(d):
        return out
    for root, _dirs, files in os.walk(d):
        for name in files:
            if not _CRASH_FILE_RE.match(name):
                continue
            path = os.path.join(root, name)
            try:
                st = os.stat(path)
                with open(path, "r", encoding="utf-8", errors="replace") as f:
                    content = f.read(max_bytes)
            except OSError:
                continue
            kind = "unknown"
            excerpt_lines = []
            for ln in content.splitlines():
                if _PANIC_RE.search(ln):
                    excerpt_lines.append(ln.strip())
                    if "panic" in ln.lower():
                        kind = "panic"
                    elif kind == "unknown":
                        kind = "oops"
                if len(excerpt_lines) >= 5:
                    break
            out.append(
                CrashRecord(
                    path=path,
                    mtime=st.st_mtime,
                    kind=kind,
                    excerpt="\n".join(excerpt_lines) or content[:500].strip(),
                )
            )
    return sorted(out, key=lambda r: r.mtime)


class PstoreHistory:
    """SQLite history of observed crash dumps, deduped by path+mtime so a
    dump is reported once across daemon restarts."""

    def __init__(self, db: DB) -> None:
        self.db = db
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                path TEXT NOT NULL,
                mtime REAL NOT NULL,
                kind TEXT NOT NULL,
                excerpt TEXT,
                recorded_at REAL NOT NULL,
                PRIMARY KEY (path, mtime)
            )"""
        )

    def record_new(self, records: List[CrashRecord]) -> List[CrashRecord]:
        """Insert unseen records; returns only the new ones."""
        fresh = []
        for r in records:
            row = self.db.query_one(
                f"SELECT 1 FROM {TABLE} WHERE path=? AND mtime=?",
                (r.path, r.mtime),
            )
            if row is not None:
                continue
            self.db.execute(
                f"INSERT INTO {TABLE} (path, mtime, kind, excerpt, recorded_at) "
                "VALUES (?, ?, ?, ?, ?)",
                (r.path, r.mtime, r.kind, r.excerpt, time.time()),
            )
            fresh.append(r)
        return fresh

    def all(self) -> List[CrashRecord]:
        return [
            CrashRecord(path=p, mtime=m, kind=k, excerpt=e)
            for p, m, k, e in self.db.query(
                f"SELECT path, mtime, kind, excerpt FROM {TABLE} ORDER BY mtime"
            )
        ]
