"""Cloud provider detection.

Reference: pkg/providers — per-cloud IMDS fetchers (aws/azure/gcp/nebius/
nscale/oci subdirs) behind a generic ``Detector``/``RegionDetector``
(detect.go:13-51), with an ASN fallback (pkg/asn) when no IMDS answers.
TPU fleets are overwhelmingly GCE, so GCP is first and richest (it also
yields the TPU accelerator-type/topology metadata).
"""

from __future__ import annotations

import concurrent.futures
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

IMDS_TIMEOUT = 1.5


@dataclass
class DetectResult:
    provider: str = ""
    region: str = ""
    zone: str = ""
    instance_type: str = ""
    accelerator_type: str = ""   # GCP TPU VMs only
    raw: Dict[str, str] = field(default_factory=dict)


def _http_get(url: str, headers: Dict[str, str], timeout: float = IMDS_TIMEOUT) -> str:
    import urllib.request

    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace").strip()


def detect_gcp(get_fn: Callable = _http_get) -> Optional[DetectResult]:
    base = "http://metadata.google.internal/computeMetadata/v1"
    h = {"Metadata-Flavor": "Google"}
    try:
        zone_path = get_fn(f"{base}/instance/zone", h)
    except Exception:  # noqa: BLE001
        return None
    zone = zone_path.rsplit("/", 1)[-1]
    region = zone.rsplit("-", 1)[0] if "-" in zone else zone
    res = DetectResult(provider="gcp", region=region, zone=zone)
    try:
        res.instance_type = get_fn(
            f"{base}/instance/machine-type", h
        ).rsplit("/", 1)[-1]
    except Exception:  # noqa: BLE001
        pass
    for attr in ("accelerator-type", "tpu-env"):
        try:
            v = get_fn(f"{base}/instance/attributes/{attr}", h)
            res.raw[attr] = v
            if attr == "accelerator-type":
                res.accelerator_type = v
        except Exception:  # noqa: BLE001
            pass
    return res


def detect_aws(get_fn: Callable = _http_get) -> Optional[DetectResult]:
    base = "http://169.254.169.254/latest"
    try:
        token = _imds_v2_token()
        h = {"X-aws-ec2-metadata-token": token} if token else {}
        doc = get_fn(f"{base}/dynamic/instance-identity/document", h)
        d = json.loads(doc)
        return DetectResult(
            provider="aws",
            region=d.get("region", ""),
            zone=d.get("availabilityZone", ""),
            instance_type=d.get("instanceType", ""),
        )
    except Exception:  # noqa: BLE001
        return None


def _imds_v2_token() -> str:
    import urllib.request

    try:
        req = urllib.request.Request(
            "http://169.254.169.254/latest/api/token",
            method="PUT",
            headers={"X-aws-ec2-metadata-token-ttl-seconds": "60"},
        )
        with urllib.request.urlopen(req, timeout=IMDS_TIMEOUT) as resp:
            return resp.read().decode()
    except Exception:  # noqa: BLE001
        return ""


def detect_azure(get_fn: Callable = _http_get) -> Optional[DetectResult]:
    try:
        doc = get_fn(
            "http://169.254.169.254/metadata/instance/compute?api-version=2021-02-01",
            {"Metadata": "true"},
        )
        d = json.loads(doc)
        return DetectResult(
            provider="azure",
            region=d.get("location", ""),
            zone=d.get("zone", ""),
            instance_type=d.get("vmSize", ""),
        )
    except Exception:  # noqa: BLE001
        return None


def detect_oci(get_fn: Callable = _http_get) -> Optional[DetectResult]:
    """OCI IMDS v2 (reference: pkg/providers/oci/imds/imds.go:14 —
    opc/v2 with the Bearer Oracle header)."""
    base = "http://169.254.169.254/opc/v2"
    h = {"Authorization": "Bearer Oracle"}
    try:
        region = get_fn(f"{base}/instance/canonicalRegionName", h)
    except Exception:  # noqa: BLE001
        return None
    res = DetectResult(provider="oci", region=region)
    try:
        res.instance_type = get_fn(f"{base}/instance/shape", h)
    except Exception:  # noqa: BLE001
        pass
    try:
        res.zone = get_fn(f"{base}/instance/availabilityDomain", h)
    except Exception:  # noqa: BLE001
        pass
    return res


# nebius/nscale mount instance identity as files, not an IMDS endpoint
# (reference: pkg/providers/nebius/nebius.go:10, nscale.go — both read
# /mnt/cloud-metadata)
CLOUD_METADATA_PATH = "/mnt/cloud-metadata"


def detect_metadata_mount(root: str = "") -> Optional[DetectResult]:
    import os

    base = root or CLOUD_METADATA_PATH
    if not os.path.isdir(base):
        return None

    def read(name: str) -> str:
        try:
            with open(os.path.join(base, name), "r", encoding="utf-8") as f:
                return f.read().strip()
        except OSError:
            return ""

    parent = read("parent-id")
    instance = read("instance-id")
    if not parent or not instance:
        return None
    cluster = read("gpu-cluster-id")
    parts = [parent] + ([cluster] if cluster else []) + [instance]
    # both nebius and nscale use this mount; distinguish on best-effort
    # markers, defaulting to nebius (reference keeps them as two detectors
    # over the same path)
    provider = "nscale" if read("org-id") else "nebius"
    return DetectResult(
        provider=provider,
        raw={"instance_id": "/".join(parts)},
    )


DETECTORS: List[Callable[[], Optional[DetectResult]]] = [
    detect_gcp,
    detect_aws,
    detect_azure,
    detect_oci,
    detect_metadata_mount,
]


def detect(timeout: float = 5.0) -> DetectResult:
    """Try all detectors concurrently; first hit wins, GCP preferred
    (reference: detect.go runs per-cloud fetchers and falls back to ASN).

    ``timeout`` is a real wall-clock bound: straggler detectors (e.g.
    blackholed IMDS on firewalled hosts) are abandoned, not joined — their
    threads die with their own HTTP timeouts."""
    ex = concurrent.futures.ThreadPoolExecutor(max_workers=len(DETECTORS))
    results: Dict[str, DetectResult] = {}
    try:
        futures = {ex.submit(d): d.__name__ for d in DETECTORS}
        try:
            for fut in concurrent.futures.as_completed(futures, timeout=timeout):
                r = fut.result()
                if r is not None:
                    results[r.provider] = r
        except concurrent.futures.TimeoutError:
            pass
    finally:
        ex.shutdown(wait=False, cancel_futures=True)
    for preferred in ("gcp", "aws", "azure", "oci", "nebius", "nscale"):
        if preferred in results:
            return results[preferred]
    # no IMDS answered: fall back to the ASN lookup. public_ip() only knows
    # GCE metadata, which just failed — so ask ip.guide about our own
    # address (self-lookup), which works from any egress-capable host
    # (reference: detect.go falls back to pkg/asn)
    try:
        from gpud_tpu import asn as asnmod

        info = asnmod.lookup("")
        if info is not None and info.provider:
            return DetectResult(
                provider=info.provider, raw={"asn": str(info.asn), "org": info.org}
            )
    except Exception:  # noqa: BLE001 — fallback must never fail detection
        pass
    return DetectResult(provider="unknown")
