"""Group NFS health checker.

Reference: pkg/nfs-checker/checker.go:15-60 — every machine in a group
writes ``<dir>/<machineID>`` with a freshness payload, then reads and
validates its peers' files; stale files past the TTL are cleaned up. This
is the only peer-to-peer observation channel in the daemon (SURVEY §2.8):
peers see each other through the shared filesystem, no network protocol.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class GroupConfig:
    """Reference: group_config.go / member_config.go."""

    dir: str = ""
    ttl_seconds: float = 300.0
    expected_members: int = 0   # 0 = whoever shows up

    def validate(self) -> Optional[str]:
        if not self.dir:
            return "nfs group dir required"
        if self.ttl_seconds < 10:
            return "ttl must be >= 10s"
        return None


@dataclass
class MemberReport:
    machine_id: str
    fresh: bool
    age_seconds: float
    error: str = ""


@dataclass
class GroupReport:
    group_dir: str
    write_ok: bool = False
    write_error: str = ""
    members: List[MemberReport] = field(default_factory=list)

    @property
    def fresh_members(self) -> int:
        return sum(1 for m in self.members if m.fresh)


class NFSChecker:
    def __init__(self, machine_id: str, configs: List[GroupConfig]) -> None:
        self.machine_id = machine_id
        self.configs = configs
        self.time_now_fn = time.time

    def check_group(self, cfg: GroupConfig) -> GroupReport:
        rep = GroupReport(group_dir=cfg.dir)
        now = self.time_now_fn()
        my_file = os.path.join(cfg.dir, self.machine_id)

        # 1. write our own freshness file
        try:
            os.makedirs(cfg.dir, exist_ok=True)
            payload = json.dumps({"machine_id": self.machine_id, "ts": now})
            tmp = my_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp, my_file)
            # read-back validation (reference: write then read/validate)
            with open(my_file, "r", encoding="utf-8") as f:
                back = json.loads(f.read())
            rep.write_ok = back.get("machine_id") == self.machine_id
            if not rep.write_ok:
                rep.write_error = "read-back mismatch"
        except OSError as e:
            rep.write_error = str(e)
            return rep

        # 2. read peers + TTL cleanup
        try:
            names = os.listdir(cfg.dir)
        except OSError as e:
            rep.write_error = rep.write_error or str(e)
            return rep
        for name in sorted(names):
            if name.endswith(".tmp"):
                continue
            path = os.path.join(cfg.dir, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    d = json.loads(f.read())
                age = now - float(d.get("ts", 0))
                fresh = age <= cfg.ttl_seconds
                rep.members.append(
                    MemberReport(machine_id=name, fresh=fresh, age_seconds=age)
                )
                if not fresh and name != self.machine_id and age > 3 * cfg.ttl_seconds:
                    # stale cleanup (reference: TTL cleanup)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            except (OSError, ValueError) as e:
                rep.members.append(
                    MemberReport(
                        machine_id=name, fresh=False, age_seconds=-1, error=str(e)
                    )
                )
        return rep

    def check_all(self) -> Dict[str, GroupReport]:
        return {cfg.dir: self.check_group(cfg) for cfg in self.configs}
