"""Micro-benchmarks as tests (reference:
pkg/eventstore/database_benchmark_test.go and
infiniband/store/insert_benchmark_test.go — Go testing.B harnesses; here
pytest functions that assert sane throughput floors and print rates, so
perf regressions surface in CI without a separate harness)."""

import time

from gpud_tpu.api.v1.types import Event
from gpud_tpu.components.tpu.ici_store import ICIStore
from gpud_tpu.eventstore import EventStore
from gpud_tpu.tpu.instance import ICILinkSnapshot


def test_eventstore_insert_throughput(tmp_db):
    es = EventStore(tmp_db)
    b = es.bucket("bench")
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        b.insert(Event(time=float(i), name=f"e{i}", message="x" * 64))
    dt = time.perf_counter() - t0
    rate = n / dt
    print(f"\n[bench] eventstore insert: {rate:.0f} events/s")
    assert rate > 200  # generous floor; catches pathological regressions


def test_eventstore_scan_throughput(tmp_db):
    es = EventStore(tmp_db)
    b = es.bucket("bench")
    es.db.executemany(
        "INSERT INTO tpud_events_v0_1 (component, timestamp, name, type, message, extra_info) "
        "VALUES ('bench', ?, 'e', 'Info', 'm', '')",
        [(float(i),) for i in range(20000)],
    )
    t0 = time.perf_counter()
    evs = b.get(0.0)
    dt = time.perf_counter() - t0
    print(f"[bench] eventstore scan: {len(evs) / dt:.0f} events/s read")
    assert len(evs) == 20000
    assert len(evs) / dt > 10000


def test_ici_store_insert_and_scan_throughput(tmp_db):
    store = ICIStore(tmp_db)
    store.time_now_fn = lambda: 100000.0
    links = [
        ICILinkSnapshot(chip_id=c, link_id=l, state="up", crc_errors=0)
        for c in range(4) for l in range(6)
    ]
    n_snapshots = 500  # ~8h of minutes for a v5p host
    t0 = time.perf_counter()
    for i in range(n_snapshots):
        store.insert_snapshot(links, ts=float(i))
    dt_insert = time.perf_counter() - t0
    rows = n_snapshots * len(links)
    t0 = time.perf_counter()
    res = store.scan(200000.0)
    dt_scan = time.perf_counter() - t0
    print(
        f"[bench] ici store: insert {rows / dt_insert:.0f} rows/s, "
        f"scan {rows / dt_scan:.0f} rows/s"
    )
    assert len(res.links) == 24
    assert rows / dt_insert > 5000
    assert rows / dt_scan > 20000


def test_metrics_store_roundtrip_throughput(tmp_db):
    from gpud_tpu.metrics.store import MetricsStore

    ms = MetricsStore(tmp_db)
    rows = [(i, f"m{i % 20}", {"component": "bench"}, float(i)) for i in range(5000)]
    t0 = time.perf_counter()
    ms.record(rows)
    dt = time.perf_counter() - t0
    print(f"[bench] metrics record: {len(rows) / dt:.0f} rows/s")
    got = ms.read(0)
    assert len(got) == 5000
    assert len(rows) / dt > 5000  # batched executemany path
