"""Manager federation tier (manager/peers.py, manager/federation.py,
session-side failover in session/outbox.py + session/session.py).

Covers, per docs/fleet.md "Federation & failover":
- rendezvous routing: deterministic, balanced, and minimal-remap (a
  dead peer's cohort moves; everyone else's owner is unchanged),
- the replication stream: shipper → wire frames → replica store, with
  the agent-outbox contract (cumulative acks, monotonic watermark,
  ack-stall redelivery) and byte-identical replica rows,
- survivor rebuild (adopt) and scatter-gather merges,
- agent-side failover: breaker peer rotation with an immediate probe,
  full-sweep cooldown, and the never-regressing acked watermark when
  acks from two different peers arrive out of order,
- the end-to-end two-manager path over real HTTP/gRPC transports.
"""

import threading
import time

import pytest

from gpud_tpu.manager import federation as fed_mod
from gpud_tpu.manager.federation import (
    REPLICA_KIND,
    FederationPlane,
    JournalShipper,
    ReplicaStore,
    journal_row_body,
    merge_agents,
    merge_fabric,
    merge_predict,
    merge_rollup,
    merge_traces,
)
from gpud_tpu.manager.peers import (
    PeerSet,
    PeerSpecError,
    owner_of,
    parse_peer_spec,
    rendezvous_rank,
)
from gpud_tpu.manager.rollup import TABLE as JOURNAL_TABLE
from gpud_tpu.manager.rollup import FleetRollupStore
from gpud_tpu.session import wire
from gpud_tpu.session.outbox import CircuitBreaker, SessionOutbox
from gpud_tpu.sqlite import DB


# -- peer specs --------------------------------------------------------------

def test_parse_peer_spec_forms():
    d = parse_peer_spec("m-a=http://127.0.0.1:8000|127.0.0.1:8001")
    assert d.peer_id == "m-a"
    assert d.endpoint == "http://127.0.0.1:8000"
    assert d.grpc_target == "127.0.0.1:8001"
    d = parse_peer_spec("m-b=http://h:9000/")
    assert (d.peer_id, d.endpoint, d.grpc_target) == ("m-b", "http://h:9000", "")


@pytest.mark.parametrize(
    "bad", ["", "http://h:9000", "m-a=", "m-a=not-a-url", "=http://h:1"]
)
def test_parse_peer_spec_rejects(bad):
    with pytest.raises(PeerSpecError):
        parse_peer_spec(bad)


# -- rendezvous routing ------------------------------------------------------

PEERS3 = ["m-a", "m-b", "m-c"]


def test_rendezvous_deterministic():
    for agent in ("tpu-vm-0", "tpu-vm-1", "x"):
        assert owner_of(agent, PEERS3) == owner_of(agent, list(PEERS3))
        # full rank is a permutation of the ring
        assert sorted(rendezvous_rank(agent, PEERS3)) == sorted(PEERS3)


def test_rendezvous_balanced():
    agents = [f"tpu-vm-{i}" for i in range(600)]
    counts = {p: 0 for p in PEERS3}
    for a in agents:
        counts[owner_of(a, PEERS3)] += 1
    # crc32 over the stable slot: not perfect, but nobody starves and
    # nobody owns the fleet
    for p, n in counts.items():
        assert 100 <= n <= 320, counts


def test_rendezvous_minimal_remap():
    """Removing one peer only remaps that peer's cohort."""
    agents = [f"tpu-vm-{i}" for i in range(400)]
    before = {a: owner_of(a, PEERS3) for a in agents}
    after = {a: owner_of(a, ["m-a", "m-c"]) for a in agents}
    for a in agents:
        if before[a] != "m-b":
            assert after[a] == before[a], a
        else:
            assert after[a] in ("m-a", "m-c")


# -- PeerSet -----------------------------------------------------------------

def _peerset(self_id="m-a", ids=PEERS3, **kw):
    descs = [parse_peer_spec(f"{p}=http://127.0.0.1:1{i}000")
             for i, p in enumerate(ids)]
    return PeerSet(self_id, descs, **kw)


def test_peerset_ring_and_neighbors():
    ps = _peerset()
    assert ps.ring == sorted(PEERS3)
    assert ps.successor().peer_id == "m-b"
    assert ps.predecessor().peer_id == "m-c"
    assert ps.successor_of("m-c").peer_id == "m-a"
    assert {p.peer_id for p in ps.others()} == {"m-b", "m-c"}


def test_peerset_single_peer_has_no_successor():
    ps = _peerset(ids=["m-a"])
    assert ps.successor() is None
    assert ps.others() == []


def test_peerset_probe_flip_edge_and_recovery():
    ps = _peerset(dead_after_probes=2)
    now = time.time()
    assert ps.is_reachable("m-b")
    assert ps.mark_probe("m-b", False, now, error="boom") is False
    # the flip edge fires exactly once, at the threshold
    assert ps.mark_probe("m-b", False, now, error="boom") is True
    assert ps.mark_probe("m-b", False, now, error="boom") is False
    assert not ps.is_reachable("m-b")
    assert [p.peer_id for p in ps.live_others()] == ["m-c"]
    ps.mark_adopted("m-b")
    assert ps.is_adopted("m-b")
    # a successful probe resurrects the peer and clears adoption
    ps.mark_probe("m-b", True, now + 1, rtt_ms=1.5)
    assert ps.is_reachable("m-b") and not ps.is_adopted("m-b")


def test_peerset_health_block_shape():
    ps = _peerset()
    rows = ps.health_block(time.time())
    assert [r["peer_id"] for r in rows][0] == "m-a"  # self first
    assert rows[0]["self"] is True
    for r in rows:
        for k in ("endpoint", "reachable", "consecutive_failures", "adopted"):
            assert k in r, r


def test_peerset_cohort_counts():
    ps = _peerset()
    counts = ps.cohort_counts([f"tpu-vm-{i}" for i in range(60)])
    assert sum(counts.values()) == 60
    assert set(counts) <= set(PEERS3)


# -- replica store -----------------------------------------------------------

def _mk_db(tmp_path, name="m.db"):
    return DB(str(tmp_path / name))


def _body(agent, seq, payload=b"\x00\x01\xffbin"):
    return {
        "agent": agent, "seq": seq, "ts": 100.0 + seq, "ingested": 101.0,
        "kind": "transition", "dedupe_key": f"k-{agent}-{seq}",
        "correlation_id": "", "payload_hex": payload.hex(), "shard": 3,
    }


def test_replica_ingest_dedupe_and_watermark(tmp_path):
    db = _mk_db(tmp_path)
    rs = ReplicaStore(db)
    recs = [(i, 0.0, REPLICA_KIND, f"j:{i}", _body("a1", i)) for i in (1, 2, 3)]
    assert rs.replica_ingest("m-b", recs) == 3
    # at-least-once redelivery: same rowids are a durable no-op
    rs.replica_ingest("m-b", recs)
    assert rs.count("m-b") == 3
    assert rs.watermark("m-b") == 3
    rows = rs.rows("m-b")
    assert [r[0] for r in rows] == [1, 2, 3]
    # payload blobs survive the hex round-trip byte-identical
    assert rows[0][8] == b"\x00\x01\xffbin"


def test_replica_ingest_rejects_malformed(tmp_path):
    rs = ReplicaStore(_mk_db(tmp_path))
    bad = [
        (1, 0.0, "wrong-kind", "k", _body("a", 1)),
        (2, 0.0, REPLICA_KIND, "k", "not-a-dict"),
        (3, 0.0, REPLICA_KIND, "k", {**_body("a", 3), "payload_hex": "zz"}),
    ]
    assert rs.replica_ingest("m-b", bad) == 0
    assert rs.stats()["malformed"] == 3
    assert rs.count("m-b") == 0


# -- journal shipper ---------------------------------------------------------

class _StubSession:
    """Stands in for the shipper's Session: always connected, records
    every frame, and can be told to fail sends."""

    def __init__(self):
        self.connected = True
        self.active_protocol = "stub"
        self.frames = []
        self.send_ok = True

    def send(self, frame):
        if self.send_ok:
            self.frames.append(frame)
        return self.send_ok

    def start(self):
        pass

    def stop(self):
        pass


def _journal_fixture(tmp_path, agents=2, per_agent=5):
    db = _mk_db(tmp_path, "src.db")
    rollup = FleetRollupStore(db, shard_count=1)
    for a in range(agents):
        recs = [
            (s, 100.0 + s, "transition",
             f"k-{a}-{s}", {"component": "cpu", "n": s})
            for s in range(1, per_agent + 1)
        ]
        rollup.ingest(f"tpu-vm-{a}", recs)
    return db, rollup


def _mk_shipper(db, clock=None, **kw):
    peer = parse_peer_spec("m-b=http://127.0.0.1:19999")
    kw.setdefault("time_fn", clock or time.monotonic)
    sh = JournalShipper(db, peer, "m-a", **kw)
    sh.session = _StubSession()
    return sh


def _decode_frames(frames):
    dec = wire.DeltaDecoder()
    out = []
    for fr in frames:
        batch = wire.parse_batch(fr.data)
        assert batch is not None
        out.extend(dec.decode_record(r) for r in batch["records"])
    return out


def test_shipper_ships_and_advances_on_ack(tmp_path):
    db, _ = _journal_fixture(tmp_path)
    sh = _mk_shipper(db, ship_batch=4)
    assert sh.tick() == 4
    assert sh.tick() == 4
    assert sh.tick() == 2  # 10 rows total
    assert sh.tick() == 0  # nothing above the delivered cursor
    decoded = _decode_frames(sh.session.frames)
    assert [seq for seq, *_ in decoded] == list(range(1, 11))
    # the shipped bodies reconstruct the journal rows exactly
    src = db.query(f"SELECT rowid, agent, seq, ts, ingested, kind, "
                   f"dedupe_key, correlation_id, payload, shard "
                   f"FROM {JOURNAL_TABLE} ORDER BY rowid")
    for (seq, _ts, kind, key, body), row in zip(decoded, src):
        assert kind == REPLICA_KIND and key == f"j:{seq}"
        assert body == journal_row_body(row)
    sh.on_ack(10)
    s = sh.stats()
    assert s["acked_rowid"] == 10 and s["lag_rows"] == 0
    assert s["frames"] == 3 and s["shipped_rows"] == 10


def test_shipper_ack_watermark_is_monotonic(tmp_path):
    db, _ = _journal_fixture(tmp_path)
    sh = _mk_shipper(db)
    sh.on_ack(7)
    sh.on_ack(3)  # late/out-of-order ack never regresses
    assert sh.stats()["acked_rowid"] == 7


def test_shipper_ack_stall_redelivers_from_watermark(tmp_path):
    db, _ = _journal_fixture(tmp_path)  # 10 rows
    clock = [0.0]
    sh = _mk_shipper(db, clock=lambda: clock[0],
                     ship_batch=100, redeliver_after=5.0)
    assert sh.tick() == 10
    sh.on_ack(4)
    assert sh.tick() == 0  # delivered cursor is ahead; acks still moving
    clock[0] = 10.0  # ack progress stalls past the window
    assert sh.tick() == 6  # rewound to the watermark, rows 5..10 again
    s = sh.stats()
    assert s["redeliveries"] == 1
    tail = _decode_frames(sh.session.frames[-1:])
    assert [seq for seq, *_ in tail] == list(range(5, 11))


def test_shipper_send_failure_rewinds(tmp_path):
    db, _ = _journal_fixture(tmp_path)
    sh = _mk_shipper(db, ship_batch=100)
    sh.session.send_ok = False
    assert sh.tick() == 0
    assert sh.stats()["delivered_rowid"] == 0
    sh.session.send_ok = True
    assert sh.tick() == 10  # keyframe-anchored retry of the full batch


def test_shipper_reconnect_resets_to_acked(tmp_path):
    db, _ = _journal_fixture(tmp_path)
    sh = _mk_shipper(db, ship_batch=100)
    sh.tick()
    sh.on_ack(6)
    sh._on_connected()  # the receiving handle's decoder is fresh
    assert sh.stats()["delivered_rowid"] == 6
    assert sh.tick() == 4  # 7..10 redelivered, starting at a keyframe
    tail = _decode_frames(sh.session.frames[-1:])
    assert tail[0][0] == 7


# -- scatter-gather merges ---------------------------------------------------

def test_merge_rollup_sums_and_weights():
    local = {
        "agents": 2, "series": 4, "records_total": 100,
        "availability": 1.0, "mttr_seconds": 0.0, "mtbf_seconds": 100.0,
        "records_by_kind": {"transition": 100}, "flapping": [],
        "max_outbox_lag_seconds": 1.0,
    }
    remote = {
        "agents": 3, "series": 12, "records_total": 50,
        "availability": 0.5, "mttr_seconds": 8.0, "mtbf_seconds": 50.0,
        "records_by_kind": {"transition": 40, "event": 10},
        "flapping": [{"agent": "b1", "component": "cpu", "flap_count": 9}],
        "max_outbox_lag_seconds": 3.0,
    }
    m = merge_rollup(local, {"m-b": remote})
    assert m["agents"] == 5 and m["records_total"] == 150
    assert m["records_by_kind"] == {"event": 10, "transition": 140}
    # series-weighted mean: (4*1.0 + 12*0.5) / 16
    assert m["availability"] == pytest.approx(0.625)
    assert m["max_outbox_lag_seconds"] == 3.0
    assert m["flapping"][0]["agent"] == "b1"
    assert m["cohorts"]["m-b"]["agents"] == 3


def test_merge_fabric_ranks_degraded():
    local = {"agents": 1, "links_total": 4, "degraded_count": 1,
             "links_by_state": {"healthy": 3, "degraded": 1},
             "degraded": [{"agent": "a", "link": "l1", "state": "degraded",
                           "last_degraded_ts": 5.0}]}
    remote = {"agents": 1, "links_total": 4, "degraded_count": 1,
              "links_by_state": {"healthy": 3, "down": 1},
              "degraded": [{"agent": "b", "link": "l2", "state": "down",
                            "last_degraded_ts": 1.0}]}
    m = merge_fabric(local, {"m-b": remote})
    assert m["links_total"] == 8
    assert m["links_by_state"] == {"degraded": 1, "down": 1, "healthy": 6}
    assert m["degraded"][0]["state"] == "down"  # severity outranks recency


def test_merge_predict_lead_distribution():
    local = {"agents": 1, "series": 2, "top_k": 3,
             "risk_buckets": {"high": 1},
             "top": [{"agent": "a", "component": "cpu", "risk": 0.9}],
             "lead": {"count": 2, "mean_seconds": 10.0,
                      "min_seconds": 5.0, "max_seconds": 15.0}}
    remote = {"agents": 1, "series": 2,
              "risk_buckets": {"low": 2},
              "top": [{"agent": "b", "component": "tpu", "risk": 0.95}],
              "lead": {"count": 2, "mean_seconds": 30.0,
                       "min_seconds": 2.0, "max_seconds": 60.0}}
    m = merge_predict(local, {"m-b": remote})
    assert m["risk_buckets"] == {"high": 1, "low": 2}
    assert m["top"][0]["agent"] == "b"
    assert m["lead"]["count"] == 4
    assert m["lead"]["mean_seconds"] == pytest.approx(20.0)
    assert m["lead"]["min_seconds"] == 2.0 and m["lead"]["max_seconds"] == 60.0


def test_merge_agents_union_annotates_peer():
    local = {"agents": [{"agent": "a-2"}], "total": 1,
             "offset": 0, "next_offset": None}
    remote = {"agents": [{"agent": "a-1"}, {"agent": "a-3"}], "total": 2,
              "next_offset": None}
    m = merge_agents(local, {"m-b": remote}, limit=10, self_id="m-a")
    assert [r["agent"] for r in m["agents"]] == ["a-1", "a-2", "a-3"]
    assert [r["peer"] for r in m["agents"]] == ["m-b", "m-a", "m-b"]
    assert m["total"] == 3 and m["next_offset"] is None
    m = merge_agents(local, {"m-b": remote}, limit=2, self_id="m-a")
    assert len(m["agents"]) == 2 and m["next_offset"] == 2


def test_merge_traces_dedupes_and_sorts():
    rec = {"agent": "a", "seq": 1, "dedupe_key": "k", "ts": 2.0}
    local = {"records": [rec], "count": 1}
    remote = {"records": [dict(rec),
                          {"agent": "b", "seq": 1, "dedupe_key": "k2",
                           "ts": 1.0}]}
    m = merge_traces(local, {"m-b": remote}, limit=10)
    assert m["count"] == 2
    assert [r["agent"] for r in m["records"]] == ["b", "a"]


# -- breaker failover --------------------------------------------------------

def _tripped(cb):
    for _ in range(cb.failure_threshold):
        cb.record_failure()


def test_breaker_rotates_and_probes_immediately():
    clock = [0.0]
    cb = CircuitBreaker(failure_threshold=2, open_seconds=30.0,
                        time_fn=lambda: clock[0],
                        peers=["http://a:1", "http://b:1", "http://c:1"])
    assert cb.current_peer() == "http://a:1"
    _tripped(cb)
    # trip #1: rotated to b, immediate probe — no cooldown served
    assert cb.current_peer() == "http://b:1"
    assert cb.seconds_until_probe() == 0.0
    assert cb.allow() is True
    assert cb.state == "half_open"
    # the probe at b fails too → rotate to c, again immediate
    cb.record_failure()
    assert cb.current_peer() == "http://c:1"
    assert cb.allow() is True
    assert cb.failover_count == 2


def test_breaker_full_sweep_falls_back_to_cooldown():
    clock = [0.0]
    cb = CircuitBreaker(failure_threshold=1, open_seconds=30.0,
                        time_fn=lambda: clock[0],
                        peers=["http://a:1", "http://b:1"])
    cb.record_failure()          # trip at a → b, immediate probe
    assert cb.allow() is True
    cb.record_failure()          # b fails: the whole tier is down
    assert cb.current_peer() == "http://a:1"  # wrapped
    assert cb.allow() is False   # normal cooldown now
    assert cb.seconds_until_probe() == pytest.approx(30.0)
    clock[0] = 31.0
    assert cb.allow() is True    # half-open probe after the cooldown


def test_breaker_success_resets_sweep():
    cb = CircuitBreaker(failure_threshold=1, peers=["http://a:1", "http://b:1"])
    cb.record_failure()
    assert cb.allow() is True
    cb.record_success()
    assert cb.state == "closed"
    # the sweep counter reset: the next trip gets an immediate probe again
    cb.record_failure()
    assert cb.allow() is True
    s = cb.stats()
    assert s["peers"] == ["http://a:1", "http://b:1"]
    assert s["failovers"] == 2


def test_breaker_without_peers_unchanged():
    clock = [0.0]
    cb = CircuitBreaker(failure_threshold=1, open_seconds=5.0,
                        time_fn=lambda: clock[0])
    cb.record_failure()
    assert cb.current_peer() == ""
    assert cb.allow() is False  # classic park-for-cooldown behavior
    clock[0] = 6.0
    assert cb.allow() is True


def test_breaker_single_peer_never_rotates():
    cb = CircuitBreaker(failure_threshold=1, peers=["http://a:1"])
    cb.record_failure()
    assert cb.current_peer() == "http://a:1"
    assert cb.failover_count == 0
    assert cb.allow() is False


# -- watermark safety across peers -------------------------------------------

def test_outbox_watermark_never_regresses_across_peers(tmp_path):
    """Acks from two different managers arriving out of order: the
    watermark is MAX in memory AND in SQL, so the late, smaller ack from
    the dead peer is a no-op."""
    db = _mk_db(tmp_path, "agent.db")
    ob = SessionOutbox(db)
    for i in range(8):
        ob.publish("transition", {"n": i}, dedupe_key=f"k{i}")
    ob.ack(3)                # peer A acked the prefix before dying
    ob.ack(8)                # peer B acked the redelivered batch
    assert ob.acked_seq == 8
    ob.ack(5)                # A's stale ack arrives late (network queue)
    assert ob.acked_seq == 8
    from gpud_tpu.session.outbox import ACK_TABLE

    row = db.query_one(f"SELECT acked_seq FROM {ACK_TABLE} WHERE id=1")
    assert int(row[0]) == 8
    assert ob.backlog() == 0


def test_outbox_watermark_concurrent_two_peer_acks(tmp_path):
    ob = SessionOutbox(_mk_db(tmp_path, "agent2.db"))
    for i in range(100):
        ob.publish("event", {"n": i})
    seqs_a = list(range(1, 101, 2))
    seqs_b = list(range(2, 101, 2))

    def hammer(seqs):
        for s in seqs:
            ob.ack(s)

    ta = threading.Thread(target=hammer, args=(seqs_a,))
    tb = threading.Thread(target=hammer, args=(seqs_b,))
    ta.start(); tb.start(); ta.join(); tb.join()
    assert ob.acked_seq == 100


# -- Session._apply_peer -----------------------------------------------------

def _mk_session():
    from gpud_tpu.session.session import Session

    return Session(endpoint="http://old:1", machine_id="m",
                   v2_target="old:2", protocol="auto")


@pytest.mark.parametrize("spec,endpoint,v2", [
    ("http://new:1", "http://new:1", ""),
    ("http://new:1|new:2", "http://new:1", "new:2"),
    ("m-b=http://new:1|new:2", "http://new:1", "new:2"),
    ("m-b=http://new:1/", "http://new:1", ""),
])
def test_apply_peer_retargets(spec, endpoint, v2):
    s = _mk_session()
    s._v2_failed = True
    s._v2_skip_cycles = 3
    s._apply_peer(spec)
    assert s.endpoint == endpoint
    assert s.v2_target == v2
    # the new peer negotiates its own transport
    assert s._v2_failed is False and s._v2_skip_cycles == 0


def test_apply_peer_noop_on_same_or_empty():
    s = _mk_session()
    s._apply_peer("")
    s._apply_peer("http://old:1|old:2")
    assert s.endpoint == "http://old:1" and s.v2_target == "old:2"


# -- end-to-end: two real managers -------------------------------------------

def _wait(pred, timeout=20.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _spec(pid, cp):
    return f"{pid}=http://127.0.0.1:{cp.port}|127.0.0.1:{cp.grpc_port}"


@pytest.fixture()
def two_managers(tmp_path):
    from gpud_tpu.manager.control_plane import ControlPlane

    cps = {}
    for pid in ("m-a", "m-b"):
        cp = ControlPlane(
            instance_id=pid, data_dir=str(tmp_path / pid), shards=1
        )
        cp.start()
        cps[pid] = cp
    specs = [_spec(pid, cp) for pid, cp in cps.items()]
    for pid, cp in cps.items():
        cp.attach_peers(
            pid, specs,
            replication_interval=0.1, probe_interval=0.3,
            fanout_timeout=2.0, dead_after_probes=2,
        )
    yield cps
    for cp in cps.values():
        try:
            cp.stop()
        except Exception:
            pass


def _http_json(url):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        return _json.loads(resp.read().decode())


def test_two_manager_replication_failover_and_scatter(two_managers):
    a, b = two_managers["m-a"], two_managers["m-b"]

    # cohort ingest at A (the agent transport path feeds rollup.ingest
    # exactly like this — the wire layers have their own e2e tests)
    for n in range(4):
        recs = [
            (s, 1000.0 + s, "transition", f"k-{n}-{s}",
             {"component": "cpu", "health": "healthy", "n": s})
            for s in range(1, 26)
        ]
        a.rollup.ingest(f"tpu-vm-a{n}", recs)
    a.writer.flush(timeout=10.0)
    head = a.federation.shipper.journal_head()
    assert head == 100

    # replication stream: B's replica converges on A's journal head.
    # Generous ceiling: on a loaded 1-core CI box the shipper's first
    # connects can fail and walk the session backoff (1s doubling,
    # BACKOFF_MAX 60s) before the stream establishes
    _wait(lambda: b.federation.replica.watermark("m-a") >= head,
          timeout=90.0, msg="replica watermark")
    b.writer.flush(timeout=10.0)
    src_rows = a.db.query(
        f"SELECT rowid, agent, seq, ts, ingested, kind, dedupe_key, "
        f"correlation_id, payload, shard FROM {JOURNAL_TABLE} ORDER BY rowid"
    )
    rep_rows = b.federation.replica.rows("m-a")
    # byte-identical survivor prefix: every column, payload blobs included
    assert [tuple(r) for r in rep_rows] == [tuple(r) for r in src_rows]

    # scatter-gather while both peers live: one pane over both cohorts
    b.rollup.ingest("tpu-vm-b0", [(1, 1000.0, "transition", "kb-1",
                                   {"component": "cpu", "health": "healthy"})])
    pane = _http_json(f"{b.endpoint}/v1/fleet/rollup")
    assert pane["federated"] is True
    assert pane["agents"] == 5
    assert {p["peer_id"] for p in pane["peers"]} == {"m-a", "m-b"}
    assert "m-a" in pane["fanout"] and "error" not in pane["fanout"]["m-a"]
    local = _http_json(f"{b.endpoint}/v1/fleet/rollup?scope=local")
    assert "federated" not in local and local["agents"] == 1

    peers_view = _http_json(f"{b.endpoint}/v1/fleet/peers")
    assert peers_view["federation"] is True
    assert peers_view["ring"] == ["m-a", "m-b"]
    assert peers_view["successor"] == "m-a"
    assert sum(peers_view["rendezvous"].values()) == 1  # B's own cohort

    # kill A; B's probes flip it dead and the survivor adopts the cohort
    records_before = b.rollup.records_total()
    a.stop()
    _wait(lambda: b.federation.peers.is_adopted("m-a"), timeout=60.0,
          msg="survivor adopt")
    assert b.rollup.records_total() == records_before + 100
    assert set(b.rollup.agent_ids()) >= {f"tpu-vm-a{n}" for n in range(4)}

    # a failed-over agent redelivers its last batch: dedupe, not growth
    recs = [(s, 1000.0 + s, "transition", f"k-0-{s}",
             {"component": "cpu", "health": "healthy", "n": s})
            for s in range(1, 26)]
    assert b.rollup.ingest("tpu-vm-a0", recs) == 0

    # the single pane survives: dead peer visibly unreachable, not silent
    pane = _http_json(f"{b.endpoint}/v1/fleet/rollup")
    assert pane["federated"] is True
    assert pane["agents"] == 5  # 4 adopted + b0, all served by the survivor
    dead = [p for p in pane["peers"] if p["peer_id"] == "m-a"]
    assert dead and dead[0]["reachable"] is False and dead[0]["adopted"]

    # federated /metrics reflects the peer map
    import urllib.request

    with urllib.request.urlopen(f"{b.endpoint}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "tpud_fleet_peers 2" in text
    assert "tpud_fleet_peer_adopts 1" in text


def test_federation_plane_standalone_bits(tmp_path):
    """FederationPlane odds and ends that don't need live peers."""
    db = _mk_db(tmp_path, "fp.db")
    rollup = FleetRollupStore(db, shard_count=1)
    descs = [parse_peer_spec("m-a=http://127.0.0.1:1"),
             parse_peer_spec("m-b=http://127.0.0.1:2")]
    fp = FederationPlane(PeerSet("m-a", descs), rollup, db,
                         probe_interval=600, replication_interval=600)
    try:
        # replica_sink strips the peer: prefix and journals the batch
        sink = fp.replica_sink(f"{fed_mod.PEER_MACHINE_PREFIX}m-b")
        body = _body("a1", 1, payload=wire.pack_obj(
            {"component": "cpu", "health": "healthy"}
        ))
        sink("peer:m-b", [(1, 0.0, REPLICA_KIND, "j:1", body)])
        assert fp.replica.count("m-b") == 1
        # adopt replays the replicated prefix into the local rollup
        fp.peers.mark_probe("m-b", False, time.time())
        fp.adopt("m-b")
        assert rollup.records_total() == 1
        assert fp.adopt("m-b") == 0  # idempotent
        view = fp.peers_view()
        assert view["replication"]["peer"] == "m-b"
        assert view["replica"]["accepted"] == 1
        stats = fp.stats()
        assert stats["peers_total"] == 2 and stats["adopts"] == 1
    finally:
        fp.stop()
