import os

from gpud_tpu.api.v1.types import PackagePhase
from gpud_tpu.login import login, normalize_node_labels
from gpud_tpu.manager.packages import PackageManager
from gpud_tpu.metadata import KEY_MACHINE_ID, KEY_TOKEN, Metadata
from gpud_tpu.nfs_checker import GroupConfig, NFSChecker
from gpud_tpu.providers.detect import DetectResult, detect_gcp
from gpud_tpu.update import (
    VersionFileWatcher,
    read_target_version,
    write_target_version,
)


# -- packages ----------------------------------------------------------------

def _mk_pkg(root, name, target="1.0", init_body="echo installed"):
    d = root / "packages" / name
    d.mkdir(parents=True)
    (d / "init.sh").write_text(f"#!/bin/bash\n{init_body}\n")
    (d / "version").write_text(target)
    return d


def test_package_install_and_status(tmp_path):
    d = _mk_pkg(tmp_path, "tooling")
    pm = PackageManager(str(tmp_path / "packages"))
    assert pm.package_names() == ["tooling"]
    st = pm.status()[0]
    assert st.phase == PackagePhase.UNKNOWN and not st.is_installed

    pm.reconcile_once()
    st = pm.status()[0]
    assert st.phase == PackagePhase.INSTALLED
    assert st.current_version == "1.0"
    assert (d / "installed_version").read_text() == "1.0"

    # version bump → reinstall
    (d / "version").write_text("2.0")
    assert pm.status()[0].phase == PackagePhase.UNKNOWN
    pm.reconcile_once()
    assert pm.status()[0].current_version == "2.0"


def test_package_install_failure_not_marked(tmp_path):
    _mk_pkg(tmp_path, "broken", init_body="exit 1")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()
    st = pm.status()[0]
    assert not st.is_installed


def test_package_status_probe(tmp_path):
    d = _mk_pkg(tmp_path, "svc")
    (d / "status.sh").write_text("#!/bin/bash\nexit 0\n")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()
    assert pm.status()[0].status == "running"


# -- update watcher ------------------------------------------------------------

def test_version_file_roundtrip(tmp_path):
    p = str(tmp_path / "target_version")
    assert read_target_version(p) == ""
    write_target_version(p, "1.2.3")
    assert read_target_version(p) == "1.2.3"


def test_update_watcher_triggers(tmp_path):
    p = str(tmp_path / "target_version")
    fired = []
    w = VersionFileWatcher(p, current_version="1.0.0", on_update=fired.append)
    assert w.check_once() is False
    write_target_version(p, "1.0.0")  # same version → no-op
    assert w.check_once() is False
    write_target_version(p, "2.0.0")
    assert w.check_once() is True
    assert fired == ["2.0.0"]


# -- login ---------------------------------------------------------------------

def test_normalize_node_labels():
    out = normalize_node_labels({"team": "ml", "user.node.tpud.dev/x": "y"})
    assert out == {"user.node.tpud.dev/team": "ml", "user.node.tpud.dev/x": "y"}


def test_login_persists_identity(tmp_db):
    md = Metadata(tmp_db)
    captured = {}

    def fake_post(url, body):
        captured["url"] = url
        captured["body"] = body
        return {"machine_id": "assigned-42", "token": "server-token",
                "machine_proof": "proof-1"}

    resp = login(
        "https://cp.example/", "join-token", md,
        node_labels={"rack": "r1"}, post_fn=fake_post,
    )
    assert captured["url"] == "https://cp.example/api/v1/login"
    assert captured["body"]["token"] == "join-token"
    assert resp.machine_id == "assigned-42"
    assert md.get(KEY_MACHINE_ID) == "assigned-42"  # overwrite semantics
    assert md.get(KEY_TOKEN) == "server-token"


def test_login_rejection_raises(tmp_db):
    md = Metadata(tmp_db)

    def fake_post(url, body):
        return {"error": "invalid token"}

    try:
        login("https://cp", "bad", md, post_fn=fake_post)
        raised = False
    except RuntimeError as e:
        raised = "invalid token" in str(e)
    assert raised


# -- nfs checker -----------------------------------------------------------------

def test_nfs_group_two_members(tmp_path):
    d = str(tmp_path / "group")
    m1 = NFSChecker("machine-1", [GroupConfig(dir=d, ttl_seconds=60)])
    m2 = NFSChecker("machine-2", [GroupConfig(dir=d, ttl_seconds=60)])
    r1 = m1.check_group(m1.configs[0])
    assert r1.write_ok
    r2 = m2.check_group(m2.configs[0])
    assert r2.fresh_members == 2
    assert {m.machine_id for m in r2.members} == {"machine-1", "machine-2"}


def test_nfs_stale_member_detected(tmp_path):
    d = str(tmp_path / "group")
    cfg = GroupConfig(dir=d, ttl_seconds=60)
    m1 = NFSChecker("m1", [cfg])
    now = [1000.0]
    m1.time_now_fn = lambda: now[0]
    m1.check_group(cfg)
    now[0] += 120  # m1's file is now stale
    m2 = NFSChecker("m2", [cfg])
    m2.time_now_fn = lambda: now[0]
    rep = m2.check_group(cfg)
    stale = [m for m in rep.members if m.machine_id == "m1"]
    assert stale and not stale[0].fresh


# -- providers --------------------------------------------------------------------

def test_detect_gcp_with_fake_imds():
    def fake_get(url, headers, timeout=1.0):
        assert headers == {"Metadata-Flavor": "Google"}
        if url.endswith("/zone"):
            return "projects/123/zones/us-central2-b"
        if url.endswith("/machine-type"):
            return "projects/123/machineTypes/ct5lp-hightpu-8t"
        if url.endswith("accelerator-type"):
            return "v5litepod-8"
        raise OSError("no such attr")

    r = detect_gcp(get_fn=fake_get)
    assert r.provider == "gcp"
    assert r.zone == "us-central2-b"
    assert r.region == "us-central2"
    assert r.instance_type == "ct5lp-hightpu-8t"
    assert r.accelerator_type == "v5litepod-8"


def test_detect_gcp_absent():
    def fake_get(url, headers, timeout=1.0):
        raise OSError("no route")

    assert detect_gcp(get_fn=fake_get) is None


def test_package_delete_marker_removes_package(tmp_path):
    """Delete loop (reference: deleteRunner, package_controller.go:274-294):
    a pushed delete marker runs the uninstall hook then drops the dir."""
    d = _mk_pkg(tmp_path, "togo")
    trace = tmp_path / "uninstalled"
    (d / "uninstall.sh").write_text(f"#!/bin/bash\necho bye > {trace}\n")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()  # installs
    assert (d / "installed_version").read_text() == "1.0"

    (d / "delete").write_text("")
    pm.reconcile_once()
    assert not d.exists()
    assert trace.read_text().strip() == "bye"
    assert pm.package_names() == []
    assert pm.status() == []


def test_package_delete_without_hook(tmp_path):
    d = _mk_pkg(tmp_path, "plain")
    pm = PackageManager(str(tmp_path / "packages"))
    (d / "delete").write_text("")
    pm.reconcile_once()
    assert not d.exists()


def test_package_delete_failing_hook_still_removes(tmp_path):
    d = _mk_pkg(tmp_path, "stubborn")
    (d / "uninstall.sh").write_text("#!/bin/bash\nexit 7\n")
    pm = PackageManager(str(tmp_path / "packages"))
    (d / "delete").write_text("")
    pm.reconcile_once()
    assert not d.exists()


def test_package_delete_marker_without_init_sh(tmp_path):
    """A partial push (no init.sh) must still honor its delete marker."""
    d = tmp_path / "packages" / "broken"
    d.mkdir(parents=True)
    (d / "delete").write_text("")
    pm = PackageManager(str(tmp_path / "packages"))
    assert pm.package_names() == []  # invisible to the install pass
    pm.reconcile_once()
    assert not d.exists()


def test_package_delete_hook_runs_once_when_rmtree_fails(tmp_path, monkeypatch):
    """If dir removal fails, the delete retries next reconcile but the
    (non-idempotent) uninstall hook must not re-run."""
    import shutil as _shutil

    d = _mk_pkg(tmp_path, "wedged")
    trace = tmp_path / "hook_runs"
    (d / "uninstall.sh").write_text(f"#!/bin/bash\necho x >> {trace}\n")
    (d / "delete").write_text("")
    pm = PackageManager(str(tmp_path / "packages"))

    calls = []
    real_rmtree = _shutil.rmtree

    def failing_rmtree(path, **kw):
        calls.append(path)
        if len(calls) < 3:
            raise OSError("device busy")
        real_rmtree(path, **kw)

    monkeypatch.setattr(_shutil, "rmtree", failing_rmtree)
    pm.reconcile_once()  # hook runs (and is consumed), rmtree fails
    assert d.exists()
    assert not (d / "uninstall.sh").exists()  # done-signal: hook removed
    pm.reconcile_once()  # rmtree fails again, hook skipped
    assert d.exists()
    pm.reconcile_once()  # rmtree succeeds
    assert not d.exists()
    assert trace.read_text().count("x") == 1


def test_detect_oci_with_fake_imds():
    from gpud_tpu.providers.detect import detect_oci

    def fake_get(url, headers, timeout=1.5):
        assert headers == {"Authorization": "Bearer Oracle"}
        if url.endswith("canonicalRegionName"):
            return "us-ashburn-1"
        if url.endswith("shape"):
            return "BM.GPU.H100.8"
        if url.endswith("availabilityDomain"):
            return "AD-1"
        raise AssertionError(url)

    r = detect_oci(get_fn=fake_get)
    assert r.provider == "oci"
    assert r.region == "us-ashburn-1"
    assert r.instance_type == "BM.GPU.H100.8"
    assert r.zone == "AD-1"


def test_detect_metadata_mount(tmp_path):
    from gpud_tpu.providers.detect import detect_metadata_mount

    assert detect_metadata_mount(root=str(tmp_path / "nope")) is None
    (tmp_path / "parent-id").write_text("proj-1\n")
    (tmp_path / "instance-id").write_text("inst-9\n")
    r = detect_metadata_mount(root=str(tmp_path))
    assert r.provider == "nebius"
    assert r.raw["instance_id"] == "proj-1/inst-9"
    (tmp_path / "gpu-cluster-id").write_text("clu-2")
    (tmp_path / "org-id").write_text("org-7")
    r = detect_metadata_mount(root=str(tmp_path))
    assert r.provider == "nscale"
    assert r.raw["instance_id"] == "proj-1/clu-2/inst-9"


def test_package_dependency_gating(tmp_path):
    """requires-file dependency gating (reference: Dependency in
    installRunner): a package waits until its dependency is installed."""
    base = _mk_pkg(tmp_path, "base")
    app = _mk_pkg(tmp_path, "app")
    (app / "requires").write_text("base\n")
    pm = PackageManager(str(tmp_path / "packages"))
    # sabotage base's first install so app must wait
    (base / "init.sh").write_text("#!/bin/bash\nexit 1\n")
    pm.reconcile_once()
    assert not (base / "installed_version").exists()
    assert not (app / "installed_version").exists()
    # base recovers: it installs this pass; app (which sorts earlier and
    # was visited before base finished) follows on the next pass — the
    # reference's periodic runner converges the same way
    (base / "init.sh").write_text("#!/bin/bash\ntrue\n")
    pm.reconcile_once()
    assert (base / "installed_version").read_text() == "1.0"
    pm.reconcile_once()
    assert (app / "installed_version").read_text() == "1.0"


def test_package_unknown_dependency_waits(tmp_path):
    app = _mk_pkg(tmp_path, "app")
    (app / "requires").write_text("ghost\n")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()
    assert not (app / "installed_version").exists()


def test_package_should_skip_probe(tmp_path):
    d = _mk_pkg(tmp_path, "preinstalled")
    (d / "should_skip.sh").write_text("#!/bin/bash\nexit 0\n")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()
    assert not (d / "installed_version").exists()
    st = pm.status(probe=False)[0]
    assert st.phase == PackagePhase.SKIPPED
    # probe flips (package removed from the image) → installs normally
    (d / "should_skip.sh").write_text("#!/bin/bash\nexit 1\n")
    pm.reconcile_once()
    assert (d / "installed_version").read_text() == "1.0"
    assert pm.status(probe=False)[0].phase == PackagePhase.INSTALLED


def test_package_dep_satisfied_by_host_provided_skip(tmp_path):
    """A dependency the host already provides (should_skip.sh exit 0)
    satisfies dependents without ever installing."""
    base = _mk_pkg(tmp_path, "base")
    (base / "should_skip.sh").write_text("#!/bin/bash\nexit 0\n")
    app = _mk_pkg(tmp_path, "zapp")  # sorts after base
    (app / "requires").write_text("base\n")
    pm = PackageManager(str(tmp_path / "packages"))
    pm.reconcile_once()
    assert not (base / "installed_version").exists()
    assert (app / "installed_version").read_text() == "1.0"


def test_package_skip_probe_cached_until_inputs_change(tmp_path):
    d = _mk_pkg(tmp_path, "cachedpkg")
    runs = tmp_path / "probe_runs"
    (d / "should_skip.sh").write_text(f"#!/bin/bash\necho x >> {runs}\nexit 0\n")
    pm = PackageManager(str(tmp_path / "packages"))
    for _ in range(5):
        pm.reconcile_once()
    assert runs.read_text().count("x") == 1  # cached, not per-pass


def test_package_informer_reacts_within_poll_interval(tmp_path):
    """File-informer parity (reference: informer/file_informer.go): a
    pushed package installs well under the fallback poll interval."""
    import time as _t

    from gpud_tpu.inotify import InotifyWatch

    pm = PackageManager(str(tmp_path / "packages"))
    pm.start()
    try:
        probe = InotifyWatch.create(str(tmp_path))
        if probe is None:
            import pytest

            pytest.skip("inotify unavailable")
        probe.close()
        _t.sleep(0.2)  # informer up
        d = _mk_pkg(tmp_path, "pushed")
        deadline = _t.time() + 5  # << RECONCILE_INTERVAL (15s)
        while _t.time() < deadline:
            if (d / "installed_version").exists():
                break
            _t.sleep(0.05)
        assert (d / "installed_version").exists(), "informer never installed"
        assert (d / "installed_version").read_text() == "1.0"
        # delete marker also reacts fast
        (d / "delete").write_text("")
        deadline = _t.time() + 5
        while _t.time() < deadline and d.exists():
            _t.sleep(0.05)
        assert not d.exists()
    finally:
        pm.close()


def test_package_informer_polling_fallback_without_inotify(tmp_path, monkeypatch):
    """Non-Linux / restricted sandboxes get the plain interval-poll loop;
    it must reconcile and survive reconcile exceptions."""
    import threading
    import time

    import gpud_tpu.manager.packages as pk
    from gpud_tpu.inotify import InotifyWatch

    monkeypatch.setattr(
        InotifyWatch, "create", staticmethod(lambda *a, **k: None)
    )
    monkeypatch.setattr(pk, "RECONCILE_INTERVAL", 0.05)
    mgr = pk.PackageManager(str(tmp_path / "pkgs"))
    calls = []
    real = mgr.reconcile_once

    def counting():
        calls.append(1)
        if len(calls) == 2:
            raise RuntimeError("transient")  # loop must survive
        return real()

    mgr.reconcile_once = counting
    mgr.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and len(calls) < 4:
            time.sleep(0.02)
        assert len(calls) >= 4  # kept polling after the exception
    finally:
        mgr.close()
