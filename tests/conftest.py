"""Test configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8) so multi-chip sharding
is exercised without TPU hardware, mirroring the reference's mock-NVML
strategy of running "with GPUs" on GPU-less CI
(reference: pkg/nvidia/nvml/lib/default.go:26-30).
"""

import os

# force-set (not setdefault): the surrounding environment may preset
# JAX_PLATFORMS to a live TPU platform, and tests must never grab real chips
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# a site hook may have imported jax before this file ran, capturing
# JAX_PLATFORMS from the outer env; only then is a config-level override
# needed (and only then is jax already paying its import cost anyway)
import sys as _sys

if "jax" in _sys.modules:
    try:
        _sys.modules["jax"].config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass

# the mock TPU backend by default so every test runs on a CPU-only box
# (reference: GPUD_NVML_MOCK_ALL_SUCCESS, SURVEY §4.3)
os.environ.setdefault("TPUD_TPU_MOCK_ALL_SUCCESS", "1")

import pytest  # noqa: E402

# opt-in line coverage: TPUD_COV=/path/out.json pytest ...
# (the image ships no coverage package; gpud_tpu.tools.cov is the
# sys.monitoring-based stand-in for the reference's go-test -cover gate)
_COV_OUT = os.environ.get("TPUD_COV")
_COV = None
if _COV_OUT:
    from gpud_tpu.tools.cov import LineCollector

    _COV = LineCollector(os.path.join(os.path.dirname(__file__), "..", "gpud_tpu"))
    _COV.start()


def pytest_sessionfinish(session, exitstatus):
    if _COV is not None:
        _COV.stop()
        _COV.dump(_COV_OUT)


# -- thread-leak audit -------------------------------------------------------
# Daemon policy: every worker thread is daemon=True (the guard-linted
# modules all spawn with daemon=True), so a non-daemon thread alive after
# the suite is a leak that would hang interpreter exit in production.
# Name prefixes here are the known transient singletons, not a dumping
# ground — justify any addition.
THREAD_LEAK_ALLOWLIST = (
    # providers.detect abandons blackholed IMDS probes by design
    # (shutdown(wait=False)); they die with their own HTTP timeouts
    "ThreadPoolExecutor-",
    # debugger/profiler helper threads when the suite runs under an IDE
    "pydevd", "Profiler",
)


@pytest.fixture(scope="session", autouse=True)
def thread_leak_audit():
    """Fail the run if the suite leaks a non-daemon thread: snapshot the
    non-daemon set before any test, and after the last test give
    stragglers a short joining grace, then fail on survivors."""
    import threading

    baseline = {t.ident for t in threading.enumerate() if not t.daemon}

    def stray():
        return [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not threading.main_thread()
            and t.ident not in baseline
            and not any(t.name.startswith(p) for p in THREAD_LEAK_ALLOWLIST)
        ]

    yield
    wait_until(lambda: not stray(), timeout=5.0)
    leaked = stray()
    if leaked:
        pytest.fail(
            "suite leaked non-daemon thread(s): "
            + ", ".join(sorted(t.name for t in leaked))
            + " — daemon threads are policy (see guard-linted modules); "
            "either join it in teardown or justify an allowlist entry",
            pytrace=False,
        )


@pytest.fixture()
def tmp_db(tmp_path):
    from gpud_tpu.sqlite import DB

    db = DB(str(tmp_path / "state.db"))
    yield db
    db.close()


def wait_until(cond, timeout=5.0, interval=0.01):
    """Poll ``cond`` until truthy or timeout; returns the final value."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    return cond()


@pytest.fixture(scope="module")
def live_server(tmp_path_factory):
    """Module-scoped daemon with mock TPU backend, fixture kmsg, no TLS,
    and the egress-dependent latency probe disabled (shared by the SDK /
    dispatcher suites — keep config changes HERE, not per-module)."""
    from gpud_tpu.config import default_config
    from gpud_tpu.server.server import Server

    tmp = tmp_path_factory.mktemp("live-server")
    kmsg = tmp / "kmsg.fixture"
    kmsg.write_text("")
    cfg = default_config(
        data_dir=str(tmp / "data"), port=0, tls=False, kmsg_path=str(kmsg)
    )
    cfg.components_disabled = ["network-latency"]  # egress-blocked sandbox
    s = Server(config=cfg)
    s.start()
    yield s
    s.stop()


