"""Robustness fuzzing over untrusted-input surfaces: kernel log bytes,
control-plane frames, and dispatch payloads must never raise — they
degrade to None/error responses (reference: the daemon's inputs are
hostile-by-default kernel and network data)."""

import json
import random
import string

from gpud_tpu.components.tpu import catalog
from gpud_tpu.kmsg.watcher import parse_line
from gpud_tpu.session.session import Frame

SEED = 1234


def _random_lines(n=500):
    rng = random.Random(SEED)
    alphabet = string.printable + "\x00\xffé中"
    out = []
    for _ in range(n):
        ln = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 200)))
        out.append(ln)
    # adversarial shapes near the real formats
    out += [
        ",",
        ";;;;",
        "6,",
        "6,1,",
        "6,1,100,-;",
        "99999999999999999999,1,1,-;x",
        "6,1,100,-;" + "A" * 65536,
        "-1,-1,-1,-;neg",
        "a,b,c,d;letters",
        "6,1,100",  # no semicolon
        "\x00\x00\x00",
        "TPU-ERR:",  # prefix of the injection format
        "accel" + "9" * 40 + ": device lost",  # huge chip id
    ]
    return out


def test_kmsg_parse_line_never_raises():
    for ln in _random_lines():
        parse_line(ln, boot_unix=0.0)  # result may be None; must not raise


def test_catalog_match_never_raises():
    for ln in _random_lines():
        m = catalog.match(ln)
        if m is not None:
            assert m.entry.name  # and a match is always well-formed
        catalog.extract_chip(ln)


def test_native_parser_agrees_on_garbage():
    from gpud_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    for ln in _random_lines():
        py = parse_line(ln, boot_unix=0.0)
        nat = native.parse_kmsg(ln)
        assert (py is None) == (nat is None), ln[:80]


def test_frame_from_json_never_raises():
    cases = [
        "", "null", "[]", "42", '"str"', "{", '{"req_id": {}}',
        '{"req_id": null, "data": []}', '{"data": {"a": 1}}',
        '{"req_id": "x", "data": null}', "\x00", "{}" * 1000,
    ]
    for raw in cases:
        f = Frame.from_json(raw)
        if f is not None:
            assert isinstance(f.req_id, str)
            assert isinstance(f.data, dict)


def test_dispatcher_malformed_payloads_error_not_raise(tmp_path):
    from gpud_tpu.config import default_config
    from gpud_tpu.server.server import Server
    from gpud_tpu.session.dispatch import Dispatcher

    kmsg = tmp_path / "k"
    kmsg.touch()
    srv = Server(config=default_config(
        data_dir=str(tmp_path / "d"), port=0, tls=False, kmsg_path=str(kmsg),
        components_disabled=["network-latency"],
    ))
    srv.start()
    try:
        dispatch = Dispatcher(srv)
        hostile = [
            {},  # no method
            {"method": None},
            {"method": 42},
            {"method": "states", "components": 42},
            {"method": "events", "since": "yesterday"},
            {"method": "metrics", "since": [1, 2]},
            {"method": "updateConfig", "configs": "not-a-dict"},
            {"method": "updateConfig", "configs": {"ici": {"scan_window": "w"}}},
            {"method": "updateConfig", "configs": {"nfs_groups": [None]}},
            {"method": "injectFault"},
            {"method": "setHealthy"},
            {"method": "bootstrap", "script_base64": 99},
            {"method": "diagnostic", "since": {"a": 1}},
            {"method": "triggerComponent", "component": ["x"]},
            {"method": "reboot", "delay_seconds": "soon"},
            {"method": "update"},
            {"method": "kapMTLSUpdateCredentials", "version": "../../etc"},
            {"method": "setPluginSpecs", "specs": "nope"},
        ]
        for req in hostile:
            out = dispatch(req)
            assert isinstance(out, dict), req
            # a hostile payload yields an error or a handled no-op — never
            # an exception escaping the dispatcher
    finally:
        srv.stop()


def test_plugin_spec_from_dict_garbage():
    from gpud_tpu.plugins.spec import PluginSpec, specs_from_list

    for d in [{}, {"name": "x"}, {"name": "x", "steps": "nope"},
              {"name": "x", "steps": [{}]}, {"steps": [{"script": "hi"}]}]:
        try:
            specs_from_list([d])
        except (ValueError, KeyError, TypeError):
            pass  # a clean validation error is the contract


def test_http_api_malformed_inputs(tmp_path):
    """Every API route degrades to 4xx/handled responses on hostile query
    strings and bodies — no 500s from input parsing."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from gpud_tpu.config import default_config
    from gpud_tpu.server.app import build_app
    from gpud_tpu.server.server import Server

    kmsg = tmp_path / "k"
    kmsg.touch()
    srv = Server(config=default_config(
        data_dir=str(tmp_path / "d"), port=0, tls=False, kmsg_path=str(kmsg),
        components_disabled=["network-latency"],
    ))
    srv.start()

    async def drive():
        client = TestClient(TestServer(build_app(srv)))
        await client.start_server()
        try:
            hostile_gets = [
                "/v1/events?startTime=banana",
                "/v1/events?startTime=nan&endTime=%00",
                "/v1/metrics?since=[]",
                "/v1/info?startTime={}",
                "/v1/states?components=%00%ff,,,",
                "/v1/components/trigger-check",
                "/v1/components/trigger-check?componentName=../../etc",
                "/v1/events?" + "x" * 4096 + "=1",
            ]
            for path in hostile_gets:
                resp = await client.get(path)
                assert resp.status < 500, (path, resp.status)
            hostile_posts = [
                ("/inject-fault", b"\x00\xff garbage"),
                ("/inject-fault", b'{"tpu_error_name": 42}'),
                ("/inject-fault", b'{"unknown": true}'),
                ("/v1/components/set-healthy?componentName=ghost", b""),
                ("/v1/components/set-healthy", b""),
            ]
            for path, body in hostile_posts:
                resp = await client.post(path, data=body)
                assert resp.status < 500, (path, resp.status, await resp.text())
            resp = await client.delete("/v1/components?componentName=nope")
            assert resp.status < 500
        finally:
            await client.close()

    try:
        asyncio.run(drive())
    finally:
        srv.stop()
