"""CI gate over the metric registry: every registered metric carries the
``tpud_`` prefix, non-empty help text, Prometheus unit conventions
(counters end ``_total``, durations in base seconds, histograms carry a
base unit), and no reserved label names
(gpud_tpu/tools/metrics_lint.py). New instrumentation that forgets any of
these fails here, not in production."""

from gpud_tpu.metrics.registry import DEFAULT_REGISTRY, Registry
from gpud_tpu.tools import metrics_lint


def test_lint_flags_bad_names_and_missing_help():
    r = Registry()
    r.gauge("unprefixed_metric", "has help")
    r.counter("tpud_ok_total", "")
    r.histogram("tpud_fine_seconds", "documented")
    problems = metrics_lint.lint_registry(r)
    assert sorted(problems) == [
        "tpud_ok_total: empty help text",
        "unprefixed_metric: missing 'tpud_' name prefix",
    ]


def test_lint_clean_registry_is_silent():
    r = Registry()
    r.gauge("tpud_a", "a")
    r.histogram("tpud_b_seconds", "b")
    assert metrics_lint.lint_registry(r) == []


def test_every_daemon_metric_passes_lint():
    """The real check: import every instrumentation site and lint the full
    default registry. A new metric without prefix/help fails THIS test."""
    metrics_lint.populate_default_registry()
    assert len(DEFAULT_REGISTRY.all_metrics()) >= 30  # the daemon is instrumented
    assert metrics_lint.lint_registry(DEFAULT_REGISTRY) == []


def test_lint_cli_exit_code():
    assert metrics_lint.main() == 0


def test_lint_counter_must_end_total():
    r = Registry()
    r.counter("tpud_things", "counted things")
    assert metrics_lint.lint_registry(r) == [
        "tpud_things: counter must end in '_total'"
    ]


def test_lint_histogram_must_carry_base_unit():
    r = Registry()
    r.histogram("tpud_request_latency", "no unit in the name")
    problems = metrics_lint.lint_registry(r)
    assert len(problems) == 1
    assert "base unit suffix" in problems[0]
    clean = Registry()
    clean.histogram("tpud_latency_seconds", "time")
    clean.histogram("tpud_payload_bytes", "size")
    assert metrics_lint.lint_registry(clean) == []


def test_lint_rejects_non_base_time_units():
    r = Registry()
    r.gauge("tpud_rtt_ms", "milliseconds are not a base unit")
    r.counter("tpud_wait_minutes_total", "neither are minutes")
    problems = sorted(metrics_lint.lint_registry(r))
    assert len(problems) == 2
    assert "'_ms'" in problems[0]
    assert "'_minutes'" in problems[1]
    # gauges that merely END in _total (cumulative-seconds mirrors) pass
    clean = Registry()
    clean.gauge("tpud_sqlite_select_seconds_total", "cumulative seconds")
    assert metrics_lint.lint_registry(clean) == []


def test_lint_rejects_reserved_label_names():
    r = Registry()
    g = r.gauge("tpud_bad_labels", "uses reserved labels")
    g.set(1.0, {"le": "0.5"})
    g.set(2.0, {"__internal": "x"})
    problems = sorted(metrics_lint.lint_registry(r))
    assert len(problems) == 2
    assert "'__internal'" in problems[0]
    assert "'le'" in problems[1]
    # a histogram's self-minted per-bucket 'le' must NOT trip the rule
    clean = Registry()
    h = clean.histogram("tpud_ok_seconds", "fine")
    h.observe(0.1, {"component": "c"})
    assert metrics_lint.lint_registry(clean) == []
