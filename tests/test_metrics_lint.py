"""CI gate over the metric registry: every registered metric carries the
``tpud_`` prefix and non-empty help text (gpud_tpu/tools/metrics_lint.py).
New instrumentation that forgets either fails here, not in production."""

from gpud_tpu.metrics.registry import DEFAULT_REGISTRY, Registry
from gpud_tpu.tools import metrics_lint


def test_lint_flags_bad_names_and_missing_help():
    r = Registry()
    r.gauge("unprefixed_metric", "has help")
    r.counter("tpud_ok_total", "")
    r.histogram("tpud_fine_seconds", "documented")
    problems = metrics_lint.lint_registry(r)
    assert sorted(problems) == [
        "tpud_ok_total: empty help text",
        "unprefixed_metric: missing 'tpud_' name prefix",
    ]


def test_lint_clean_registry_is_silent():
    r = Registry()
    r.gauge("tpud_a", "a")
    r.histogram("tpud_b_seconds", "b")
    assert metrics_lint.lint_registry(r) == []


def test_every_daemon_metric_passes_lint():
    """The real check: import every instrumentation site and lint the full
    default registry. A new metric without prefix/help fails THIS test."""
    metrics_lint.populate_default_registry()
    assert len(DEFAULT_REGISTRY.all_metrics()) >= 30  # the daemon is instrumented
    assert metrics_lint.lint_registry(DEFAULT_REGISTRY) == []


def test_lint_cli_exit_code():
    assert metrics_lint.main() == 0
