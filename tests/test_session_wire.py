"""Session wire codec: delta streams, batch frames, rev-3 payload framing.

The codec is the perf tentpole behind ``bench.py --wire`` (>=100k
records/sec, >=3x bytes-on-the-wire vs per-record JSON): positional
array records (keyframe length 6, delta length 7), per-stream dict
diffs with a keyframe every K records, and a 1-byte codec prefix
(j/z/m/M) on every rev-3 tunnel payload. Correctness here is what makes
the speed safe to ship: exact roundtrips, deterministic resync after
encoder resets, and loud failures on desync so the ack watermark never
passes an undecodable record.
"""

import random
import time

import pytest

from gpud_tpu.session import wire
from gpud_tpu.session.wire import (
    DeltaDecodeError,
    DeltaDecoder,
    DeltaEncoder,
    WireCodecError,
)


def _roundtrip(enc, dec, records):
    """Encode then decode a (seq, ts, kind, key, payload) list; assert
    the decoder reproduces every payload exactly."""
    for seq, ts, kind, key, payload in records:
        arr = enc.encode_record(seq, ts, kind, key, payload)
        got = dec.decode_record(arr)
        assert got == (seq, ts, kind, key, payload), f"seq {seq} diverged"


# -- delta codec -------------------------------------------------------------

def test_delta_roundtrip_identity_over_random_mutations():
    rng = random.Random(0xC0FFEE)
    components = [f"tpu-chip-{i}" for i in range(4)]
    states = ["healthy", "degraded", "unhealthy"]
    payloads = {c: {"component": c, "state": "healthy", "value": 0.0,
                    "labels": {"pod": "p0"}} for c in components}
    records = []
    for seq in range(1, 401):
        c = rng.choice(components)
        p = dict(payloads[c])  # encoder keeps refs: never mutate in place
        mutation = rng.random()
        if mutation < 0.5:
            p["value"] = rng.randrange(1000) / 10.0
        elif mutation < 0.7:
            p["state"] = rng.choice(states)
        elif mutation < 0.85:
            p[f"extra_{rng.randrange(3)}"] = rng.randrange(10)  # key added
        else:
            for k in [k for k in p if k.startswith("extra_")]:
                p.pop(k)  # keys removed -> exercises the del list
        payloads[c] = p
        records.append((seq, float(seq), "metric", f"k{seq}", p))
    _roundtrip(DeltaEncoder(keyframe_interval=16), DeltaDecoder(), records)


def test_keyframe_cadence_every_k_records_per_stream():
    enc = DeltaEncoder(keyframe_interval=4)
    lengths = [
        len(enc.encode_record(i + 1, 0.0, "event", f"k{i}",
                              {"component": "a", "i": i}))
        for i in range(9)
    ]
    # keyframe (6), then K-1 deltas (7), then the cadence repeats
    assert lengths == [6, 7, 7, 7, 6, 7, 7, 7, 6]
    # a second stream keeps its own cadence counter
    other = enc.encode_record(10, 0.0, "event", "kx", {"component": "b"})
    assert len(other) == 6


def test_encoder_reset_restarts_streams_and_decoder_resyncs():
    enc, dec = DeltaEncoder(keyframe_interval=64), DeltaDecoder()
    p1 = {"component": "a", "i": 1}
    dec.decode_record(enc.encode_record(1, 0.0, "event", "k1", p1))
    # reconnect: a fresh decoder would desync on a delta, so the encoder
    # reset forces the next record out as a keyframe
    enc.reset()
    dec2 = DeltaDecoder()
    p2 = {"component": "a", "i": 2}
    arr = enc.encode_record(2, 0.0, "event", "k2", p2)
    assert len(arr) == 6
    assert dec2.decode_record(arr)[4] == p2


def test_delta_without_base_and_malformed_records_raise():
    enc = DeltaEncoder()
    enc.encode_record(1, 0.0, "event", "k1", {"component": "a", "i": 0})
    delta = enc.encode_record(2, 0.0, "event", "k2", {"component": "a", "i": 1})
    assert len(delta) == 7
    with pytest.raises(DeltaDecodeError):
        DeltaDecoder().decode_record(delta)  # keyframe never arrived
    with pytest.raises(DeltaDecodeError):
        DeltaDecoder().decode_record([1, 0.0, "event"])  # truncated
    with pytest.raises(DeltaDecodeError):
        DeltaDecoder().decode_record(delta + ["junk"])  # wrong length
    with pytest.raises(DeltaDecodeError):
        DeltaDecoder().decode_record({"not": "an array"})
    with pytest.raises(DeltaDecodeError):
        DeltaDecoder().decode_record(None)


def test_non_dict_payloads_skip_delta_and_clear_the_stream():
    enc, dec = DeltaEncoder(), DeltaDecoder()
    _roundtrip(enc, dec, [
        (1, 0.0, "event", "k1", {"i": 0}),       # keyframe on "event:"
        (2, 0.0, "event", "k2", "plain-string"),  # non-dict drops the base
        (3, 0.0, "event", "k3", {"i": 1}),       # must re-keyframe
    ])


def test_decoder_does_not_mutate_prior_payloads():
    enc, dec = DeltaEncoder(), DeltaDecoder()
    first = dec.decode_record(
        enc.encode_record(1, 0.0, "event", "k1", {"component": "a", "i": 0})
    )[4]
    second = dec.decode_record(
        enc.encode_record(2, 0.0, "event", "k2", {"component": "a", "i": 1})
    )[4]
    assert first["i"] == 0 and second["i"] == 1


# -- batch envelope ----------------------------------------------------------

def test_build_and_parse_batch_envelope():
    enc = DeltaEncoder()
    recs = [
        enc.encode_record(i, float(i), "event", f"k{i}", {"i": i})
        for i in (3, 4, 5)
    ]
    data = wire.build_batch(recs)
    batch = wire.parse_batch(data)
    assert batch is not None
    assert (batch["v"], batch["first_seq"], batch["last_seq"],
            batch["count"]) == (wire.BATCH_VERSION, 3, 5, 3)
    assert wire.parse_batch({"outbox_seq": 1}) is None
    assert wire.parse_batch("nope") is None
    assert wire.build_batch([])[wire.BATCH_KEY]["count"] == 0


# -- rev-3 payload framing ---------------------------------------------------

def test_encode_decode_payload_roundtrip_small_and_large():
    small = {"method": "outboxAck", "seq": 7}
    buf = wire.encode_payload(small)
    assert buf[:1] in (wire.PREFIX_JSON, wire.PREFIX_MSGPACK)
    assert wire.decode_payload(buf) == small

    # repetitive batch-shaped payload above the floor: zlib framing wins
    big = {"records": [
        {"component": f"tpu-chip-{i % 8}", "state": "healthy",
         "name": "hbm_utilization", "value": i} for i in range(200)
    ]}
    zbuf = wire.encode_payload(big, min_bytes=64)
    assert zbuf[:1] in (wire.PREFIX_ZLIB, wire.PREFIX_ZLIB_MSGPACK)
    assert wire.decode_payload(zbuf) == big


def test_encode_payload_skips_zlib_below_floor_or_when_it_grows():
    small = {"a": 1}
    assert wire.encode_payload(small, min_bytes=10_000)[:1] in (
        wire.PREFIX_JSON, wire.PREFIX_MSGPACK
    )
    # high-entropy bytes don't compress: stays on the plain framing even
    # above the floor (msgpack's bin type carries raw bytes losslessly)
    if wire._msgpack is not None:
        rng = random.Random(7)
        noise = {"blob": bytes(rng.randrange(256) for _ in range(2048))}
        buf = wire.encode_payload(noise, min_bytes=0)
        assert buf[:1] == wire.PREFIX_MSGPACK
        assert wire.decode_payload(buf) == noise


def test_decode_payload_rejects_garbage():
    with pytest.raises(WireCodecError):
        wire.decode_payload(b"")
    with pytest.raises(WireCodecError):
        wire.decode_payload(b"?whatever")
    with pytest.raises(WireCodecError):
        wire.decode_payload(wire.PREFIX_ZLIB + b"not-zlib")
    with pytest.raises(WireCodecError):
        wire.decode_payload(wire.PREFIX_JSON + b"{broken")


def test_codec_stats_track_egress_ratio():
    before = wire.codec_stats()
    wire.encode_payload({"records": ["x" * 50] * 100}, min_bytes=0)
    after = wire.codec_stats()
    assert after["wire_egress_bytes"] > before["wire_egress_bytes"]
    assert after["raw_egress_bytes"] > before["raw_egress_bytes"]
    assert after["compression_ratio"] >= 1.0


# -- journal column packing --------------------------------------------------

def test_pack_unpack_obj_and_legacy_json_rows():
    obj = {"component": "tpu0", "value": 1.5, "labels": {"pod": "p"}}
    assert wire.unpack_obj(wire.pack_obj(obj)) == obj
    # rows journaled before the binary column encoding are JSON text
    assert wire.unpack_obj('{"legacy": true}') == {"legacy": True}
    with pytest.raises(ValueError):
        wire.unpack_obj("not json")


def test_unpack_many_bulk_path_and_mixed_legacy_fallback():
    objs = [{"i": i, "component": f"c{i % 3}"} for i in range(50)]
    raws = [wire.pack_obj(o) for o in objs]
    assert wire.unpack_many(raws) == objs
    # a legacy JSON text row in the middle forces the row-by-row path
    mixed = raws[:10] + ['{"legacy": 1}'] + raws[10:]
    assert wire.unpack_many(mixed) == objs[:10] + [{"legacy": 1}] + objs[10:]
    assert wire.unpack_many([]) == []


# -- cross-revision handshake ------------------------------------------------

def test_rev2_agent_against_rev3_manager_negotiates_down(monkeypatch):
    """A fleet mid-rollout runs old agents against a new manager: the
    hello clamps to rev 2 and payloads stay bare JSON (no codec prefix
    the old peer wouldn't understand)."""
    pytest.importorskip("grpc")
    from gpud_tpu.manager.control_plane import ControlPlane
    from gpud_tpu.session.session import Session
    from gpud_tpu.session.v2 import client as v2_client

    monkeypatch.setattr(v2_client, "MAX_REVISION", 2)
    cp = ControlPlane()
    cp.start()
    try:
        monkeypatch.setenv(
            "TPUD_SESSION_V2_TARGET", f"127.0.0.1:{cp.grpc_port}"
        )
        s = Session(
            endpoint=cp.endpoint,
            machine_id="old-agent",
            token="t",
            machine_proof="p",
            dispatch_fn=lambda req: {"echo": req.get("method")},
            protocol="auto",
        )
        s.start()
        try:
            deadline = time.time() + 15
            while time.time() < deadline and "old-agent" not in cp.agents:
                time.sleep(0.05)
            h = cp.agent("old-agent")
            assert h.transport == "v2-rev2"
            assert h.request({"method": "states"}, timeout=10) == {
                "echo": "states"
            }
        finally:
            s.stop()
    finally:
        cp.stop()
