import os

from gpud_tpu import config as cfg
from gpud_tpu.metadata import KEY_MACHINE_ID, KEY_TOKEN, Metadata
from gpud_tpu.sqlite import DB, open_rw_ro, stats


def test_metadata_set_get_delete(tmp_db):
    md = Metadata(tmp_db)
    assert md.machine_id() is None
    md.set(KEY_MACHINE_ID, "m-123")
    md.set(KEY_TOKEN, "t-1")
    md.set(KEY_TOKEN, "t-2")  # upsert
    assert md.machine_id() == "m-123"
    assert md.get(KEY_TOKEN) == "t-2"
    assert md.all() == {KEY_MACHINE_ID: "m-123", KEY_TOKEN: "t-2"}
    md.delete(KEY_TOKEN)
    assert md.get(KEY_TOKEN) == ""


def test_sqlite_rw_ro_pair(tmp_path):
    rw, ro = open_rw_ro(str(tmp_path / "s.db"))
    rw.execute("CREATE TABLE t (x INTEGER)")
    rw.execute("INSERT INTO t VALUES (7)")
    assert ro.query_one("SELECT x FROM t")[0] == 7
    try:
        ro.execute("INSERT INTO t VALUES (8)")
        raised = False
    except Exception:
        raised = True
    assert raised  # RO handle refuses writes
    rw.close()
    ro.close()


def test_sqlite_in_memory_shared():
    db = DB(":memory:")
    db.execute("CREATE TABLE t (x INTEGER)")
    db.execute("INSERT INTO t VALUES (1)")
    import threading

    seen = []
    t = threading.Thread(target=lambda: seen.append(db.query_one("SELECT x FROM t")))
    t.start()
    t.join()
    assert seen[0][0] == 1


def test_sqlite_compact_and_size(tmp_db):
    tmp_db.execute("CREATE TABLE t (x TEXT)")
    tmp_db.executemany("INSERT INTO t VALUES (?)", [("y" * 100,)] * 100)
    assert tmp_db.size_bytes() > 0
    assert tmp_db.compact() >= 0.0
    assert stats()["vacuum_total"] >= 1


def test_config_defaults_and_paths(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUD_DATA_DIR", str(tmp_path))
    c = cfg.default_config()
    assert c.port == 15132
    assert c.metrics_retention_seconds == 3 * 3600
    assert c.events_retention_seconds == 14 * 86400
    assert c.validate() is None
    assert c.state_file() == os.path.join(str(tmp_path), "tpud.state")
    assert c.packages_dir().endswith("packages")
    c2 = cfg.default_config(db_in_memory=True)
    assert c2.state_file() == ":memory:"
    bad = cfg.default_config(port=-1)
    assert bad.validate() is not None


def test_config_unknown_override_rejected():
    try:
        cfg.default_config(bogus=1)
        raised = False
    except AttributeError:
        raised = True
    assert raised


def test_prometheus_exposition_escaping():
    """Label values and HELP text with quotes/backslashes/newlines must
    escape per the exposition format — one bad value must not corrupt the
    whole /metrics page."""
    from gpud_tpu.metrics.registry import Registry

    r = Registry()
    g = r.gauge("esc_metric", "help with\nnewline and \\slash")
    g.set(1.0, {"link": 'weird"name\\with\n stuff'})
    out = r.render_prometheus()
    assert '# HELP esc_metric help with\\nnewline and \\\\slash' in out
    assert 'link="weird\\"name\\\\with\\n stuff"' in out
    # every physical line is a comment or a sample — no stray fragments
    for ln in out.strip().splitlines():
        assert ln.startswith("#") or " " in ln, ln
