"""Infrastructure-module edges: TLS cert generation, netutil measurement
math, ASN parsing, log setup + audit logger behavior (reference: pkg/log,
pkg/netutil, pkg/asn unit suites)."""

import json
import logging
import logging.handlers
import socket
import ssl
import threading

import pytest

from gpud_tpu import asn as asnmod
from gpud_tpu import netutil
from gpud_tpu.log import AuditLogger
from gpud_tpu.server import tls as tlsmod


# -- TLS --------------------------------------------------------------------

def test_self_signed_cert_usable_for_tls():
    cert_path, key_path = tlsmod.generate_self_signed("unit.tpud.local")
    ctx = tlsmod.server_ssl_context(cert_path, key_path)
    assert isinstance(ctx, ssl.SSLContext)
    # a real TLS handshake against a one-shot server proves the pair works
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def accept():
        conn, _ = srv.accept()
        try:
            ctx.wrap_socket(conn, server_side=True)
        except ssl.SSLError:
            pass  # client aborts after handshake — fine

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client.check_hostname = False
    client.verify_mode = ssl.CERT_NONE
    with socket.create_connection(("127.0.0.1", port), timeout=5) as raw:
        with client.wrap_socket(raw, server_hostname="unit.tpud.local") as s:
            assert s.version() is not None  # handshake completed
    srv.close()


def test_self_signed_certs_are_unique():
    c1, k1 = tlsmod.generate_self_signed()
    c2, k2 = tlsmod.generate_self_signed()
    assert open(c1).read() != open(c2).read()  # fresh keypair per boot
    assert open(k1).read() != open(k2).read()
    import os as _os

    assert _os.stat(k1).st_mode & 0o777 == 0o600  # key is private


def test_cert_contains_common_name():
    from cryptography import x509

    cert_path, _ = tlsmod.generate_self_signed("cn.example")
    cert = x509.load_pem_x509_certificate(open(cert_path, "rb").read())
    assert "cn.example" in cert.subject.rfc4514_string()
    # SAN covers localhost for the local API client
    san = cert.extensions.get_extension_for_class(x509.SubjectAlternativeName)
    assert "localhost" in san.value.get_values_for_type(x509.DNSName)


# -- netutil ----------------------------------------------------------------

def test_private_ip_is_an_address():
    ip = netutil.private_ip()
    assert ip == "" or len(ip.split(".")) == 4 or ":" in ip


def test_port_probe_against_real_listener():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    try:
        assert netutil.is_port_open("127.0.0.1", port, timeout=2)
        rtt = netutil.tcp_rtt_ms("127.0.0.1", port, timeout=2)
        assert rtt is not None and 0 <= rtt < 2000
    finally:
        srv.close()
    assert not netutil.is_port_open("127.0.0.1", port, timeout=0.5)
    assert netutil.tcp_rtt_ms("127.0.0.1", port, timeout=0.5) is None


def test_measure_edges_mixed_reachability():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    try:
        res = netutil.measure_edges(
            [("local", "127.0.0.1", port), ("dead", "127.0.0.1", 1)],
            timeout=0.5,
        )
    finally:
        srv.close()
    assert res["local"] is not None
    assert res["dead"] is None


# -- ASN --------------------------------------------------------------------

def test_asn_lookup_shapes():
    payload = {
        "network": {
            "autonomous_system": {
                "asn": 396982, "organization": "GOOGLE-CLOUD-PLATFORM",
            }
        }
    }
    info = asnmod.lookup("8.8.8.8", fetch_fn=lambda url: payload)
    assert info is not None
    assert info.asn == 396982
    assert "google" in info.provider.lower() or info.org


def test_asn_lookup_handles_partial_and_none():
    assert asnmod.lookup("1.2.3.4", fetch_fn=lambda url: None) is None
    info = asnmod.lookup("1.2.3.4", fetch_fn=lambda url: {"network": {}})
    assert info is None or info.asn == 0


# -- audit logger ------------------------------------------------------------

def test_audit_logger_writes_ndjson(tmp_path):
    path = tmp_path / "audit.log"
    a = AuditLogger(str(path))
    a.log("reboot", requested_by="session", delay=5)
    a.log("set_healthy", component="cpu")
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["action"] == "reboot"
    assert rec["requested_by"] == "session"
    assert "ts" in rec or "time" in rec


def test_audit_logger_nop_without_path():
    a = AuditLogger("")
    a.log("anything", x=1)  # must not raise


def test_audit_logger_concurrent_writes_line_atomic(tmp_path):
    path = tmp_path / "audit.log"
    a = AuditLogger(str(path))

    def work(tid):
        for i in range(100):
            a.log("stress", tid=tid, i=i)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 400
    for ln in lines:
        json.loads(ln)  # every line is a complete record — no interleaving


# -- log setup ---------------------------------------------------------------

def _flush_file_handlers():
    # only the file handlers matter here; a stream handler may point at a
    # pytest-captured stream an earlier test already closed
    for h in logging.getLogger("tpud").handlers:
        if isinstance(h, logging.FileHandler):
            h.flush()


def test_log_setup_configure_once_semantics(tmp_path, monkeypatch):
    """setup() attaches handlers exactly once; later calls only adjust
    the level (the daemon calls it at boot and again on updateConfig).
    Run against a pristine logger state, restored afterwards."""
    import gpud_tpu.log as logmod

    root = logging.getLogger("tpud")
    saved_handlers = root.handlers[:]
    saved_level = root.level
    saved_configured = logmod._configured
    try:
        root.handlers = []
        monkeypatch.setattr(logmod, "_configured", False)
        logfile = tmp_path / "tpud.log"
        logmod.setup(level="debug", log_file=str(logfile))
        lg = logmod.get_logger("tpud.unit-test")
        lg.debug("debug-visible")
        _flush_file_handlers()
        assert "debug-visible" in logfile.read_text()
        rotating = [
            h for h in root.handlers
            if isinstance(h, logging.handlers.RotatingFileHandler)
        ]
        assert len(rotating) == 1  # lumberjack-style rotation attached

        # second setup: level changes, NO second handler appears
        logmod.setup(level="info", log_file=str(tmp_path / "other.log"))
        assert len(root.handlers) == 1
        lg.debug("debug-hidden")
        _flush_file_handlers()
        assert "debug-hidden" not in logfile.read_text()
        assert not (tmp_path / "other.log").exists()
    finally:
        root.handlers = saved_handlers
        root.setLevel(saved_level)
        logmod._configured = saved_configured
