"""Mixed-protocol fleet against one manager.

A real fleet upgrades gradually: v1-only agents (legacy chunked-stream
transport) and v2-rev3 agents (typed gRPC) coexist on the SAME control
plane. The manager must serve operator requests to both, keep their
handles separate, and deliver drain semantics appropriately per
transport (v2 gets a DrainNotice; v1 streams just close). Reference:
session v1/v2 coexistence (pkg/session vs pkg/session/v2 — the
reference agent picks one, the manager must accept both)."""

import time

import pytest

from gpud_tpu.manager.control_plane import ControlPlane
from gpud_tpu.session.session import Session


@pytest.fixture()
def cp(monkeypatch):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    cp = ControlPlane()
    cp.start()
    assert cp.grpc_port > 0
    monkeypatch.setenv("TPUD_SESSION_V2_TARGET", f"127.0.0.1:{cp.grpc_port}")
    yield cp
    cp.stop()


def _agent(cp, machine_id, protocol):
    s = Session(
        endpoint=cp.endpoint,
        machine_id=machine_id,
        token="t",
        machine_proof="p",
        dispatch_fn=lambda req: {
            "from": machine_id,
            "method": req.get("method"),
        },
        protocol=protocol,
    )
    s.start()
    return s


def _wait_enrolled(cp, *machine_ids, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(m in cp.agents for m in machine_ids):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"not all of {machine_ids} enrolled; have {sorted(cp.agents)}"
    )


def test_v1_and_v2_agents_coexist_and_answer(cp):
    v1 = _agent(cp, "legacy-box", "v1")
    v2 = _agent(cp, "typed-box", "auto")
    try:
        _wait_enrolled(cp, "legacy-box", "typed-box")
        h1, h2 = cp.agent("legacy-box"), cp.agent("typed-box")
        assert h1.transport == "v1"
        assert h2.transport == "v2-rev3"
        # requests route to the right agent over the right transport
        r1 = h1.request({"method": "states"}, timeout=10)
        r2 = h2.request({"method": "states"}, timeout=10)
        assert r1["from"] == "legacy-box"
        assert r2["from"] == "typed-box"
        # machine list reports both with their transports
        listed = {m["machine_id"]: m for m in cp.machines()}
        assert listed["legacy-box"]["transport"] == "v1"
        assert listed["typed-box"]["transport"] == "v2-rev3"
    finally:
        v1.stop()
        v2.stop()


def test_interleaved_requests_do_not_cross_wires(cp):
    """Concurrent requests to both transports must come back with the
    right per-agent payloads — no response cross-delivery between the v1
    pump and the v2 typed stream."""
    import concurrent.futures

    v1 = _agent(cp, "ix-v1", "v1")
    v2 = _agent(cp, "ix-v2", "auto")
    try:
        _wait_enrolled(cp, "ix-v1", "ix-v2")
        h1, h2 = cp.agent("ix-v1"), cp.agent("ix-v2")
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
            futs = []
            for i in range(12):
                h = h1 if i % 2 == 0 else h2
                futs.append(ex.submit(h.request, {"method": f"m{i}"}, 10))
            for i, f in enumerate(futs):
                want_from = "ix-v1" if i % 2 == 0 else "ix-v2"
                got = f.result(timeout=15)
                assert got == {"from": want_from, "method": f"m{i}"}, (i, got)
    finally:
        v1.stop()
        v2.stop()


def test_drain_disconnects_both_transports(cp):
    """Drain must push every agent off: v2 via DrainNotice, v1 by the
    stream closing — and both reconnect afterwards."""
    v1 = _agent(cp, "dr-v1", "v1")
    v2 = _agent(cp, "dr-v2", "auto")
    try:
        _wait_enrolled(cp, "dr-v1", "dr-v2")
        r1 = v1.reconnect_count
        r2 = v2.reconnect_count
        cp.drain("mixed-fleet maintenance")
        deadline = time.time() + 20
        while time.time() < deadline and (
            v1.reconnect_count == r1 or v2.reconnect_count == r2
        ):
            time.sleep(0.05)
        assert v1.reconnect_count > r1, "v1 agent never saw the drain"
        assert v2.reconnect_count > r2, "v2 agent never saw the drain"
        # both re-enroll (the manager keeps serving after a drain)
        _wait_enrolled(cp, "dr-v1", "dr-v2")
        assert cp.agent("dr-v1").request({"method": "post"}, 10)["from"] == "dr-v1"
        assert cp.agent("dr-v2").request({"method": "post"}, 10)["from"] == "dr-v2"
    finally:
        v1.stop()
        v2.stop()


def test_same_machine_upgrading_transport_replaces_handle(cp):
    """An agent that upgrades from v1 to v2 (daemon update) re-enrolls
    under the same machine_id; the newest handle wins and requests flow
    over the NEW transport."""
    v1 = _agent(cp, "upgrade-box", "v1")
    try:
        _wait_enrolled(cp, "upgrade-box")
        assert cp.agent("upgrade-box").transport == "v1"
    finally:
        v1.stop()
    v2 = _agent(cp, "upgrade-box", "auto")
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            h = cp.agents.get("upgrade-box")
            if h is not None and h.transport == "v2-rev3":
                break
            time.sleep(0.05)
        h = cp.agent("upgrade-box")
        assert h.transport == "v2-rev3"
        assert h.request({"method": "states"}, 10)["from"] == "upgrade-box"
    finally:
        v2.stop()
