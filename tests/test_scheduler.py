"""Unified check scheduler: deadline heap + bounded pool semantics.

Covers the behaviors the per-thread pollers guaranteed (poke priority,
adaptive interval re-read, no-overlap) plus the new ones only the
scheduler provides (pool saturation accounting, hung-check watchdog with
a sacrificial thread, deterministic jitter, startup readiness), and the
covering indexes the since-scan queries rely on (EXPLAIN QUERY PLAN).
"""

import threading
import time

import pytest

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import (
    CheckResult,
    PollingComponent,
    TpudInstance,
)
from gpud_tpu.scheduler import Scheduler
from gpud_tpu.scheduler.core import _c_saturation, _c_watchdog


def _wait_for(pred, timeout=5.0, step=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


@pytest.fixture
def sched():
    s = Scheduler(workers=2, hang_timeout=60.0)
    yield s
    s.close()


# -- basic dispatch ---------------------------------------------------------
def test_first_runs_happen_on_pool_and_readiness_records(sched):
    ran = threading.Event()
    sched.add_job("a", ran.set, interval=3600.0)
    sched.add_job("deferred", lambda: None, interval=3600.0,
                  initial_delay=3600.0)
    sched.start()
    assert ran.wait(5.0)
    # the deferred job is NOT part of the readiness set: readiness means
    # "every immediate first check completed", and it completes fast
    ready = sched.wait_first_runs(timeout=5.0)
    assert ready is not None and ready < 5.0
    assert sched.startup_ready_seconds == ready


def test_submit_one_shot_runs_once_and_unregisters(sched):
    sched.start()
    hits = []
    sched.submit("oneshot", lambda: hits.append(1))
    assert _wait_for(lambda: hits == [1])
    assert _wait_for(lambda: "oneshot" not in sched.job_names())
    time.sleep(0.1)
    assert hits == [1]


def test_cancel_stops_future_runs(sched):
    runs = []
    job = sched.add_job("c", lambda: runs.append(1), interval=0.02)
    sched.start()
    assert _wait_for(lambda: len(runs) >= 2)
    job.cancel()
    assert _wait_for(lambda: "c" not in sched.job_names())
    n = len(runs)
    time.sleep(0.15)
    assert len(runs) == n


def test_poke_jumps_job_to_front(sched):
    runs = []
    sched.add_job("poked", lambda: runs.append(time.monotonic()),
                  interval=3600.0)
    sched.start()
    assert _wait_for(lambda: len(runs) == 1)
    # the next natural deadline is an hour away; poke must beat it
    sched.poke("poked")
    assert _wait_for(lambda: len(runs) == 2)
    assert runs[1] - runs[0] < 5.0


def test_poke_during_run_queues_immediate_rerun(sched):
    gate = threading.Event()
    runs = []

    def fn():
        runs.append(1)
        if len(runs) == 1:
            gate.wait(5.0)

    job = sched.add_job("busy", fn, interval=3600.0)
    sched.start()
    assert _wait_for(lambda: len(runs) == 1)
    job.poke()  # lands while the first run is still in flight
    gate.set()
    assert _wait_for(lambda: len(runs) == 2)


def test_adaptive_interval_reread_after_every_run(sched):
    interval = [3600.0]
    runs = []
    sched.add_job("adaptive", lambda: runs.append(1),
                  interval_fn=lambda: interval[0], jitter=False)
    sched.start()
    assert _wait_for(lambda: len(runs) == 1)
    # fast-poll window opens (the ICI pattern): the NEXT deadline must
    # use the new value — re-read after the poked run, no restart needed
    interval[0] = 0.01
    sched.poke("adaptive")
    assert _wait_for(lambda: len(runs) >= 4)


def test_failing_job_is_rescheduled(sched):
    runs = []

    def fn():
        runs.append(1)
        raise RuntimeError("boom")

    sched.add_job("crashy", fn, interval=0.02)
    sched.start()
    assert _wait_for(lambda: len(runs) >= 3)
    assert sched.get_job("crashy").failures >= 3


# -- pool saturation --------------------------------------------------------
def test_pool_saturation_counts_and_all_jobs_complete():
    s = Scheduler(workers=1, hang_timeout=60.0)
    try:
        before = _c_saturation.get()
        gate = threading.Event()
        done = []
        for i in range(3):
            s.add_job(f"slow-{i}",
                      lambda i=i: (gate.wait(5.0), done.append(i)),
                      interval=3600.0)
        s.start()
        # one worker, three due jobs: at least two dispatches saw a full
        # pool and had to queue
        assert _wait_for(lambda: _c_saturation.get() >= before + 2)
        gate.set()
        assert s.wait_first_runs(timeout=5.0) is not None
        assert sorted(done) == [0, 1, 2]
    finally:
        s.close()


# -- watchdog ---------------------------------------------------------------
def test_watchdog_sacrifices_worker_and_keeps_cadence():
    s = Scheduler(workers=1, hang_timeout=0.15)
    try:
        release = threading.Event()
        hangs = []
        fast_runs = []
        s.add_job("wedged", lambda: release.wait(10.0), interval=3600.0,
                  on_hang=lambda e: hangs.append(e))
        s.add_job("fast", lambda: fast_runs.append(1), interval=0.03)
        before = _c_watchdog.get(labels={"job": "wedged"})
        s.start()
        # the wedged job occupies the single worker; the watchdog must
        # fire, spawn a replacement, and the fast job must keep cadence
        assert _wait_for(lambda: hangs)
        assert hangs[0] >= 0.15
        assert _c_watchdog.get(labels={"job": "wedged"}) == before + 1
        n0 = len(fast_runs)
        assert _wait_for(lambda: len(fast_runs) >= n0 + 3)
        assert s.stats()["workers"] == 2  # sacrificial + replacement
        # release: the sacrificial thread finishes its job and retires,
        # the pool shrinks back to its configured size
        release.set()
        assert _wait_for(lambda: s.stats()["workers"] == 1)
        # the formerly-hung job reschedules normally afterwards
        assert s.get_job("wedged").runs >= 1
    finally:
        s.close()


def test_hung_component_marked_degraded_stale():
    class WedgedComp(PollingComponent):
        NAME = "wedged-comp"

        def __init__(self, inst):
            super().__init__(inst)
            self.release = threading.Event()

        def check_once(self):
            self.release.wait(10.0)
            return CheckResult(self.NAME, reason="finally fine")

    s = Scheduler(workers=2, hang_timeout=0.15)
    inst = TpudInstance(scheduler=s)
    comp = WedgedComp(inst)
    try:
        comp.start()
        assert comp._job is not None  # scheduler path, no thread
        assert comp._thread is None
        s.start()
        assert _wait_for(
            lambda: comp.last_health_states()[0].health
            == HealthStateType.DEGRADED
        )
        state = comp.last_health_states()[0]
        assert "check stale" in state.reason
        # the real check eventually returning overwrites the stale marker
        comp.release.set()
        assert _wait_for(
            lambda: comp.last_health_states()[0].health
            == HealthStateType.HEALTHY
        )
    finally:
        comp.close()
        s.close()


# -- jitter -----------------------------------------------------------------
def test_jitter_is_deterministic_and_bounded():
    s1 = Scheduler(jitter_fraction=0.05)
    s2 = Scheduler(jitter_fraction=0.05)
    from gpud_tpu.scheduler.core import Job

    for name in ("component:cpu", "component:disk", "metrics-syncer"):
        j = Job(name, lambda: None, lambda: 60.0)
        v1 = s1._jittered(j, 60.0)
        v2 = s2._jittered(j, 60.0)
        assert v1 == v2  # stable across instances (and restarts)
        assert 57.0 <= v1 <= 63.0  # within ±5%
    # distinct names spread out (the whole point of jitter)
    vals = {
        s1._jittered(Job(n, lambda: None, lambda: 60.0), 60.0)
        for n in ("component:cpu", "component:disk", "component:memory",
                  "component:os", "component:pci")
    }
    assert len(vals) > 1
    # jitter=False pins the exact cadence
    j = Job("exact", lambda: None, lambda: 60.0, jitter_fraction=0.0)
    assert s1._jittered(j, 60.0) == 60.0


# -- covering indexes (satellite: since-scan query plans) -------------------
def test_eventstore_since_scan_uses_timestamp_index():
    from gpud_tpu import eventstore
    from gpud_tpu.sqlite import DB

    db = DB(":memory:")
    try:
        es = eventstore.EventStore(db)
        es.bucket("cpu").insert(
            eventstore.Event(component="cpu", time=1.0, name="ev",
                             type="Warning", message="m")
        )
        plan = " ".join(
            str(r[-1]) for r in db.query(
                "EXPLAIN QUERY PLAN "
                f"SELECT component, timestamp FROM {eventstore.TABLE} "
                "WHERE timestamp>=? ORDER BY timestamp DESC",
                (0.0,),
            )
        )
        assert f"idx_{eventstore.TABLE}_ts" in plan
        assert "SCAN" not in plan.replace(
            f"USING INDEX idx_{eventstore.TABLE}_ts", ""
        ) or "USING INDEX" in plan
        es.close()
    finally:
        db.close()


def test_health_history_since_scan_uses_timestamp_index():
    from gpud_tpu import health_history
    from gpud_tpu.sqlite import DB

    db = DB(":memory:")
    try:
        ledger = health_history.HealthLedger(db)
        plan = " ".join(
            str(r[-1]) for r in db.query(
                "EXPLAIN QUERY PLAN "
                f"SELECT component, timestamp FROM {health_history.TABLE} "
                "WHERE timestamp>=? ORDER BY timestamp DESC",
                (0.0,),
            )
        )
        assert f"idx_{health_history.TABLE}_ts" in plan
        ledger.close()
    finally:
        db.close()


# -- lifecycle --------------------------------------------------------------
def test_close_without_start_is_safe():
    s = Scheduler()
    s.add_job("never", lambda: None, interval=1.0)
    s.close()
    assert s.submit("late", lambda: None) is None  # refused after close


def test_stats_shape(sched):
    sched.add_job("s", lambda: None, interval=3600.0)
    sched.start()
    sched.wait_first_runs(timeout=5.0)
    st = sched.stats()
    assert st["jobs"] == 1
    assert st["workers"] == 2
    assert st["workers_busy"] == 0
    assert st["dispatch_lag_p95_seconds"] >= 0.0
    assert st["startup_ready_seconds"] is not None
