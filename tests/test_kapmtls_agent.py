"""kapmtls lifecycle against a scripted fake agent (round-2 verdict,
item #3: "kapmtls never runs against an agent process").

The fake agent is what a real node-local mTLS agent is to the manager: a
concurrent consumer that continuously loads ``<root>/current``'s
credentials into an ``ssl.SSLContext`` (a real TLS keypair consumer, not
a file-existence check). The lifecycle — install → activate → rotate →
re-push-active → rollback — runs against it, and the agent must never
observe missing, partial, or mismatched credentials.

Reference: pkg/kapmtls/manager.go:29-50 (atomic release dirs + current
symlink + readiness + rollback).
"""

import os
import ssl
import threading
import time

import pytest

from gpud_tpu.kapmtls import CertManager

cryptography = pytest.importorskip("cryptography")

from cryptography import x509
from cryptography.hazmat.primitives import serialization
from cryptography.x509.oid import NameOID


from tests.helpers import keypair as _keypair  # shared with the fallback suite


class FakeAgent:
    """Continuously consumes <root>/current per the documented consumer
    contract (kapmtls.py module docstring): resolve ``current`` once,
    hold the release DIRECTORY open, read both files through that handle
    — then prove the pair actually matches (cert pubkey == key pubkey,
    the check ssl.load_cert_chain enforces). Any load error or torn pair
    is a rotation-atomicity failure."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.errors: list = []
        self.seen_cns: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        # the documented contract allows a retry-once on transient ENOENT
        # ONLY on filesystems without RENAME_EXCHANGE (the fallback
        # dance); on exchange-capable hosts an ENOENT is a real GC/unlink
        # bug the tests must catch, so no retry there
        self.retry_enoent = not self._exchange_capable(root)

    @staticmethod
    def _exchange_capable(root: str) -> bool:
        from gpud_tpu.kapmtls import _exchange_dirs

        a = os.path.join(root, ".probe-a")
        b = os.path.join(root, ".probe-b")
        os.makedirs(a, exist_ok=True)
        os.makedirs(b, exist_ok=True)
        try:
            return _exchange_dirs(a, b)
        finally:
            os.rmdir(a)
            os.rmdir(b)

    def _load_once(self) -> str:
        """One credential load through a held dirfd; returns the CN."""
        resolved = os.path.realpath(os.path.join(self.root, "current"))
        dfd = os.open(resolved, os.O_RDONLY | os.O_DIRECTORY)
        try:
            def read(name):
                fd = os.open(name, os.O_RDONLY, dir_fd=dfd)
                try:
                    return os.read(fd, 1 << 20)
                finally:
                    os.close(fd)

            crt_pem, key_pem = read("client.crt"), read("client.key")
        finally:
            os.close(dfd)
        cert = x509.load_pem_x509_certificate(crt_pem)
        key = serialization.load_pem_private_key(key_pem, password=None)
        pub_c = cert.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        pub_k = key.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        if pub_c != pub_k:
            raise ssl.SSLError("KEY_VALUES_MISMATCH: torn cert/key pair")
        return cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)[0].value

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not os.path.exists(os.path.join(self.root, "current")):
                time.sleep(0.001)
                continue
            try:
                try:
                    cn = self._load_once()
                except FileNotFoundError:
                    if not self.retry_enoent:
                        raise  # exchange-capable fs: ENOENT is a real bug
                    # fallback-dance contract: retry once on transient ENOENT
                    cn = self._load_once()
                if not self.seen_cns or self.seen_cns[-1] != cn:
                    self.seen_cns.append(cn)
            except Exception as e:  # noqa: BLE001 — any failure is the bug
                self.errors.append(repr(e))
            time.sleep(0.0005)

    def __enter__(self) -> "FakeAgent":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def wait_for_cn(self, cn: str, timeout: float = 5.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.seen_cns and self.seen_cns[-1] == cn:
                return True
            time.sleep(0.005)
        return False


def test_full_lifecycle_against_live_agent(tmp_path):
    mgr = CertManager(root=str(tmp_path))
    with FakeAgent(str(tmp_path)) as agent:
        # install + activate v1 → agent picks it up
        c1, k1 = _keypair("tpud-v1")
        assert mgr.install("v1", c1, k1) is None
        assert mgr.activate("v1") is None
        assert agent.wait_for_cn("tpud-v1")

        # rotate to v2 without downtime
        c2, k2 = _keypair("tpud-v2")
        assert mgr.install("v2", c2, k2) is None
        assert mgr.activate("v2") is None
        assert agent.wait_for_cn("tpud-v2")

        # rollback lands on v1 again
        assert mgr.rollback() is None
        assert agent.wait_for_cn("tpud-v1")

        assert agent.errors == [], agent.errors
    # the agent only ever saw complete, matching keypairs
    assert set(agent.seen_cns) <= {"tpud-v1", "tpud-v2"}


def test_rotation_churn_never_breaks_the_agent(tmp_path):
    """Aggressive rotation + active-version re-push while the agent loads
    credentials as fast as it can: zero load errors allowed."""
    mgr = CertManager(root=str(tmp_path))
    c, k = _keypair("tpud-r0")
    assert mgr.install("r0", c, k) is None
    assert mgr.activate("r0") is None
    with FakeAgent(str(tmp_path)) as agent:
        assert agent.wait_for_cn("tpud-r0")
        for i in range(1, 16):
            cn = f"tpud-r{i}"
            ci, ki = _keypair(cn)
            version = f"r{i}"
            assert mgr.install(version, ci, ki) is None
            assert mgr.activate(version) is None
            if i % 3 == 0:
                # re-push of the ACTIVE version (the hardest path: the
                # version dir must be vacated and re-created under the
                # agent's feet)
                ci2, ki2 = _keypair(cn + "-repush")
                assert mgr.install(version, ci2, ki2) is None
        # i=15 is a multiple of 3, so the final push re-pushed the active
        # release with the -repush CN
        assert agent.wait_for_cn("tpud-r15-repush")
        assert agent.errors == [], agent.errors[:3]


def test_activation_refuses_unready_release_agent_unaffected(tmp_path):
    mgr = CertManager(root=str(tmp_path))
    c1, k1 = _keypair("tpud-good")
    assert mgr.install("good", c1, k1) is None
    assert mgr.activate("good") is None
    with FakeAgent(str(tmp_path)) as agent:
        assert agent.wait_for_cn("tpud-good")
        # a corrupt push must not activate nor disturb the live creds
        err = mgr.install("bad", "not a certificate", "not a key")
        assert err is None  # install writes; readiness gates activation
        err = mgr.activate("bad")
        assert err is not None and "readiness" in err
        time.sleep(0.05)
        assert agent.errors == []
        assert agent.seen_cns[-1] == "tpud-good"
    st = mgr.status()
    assert st.current_version == "good" and st.ready


def test_rollback_skips_newer_inactive_release(tmp_path):
    mgr = CertManager(root=str(tmp_path))
    for v in ("v1", "v2", "v3"):
        c, k = _keypair(f"tpud-{v}")
        assert mgr.install(v, c, k) is None
    assert mgr.activate("v2") is None
    # v3 is newer but inactive: rollback must land on v1, not v3
    assert mgr.rollback() is None
    assert mgr.status().current_version == "v1"


def test_version_path_traversal_rejected(tmp_path):
    mgr = CertManager(root=str(tmp_path))
    c, k = _keypair("x")
    assert mgr.install("../evil", c, k) is not None
    assert mgr.install(".hidden", c, k) is not None
    assert mgr.install("", c, k) is not None
    assert not os.path.exists(str(tmp_path.parent / "evil"))


def test_gc_grace_uses_vacate_time_not_mtime(tmp_path):
    """A release installed long ago and re-pushed NOW parks an .old dir
    whose mtime is ancient; GC must key off the vacate stamp in the dir
    NAME, or it deletes the dir milliseconds after parking — under a
    consumer's feet."""
    mgr = CertManager(root=str(tmp_path))
    c1, k1 = _keypair("v1")
    assert mgr.install("v1", c1, k1) is None
    # age the release (simulates an install > grace ago)
    old_time = time.time() - 3600
    os.utime(str(tmp_path / "releases" / "v1"), (old_time, old_time))
    c2, k2 = _keypair("v1b")
    assert mgr.install("v1", c2, k2) is None  # re-push parks the old dir
    parked = [p for p in os.listdir(str(tmp_path / "releases")) if ".old-" in p]
    assert len(parked) == 1
    # another install triggers GC — the freshly-parked dir must survive
    c3, k3 = _keypair("v2")
    assert mgr.install("v2", c3, k3) is None
    assert any(
        ".old-" in p for p in os.listdir(str(tmp_path / "releases"))
    ), "grace period ignored: freshly-vacated release collected"
    # once the stamp is old, GC collects it
    mgr._gc_stale_dirs(grace=0.0)
    assert not any(
        ".old-" in p for p in os.listdir(str(tmp_path / "releases"))
    )


def test_version_in_staging_namespace_rejected(tmp_path):
    """Versions in the staging-dir namespace (the substring status() uses
    to hide staging dirs) would either be GC'd or invisible in status —
    the whole namespace is rejected at install time."""
    mgr = CertManager(root=str(tmp_path))
    c, k = _keypair("x")
    assert mgr.install("v1.old-2", c, k) is not None
    assert mgr.install("v1.tmp-99", c, k) is not None
    assert mgr.install("v2.tmp-rc1", c, k) is not None  # hidden-from-status case
    assert mgr.install("v1.older-2", c, k) is None  # outside the namespace
    assert "v1.older-2" in mgr.status().versions  # and fully visible


def test_status_hides_staging_dirs(tmp_path):
    mgr = CertManager(root=str(tmp_path))
    c, k = _keypair("tpud-v1")
    assert mgr.install("v1", c, k) is None
    os.makedirs(str(tmp_path / "releases" / "v9.tmp-123"))
    os.makedirs(str(tmp_path / "releases" / "v8.old-456"))
    st = mgr.status()
    assert st.versions == ["v1"]
