"""Learned per-component-class thresholds (predict/calibrate.py).

Covers the calibration contracts: component-class mapping, thin-history
fallback to defaults, the zero-historical-false-positive guarantee
(threshold strictly above every benign replay sample), earlier warnings
than the global default on a precursor ramp, noisy-feature weight
scaling with its floor, the engine integration (periodic refit job,
per-class threshold/weight lookup, calibration view, versioned publish
payload), and cross-component co-occurrence corroboration."""

import time

import pytest

from gpud_tpu.predict.calibrate import (
    DEFAULT_MIN_HISTORY,
    MIN_WEIGHT_FRACTION,
    PREDICT_SCHEMA,
    ClassCalibration,
    ThresholdCalibrator,
    component_class,
)
from gpud_tpu.predict.features import (
    FEATURE_WEIGHTS,
    cadence_score,
    fuse,
    peer_corroboration,
    trajectory_score,
)


# -- component_class ------------------------------------------------------

@pytest.mark.parametrize("name,cls", [
    ("accelerator-tpu-3", "accelerator-tpu"),
    ("accelerator-tpu-temperature", "accelerator-tpu-temperature"),
    ("tpu-hbm", "tpu-hbm"),
    ("disk0", "disk"),
    ("cpu", "cpu"),
    ("c0", "c"),
    ("42", "42"),  # all-digits: its own class, never empty
])
def test_component_class(name, cls):
    assert component_class(name) == cls


# -- synthetic ledgers ----------------------------------------------------

class _Ledger:
    flap_threshold = 5

    def __init__(self, rows):
        self._rows = sorted(rows, key=lambda r: r["time"])

    def history(self):
        return list(reversed(self._rows))  # newest-first, like the real one


def _row(comp, t, frm, to):
    return {"component": comp, "time": t, "from": frm, "to": to,
            "reason": "r"}


def _benign_rows(comp="accelerator-tpu-1", t0=1_000_000.0, blips=12):
    """Quiet history: sparse restart-recovery transitions hours apart,
    never within a window of each other, never near an Unhealthy —
    the benign replay scores stay near the noise floor."""
    return [
        _row(comp, t0 + d * 7200.0, "Initializing", "Healthy")
        for d in range(blips)
    ]


def _ramp_rows(comp="accelerator-tpu-1", t0=2_000_000.0):
    """Accelerating restart ramp ending in a hard failure: the cadence
    feature climbs with the flap rate (trajectory stays quiet until the
    end — restarts are not Degraded excursions), so fused scores walk
    up THROUGH the calibrated band before crossing the global default."""
    rows, t = [], t0
    for gap in (200.0, 120.0, 80.0, 60.0, 45.0, 35.0, 25.0, 20.0):
        rows.append(_row(comp, t, "Healthy", "Initializing"))
        t += gap
    rows.append(_row(comp, t, "Initializing", "Unhealthy"))
    return rows, t


# -- fitting --------------------------------------------------------------

def test_thin_history_falls_back_to_defaults():
    rows = _benign_rows(blips=(DEFAULT_MIN_HISTORY - 1) // 2)
    cal = ThresholdCalibrator(_Ledger(rows)).calibrate(now=3_000_000.0)
    c = cal["accelerator-tpu"]
    assert c.source == "default"
    assert c.threshold == 0.6
    assert c.weights == FEATURE_WEIGHTS
    assert c.samples < DEFAULT_MIN_HISTORY


def test_calibrated_threshold_zero_historical_fps():
    rows = _benign_rows()
    ramp, _fail = _ramp_rows()
    cal = ThresholdCalibrator(_Ledger(rows + ramp)).calibrate(
        now=3_000_000.0
    )["accelerator-tpu"]
    assert cal.source == "calibrated"
    # never raises the global bar, never sits at or below a benign sample
    assert cal.threshold <= 0.6
    assert cal.threshold > cal.benign_max
    assert cal.benign_samples > 0


def test_empty_ledger_and_no_ledger():
    assert ThresholdCalibrator(None).calibrate(now=0.0) == {}
    assert ThresholdCalibrator(_Ledger([])).calibrate(now=0.0) == {}


def test_components_filter_restricts_classes():
    rows = _benign_rows() + _benign_rows(comp="cpu")
    cal = ThresholdCalibrator(_Ledger(rows)).calibrate(
        now=3_000_000.0, components=["accelerator-tpu-1"]
    )
    assert set(cal) == {"accelerator-tpu"}


def test_class_pools_members_history():
    """Two thin members of one class calibrate together: the class pool
    is what crosses min_history, not each instance alone."""
    rows = _benign_rows(comp="accelerator-tpu-1", blips=4)
    rows += _benign_rows(comp="accelerator-tpu-2", t0=1_500_000.0, blips=4)
    cal = ThresholdCalibrator(_Ledger(rows)).calibrate(
        now=3_000_000.0
    )["accelerator-tpu"]
    assert cal.components == 2
    assert cal.samples == 8
    assert cal.source == "calibrated"


def _first_warn(rows, threshold, weights, window=600.0, saturation=5):
    times = [r["time"] for r in rows]
    seen = [(r["time"], r["from"], r["to"]) for r in rows]
    for i, r in enumerate(rows):
        feats = {
            "cadence": cadence_score(times[:i + 1], r["time"], window,
                                     saturation=saturation),
            "trajectory": trajectory_score(r["to"], seen[:i + 1],
                                           r["time"], window),
        }
        if fuse(feats, weights) >= threshold:
            return r["time"]
    return None


def test_calibrated_warns_earlier_than_default_on_ramp():
    """The whole point: on the same precursor ramp, the fitted
    threshold crosses at least one transition before the global
    default would — and still before the failure."""
    benign = _benign_rows()
    ramp, fail_ts = _ramp_rows()
    rows = sorted(benign + ramp, key=lambda r: r["time"])
    cal = ThresholdCalibrator(_Ledger(rows)).calibrate(
        now=3_000_000.0
    )["accelerator-tpu"]
    assert cal.threshold < 0.6
    warn_default = _first_warn(rows, 0.6, None)
    warn_cal = _first_warn(rows, cal.threshold, cal.weights)
    assert warn_cal is not None
    assert warn_cal < fail_ts
    assert warn_default is None or warn_cal < warn_default
    # and the fitted threshold never fires on the benign prefix
    assert _first_warn(benign, cal.threshold, cal.weights) is None


def test_noisy_feature_weight_scaled_with_floor():
    """A feature whose benign replay maximum could alone cross the
    fitted threshold gets scaled down, but never below the floor."""
    # tight benign flapping: high benign cadence scores
    rows = []
    t = 1_000_000.0
    for d in range(10):
        rows.append(_row("noisy-1", t, "Healthy", "Degraded"))
        rows.append(_row("noisy-1", t + 5.0, "Degraded", "Healthy"))
        t += 40.0
    cal = ThresholdCalibrator(_Ledger(rows)).calibrate(
        now=2_000_000.0
    )["noisy"]
    assert cal.source == "calibrated"
    # a benign Degraded-blip class can never beat the global bar: the
    # clamp only ever lowers, and a noisy benign_max pins it at 0.6
    assert cal.threshold == 0.6
    for f in ("cadence", "trajectory"):
        assert cal.weights[f] < FEATURE_WEIGHTS[f]  # scaled down
        assert cal.weights[f] >= FEATURE_WEIGHTS[f] * MIN_WEIGHT_FRACTION


def test_class_calibration_as_dict_round():
    c = ClassCalibration(0.5, {"cadence": 0.6})
    d = c.as_dict()
    assert d["threshold"] == 0.5
    assert d["source"] == "default"
    assert d["precursor_min"] is None


# -- engine integration ---------------------------------------------------

class _StubRegistry:
    def __init__(self, *names):
        self._names = list(names)

    def names(self):
        return list(self._names)


class _EngineLedger:
    """Both ledger faces the engine touches: ``history()`` for the
    calibrator replay, ``recent_transitions``/``last_state`` for the
    live scorer."""

    flap_threshold = 5

    def __init__(self):
        self.rows = []
        self.live = {}  # component -> (state, [transition dicts])
        self.annotations = {}

    def history(self):
        return list(reversed(sorted(self.rows,
                                    key=lambda r: r["time"])))

    def recent_transitions(self, component, limit=0):
        return list(self.live.get(component, (None, []))[1])

    def last_state(self, component):
        state = self.live.get(component, (None, []))[0]
        return {"state": state, "since": 0.0} if state else None

    def set_annotation(self, component, key, value):
        self.annotations.setdefault(component, {})[key] = value

    def clear_annotation(self, component, key):
        self.annotations.get(component, {}).pop(key, None)


def _mk_engine(*names, **kw):
    from gpud_tpu.predict.engine import PredictEngine

    led = _EngineLedger()
    kw.setdefault("registry", _StubRegistry(*names))
    eng = PredictEngine(ledger=led, **kw)
    return eng, led


def test_engine_calibrate_now_swaps_thresholds():
    eng, led = _mk_engine()
    benign = _benign_rows()
    ramp, _ = _ramp_rows()
    led.rows = benign + ramp
    out = eng.calibrate_now()
    assert out["calibrated"] >= 1
    view = eng.calibration()
    assert view["schema"] == PREDICT_SCHEMA
    cls = view["classes"]["accelerator-tpu"]
    assert cls["source"] == "calibrated"
    assert cls["threshold"] < 0.6
    # per-component lookup honors the fitted class
    assert eng._threshold_for("accelerator-tpu-1") == pytest.approx(
        cls["threshold"], abs=1e-4
    )
    # a class the fit never saw keeps the global default
    assert eng._threshold_for("cpu") == eng.threshold


def test_engine_thin_history_keeps_default_threshold():
    eng, led = _mk_engine()
    led.rows = _benign_rows(blips=2)
    eng.calibrate_now()
    view = eng.calibration()
    assert view["classes"]["accelerator-tpu"]["source"] == "default"
    assert eng._threshold_for("accelerator-tpu-1") == eng.threshold


def test_engine_status_and_scores_carry_calibration():
    eng, led = _mk_engine("accelerator-tpu-1")
    led.rows = _benign_rows() + _ramp_rows()[0]
    eng.calibrate_now()
    st = eng.status()
    assert st["schema"] == PREDICT_SCHEMA
    assert st["calibrate_enabled"] is True
    assert st["classes_calibrated"] >= 1
    now = time.time()
    led.live["accelerator-tpu-1"] = ("Degraded", [
        {"time": now - 30.0, "from": "Healthy", "to": "Degraded"},
    ])
    eng.time_now_fn = lambda: now
    eng.tick_once()
    sc = eng.scores()["components"]["accelerator-tpu-1"]
    assert sc["component_class"] == "accelerator-tpu"
    assert sc["threshold"] == pytest.approx(
        eng._threshold_for("accelerator-tpu-1"), abs=1e-6
    )


def test_publish_payload_is_versioned():
    eng, led = _mk_engine("accelerator-tpu-1", arm_ticks=1,
                          warn_cooldown_seconds=0.0)
    got = []
    eng.on_publish = lambda payload: got.append(payload)
    now = time.time()
    # flapping hard + sitting Degraded: fused score over the default bar
    led.live["accelerator-tpu-1"] = ("Degraded", [
        {"time": now - 50 + i * 10, "from": "Healthy", "to": "Degraded"}
        for i in range(6)
    ])
    eng.time_now_fn = lambda: now
    eng.tick_once()
    assert got, "engine never published"
    p = got[-1]
    assert p["schema"] == PREDICT_SCHEMA
    assert p["component"] == "accelerator-tpu-1"
    assert p["component_class"] == "accelerator-tpu"
    assert p["event"] == "warn"
    assert p["armed"] is True
    assert "threshold" in p and "features" in p and "score" in p


def test_scheduler_jobs_registered_when_enabled():
    from gpud_tpu.scheduler import Scheduler

    eng, led = _mk_engine()
    led.rows = _benign_rows() + _ramp_rows()[0]
    sched = Scheduler()
    try:
        eng.start(sched)
        names = set(sched.job_names())
        assert "predict-scan" in names
        assert "predict-calibrate" in names
    finally:
        eng.close()
        sched.close()


def test_calibrate_disabled_skips_job():
    from gpud_tpu.scheduler import Scheduler

    eng, _ = _mk_engine(calibrate_enabled=False)
    sched = Scheduler()
    try:
        eng.start(sched)
        assert "predict-calibrate" not in set(sched.job_names())
    finally:
        eng.close()
        sched.close()


# -- co-occurrence --------------------------------------------------------

def test_peer_corroboration_pairwise_min():
    scores = {"a-1": 0.8, "a-2": 0.5, "b": 0.0}
    assert peer_corroboration("a-1", scores, ["a-2", "b"]) == 0.5
    assert peer_corroboration("a-1", scores, ["b"]) == 0.0
    assert peer_corroboration("b", scores, ["a-1"]) == 0.0  # own zero
    assert peer_corroboration("a-1", scores, ["a-1"]) == 0.0  # self skip


def test_cooccur_feature_raises_fused_score():
    """Two same-class siblings elevated together score higher than one
    alone — correlated precursors corroborate each other."""
    base = {"cadence": 0.5}
    alone = fuse(base)
    together = fuse({**base, "cooccur": 0.5})
    assert together > alone


def test_engine_cooccur_peers():
    from gpud_tpu.predict.engine import PredictEngine

    peers = PredictEngine._cooccur_peers(
        "accelerator-tpu-1",
        {"accelerator-tpu-1": 0.5, "accelerator-tpu-2": 0.4,
         "cpu": 0.9, "fabric": 0.3},
        "fabric",
    )
    # same-class sibling + the fabric component; never the unrelated cpu
    assert set(peers) == {"accelerator-tpu-2", "fabric"}
    # the fabric component corroborates with every accelerator
    fab_peers = PredictEngine._cooccur_peers(
        "fabric",
        {"accelerator-tpu-1": 0.5, "cpu": 0.9, "fabric": 0.3},
        "fabric",
    )
    assert set(fab_peers) == {"accelerator-tpu-1"}
