"""Crash consistency: SIGKILL the real daemon mid-work and prove the
state directory survives.

The reference leans on SQLite WAL + Find-before-Insert for restart
safety but only ever tests CLEAN restarts; a health daemon's actual
failure mode is the hard kill (OOM, node crash — the exact events it
monitors). These tests kill -9 a live daemon during event churn and
credential rotation, then restart on the same data dir and assert: the
DB passes integrity_check, detected events survive, re-reads don't
double-count, and the credential pair is never torn (metadata.set_many
single-transaction contract).
"""

import json
import os
import signal
import sqlite3
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _boot(data_dir: str, kmsg: str, extra=()):
    env = dict(
        os.environ,
        TPUD_TPU_MOCK_ALL_SUCCESS="1",
        PYTHONUNBUFFERED="1",
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gpud_tpu.cli", "run",
            "--data-dir", data_dir, "--port", "0", "--no-tls",
            "--kmsg-path", kmsg,
            "--disable-components", "network-latency",
            *extra,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=REPO,
        env=env,
    )
    import select

    deadline = time.time() + 60
    base = None
    pending = ""
    while time.time() < deadline:
        # bounded read: a daemon that hangs pre-print must FAIL the test,
        # not hang pytest (readline alone would block forever)
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if not ready:
            assert proc.poll() is None, "daemon died during boot"
            continue
        pending += os.read(proc.stdout.fileno(), 4096).decode(
            "utf-8", "replace"
        )
        for line in pending.splitlines():
            if "listening on" in line:
                base = line.rsplit(" ", 1)[-1].strip()
        if base:
            break
    assert base, "daemon never printed its listen URL within 60s"
    return proc, base


def _get(base: str, path: str, timeout=10):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _post(base: str, path: str, body: dict, timeout=10):
    req = urllib.request.Request(
        f"{base}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _integrity_ok(state_file: str) -> None:
    con = sqlite3.connect(state_file)
    try:
        (res,) = con.execute("PRAGMA integrity_check").fetchone()
        assert res == "ok", res
    finally:
        con.close()


def test_sigkill_during_event_churn_state_survives(tmp_path):
    """Inject faults through the real HTTP API (kmsg writer → watcher →
    syncer → eventstore), SIGKILL mid-churn, restart on the same data
    dir: the DB is intact, detected events survived, and the restart's
    ring re-read does not double-count them."""
    data_dir = str(tmp_path / "data")
    kmsg = str(tmp_path / "kmsg.fixture")
    open(kmsg, "w").close()

    proc, base = _boot(data_dir, kmsg)
    killed_mid_flight = False
    try:
        # churn: a burst of distinct catalogued faults
        names = ["tpu_chip_lost", "tpu_hbm_ecc_uncorrectable", "tpu_dma_error"]
        for i, name in enumerate(names):
            _post(base, "/inject-fault",
                  {"tpu_error_name": name, "chip_id": i})
        # wait until at least one is detected so the kill lands mid-churn,
        # not before any work happened
        deadline = time.time() + 30
        detected = []
        while time.time() < deadline and not detected:
            evs = _get(base, "/v1/events")
            detected = [
                e for grp in evs for e in grp.get("events", [])
                if e.get("name", "").startswith("tpu_")
            ]
            time.sleep(0.2)
        assert detected, "no fault detected before the kill"
        os.kill(proc.pid, signal.SIGKILL)
        killed_mid_flight = True
        proc.wait(timeout=10)
    finally:
        if not killed_mid_flight and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    state = os.path.join(data_dir, "tpud.state")
    _integrity_ok(state)

    # restart on the same dir: events are still there, and the re-read of
    # the same kmsg ring does not duplicate them
    proc2, base2 = _boot(data_dir, kmsg)
    try:
        deadline = time.time() + 30
        names_seen = []
        while time.time() < deadline:
            evs = _get(base2, "/v1/events")
            names_seen = [
                (e["name"], e["time"])
                for grp in evs
                for e in grp.get("events", [])
                if e.get("name", "").startswith("tpu_")
            ]
            if names_seen:
                break
            time.sleep(0.2)
        assert names_seen, "events lost across SIGKILL"
        assert len(names_seen) == len(set(names_seen)), (
            f"restart double-counted events: {names_seen}"
        )
        # the daemon is fully functional: health endpoint answers ok
        hz = _get(base2, "/healthz")
        assert hz.get("status") == "ok", hz
    finally:
        proc2.send_signal(signal.SIGTERM)
        assert proc2.wait(timeout=20) == 0


def test_sigkill_during_rotation_never_tears_credential_pair(tmp_path):
    """Hammer token rotations through the FIFO and SIGKILL the daemon
    while they're in flight. After every kill the persisted endpoint+
    token must be one of the CONSISTENT pairs that existed — never the
    old endpoint with a new token or vice versa (metadata.set_many
    transactional contract)."""
    from gpud_tpu.server.server import Server

    data_dir = str(tmp_path / "data")
    kmsg = str(tmp_path / "kmsg.fixture")
    open(kmsg, "w").close()
    endpoint = "http://127.0.0.1:1"  # unreachable is fine: persistence
    tokens = [f"rot-{i}" for i in range(12)]

    state = os.path.join(data_dir, "tpud.state")

    def _pair():
        con = sqlite3.connect(state)
        try:
            return dict(
                con.execute(
                    "SELECT key, value FROM tpud_metadata_v0_1 "
                    "WHERE key IN ('endpoint', 'token')"
                )
            )
        finally:
            con.close()

    proc, _base = _boot(
        data_dir, kmsg, extra=("--endpoint", endpoint, "--token", "boot-T")
    )
    killed = False
    try:
        fifo = os.path.join(data_dir, "tpud.fifo")
        # phase 1: deliver half the rotations and WAIT until one is
        # durably persisted, so the kill below lands on a daemon that has
        # real rotation state (not one that never got to work)
        deadline = time.time() + 30
        wrote = 0
        while time.time() < deadline and wrote < 6:
            err = Server.write_token(tokens[wrote], fifo)
            if err is None:
                wrote += 1
            else:
                time.sleep(0.05)
        assert wrote == 6
        deadline = time.time() + 30
        while time.time() < deadline and _pair().get("token") not in tokens:
            time.sleep(0.1)
        assert _pair().get("token") in tokens, _pair()
        # phase 2: a rapid burst racing the persist path, then kill -9
        while time.time() < deadline and wrote < len(tokens):
            err = Server.write_token(tokens[wrote], fifo)
            if err is None:
                wrote += 1  # no sleep: keep rotations in flight
            else:
                time.sleep(0.02)
        os.kill(proc.pid, signal.SIGKILL)
        killed = True
        proc.wait(timeout=10)
    finally:
        if not killed and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    _integrity_ok(state)
    rows = _pair()
    # the token is one that actually existed — a DELIVERED rotation (a
    # burst write the watcher never processed before the kill is allowed
    # to be lost; it was never acknowledged) and never a corrupt
    # multi-line join of several deliveries
    assert rows.get("token") in set(tokens), rows
    assert "\n" not in rows["token"]
    # the pair is never torn: the endpoint those tokens were issued for
    assert rows.get("endpoint") == endpoint, rows


def test_repeated_sigkill_restart_cycles_stay_healthy(tmp_path):
    """Three kill -9 / restart cycles with live injection each round: the
    store keeps passing integrity_check and the daemon keeps detecting —
    crash damage must not accumulate."""
    data_dir = str(tmp_path / "data")
    kmsg = str(tmp_path / "kmsg.fixture")
    open(kmsg, "w").close()
    state = os.path.join(data_dir, "tpud.state")

    for cycle in range(3):
        proc, base = _boot(data_dir, kmsg)
        killed = False
        try:
            _post(
                base, "/inject-fault",
                {"tpu_error_name": "tpu_chip_lost", "chip_id": cycle},
            )
            deadline = time.time() + 30
            ok = False
            while time.time() < deadline and not ok:
                evs = _get(base, "/v1/events")
                ok = any(
                    e.get("name") == "tpu_chip_lost"
                    for grp in evs
                    for e in grp.get("events", [])
                )
                time.sleep(0.2)
            assert ok, f"cycle {cycle}: injection not detected"
            os.kill(proc.pid, signal.SIGKILL)
            killed = True
            proc.wait(timeout=10)
        finally:
            if not killed and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        _integrity_ok(state)

    # final boot must come up clean and still hold history
    proc, base = _boot(data_dir, kmsg)
    try:
        evs = _get(base, "/v1/events")
        got = [
            e for grp in evs for e in grp.get("events", [])
            if e.get("name") == "tpu_chip_lost"
        ]
        assert got, "history lost after repeated crashes"
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=20) == 0


_BATCH_CHILD = r"""
import sys
from gpud_tpu.api.v1.types import Event, EventType
from gpud_tpu.eventstore import EventStore
from gpud_tpu.sqlite import DB
from gpud_tpu.storage.writer import BatchWriter

db = DB(sys.argv[1])
# no scheduler: the ONLY drain is the explicit flush barrier, so each
# ACKed batch maps to exactly one group commit (one transaction)
writer = BatchWriter(db, fsync=True)
store = EventStore(db, writer=writer)
bucket = store.bucket("crash-batch")
k = 0
while True:
    for i in range(50):
        bucket.insert(Event(
            component="crash-batch", time=1000.0 + k, name=f"batch-{k}",
            type=EventType.INFO, message=f"row {i}",
        ))
    writer.flush(timeout=30.0)
    print(f"ACK {k}", flush=True)
    k += 1
"""


def test_sigkill_mid_group_commit_batches_are_atomic(tmp_path):
    """SIGKILL a writer mid-ingest through the write-behind layer: every
    group commit is one transaction, so after the kill each batch is
    all-or-none (never torn), every flush-ACKed batch survived in full,
    and the DB passes integrity_check. The unACKed tail — at most one
    flush window of buffered rows — is the documented loss budget."""
    state = str(tmp_path / "batch.state")
    child = subprocess.Popen(
        [sys.executable, "-c", _BATCH_CHILD, state],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=REPO,
        env=dict(os.environ, PYTHONUNBUFFERED="1"),
    )
    acked = -1
    try:
        deadline = time.time() + 60
        while time.time() < deadline and acked < 5:
            line = child.stdout.readline()
            assert line, "writer child died before 6 batches ACKed"
            if line.startswith("ACK "):
                acked = int(line.split()[1])
        assert acked >= 5, "never reached 6 ACKed batches"
    finally:
        # no drain between ACKs: the kill lands while batch acked+1 is
        # buffered or mid-commit
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=10)

    _integrity_ok(state)
    con = sqlite3.connect(state)
    try:
        counts = dict(con.execute(
            "SELECT name, COUNT(*) FROM tpud_events_v0_1 "
            "WHERE component = 'crash-batch' GROUP BY name"
        ))
    finally:
        con.close()
    # every ACKed batch is fully present
    for k in range(acked + 1):
        assert counts.get(f"batch-{k}") == 50, (
            f"ACKed batch {k} torn/lost: {counts.get(f'batch-{k}')}"
        )
    # NO batch is ever partial — committed whole or lost whole
    torn = {n: c for n, c in counts.items() if c != 50}
    assert not torn, f"torn group commits: {torn}"
    # loss is bounded to the in-flight flush window: at most one
    # unACKed batch can have committed
    assert len(counts) <= acked + 2, counts


# ---------------------------------------------------------------------------
# SIGKILL mid outbox replay: the acked watermark never regresses and no
# frame is delivered zero times.

_REPLAY_CHILD = r"""
import sys
import time

from gpud_tpu.session.outbox import SessionOutbox
from gpud_tpu.sqlite import DB
from gpud_tpu.storage.writer import BatchWriter

state = sys.argv[1]
db = DB(state)
writer = BatchWriter(db, flush_interval_seconds=0.05, fsync=True)
outbox = SessionOutbox(db, writer=writer, replay_batch=20)

TOTAL = 600
for i in range(TOTAL):
    outbox.publish("event", {"i": i}, dedupe_key=f"crash:{i}")


class Loopback:
    connected = True
    auth_failed = False

    def send(self, frame):
        # batched delivery (docs/session.md wire format): one DEL line
        # per record so the parent can track per-seq delivery
        for rec in frame.data["outbox_batch"]["records"]:
            print("DEL", rec[0], flush=True)
        return True


sess = Loopback()
while outbox.backlog() > 0:
    sent = outbox.replay_once(sess)
    if not sent:
        break
    # the "manager" acks the batch it just saw; the flush barrier makes
    # the watermark durable BEFORE the ACK line is printed, so every
    # printed ACK is a floor the restart watermark may never sink below
    outbox.ack(outbox.acked_seq + sent)
    writer.flush(timeout=30)
    print("ACK", outbox.acked_seq, flush=True)
    time.sleep(0.05)
print("DONE", flush=True)
"""


def test_sigkill_mid_outbox_replay_watermark_and_delivery(tmp_path):
    """Kill the daemon between outbox replay batches. On restart the
    acked watermark must never regress below the last durable ack (or
    frames already consumed by the manager replay again forever), and
    must never pass a frame that was not handed to the transport (or
    that frame is delivered zero times — silent loss)."""
    from gpud_tpu.session.outbox import SessionOutbox
    from gpud_tpu.sqlite import DB

    state = str(tmp_path / "outbox.state")
    child = subprocess.Popen(
        [sys.executable, "-c", _REPLAY_CHILD, state],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    delivered = set()
    acks = []
    try:
        deadline = time.time() + 60
        while len(acks) < 4 and time.time() < deadline:
            line = child.stdout.readline()
            assert line, "replay child died before 4 batches ACKed"
            if line.startswith("DEL "):
                delivered.add(int(line.split()[1]))
            elif line.startswith("ACK "):
                acks.append(int(line.split()[1]))
        assert len(acks) >= 4, "never reached 4 ACKed replay batches"
    finally:
        # kill between batches: frames past the last ACK may already be
        # DEL-printed (delivered, unacked) — exactly the at-least-once
        # redelivery window
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=10)

    _integrity_ok(state)

    db = DB(state)
    try:
        outbox = SessionOutbox(db)
        watermark = outbox.acked_seq
        # never regresses: every printed ACK was flushed+fsynced first
        assert watermark >= acks[-1], (
            f"watermark {watermark} regressed below durable ack {acks[-1]}"
        )
        # never acks the undelivered: the child only acked frames its
        # transport already accepted
        assert watermark <= max(delivered), (
            f"watermark {watermark} passed frames never handed to the "
            f"transport (max delivered {max(delivered)})"
        )
        total = outbox.last_seq
        assert total == 600, f"journal lost publishes: last_seq={total}"

        class Drain:
            connected = True
            auth_failed = False

            def __init__(self):
                self.seqs = set()

            def send(self, frame):
                for rec in frame.data["outbox_batch"]["records"]:
                    self.seqs.add(rec[0])
                return True

        sess = Drain()
        while outbox.backlog() > 0:
            sent = outbox.replay_once(sess)
            if not sent:
                break
            outbox.ack(max(sess.seqs))
        # replay resumes exactly above the watermark...
        assert sess.seqs == set(range(watermark + 1, total + 1))
        # ...so pre-kill deliveries + post-restart replay cover every
        # journaled frame: nothing is delivered zero times
        assert delivered | sess.seqs == set(range(1, total + 1))
    finally:
        db.close()
