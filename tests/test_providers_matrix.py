"""Cloud provider detection matrix (reference: pkg/providers/* — per-cloud
IMDS fetchers with fake transports). Every detector is driven with a fake
IMDS, plus partial-metadata and failure-shape cases."""

import json

import pytest

from gpud_tpu.providers import detect as det
from gpud_tpu.providers.detect import (
    DetectResult,
    detect_aws,
    detect_azure,
    detect_gcp,
    detect_metadata_mount,
    detect_oci,
)


def _getter(routes):
    """Fake IMDS transport: url-substring → response (str or Exception)."""

    def get(url, headers, timeout=1.0):
        for frag, resp in routes.items():
            if frag in url:
                if isinstance(resp, Exception):
                    raise resp
                return resp
        raise OSError(f"unrouted {url}")

    return get


# -- GCP --------------------------------------------------------------------

def test_gcp_tpu_vm_full():
    g = _getter({
        "instance/zone": "projects/12345/zones/us-east5-b",
        "machine-type": "projects/12345/machineTypes/ct5p-hightpu-4t",
        "accelerator-type": "v5p-256",
        "tpu-env": "TPU_CHIPS_PER_HOST: '4'",
    })
    r = detect_gcp(get_fn=g)
    assert r.provider == "gcp"
    assert r.zone == "us-east5-b" and r.region == "us-east5"
    assert r.instance_type == "ct5p-hightpu-4t"
    assert r.accelerator_type == "v5p-256"
    assert "tpu-env" in r.raw


def test_gcp_non_tpu_vm_partial_attributes():
    g = _getter({
        "instance/zone": "projects/1/zones/europe-west4-a",
        "machine-type": "projects/1/machineTypes/n2-standard-8",
        # no accelerator attributes routed → OSError → tolerated
    })
    r = detect_gcp(get_fn=g)
    assert r.provider == "gcp" and r.accelerator_type == ""
    assert r.instance_type == "n2-standard-8"


def test_gcp_absent_returns_none():
    assert detect_gcp(get_fn=_getter({})) is None


# -- AWS --------------------------------------------------------------------

def test_aws_identity_document(monkeypatch):
    monkeypatch.setattr(det, "_imds_v2_token", lambda: "tok-123")
    seen_headers = {}

    def g(url, headers, timeout=1.0):
        seen_headers.update(headers)
        assert "instance-identity/document" in url
        return json.dumps({
            "region": "us-west-2",
            "availabilityZone": "us-west-2b",
            "instanceType": "trn1.32xlarge",
        })

    r = detect_aws(get_fn=g)
    assert r.provider == "aws" and r.region == "us-west-2"
    assert r.instance_type == "trn1.32xlarge"
    assert seen_headers.get("X-aws-ec2-metadata-token") == "tok-123"


def test_aws_malformed_document_returns_none(monkeypatch):
    monkeypatch.setattr(det, "_imds_v2_token", lambda: "")
    r = detect_aws(get_fn=_getter({"instance-identity": "<html>error</html>"}))
    assert r is None


# -- Azure ------------------------------------------------------------------

def test_azure_compute_document():
    r = detect_azure(get_fn=_getter({
        "metadata/instance/compute": json.dumps({
            "location": "eastus2", "zone": "1", "vmSize": "ND96asr_v4",
        })
    }))
    assert r.provider == "azure" and r.region == "eastus2"
    assert r.zone == "1" and r.instance_type == "ND96asr_v4"


def test_azure_absent_returns_none():
    assert detect_azure(get_fn=_getter({})) is None


# -- OCI --------------------------------------------------------------------

def test_oci_v2_with_bearer_header():
    seen = {}

    def g(url, headers, timeout=1.0):
        seen.update(headers)
        if "canonicalRegionName" in url:
            return "us-ashburn-1"
        if "shape" in url:
            return "BM.GPU4.8"
        if "availabilityDomain" in url:
            return "AD-1"
        raise OSError("unrouted")

    r = detect_oci(get_fn=g)
    assert r.provider == "oci" and r.region == "us-ashburn-1"
    assert r.instance_type == "BM.GPU4.8" and r.zone == "AD-1"
    assert seen.get("Authorization") == "Bearer Oracle"


def test_oci_partial_shape_tolerated():
    def g(url, headers, timeout=1.0):
        if "canonicalRegionName" in url:
            return "eu-frankfurt-1"
        raise OSError("unrouted")

    r = detect_oci(get_fn=g)
    assert r.provider == "oci" and r.instance_type == ""


# -- metadata-mount clouds (nebius/nscale) ----------------------------------

def test_metadata_mount_nebius(tmp_path):
    (tmp_path / "parent-id").write_text("project-abc\n")
    (tmp_path / "instance-id").write_text("computeinstance-xyz\n")
    (tmp_path / "gpu-cluster-id").write_text("cluster-7\n")
    r = detect_metadata_mount(root=str(tmp_path))
    assert r.provider == "nebius"
    assert r.raw["instance_id"] == "project-abc/cluster-7/computeinstance-xyz"


def test_metadata_mount_nscale_marker(tmp_path):
    (tmp_path / "parent-id").write_text("p\n")
    (tmp_path / "instance-id").write_text("i\n")
    (tmp_path / "org-id").write_text("org-9\n")
    r = detect_metadata_mount(root=str(tmp_path))
    assert r.provider == "nscale"


def test_metadata_mount_incomplete_returns_none(tmp_path):
    (tmp_path / "parent-id").write_text("p\n")  # no instance-id
    assert detect_metadata_mount(root=str(tmp_path)) is None
    assert detect_metadata_mount(root=str(tmp_path / "missing")) is None


# -- aggregation ordering ----------------------------------------------------

def test_detect_prefers_gcp_over_others(monkeypatch):
    monkeypatch.setattr(
        det, "DETECTORS",
        [
            lambda: DetectResult(provider="aws", region="us-west-2"),
            lambda: DetectResult(provider="gcp", region="us-east5"),
        ],
    )
    r = det.detect(timeout=5.0)
    assert r.provider == "gcp"


def test_detect_straggler_does_not_block(monkeypatch):
    import threading
    import time as _time

    release = threading.Event()  # released in teardown so the abandoned
    # worker never stalls interpreter exit (concurrent.futures joins
    # non-daemon workers at atexit)

    def slow():
        release.wait(30)
        return DetectResult(provider="aws")

    monkeypatch.setattr(
        det, "DETECTORS",
        [slow, lambda: DetectResult(provider="oci", region="r")],
    )
    try:
        t0 = _time.time()
        r = det.detect(timeout=3.0)
        assert _time.time() - t0 < 10
        assert r.provider == "oci"
    finally:
        release.set()


def test_detect_falls_back_to_asn(monkeypatch):
    from gpud_tpu import asn as asnmod

    monkeypatch.setattr(det, "DETECTORS", [lambda: None])

    class Info:
        provider = "hetzner"
        asn = 24940
        org = "Hetzner Online"

    monkeypatch.setattr(asnmod, "lookup", lambda ip: Info())
    r = det.detect(timeout=2.0)
    assert r.provider == "hetzner"
    assert r.raw["asn"] == "24940"


def test_detect_unknown_when_everything_fails(monkeypatch):
    from gpud_tpu import asn as asnmod

    monkeypatch.setattr(det, "DETECTORS", [lambda: None])
    monkeypatch.setattr(asnmod, "lookup", lambda ip: None)
    assert det.detect(timeout=2.0).provider == "unknown"
