"""kmsg path against the REAL /dev/kmsg character device.

Everything else in the suite runs fixture files (env override); this
suite closes the loop on the char-device code paths the fixtures can't
reach — one-record-per-read semantics, EAGAIN end-of-ring, poll()
wakeups — by reading the live kernel ring and injecting one benign,
clearly-labelled record through the product writer (the reference
injects via /dev/kmsg the same way, pkg/kmsg/writer/kmsg.go:35).
Skips cleanly where the sandbox denies the device.
"""

import os
import threading
import time
import uuid

import pytest

from gpud_tpu.kmsg.watcher import Watcher, read_all
from gpud_tpu.kmsg.writer import KmsgWriter

KMSG = "/dev/kmsg"


def _kmsg_readable() -> bool:
    try:
        fd = os.open(KMSG, os.O_RDONLY | os.O_NONBLOCK)
        os.close(fd)
        return True
    except OSError:
        return False


def _kmsg_writable() -> bool:
    try:
        fd = os.open(KMSG, os.O_WRONLY)
        os.close(fd)
        return True
    except OSError:
        return False


readable = pytest.mark.skipif(not _kmsg_readable(), reason="/dev/kmsg unreadable")
writable = pytest.mark.skipif(
    not (_kmsg_readable() and _kmsg_writable()), reason="/dev/kmsg not writable"
)


@readable
def test_read_all_real_ring():
    msgs = read_all(KMSG)
    assert msgs, "kernel ring buffer is never empty after boot"
    # char-device reads return one well-formed record each
    seqs = [m.sequence for m in msgs]
    assert seqs == sorted(seqs)
    assert all(m.raw and m.message is not None for m in msgs)
    # boot-relative timestamps were converted to wall clock
    assert all(m.time > 1_000_000_000 for m in msgs)


@readable
def test_read_all_limit_stops_early():
    limited = read_all(KMSG, limit=5)
    assert len(limited) == 5


@writable
def test_writer_record_roundtrips_through_real_ring():
    """Product writer → kernel ring → product reader, verbatim."""
    marker = f"tpud-test {uuid.uuid4().hex}: benign writer roundtrip"
    err = KmsgWriter(path=KMSG).write(marker, priority=6)
    assert err is None
    deadline = time.time() + 5
    while time.time() < deadline:
        hits = [m for m in read_all(KMSG) if marker in m.message]
        if hits:
            assert hits[0].priority == 6
            return
        time.sleep(0.2)
    raise AssertionError("record never appeared in the ring")


@writable
def test_watcher_follows_real_device():
    """Watcher in from_now mode sees only records injected after start —
    the poll()+EAGAIN device loop, not the fixture tail."""
    marker = f"tpud-test {uuid.uuid4().hex}: benign watcher follow"
    got = threading.Event()
    seen = []

    def cb(m):
        if marker in m.message:
            seen.append(m)
            got.set()

    w = Watcher(path=KMSG, callback=cb, from_now=True)
    w.start()
    try:
        time.sleep(0.3)  # let the follow loop reach the ring tail
        assert KmsgWriter(path=KMSG).write(marker, priority=5) is None
        assert got.wait(5.0), "watcher missed the injected record"
        assert seen[0].priority == 5
    finally:
        w.close()


@writable
def test_device_detection_latency_subsecond():
    """The headline property: a fault line hitting the real kernel ring is
    delivered to the callback in well under a second (BENCH kmsg p50 is
    ~1ms against fixtures; the device path must be the same order)."""
    marker = f"tpud-test {uuid.uuid4().hex}: benign latency probe"
    t_seen = {}
    got = threading.Event()

    def cb(m):
        if marker in m.message and "t" not in t_seen:
            t_seen["t"] = time.monotonic()
            got.set()

    w = Watcher(path=KMSG, callback=cb, from_now=True)
    w.start()
    try:
        time.sleep(0.3)
        t0 = time.monotonic()
        KmsgWriter(path=KMSG).write(marker, priority=6)
        assert got.wait(5.0)
        latency = t_seen["t"] - t0
        assert latency < 1.0, f"device-path delivery took {latency:.3f}s"
    finally:
        w.close()


@readable
def test_scan_error_component_reads_real_ring():
    """Scan mode's kmsg source works against the live ring (the scan CLI
    on a real host takes exactly this path)."""
    from gpud_tpu.kmsg.watcher import kmsg_path

    # env override points at fixtures during tests; bypass it explicitly
    msgs = read_all(KMSG, limit=50)
    assert len(msgs) == 50
    assert kmsg_path("") != ""  # env override still wins for the daemon
