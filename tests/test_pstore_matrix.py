"""pstore crash-dump classification matrix (reference: pkg/pstore —
1441 test LoC over real dump fixtures)."""

import os

import pytest

from gpud_tpu.pstore import PstoreHistory, read_crash_files

PANIC_DUMP = """\
<6>[  100.000000] systemd[1]: started something
<0>[  245.123456] Kernel panic - not syncing: Fatal exception in interrupt
<0>[  245.123999] CPU: 3 PID: 0 Comm: swapper/3 Tainted: G W
<0>[  245.124500] Call Trace:
"""

OOPS_DUMP = """\
<4>[  881.000000] BUG: unable to handle page fault for address: ffffdead
<4>[  881.000100] Oops: 0002 [#1] PREEMPT SMP NOPTI
<4>[  881.000200] RIP: 0010:tpu_dma_complete+0x24/0x90 [google_tpu]
"""

GPF_DUMP = "<1>[ 12.0] general protection fault, probably for non-canonical address\n"

HARD_LOCKUP_DUMP = "<0>[ 55.5] watchdog: hard LOCKUP on cpu 7\n"

BENIGN_DUMP = """\
<6>[    1.000000] Linux version 6.1.0
<6>[    2.000000] systemd[1]: Reached target basic.target
"""


from tests.helpers import write_pstore_dump as _write


@pytest.mark.parametrize(
    "content,kind,token",
    [
        (PANIC_DUMP, "panic", "Kernel panic"),
        (OOPS_DUMP, "oops", "BUG:"),
        (GPF_DUMP, "oops", "general protection fault"),
        (HARD_LOCKUP_DUMP, "oops", "hard LOCKUP"),
    ],
)
def test_classification_matrix(tmp_path, content, kind, token):
    _write(tmp_path, "dmesg-efi-172000000001", content)
    recs = read_crash_files(str(tmp_path))
    assert len(recs) == 1
    assert recs[0].kind == kind
    assert token.lower() in recs[0].excerpt.lower()


def test_benign_dump_is_unknown_with_head_excerpt(tmp_path):
    _write(tmp_path, "dmesg-efi-172000000002", BENIGN_DUMP)
    recs = read_crash_files(str(tmp_path))
    assert recs[0].kind == "unknown"
    assert "Linux version" in recs[0].excerpt  # head fallback, not empty


def test_non_crash_files_ignored(tmp_path):
    _write(tmp_path, "pmsg-ramoops-0", "userspace junk")
    _write(tmp_path, "notes.txt", "operator notes")
    _write(tmp_path, "console-ramoops-0", PANIC_DUMP)
    recs = read_crash_files(str(tmp_path))
    assert [os.path.basename(r.path) for r in recs] == ["console-ramoops-0"]


def test_ordering_by_mtime_and_nested_dirs(tmp_path):
    sub = tmp_path / "196000000" / "000"
    sub.mkdir(parents=True)
    _write(tmp_path, "dmesg-efi-2", OOPS_DUMP, mtime=2000)
    _write(sub, "dmesg-efi-1", PANIC_DUMP, mtime=1000)
    recs = read_crash_files(str(tmp_path))
    assert [r.kind for r in recs] == ["panic", "oops"]  # oldest first


def test_excerpt_caps_at_five_matches(tmp_path):
    many = "".join(f"<0>[ {i}.0] BUG: repeated fault {i}\n" for i in range(50))
    _write(tmp_path, "dmesg-efi-9", many)
    recs = read_crash_files(str(tmp_path))
    assert len(recs[0].excerpt.splitlines()) == 5


def test_env_override_and_missing_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUD_PSTORE_DIR", str(tmp_path / "nope"))
    assert read_crash_files() == []
    monkeypatch.setenv("TPUD_PSTORE_DIR", str(tmp_path))
    _write(tmp_path, "dmesg-efi-1", PANIC_DUMP)
    assert len(read_crash_files()) == 1


def test_history_dedupes_across_restarts(tmp_path, tmp_db):
    _write(tmp_path, "dmesg-efi-1", PANIC_DUMP, mtime=1000)
    h1 = PstoreHistory(tmp_db)
    fresh = h1.record_new(read_crash_files(str(tmp_path)))
    assert len(fresh) == 1
    # daemon restart: same dump, no new report
    h2 = PstoreHistory(tmp_db)
    assert h2.record_new(read_crash_files(str(tmp_path))) == []
    # the kernel rewrites the dump (new mtime) → a NEW crash
    _write(tmp_path, "dmesg-efi-1", PANIC_DUMP, mtime=2000)
    assert len(h2.record_new(read_crash_files(str(tmp_path)))) == 1
    assert len(h2.all()) == 2


def test_unreadable_file_skipped(tmp_path):
    p = _write(tmp_path, "dmesg-efi-3", PANIC_DUMP)
    os.chmod(p, 0o000)
    try:
        if os.geteuid() == 0:
            pytest.skip("root ignores file modes")
        assert read_crash_files(str(tmp_path)) == []
    finally:
        os.chmod(p, 0o644)
