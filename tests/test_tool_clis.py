"""CLI surfaces of the dev tools: helm_render main (render + --set +
failure modes) and gen_catalog_doc --check (the CI sync gate)."""

import os
import subprocess
import sys

import pytest

yaml = pytest.importorskip("yaml")

from gpud_tpu.tools import helm_render  # noqa: F401 - import sanity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = "deployments/helm/tpud"


def _run(mod, *args):
    return subprocess.run(
        [sys.executable, "-m", mod, *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )


def test_helm_render_cli_renders_real_chart():
    res = _run("gpud_tpu.tools.helm_render", CHART)
    assert res.returncode == 0, res.stderr
    assert "# Source:" in res.stdout
    docs = [d for d in yaml.safe_load_all(
        "\n".join(l for l in res.stdout.splitlines() if not l.startswith("# Source:"))
    ) if d]
    kinds = {d.get("kind") for d in docs}
    assert "DaemonSet" in kinds


def test_helm_render_cli_set_override():
    res = _run(
        "gpud_tpu.tools.helm_render", CHART, "--set", "image.tag=v9.9.9"
    )
    assert res.returncode == 0
    assert "v9.9.9" in res.stdout


def test_helm_render_cli_missing_chart_fails_cleanly(tmp_path):
    res = _run("gpud_tpu.tools.helm_render", str(tmp_path / "nochart"))
    assert res.returncode == 1
    assert "render failed" in res.stderr
    assert "Traceback" not in res.stderr


def test_helm_render_cli_unsupported_construct_fails_before_output(tmp_path):
    """Constructs the subset renderer can't honor (e.g. `lookup`) fail
    loudly, and validation happens before any output is printed. (A
    missing .Values path rendering empty is FAITHFUL helm behavior and
    deliberately not an error.)"""
    chart = tmp_path / "chart"
    (chart / "templates").mkdir(parents=True)
    (chart / "Chart.yaml").write_text("name: x\nversion: 0.1.0\n")
    (chart / "values.yaml").write_text("a: 1\n")
    (chart / "templates" / "bad.yaml").write_text(
        'kind: ConfigMap\nmeta: {{ lookup "v1" "Pod" "ns" "x" }}\n'
    )
    res = _run("gpud_tpu.tools.helm_render", str(chart))
    assert res.returncode == 1
    assert "render failed" in res.stderr and "unsupported" in res.stderr
    assert res.stdout == ""  # validate-before-print contract


def test_helm_render_missing_values_path_is_empty_like_helm(tmp_path):
    chart = tmp_path / "chart"
    (chart / "templates").mkdir(parents=True)
    (chart / "Chart.yaml").write_text("name: x\nversion: 0.1.0\n")
    (chart / "values.yaml").write_text("a: 1\n")
    (chart / "templates" / "c.yaml").write_text(
        "kind: ConfigMap\nmeta: {{ .Values.missing.deep.path }}\n"
    )
    res = _run("gpud_tpu.tools.helm_render", str(chart))
    assert res.returncode == 0
    assert "meta:" in res.stdout


def test_gen_catalog_doc_check_in_sync():
    res = _run("gpud_tpu.tools.gen_catalog_doc", "--check")
    assert res.returncode == 0
    assert "in sync" in res.stdout


def test_gen_catalog_doc_check_detects_drift(tmp_path):
    """--check against a stale copy exits 1 (the CI gate actually gates)."""

    work = tmp_path / "repo"
    work.mkdir()
    (work / "docs").mkdir()
    (work / "docs" / "CATALOG.md").write_text("stale\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "gpud_tpu.tools.gen_catalog_doc", "--check"],
        capture_output=True,
        text=True,
        cwd=str(work),
        env=env,
        timeout=120,
    )
    assert res.returncode == 1
    assert "out of date" in res.stderr
