"""Accelerator-type → topology parsing matrix (reference analog:
pkg/nvidia/product name→capability mapping tests). The table pins the
public facts the whole daemon keys off: chips-per-host, ICI link counts,
HBM capacities, host counts — a regression here silently mis-sizes every
expectation downstream (chip-counts, ICI baseline, HBM totals)."""

import pytest

from gpud_tpu.tpu.topology import (
    GENERATIONS,
    expected_local_chips,
    normalize_generation,
    parse_accelerator_type,
)

_GiB = 1024**3

# (accel_type, gen, total_chips, total_cores, hosts, chips_per_host,
#  links_per_chip, hbm_per_chip)
MATRIX = [
    # suffix counts TensorCores (v2/v3/v4/v5p)
    ("v2-8",    "v2",  4,   8,   1,  4, 4,  8 * _GiB),
    ("v3-32",   "v3",  16,  32,  4,  4, 4,  16 * _GiB),
    ("v4-8",    "v4",  4,   8,   1,  4, 6,  32 * _GiB),
    ("v4-32",   "v4",  16,  32,  4,  4, 6,  32 * _GiB),
    ("v4-4096", "v4",  2048, 4096, 512, 4, 6, 32 * _GiB),
    ("v5p-8",   "v5p", 4,   8,   1,  4, 6,  95 * _GiB),
    ("v5p-256", "v5p", 128, 256, 32, 4, 6,  95 * _GiB),
    # suffix counts chips (v5e/v6e)
    ("v5e-1",   "v5e", 1,   1,   1,  1, 4,  16 * _GiB),
    ("v5e-4",   "v5e", 4,   4,   1,  4, 4,  16 * _GiB),
    ("v5e-8",   "v5e", 8,   8,   1,  8, 4,  16 * _GiB),
    ("v5e-64",  "v5e", 64,  64,  8,  8, 4,  16 * _GiB),
    ("v5e-256", "v5e", 256, 256, 32, 8, 4,  16 * _GiB),
    ("v6e-8",   "v6e", 8,   8,   1,  8, 4,  32 * _GiB),
    ("v6e-256", "v6e", 256, 256, 32, 8, 4,  32 * _GiB),
    # alias spelling
    ("v5litepod-16", "v5e", 16, 16, 2, 8, 4, 16 * _GiB),
]


@pytest.mark.parametrize("accel,gen,chips,cores,hosts,cph,links,hbm", MATRIX)
def test_topology_matrix(accel, gen, chips, cores, hosts, cph, links, hbm):
    t = parse_accelerator_type(accel)
    assert t is not None, accel
    assert t.generation == gen
    assert t.total_chips == chips
    assert t.total_cores == cores
    assert t.hosts == hosts
    assert t.chips_per_host == cph
    assert t.ici_links_per_chip == links
    assert t.hbm_bytes_per_chip == hbm
    assert t.multi_host == (hosts > 1)
    assert expected_local_chips(accel) == cph


@pytest.mark.parametrize(
    "bad",
    ["", "v7-8", "tpu", "v5p", "v5p-", "-8", "v5p-abc", "8-v5p", "gpu-8",
     "v5p_8", "v5p-0x8"],
)
def test_unparseable_types_return_none(bad):
    assert parse_accelerator_type(bad) is None
    assert expected_local_chips(bad) == 0


def test_case_and_whitespace_tolerance():
    t = parse_accelerator_type("  V5P-256  ")
    assert t is not None and t.generation == "v5p"


@pytest.mark.parametrize(
    "alias,gen",
    [
        ("TPU v4", "v4"),
        ("TPU v5 lite", "v5e"),
        ("TPU v5 lite0", "v5e"),   # jax device_kind with trailing digit
        ("tpu v5p", "v5p"),
        ("TPU v6 lite", "v6e"),
        ("v5litepod", "v5e"),
        ("v5e", "v5e"),
        ("unknown thing", "unknown thing"),  # passthrough, not a crash
    ],
)
def test_generation_aliases(alias, gen):
    assert normalize_generation(alias) == gen


def test_generation_table_invariants():
    for name, spec in GENERATIONS.items():
        assert spec.name == name
        assert spec.cores_per_chip in (1, 2)
        assert spec.chips_per_host in (4, 8)
        # 3D-torus generations expose 6 links, 2D expose 4
        assert spec.ici_links_per_chip in (4, 6)
        assert spec.hbm_bytes_per_chip >= 8 * _GiB
        # suffix-counts-chips implies single-core chips (v5e/v6e)
        if spec.suffix_counts_chips:
            assert spec.cores_per_chip == 1
