"""Adaptive sysfs-ICI polling (round-2 verdict, item #7): suspicion —
a fabric-class kmsg match via the ~ms inotify path, or a sample delta —
opens a bounded fast-poll window; steady state stays on the 60s cadence."""

import time

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.components.tpu.ici import (
    DEFAULT_FAST_POLL_INTERVAL,
    DEFAULT_SUSPICION_WINDOW,
    TPUICIComponent,
)
from gpud_tpu.eventstore import EventStore
from gpud_tpu.tpu.instance import ICILinkSnapshot, LinkState, MockBackend


def _component(tmp_db, clock):
    inst = TpudInstance(
        tpu_instance=MockBackend(accelerator_type="v5e-4"),
        db_rw=tmp_db,
        event_store=EventStore(tmp_db),
    )
    c = TPUICIComponent(inst)
    c.time_now_fn = lambda: clock[0]
    if c.store is not None:
        c.store.time_now_fn = lambda: clock[0]
    c.sampler.ttl = 0.0
    return c, inst


def test_steady_state_uses_production_cadence(tmp_db):
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    assert c.poll_interval() == c.POLL_INTERVAL == 60.0


def test_suspicion_opens_fast_window_and_decays(tmp_db):
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    c.raise_suspicion("tpu_ici_link_down")
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL
    clock[0] += DEFAULT_SUSPICION_WINDOW - 1
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL
    clock[0] += 2  # window expired with no further deltas → decay
    assert c.poll_interval() == c.POLL_INTERVAL


def test_sample_delta_extends_window(tmp_db):
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    c.check_once()  # baseline sample
    assert c.poll_interval() == c.POLL_INTERVAL  # first sample: no delta
    # a link goes down between polls → the next check flags the delta
    c.tpu._down_links.add("chip1/ici2")
    clock[0] += 60
    r = c.check_once()
    assert r.health == HealthStateType.UNHEALTHY
    assert r.extra_info["poll_mode"] == "fast"  # window opened on this poll
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL
    # still down but no NEW delta: window expires, cadence decays while
    # the sticky unhealthy state persists
    clock[0] += DEFAULT_SUSPICION_WINDOW + 1
    r2 = c.check_once()
    assert r2.health == HealthStateType.UNHEALTHY
    assert c.poll_interval() == c.POLL_INTERVAL


def test_counter_step_is_suspicious(tmp_db):
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)

    links = [ICILinkSnapshot(chip_id=0, link_id=0, state=LinkState.UP)]
    c.sampler.ici_links = lambda: [
        ICILinkSnapshot(
            chip_id=0, link_id=0, state=LinkState.UP, crc_errors=links[0].crc_errors
        )
    ]
    c.check_once()
    assert c.poll_interval() == c.POLL_INTERVAL
    links[0].crc_errors += 5
    clock[0] += 60
    c.check_once()
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL


def test_fabric_kmsg_listener_wiring(tmp_db):
    clock = [1000.0]
    c, inst = _component(tmp_db, clock)
    assert c._on_fabric_kmsg in inst.fabric_suspicion_listeners
    for listener in inst.fabric_suspicion_listeners:
        listener("tpu_ici_link_down")
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL


def test_non_fabric_kmsg_does_not_trigger(tmp_db):
    clock = [1000.0]
    c, inst = _component(tmp_db, clock)
    for listener in inst.fabric_suspicion_listeners:
        listener("tpu_hbm_ecc_uncorrectable")
    assert c.poll_interval() == c.POLL_INTERVAL


def test_error_kmsg_event_opens_ici_fast_window(tmp_db):
    """End-to-end wiring: an ICI-class event recorded by the error-kmsg
    component opens the ICI component's fast window through the shared
    TpudInstance listener list."""
    from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent

    clock = [1000.0]
    c, inst = _component(tmp_db, clock)
    ek = TPUErrorKmsgComponent(inst)
    ek._on_event(
        Event(
            component=ek.NAME,
            name="tpu_ici_link_down",
            type=EventType.CRITICAL,
            message="ICI link 3 down on chip 1",
        )
    )
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL


def test_counter_step_retrigger_respects_cooldown(tmp_db):
    """A continuously rising counter opens ONE window per cooldown — it
    must not hold the poller at (or near) 1 Hz indefinitely."""
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    crc = [0]
    c.sampler.ici_links = lambda: [
        ICILinkSnapshot(chip_id=0, link_id=0, state=LinkState.UP, crc_errors=crc[0])
    ]
    c.check_once()  # baseline
    crc[0] += 1
    clock[0] += 60
    c.check_once()
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL  # window opened
    # window expires; counter keeps rising at every steady poll — within
    # the cooldown no new window opens
    clock[0] += DEFAULT_SUSPICION_WINDOW + 1
    crc[0] += 1
    c.check_once()
    assert c.poll_interval() == c.POLL_INTERVAL
    # after the cooldown the trigger re-arms
    clock[0] += c.counter_retrigger_cooldown + 1
    crc[0] += 1
    c.check_once()
    assert c.poll_interval() == DEFAULT_FAST_POLL_INTERVAL


def test_fast_polls_throttle_store_writes(tmp_db):
    """1 Hz fast polls must not insert a history row per poll — steady
    60s granularity plus one immediate row per delta."""
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    c.check_once()  # baseline insert (first poll always writes)
    c.raise_suspicion("tpu_ici_link_down")
    rows0 = tmp_db.query("SELECT COUNT(*) FROM tpud_ici_snapshots_v0_1")[0][0]
    for _ in range(10):  # ten fast polls, nothing changing
        clock[0] += 1
        c.check_once()
    rows1 = tmp_db.query("SELECT COUNT(*) FROM tpud_ici_snapshots_v0_1")[0][0]
    assert rows1 == rows0  # no per-fast-poll inserts
    clock[0] += 60  # steady cadence elapsed → one more row
    c.check_once()
    rows2 = tmp_db.query("SELECT COUNT(*) FROM tpud_ici_snapshots_v0_1")[0][0]
    assert rows2 > rows1


def test_noisy_counter_fast_polls_do_not_write_per_poll(tmp_db):
    """A counter rising on EVERY fast poll (noisy link) must not turn the
    fast window into 1 Hz inserts + scans — only state transitions earn
    an off-cadence row."""
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    crc = [0]
    c.sampler.ici_links = lambda: [
        ICILinkSnapshot(chip_id=0, link_id=0, state=LinkState.UP, crc_errors=crc[0])
    ]
    c.check_once()  # baseline insert
    crc[0] += 1
    clock[0] += 60
    c.check_once()  # opens the window (also inserts: steady cadence hit)
    rows0 = tmp_db.query("SELECT COUNT(*) FROM tpud_ici_snapshots_v0_1")[0][0]
    for _ in range(10):  # counter keeps stepping during fast polls
        crc[0] += 1
        clock[0] += 1
        c.check_once()
    rows1 = tmp_db.query("SELECT COUNT(*) FROM tpud_ici_snapshots_v0_1")[0][0]
    assert rows1 == rows0


def test_set_healthy_invalidates_cached_scan(tmp_db):
    """set_healthy tombstones history; the cached window scan must not
    keep the sticky flap alive past the operator clear."""
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    c.check_once()
    # drop + recover = flap (sticky)
    c.tpu._down_links.add("chip0/ici0")
    clock[0] += 60
    c.check_once()
    c.tpu._down_links.clear()
    clock[0] += 60
    r = c.check_once()
    assert r.health != HealthStateType.HEALTHY  # sticky flap
    c.set_healthy()
    assert c.last_health_states()[0].health == HealthStateType.HEALTHY


def test_close_removes_fabric_listener(tmp_db):
    clock = [1000.0]
    c, inst = _component(tmp_db, clock)
    assert c._on_fabric_kmsg in inst.fabric_suspicion_listeners
    c.close()
    assert c._on_fabric_kmsg not in inst.fabric_suspicion_listeners


def test_poke_wakes_poller_immediately(tmp_db):
    """raise_suspicion must not wait out a sleeping 60s poller."""
    clock = [1000.0]
    c, _ = _component(tmp_db, clock)
    c.time_now_fn = time.time  # real clock for the live poller
    checks = []
    orig = c.check_once

    def counted():
        checks.append(time.time())
        return orig()

    c.check_once = counted
    c.start()
    try:
        deadline = time.time() + 5
        while not checks and time.time() < deadline:
            time.sleep(0.01)
        n0 = len(checks)
        c.raise_suspicion("tpu_ici_link_down")
        deadline = time.time() + 3
        while len(checks) <= n0 and time.time() < deadline:
            time.sleep(0.01)
        assert len(checks) > n0, "poke did not wake the sleeping poller"
    finally:
        c.close()
