import pytest

from gpud_tpu.api.v1.types import EventType
from gpud_tpu.components.tpu import catalog


def test_match_driver_lines():
    cases = {
        "accel0: device lost after reset": "tpu_chip_lost",
        "google_tpu: request timeout on queue 3": "tpu_driver_timeout",
        "accel accel1: firmware crash detected": "tpu_driver_crash",
        "uncorrectable HBM ECC error on channel 2": "tpu_hbm_ecc_uncorrectable",
        "HBM correctable ecc count=3": "tpu_hbm_ecc_correctable",
        "ICI link 4 down on chip 2": "tpu_ici_link_down",
        "ICI port 1 retrain complete": "tpu_ici_link_flap",
        "pcieport 0000:00:05.0: AER: uncorrectable error": "tpu_pcie_uncorrectable",
        "libtpu.so: fatal: check failure in tpu_program": "tpu_runtime_fatal",
        "megascale: DCN transport error to peer 12": "tpu_megascale_dcn_error",
        "TPU thermal trip: chip 0 at 104C": "tpu_thermal_trip",
    }
    for line, want in cases.items():
        m = catalog.match(line)
        assert m is not None, line
        assert m.entry.name == want, line


def test_no_match_on_ordinary_lines():
    for line in (
        "systemd[1]: Started Daily apt upgrade.",
        "EXT4-fs (sda1): mounted filesystem",
        "audit: type=1400 apparmor",
    ):
        assert catalog.match(line) is None, line


def test_chip_id_extraction():
    m = catalog.match("ICI link 4 down on chip 2")
    assert m.chip_id == 2
    m = catalog.match("accel3: device lost")
    assert m.chip_id == 3
    m = catalog.match("uncorrectable HBM ECC error")
    assert m.chip_id is None


def test_injection_line_roundtrip():
    for entry in catalog.CATALOG:
        line = catalog.injection_line(entry.name, chip_id=5)
        m = catalog.match(line)
        assert m is not None, entry.name
        assert m.entry.name == entry.name, f"{entry.name} matched {m.entry.name}"
        assert m.chip_id == 5


def test_injection_unknown_name():
    with pytest.raises(KeyError):
        catalog.injection_line("nope")


def test_catalog_integrity():
    names = [e.name for e in catalog.CATALOG]
    assert len(names) == len(set(names))
    codes = [e.code for e in catalog.CATALOG]
    assert len(codes) == len(set(codes))
    for e in catalog.CATALOG:
        assert e.event_type in (
            EventType.INFO, EventType.WARNING, EventType.CRITICAL, EventType.FATAL
        )
        assert catalog.lookup(e.name) is e
        assert catalog.lookup_code(e.code) is e
