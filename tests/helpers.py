"""Shared plain-module test helpers.

Import from here (``from tests.helpers import ...``), never from
``tests.conftest`` — importing conftest under a second module name
re-runs its module-level environment setup (and would double-start the
TPUD_COV line collector but for cov.py's ownership guard).
"""

import os


def write_pstore_dump(dir_path, name, content, mtime=None):
    """Stage a pstore crash-dump fixture (shared by the pstore suites)."""
    p = dir_path / name
    p.write_text(content)
    if mtime is not None:
        os.utime(str(p), (mtime, mtime))
    return str(p)
