"""Shared plain-module test helpers.

Import from here (``from tests.helpers import ...``), never from
``tests.conftest`` — importing conftest under a second module name
re-runs its module-level environment setup (and would double-start the
TPUD_COV line collector but for cov.py's ownership guard).
"""

import os


def keypair(common_name: str):
    """Self-signed EC cert (fast) with a marker burned into the CN —
    passes kapmtls's readiness probe (shared by the kapmtls suites)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM).decode()
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    return cert_pem, key_pem


def write_pstore_dump(dir_path, name, content, mtime=None):
    """Stage a pstore crash-dump fixture (shared by the pstore suites)."""
    p = dir_path / name
    p.write_text(content)
    if mtime is not None:
        os.utime(str(p), (mtime, mtime))
    return str(p)
