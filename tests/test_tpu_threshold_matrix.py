"""TPU accelerator-component threshold matrices (reference style:
temperature/component_test.go tables over margin/threshold combos).
The mock backend's telemetry is shaped per-case via a stub sampler so
every health transition edge is pinned exactly."""

import pytest

from gpud_tpu.api.v1.types import HealthStateType, RepairActionType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.components.tpu.hbm import TPUHbmComponent
from gpud_tpu.components.tpu.temperature import (
    DEFAULT_DEGRADED_C,
    DEFAULT_UNHEALTHY_C,
    TPUTemperatureComponent,
)
from gpud_tpu.eventstore import EventStore
from gpud_tpu.tpu.instance import MockBackend, TPUChipTelemetry


def _tel(per_chip):
    """{cid: dict-of-fields} → telemetry mapping."""
    out = {}
    for cid, fields in per_chip.items():
        t = TPUChipTelemetry(chip_id=cid, hbm_total_bytes=16 << 30)
        for k, v in fields.items():
            setattr(t, k, v)
        out[cid] = t
    return out


def _temp_component(tel):
    c = TPUTemperatureComponent(TpudInstance(tpu_instance=MockBackend()))
    c.sampler.telemetry = lambda: tel
    return c


# -- temperature ------------------------------------------------------------

TEMP_MATRIX = [
    # (worst_temp, slowdown, expected_health)
    (45.0, False, HealthStateType.HEALTHY),
    (DEFAULT_DEGRADED_C - 0.1, False, HealthStateType.HEALTHY),
    (DEFAULT_DEGRADED_C, False, HealthStateType.DEGRADED),       # at threshold
    (DEFAULT_UNHEALTHY_C - 0.1, False, HealthStateType.DEGRADED),
    (DEFAULT_UNHEALTHY_C, False, HealthStateType.UNHEALTHY),     # at threshold
    (60.0, True, HealthStateType.UNHEALTHY),  # slowdown flag outranks temp
]


@pytest.mark.parametrize("worst,slowdown,expected", TEMP_MATRIX)
def test_temperature_threshold_matrix(worst, slowdown, expected):
    tel = _tel(
        {0: {"temperature_c": 40.0}, 1: {"temperature_c": worst,
                                         "thermal_slowdown": slowdown}}
    )
    r = _temp_component(tel).check_once()
    assert r.health == expected, (worst, slowdown, r.reason)
    if expected == HealthStateType.UNHEALTHY:
        assert "1" in r.reason  # the culprit chip is named
        assert RepairActionType.HARDWARE_INSPECTION in (
            r.suggested_actions.repair_actions
        )


def test_temperature_threshold_overrides():
    tel = _tel({0: {"temperature_c": 70.0}})
    c = _temp_component(tel)
    c.degraded_c, c.unhealthy_c = 60.0, 69.0  # operator lowered thresholds
    r = c.check_once()
    assert r.health == HealthStateType.UNHEALTHY


def test_temperature_extra_info_per_chip():
    tel = _tel({0: {"temperature_c": 41.5}, 3: {"temperature_c": 44.25}})
    r = _temp_component(tel).check_once()
    assert r.extra_info["chip0_temp_c"] == "41.5"
    assert r.extra_info["chip3_temp_c"] == "44.2"  # .1f formatting


# -- HBM ECC ----------------------------------------------------------------

def _hbm_component(tel, db=None):
    inst = TpudInstance(
        tpu_instance=MockBackend(),
        db_rw=db,
        event_store=EventStore(db) if db is not None else None,
    )
    c = TPUHbmComponent(inst)
    c.sampler.telemetry = lambda: tel
    return c


def test_hbm_pending_flag_alone_is_unhealthy():
    tel = _tel({0: {"hbm_ecc_pending": True}})
    r = _hbm_component(tel).check_once()
    assert r.health == HealthStateType.UNHEALTHY
    assert RepairActionType.REBOOT_SYSTEM in r.suggested_actions.repair_actions


def test_hbm_uncorrectable_count_alone_is_unhealthy():
    tel = _tel({2: {"hbm_ecc_uncorrectable": 1}})
    r = _hbm_component(tel).check_once()
    assert r.health == HealthStateType.UNHEALTHY
    assert "2" in r.reason


def test_hbm_correctable_only_stays_healthy():
    tel = _tel({0: {"hbm_ecc_correctable": 500}})
    r = _hbm_component(tel).check_once()
    assert r.health == HealthStateType.HEALTHY


def test_hbm_event_recorded_once_while_pending(tmp_db):
    tel = _tel({1: {"hbm_ecc_pending": True}})
    c = _hbm_component(tel, db=tmp_db)
    c.check_once()
    c.check_once()  # still pending: must not insert a duplicate event
    evs = [e for e in c.events(0) if e.name == "hbm_ecc_uncorrectable"]
    assert len(evs) == 1
    assert "chip(s) [1]" in evs[0].message


def test_hbm_usage_pct_reported():
    tel = _tel({0: {"hbm_used_bytes": 8 << 30}})
    r = _hbm_component(tel).check_once()
    assert r.extra_info["chip0_hbm_used_pct"] == "50.0"


def test_hbm_zero_total_no_division():
    t = TPUChipTelemetry(chip_id=0, hbm_total_bytes=0, hbm_used_bytes=0)
    r = _hbm_component({0: t}).check_once()
    assert r.health == HealthStateType.HEALTHY
    assert "chip0_hbm_used_pct" not in r.extra_info
