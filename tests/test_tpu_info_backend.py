"""tpu-info CLI backend parsing against fixture outputs (the binary is
mocked the way the reference mocks lspci, e2e/mock/common.go:16-31)."""

from gpud_tpu.process import RunResult
from gpud_tpu.tpu.instance import LinkState
from gpud_tpu.tpu.tpu_info_backend import TpuInfoBackend

# a representative v4-8 single-host output (tolerant parser: the exact
# frame characters don't matter, only the stable tokens)
FIXTURE_V4 = """\
TPU Chips
┌─────────────┬─────────┬─────────┬──────┐
│ Chip        │ Type    │ Devices │ PID  │
├─────────────┼─────────┼─────────┼──────┤
│ /dev/accel0 │ v4 chip │ 1       │ 1001 │
│ /dev/accel1 │ v4 chip │ 1       │ 1001 │
│ /dev/accel2 │ v4 chip │ 1       │ 1001 │
│ /dev/accel3 │ v4 chip │ 1       │ 1001 │
└─────────────┴─────────┴─────────┴──────┘
TPU Runtime Utilization
┌────────┬───────────────────┬────────────┐
│ Device │ Memory usage      │ Duty cycle │
├────────┼───────────────────┼────────────┤
│ 0      │ 1.25 GiB / 30.75 GiB │  12.50% │
│ 1      │ 2.50 GiB / 30.75 GiB │  99.00% │
│ 2      │ 0.00 GiB / 30.75 GiB │   0.00% │
│ 3      │ 3.75 GiB / 30.75 GiB │  45.25% │
└────────┴───────────────────┴────────────┘
"""

FIXTURE_EMPTY = "TPU Chips\n(no devices found)\n"


def _runner(output, exit_code=0):
    def run(args):
        return RunResult(exit_code=exit_code, output=output)

    return run


def test_enumerates_chips_and_infers_type():
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_V4))
    assert b.tpu_lib_exists()
    devs = b.devices()
    assert sorted(devs) == [0, 1, 2, 3]
    assert devs[0].device_path == "/dev/accel0"
    assert devs[0].generation == "v4"
    assert b.accelerator_type() == "v4-8"  # 4 chips × 2 cores
    assert b.generation() == "v4"


def test_parses_telemetry():
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_V4))
    tel = b.telemetry()
    assert len(tel) == 4
    assert abs(tel[0].hbm_used_bytes / (1 << 30) - 1.25) < 0.01
    assert abs(tel[0].hbm_total_bytes / (1 << 30) - 30.75) < 0.01
    assert tel[1].duty_cycle_pct == 99.0
    assert tel[2].duty_cycle_pct == 0.0


def test_no_chips_is_init_error():
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_EMPTY))
    assert not b.tpu_lib_exists()
    assert "no chips parsed" in b.init_error()


def test_binary_failure_is_init_error():
    b = TpuInfoBackend(run_fn=_runner("boom", exit_code=127))
    assert not b.tpu_lib_exists()
    assert b.init_error()


def test_telemetry_failure_degrades():
    calls = {"n": 0}

    def flaky(args):
        calls["n"] += 1
        if calls["n"] == 1:
            return RunResult(exit_code=0, output=FIXTURE_V4)
        return RunResult(exit_code=1, error="transient")

    b = TpuInfoBackend(run_fn=flaky)
    assert b.tpu_lib_exists()
    assert b.telemetry() == {}  # degraded, not raising


def test_subset_table_keys_by_device_index():
    fix = (
        "TPU Chips\n"
        "| /dev/accel0 | v4 chip | 1 | 1 |\n"
        "| /dev/accel1 | v4 chip | 1 | 1 |\n"
        "| /dev/accel2 | v4 chip | 1 | 1 |\n"
        "TPU Runtime Utilization\n"
        "| 2 | 5.00 GiB / 30.75 GiB | 70.00% |\n"
        "| 1 | 1.00 GiB / 30.75 GiB | 10.00% |\n"
    )
    b = TpuInfoBackend(run_fn=_runner(fix))
    tel = b.telemetry()
    assert tel[2].duty_cycle_pct == 70.0
    assert tel[1].duty_cycle_pct == 10.0
    assert tel[0].hbm_used_bytes == 0  # chip absent from the table


def test_explicit_accelerator_type_wins():
    b = TpuInfoBackend(accelerator_type="v4-16", run_fn=_runner(FIXTURE_V4))
    assert b.accelerator_type() == "v4-16"
    assert b.topology().hosts == 2


def test_tpu_info_backend_ici_via_sysfs(tmp_path, monkeypatch):
    """ICI links ride the shared sysfs exposure even when chips were
    enumerated via the CLI (the CLI prints no per-link state)."""
    from gpud_tpu.tpu.instance import LinkState

    root = tmp_path / "ici"
    for c in range(2):
        for l in range(2):
            d = root / f"chip{c}" / f"ici{l}"
            d.mkdir(parents=True)
            (d / "state").write_text("down" if (c, l) == (1, 0) else "up")
            (d / "crc_errors").write_text("7")
    monkeypatch.setenv("TPUD_ICI_SYSFS_ROOT", str(root))
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_V4))
    assert b.ici_supported()
    links = {x.name: x for x in b.ici_links()}
    assert len(links) == 4
    assert links["chip1/ici0"].state == LinkState.DOWN
    assert links["chip0/ici0"].crc_errors == 7


def test_tpu_info_backend_derived_ici_without_root(monkeypatch):
    # without a mapped per-link layout the stock default applies: the
    # link inventory is derived from the slice topology, all up (chips
    # the CLI lists are live by construction)
    monkeypatch.delenv("TPUD_ICI_SYSFS_ROOT", raising=False)
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_V4))
    assert b.ici_supported()
    assert b.ici_source() == "derived-topology"
    links = b.ici_links()
    topo = b.topology()
    assert topo is not None
    assert len(links) == len(b.devices()) * topo.ici_links_per_chip
    assert all(ln.state == LinkState.UP for ln in links)


def test_tpu_info_backend_no_topology_no_derived_ici(monkeypatch):
    # unknown generation → no inventory can be derived → unsupported
    monkeypatch.delenv("TPUD_ICI_SYSFS_ROOT", raising=False)
    b = TpuInfoBackend(run_fn=_runner(FIXTURE_V4))
    b._accel_type = ""
    assert not b.ici_supported()
    assert b.ici_links() == []


def test_telemetry_row_order_fallback_when_no_device_index():
    """Rows whose head carries no parseable device index fall back to
    enumeration order; rows with no percent columns keep zeros."""
    from gpud_tpu.tpu.tpu_info_backend import TpuInfoBackend

    fixture = """\
TPU Chips
/dev/accel0  TPU v4 chip  0
/dev/accel1  TPU v4 chip  1

HBM Usage
x: 1.00 GiB / 30.75 GiB
y: 2.00 GiB / 30.75 GiB
"""
    b = TpuInfoBackend(run_fn=_runner(fixture))
    tel = b.telemetry()
    assert abs(tel[0].hbm_used_bytes / (1 << 30) - 1.00) < 0.01
    assert abs(tel[1].hbm_used_bytes / (1 << 30) - 2.00) < 0.01
    assert tel[0].duty_cycle_pct == 0.0  # no percent column on the row


def test_telemetry_extra_rows_beyond_chip_count_ignored():
    from gpud_tpu.tpu.tpu_info_backend import TpuInfoBackend

    fixture = """\
TPU Chips
/dev/accel0  TPU v4 chip  0

HBM Usage
a: 1.00 GiB / 30.75 GiB
b: 2.00 GiB / 30.75 GiB
c: 3.00 GiB / 30.75 GiB
"""
    b = TpuInfoBackend(run_fn=_runner(fixture))
    tel = b.telemetry()
    assert list(tel) == [0]
    assert abs(tel[0].hbm_used_bytes / (1 << 30) - 1.00) < 0.01
