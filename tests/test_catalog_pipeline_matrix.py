"""Per-entry catalog → component pipeline matrix.

test_catalog_organic proves every entry's REGEX matches its organic
driver line; this suite proves the whole per-entry PIPELINE behaves:
scan mode (kmsg ring → catalog → evolve_health → CheckResult) and daemon
mode (Syncer → EventStore → health evaluation) both surface each entry
with the severity, repair action and event type its catalog row
declares. This mirrors the reference's per-XID component tests, which
drive each code through component state rather than only the matcher
(reference: components/accelerator/nvidia/xid/component_test.go — every
code asserted through States(), not just the regex table).
"""

import pytest

from gpud_tpu.api.v1.types import EventType, HealthStateType, RepairActionType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.components.tpu import catalog
from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent
from gpud_tpu.eventstore import EventStore
from gpud_tpu.kmsg.watcher import Message

from tests.test_catalog_organic import ORGANIC

ENTRY_NAMES = sorted(e.name for e in catalog.CATALOG)


def _organic_line(name: str) -> str:
    lines = ORGANIC.get(name)
    assert lines, f"no organic corpus line for catalog entry {name}"
    return lines[0]


def _scan_component(tmp_path, monkeypatch, lines) -> TPUErrorKmsgComponent:
    """Scan-mode component (no event store) over a fixture ring buffer."""
    fixture = tmp_path / "kmsg.fixture"
    fixture.write_text(
        "".join(
            f"2,{200 + i},{100_000_000 + i * 1000},-;{line}\n"
            for i, line in enumerate(lines)
        )
    )
    monkeypatch.setenv("TPUD_KMSG_FILE_PATH", str(fixture))
    return TPUErrorKmsgComponent(TpudInstance())


@pytest.mark.parametrize("name", ENTRY_NAMES)
def test_scan_mode_surfaces_entry_with_declared_severity(
    name, tmp_path, monkeypatch
):
    """One organic line in the ring → check_once reports the entry by
    name, with health driven by the entry's `critical` flag and the
    entry's repair actions plumbed into suggested_actions."""
    entry = catalog.lookup(name)
    c = _scan_component(tmp_path, monkeypatch, [_organic_line(name)])
    r = c.check_once()
    assert name in r.reason, (name, r.reason)
    if entry.critical:
        assert r.health == HealthStateType.UNHEALTHY, (name, r.health)
    else:
        # "non-critical errors never push past Degraded"
        assert r.health != HealthStateType.UNHEALTHY, (name, r.health)
    wanted = [
        a for a in entry.repair_actions
        if a != RepairActionType.IGNORE_NO_ACTION_REQUIRED
    ]
    if wanted:
        assert r.suggested_actions is not None, name
        got = r.suggested_actions.repair_actions
        for act in wanted:
            assert act in got, (name, act, got)


@pytest.mark.parametrize("name", ENTRY_NAMES)
def test_daemon_mode_persists_entry_event(name, tmp_db):
    """The daemon path: Syncer matches the organic line, persists an
    Event carrying the entry's name/type plus the raw kmsg line, and the
    component's event-sourced health sees it."""
    es = EventStore(tmp_db)
    inst = TpudInstance(event_store=es)
    c = TPUErrorKmsgComponent(inst)
    entry = catalog.lookup(name)
    msg = Message(
        priority=2,
        sequence=1,
        timestamp_us=1_000_000,
        message=_organic_line(name),
        time=1_700_000_000.0,
    )
    ev = c.syncer.process(msg)
    assert ev is not None, (name, msg.message)
    assert ev.name == name
    assert ev.type == entry.event_type
    assert ev.extra_info["kmsg"] == msg.message
    # persisted (Find-before-Insert restart contract)
    stored = c.events(since=0)
    assert [e.name for e in stored] == [name]
    # the ticker-driven evaluation path sees the persisted event
    c.time_now_fn = lambda: 1_700_000_100.0
    r = c.check_once()
    assert name in r.reason
    if entry.critical:
        assert r.health == HealthStateType.UNHEALTHY


@pytest.mark.parametrize("name", ENTRY_NAMES)
def test_daemon_mode_dedupes_identical_line(name, tmp_db):
    """Two dedupe layers, asserted per entry: the same line within the
    same second is dropped by the deduper, and a ring RE-READ after a
    restart (fresh deduper, identical message+time) is dropped by the
    store's Find-before-Insert (reference: xid/component.go:545-570)."""
    es = EventStore(tmp_db)
    c = TPUErrorKmsgComponent(TpudInstance(event_store=es))
    line = _organic_line(name)
    msg = Message(
        priority=2,
        sequence=1,
        timestamp_us=1_000_000,
        message=line,
        time=1_700_000_000.0,
    )
    assert c.syncer.process(msg) is not None
    # same line, same second: deduper drops it
    assert c.syncer.process(msg) is None, name
    assert len(c.events(since=0)) == 1, name
    # daemon restart: a new component re-reads the same ring; the fresh
    # deduper lets the line through but the store refuses the duplicate
    c2 = TPUErrorKmsgComponent(TpudInstance(event_store=EventStore(tmp_db)))
    c2.syncer.process(msg)
    assert len(c2.events(since=0)) == 1, name


@pytest.mark.parametrize("name", ENTRY_NAMES)
def test_injected_form_reaches_same_entry(name, tmp_path, monkeypatch):
    """The fault injector's canonical ``TPU-ERR:`` line for each entry
    must land on the SAME catalog entry as the organic kernel line —
    injection and organic detection share one path (SURVEY §4.7)."""
    line = catalog.injection_line(name, chip_id=3, detail="matrix")
    m = catalog.match(line)
    assert m is not None, (name, line)
    assert m.entry.name == name
    c = _scan_component(tmp_path, monkeypatch, [line])
    r = c.check_once()
    assert name in r.reason


def test_set_healthy_clears_every_entry(tmp_db):
    """SetHealthy wipes the slate regardless of which entry was active —
    one marker clears ALL accumulated error tracks (reference:
    xid/set_healthy.go semantics), exercised across the full catalog."""
    es = EventStore(tmp_db)
    c = TPUErrorKmsgComponent(TpudInstance(event_store=es))
    t = 1_700_000_000.0
    for i, name in enumerate(ENTRY_NAMES):
        c.syncer.process(
            Message(
                priority=2,
                sequence=i,
                timestamp_us=i * 1_000_000,
                message=_organic_line(name),
                time=t + i,
            )
        )
    c.time_now_fn = lambda: t + 10_000
    r = c.check_once()
    assert r.health == HealthStateType.UNHEALTHY
    c.set_healthy()
    r = c.check_once()
    assert r.health == HealthStateType.HEALTHY, r.reason


def test_full_catalog_scan_reports_all_criticals(tmp_path, monkeypatch):
    """Every entry's organic line in one ring buffer: the single scan
    reports every critical entry simultaneously (no first-error
    short-circuit) and health is Unhealthy."""
    lines = [_organic_line(n) for n in ENTRY_NAMES]
    c = _scan_component(tmp_path, monkeypatch, lines)
    r = c.check_once()
    assert r.health == HealthStateType.UNHEALTHY
    criticals = [e.name for e in catalog.CATALOG if e.critical]
    for name in criticals:
        assert name in r.reason, f"critical entry {name} missing from reason"


def test_event_types_match_catalog_rows():
    """Catalog rows declare Fatal/Critical/Warning/Info event types that
    the API layer understands — no entry can carry a type the event
    pipeline would refuse to serialize."""
    valid = {
        EventType.FATAL,
        EventType.CRITICAL,
        EventType.WARNING,
        EventType.INFO,
    }
    for e in catalog.CATALOG:
        assert e.event_type in valid, (e.name, e.event_type)
