"""Fleet-ranked predictive observability (manager/rollup.py predict leg).

Covers the predict→fleet loop contracts: ``predict_score`` ingest into
first-class per-(agent, component) aggregates, schema versioning
(newer-schema records journaled + counted, never applied), the ranked
``fleet_predict`` pane (top-K by decayed risk, deterministic for an
explicit ``now`` across any shard count), stale-score decay, windowed
link-degradation counters on the fabric pane, SIGKILL-mid-ingest
rebuild consistency, and the live ``GET /v1/fleet/predict`` route."""

import json
import os
import sqlite3
import subprocess
import sys
import time

import pytest

from gpud_tpu.manager.rollup import (
    DEFAULT_PREDICT_DECAY,
    MAX_PREDICT_PER_AGENT,
    PREDICT_SCHEMA_MAX,
    TABLE,
    FleetRollupStore,
)
from gpud_tpu.sqlite import DB
from gpud_tpu.storage.writer import BatchWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _transition(seq, ts, comp="c0", frm="Healthy", to="Unhealthy"):
    return (
        seq, ts, "transition", f"transition:{comp}:{ts}:{to}",
        {"component": comp, "from": frm, "to": to, "ts": ts, "reason": "x"},
    )


def _predict(seq, ts, comp="accelerator-tpu-0", event="snapshot",
             score=0.5, armed=False, schema=1, **extra):
    body = {
        "schema": schema,
        "component": comp,
        "component_class": "accelerator-tpu",
        "event": event,
        "ts": ts,
        "score": score,
        "threshold": 0.6,
        "features": {"cadence": score * 0.7},
        "armed": armed,
    }
    body.update(extra)
    return (
        seq, ts, "predict_score",
        f"predict:{comp}:{event}:{ts}", body,
    )


def _link(seq, ts, link="c0-c1/x", state="degraded"):
    return (
        seq, ts, "ici_link", f"ici_link:{link}:{ts}",
        {"link": link, "src_chip": 0, "dst_chip": 1, "axis": "x",
         "state": state, "deviation": 0.5, "ts": ts},
    )


@pytest.fixture()
def store(tmp_path):
    db = DB(str(tmp_path / "fleet.db"))
    writer = BatchWriter(db)
    st = FleetRollupStore(db, writer)
    yield st
    writer.close()
    db.close()


# -- predict_score ingest -------------------------------------------------

def test_predict_ingest_aggregates(store):
    t = 1000.0
    store.ingest("a1", [
        _predict(1, t, score=0.3),
        _predict(2, t + 10, event="warn", score=0.7, armed=True,
                 warned_at=t + 10),
        _predict(3, t + 20, event="lead", score=0.8, armed=True,
                 warned_at=t + 10, lead_seconds=10.0),
        _predict(4, t + 30, event="clear", score=0.1),
    ])
    snap = store.agent_snapshot("a1")
    pr = snap["predict"]["accelerator-tpu-0"]
    assert pr["warn_count"] == 1
    assert pr["clear_count"] == 1
    assert pr["snapshot_count"] == 1
    assert pr["lead"]["count"] == 1
    assert pr["lead"]["mean_seconds"] == 10.0
    assert pr["lead"]["p50_seconds"] == 10.0
    # latest-wins fields follow the newest record
    assert pr["last_event"] == "clear"
    assert pr["score"] == pytest.approx(0.1)
    assert not pr["armed"]
    assert pr["component_class"] == "accelerator-tpu"
    assert snap["records_by_kind"]["predict_score"] == 4


def test_predict_replay_is_idempotent(store):
    t = 1000.0
    recs = [_predict(1, t, event="warn", score=0.7, armed=True)]
    assert store.ingest("a1", recs) == 1
    assert store.ingest("a1", recs) == 0
    pr = store.agent_snapshot("a1")["predict"]["accelerator-tpu-0"]
    assert pr["warn_count"] == 1


def test_unknown_schema_counted_never_dropped(store):
    """A newer-schema record from a newer agent is journaled and
    surfaced as accounting — not applied, not silently dropped."""
    t = 1000.0
    store.ingest("a1", [
        _predict(1, t, score=0.4),
        _predict(2, t + 1, event="warn", score=1.0,
                 schema=PREDICT_SCHEMA_MAX + 1),
    ])
    assert store.journal_count() == 2  # both journaled
    snap = store.agent_snapshot("a1")
    assert snap["predict_unknown_schema"] == 1
    pr = snap["predict"]["accelerator-tpu-0"]
    assert pr["warn_count"] == 0  # the future-schema warn never applied
    assert pr["score"] == pytest.approx(0.4)
    pane = store.fleet_predict(now=t + 2)
    assert pane["unknown_schema_records"] == 1
    # records_total still counts it: counted, never dropped
    assert store.fleet_rollup()["records_total"] == 2


def test_predict_series_cap_truncates(store):
    t = 1000.0
    recs = [
        _predict(i + 1, t + i, comp=f"c{i}")
        for i in range(MAX_PREDICT_PER_AGENT + 5)
    ]
    store.ingest("a1", recs)
    snap = store.agent_snapshot("a1")
    assert len(snap["predict"]) == MAX_PREDICT_PER_AGENT
    pane = store.fleet_predict(now=t)
    assert pane["predict_truncated"] == 5


# -- ranking + decay ------------------------------------------------------

def _seed_fleet(store, t=1000.0):
    store.ingest("quiet-1", [_predict(1, t, score=0.05)])
    store.ingest("quiet-2", [_predict(1, t, score=0.1)])
    store.ingest("loud-1", [
        _predict(1, t, event="warn", score=0.8, armed=True, warned_at=t),
        _predict(2, t + 5, event="lead", score=0.85, armed=True,
                 warned_at=t, lead_seconds=5.0),
    ])
    return t


def test_fleet_predict_ranks_by_risk(store):
    t = _seed_fleet(store)
    pane = store.fleet_predict(top=2, now=t + 10)
    assert pane["series"] == 3
    assert pane["armed"] == 1
    assert pane["warns_total"] == 1
    assert pane["top_k"] == 2
    assert [r["agent"] for r in pane["top"]] == ["loud-1", "quiet-2"]
    assert pane["top"][0]["risk"] > pane["top"][1]["risk"]
    assert pane["lead"]["count"] == 1
    assert pane["lead"]["mean_seconds"] == 5.0
    # risk buckets partition every series
    assert sum(pane["risk_buckets"].values()) == 3


def test_stale_scores_decay(store):
    t = _seed_fleet(store)
    fresh = store.fleet_predict(now=t + 10)["top"][0]["risk"]
    stale = store.fleet_predict(
        now=t + 10 + 3 * DEFAULT_PREDICT_DECAY
    )["top"][0]["risk"]
    assert stale < fresh * 0.1  # three e-foldings down
    # decay is monotone: a dead agent keeps sinking
    deader = store.fleet_predict(
        now=t + 10 + 6 * DEFAULT_PREDICT_DECAY
    )["top"][0]["risk"]
    assert deader < stale


def test_explicit_now_bypasses_cache(store):
    t = _seed_fleet(store)
    a = store.fleet_predict(now=t + 1)
    b = store.fleet_predict(now=t + 1000)
    assert a["top"][0]["risk"] != b["top"][0]["risk"]


def test_top_clamping(store):
    _seed_fleet(store)
    assert store.fleet_predict(top=0, now=2000.0)["top_k"] == 1
    assert store.fleet_predict(top=10 ** 6, now=2000.0)["top_k"] == 500


@pytest.mark.parametrize("shards", [1, 2, 8])
def test_ranking_deterministic_across_shard_counts(tmp_path, shards):
    """The pane for a fixed ``now`` is byte-identical however the
    in-memory state is striped — ranking must be a pure function of
    the journal."""
    db = DB(str(tmp_path / "fleet.db"))
    writer = BatchWriter(db)
    st = FleetRollupStore(db, writer, shard_count=4)
    t = 1000.0
    for i in range(12):
        st.ingest(f"m-{i:02d}", [
            _predict(1, t + i, comp=f"accelerator-tpu-{i % 3}",
                     event="warn" if i % 4 == 0 else "snapshot",
                     score=(i * 7 % 10) / 10.0, armed=i % 4 == 0),
            _predict(2, t + i + 1, comp=f"accelerator-tpu-{i % 3}",
                     event="lead" if i % 4 == 0 else "snapshot",
                     score=(i * 3 % 10) / 10.0, lead_seconds=float(i)),
        ])
    writer.flush()
    baseline = st.fleet_predict(top=50, now=t + 100)
    baseline.pop("generation")
    for n in ([shards] if shards != 1 else [1]):
        re = FleetRollupStore(db, None, shard_count=n)
        pane = re.fleet_predict(top=50, now=t + 100)
        pane.pop("generation")
        assert json.dumps(pane, sort_keys=True) == json.dumps(
            baseline, sort_keys=True
        ), f"pane diverged at shard_count={n}"
    writer.close()
    db.close()


def test_agents_page_exposes_predict_risk(store):
    t = _seed_fleet(store)
    page = store.agents_page()
    by_agent = {a["agent"]: a for a in page["agents"]}
    assert by_agent["loud-1"]["predict_risk"] > 0.5
    assert by_agent["quiet-1"]["predict_risk"] < 0.3
    # anchored at the agent's own last_ts: a pure function of the
    # journal, so pagination stays rebuild-deterministic
    pr = by_agent["loud-1"]["predict"]["accelerator-tpu-0"]
    assert pr["age_seconds"] == 0.0


# -- windowed link history ------------------------------------------------

def test_link_degraded_windows(store):
    t = 1_000_000.0
    recs = []
    seq = 0
    # 3 in the last hour, 2 more within 24h, 1 more within 7d
    for dt in (30.0, 600.0, 3000.0, 7200.0, 50_000.0, 500_000.0):
        seq += 1
        recs.append(_link(seq, t - dt))
    seq += 1
    recs.append(_link(seq, t, state="up"))
    store.ingest("a1", recs)
    pane = store.fleet_fabric(now=t)
    (row,) = [
        l for l in pane["degraded"] if l["link"] == "c0-c1/x"
    ]
    assert row["degraded_windows"] == {"1h": 3, "24h": 5, "7d": 6}
    # the window anchor slides with now: an hour later the 1h bucket
    # drains but history is not lost
    pane2 = store.fleet_fabric(now=t + 3600.0)
    (row2,) = [
        l for l in pane2["degraded"] if l["link"] == "c0-c1/x"
    ]
    assert row2["degraded_windows"]["1h"] == 0
    assert row2["degraded_windows"]["7d"] == 6


def test_link_windows_rebuild_parity(tmp_path):
    db = DB(str(tmp_path / "fleet.db"))
    writer = BatchWriter(db)
    st = FleetRollupStore(db, writer)
    t = 1_000_000.0
    st.ingest("a1", [
        _link(i + 1, t - i * 4000.0) for i in range(10)
    ])
    writer.flush()
    before = st.fleet_fabric(now=t)
    before.pop("generation")
    for n in (1, 2, 8):
        re = FleetRollupStore(db, None, shard_count=n)
        after = re.fleet_fabric(now=t)
        after.pop("generation")
        assert json.dumps(after, sort_keys=True) == json.dumps(
            before, sort_keys=True
        ), f"fabric pane diverged at shard_count={n}"
    writer.close()
    db.close()


# -- crash consistency ----------------------------------------------------

def test_sigkill_mid_predict_ingest_rebuilds_consistently(tmp_path):
    """Hard-kill a writer streaming predict_score records: the journal
    may lose its last durability window, but the rebuilt predictive
    aggregates must agree exactly with the surviving rows."""
    db_path = str(tmp_path / "fleet.db")
    script = f"""
from gpud_tpu.manager.rollup import FleetRollupStore
from gpud_tpu.sqlite import DB
from gpud_tpu.storage.writer import BatchWriter
db = DB({db_path!r})
w = BatchWriter(db)
st = FleetRollupStore(db, w)
seq = 0
while True:
    seq += 1
    ts = 1000.0 + seq
    ev = "warn" if seq % 3 == 0 else "snapshot"
    st.ingest("a1", [(seq, ts, "predict_score",
                      f"predict:c0:{{ev}}:{{ts}}",
                      {{"schema": 1, "component": "c0", "event": ev,
                        "ts": ts, "score": 0.5, "armed": ev == "warn"}})])
    if seq % 50 == 0:
        w.flush()
    if seq == 100:
        print("primed", flush=True)
"""
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO,
    )
    try:
        line = proc.stdout.readline()
        assert "primed" in line, "writer subprocess never primed"
        time.sleep(0.2)
    finally:
        proc.kill()
        proc.wait(timeout=10)
    con = sqlite3.connect(db_path)
    try:
        (res,) = con.execute("PRAGMA integrity_check").fetchone()
        assert res == "ok", res
        (journaled,) = con.execute(
            f"SELECT COUNT(*) FROM {TABLE}"
        ).fetchone()
    finally:
        con.close()
    assert journaled >= 50
    db = DB(db_path)
    try:
        st = FleetRollupStore(db, None)
        assert st.fleet_rollup()["records_total"] == journaled
        pr = st.agent_snapshot("a1")["predict"]["c0"]
        # every journaled row applied exactly once: counters add up
        assert pr["warn_count"] == journaled // 3
        assert pr["snapshot_count"] == journaled - journaled // 3
        pane = st.fleet_predict(now=1000.0 + journaled)
        assert pane["series"] == 1
        assert pane["warns_total"] == journaled // 3
    finally:
        db.close()


# -- mixed-kind interplay -------------------------------------------------

def test_predict_rides_alongside_transitions(store):
    t = 1000.0
    store.ingest("a1", [
        _transition(1, t),
        _predict(2, t + 1, event="warn", score=0.7, armed=True),
        _transition(3, t + 2, frm="Unhealthy", to="Healthy"),
    ])
    roll = store.fleet_rollup()
    assert roll["records_total"] == 3
    assert roll["records_by_kind"]["predict_score"] == 1
    snap = store.agent_snapshot("a1")
    assert snap["components"]["c0"]["transitions"] == 2
    assert snap["predict"]["accelerator-tpu-0"]["warn_count"] == 1


def test_shard_stats_count_predict_series(store):
    _seed_fleet(store)
    stats = store.shard_stats()
    assert sum(s["predict_series"] for s in stats) == 3
    assert sum(s["predict_unknown_schema"] for s in stats) == 0


# -- live HTTP surface ----------------------------------------------------

@pytest.fixture(scope="module")
def predict_cp():
    requests = pytest.importorskip("requests")
    from gpud_tpu.manager.control_plane import ControlPlane

    cp = ControlPlane()
    cp.start()
    t = time.time()
    cp.rollup.ingest("pred-m1", [
        (1, t, "predict_score", f"predict:c0:warn:{t}",
         {"schema": 1, "component": "c0", "component_class": "c",
          "event": "warn", "ts": t, "score": 0.75, "armed": True,
          "warned_at": t}),
        (2, t + 1, "predict_score", f"predict:c0:lead:{t + 1}",
         {"schema": 1, "component": "c0", "component_class": "c",
          "event": "lead", "ts": t + 1, "score": 0.8, "armed": True,
          "warned_at": t, "lead_seconds": 42.0}),
    ])
    yield cp, requests
    cp.stop()


def test_http_fleet_predict(predict_cp):
    cp, requests = predict_cp
    pane = requests.get(
        f"{cp.endpoint}/v1/fleet/predict", timeout=10
    ).json()
    assert pane["series"] == 1
    assert pane["warns_total"] == 1
    assert pane["lead"]["count"] == 1
    assert pane["lead"]["mean_seconds"] == 42.0
    (row,) = pane["top"]
    assert row["agent"] == "pred-m1"
    assert row["component"] == "c0"
    assert row["armed"]
    assert row["risk"] > 0.5


def test_http_fleet_predict_top_param(predict_cp):
    cp, requests = predict_cp
    pane = requests.get(
        f"{cp.endpoint}/v1/fleet/predict?top=1", timeout=10
    ).json()
    assert pane["top_k"] == 1
    r = requests.get(
        f"{cp.endpoint}/v1/fleet/predict?top=zap", timeout=10
    )
    assert r.status_code == 400


def test_federated_metrics_include_predict(predict_cp):
    cp, requests = predict_cp
    body = requests.get(f"{cp.endpoint}/metrics", timeout=10).text
    assert "tpud_fleet_predict_armed_series 1" in body
    assert "tpud_fleet_predict_warns 1" in body
    assert "tpud_fleet_predict_lead_mean_seconds 42" in body
    assert 'tpud_fleet_agent_predict_risk{agent="pred-m1"}' in body
    assert "tpud_fleet_predict_series 1" in body
