"""Metrics pipeline unit depth (reference: pkg/metrics{,/scraper,/store,
/syncer} — 2935 test LoC): registry semantics, Prometheus exposition
escaping/format, gather→store→read pipeline, syncer retention."""

import threading

from gpud_tpu.metrics.registry import Registry
from gpud_tpu.metrics.store import MetricsStore, Syncer


def test_gauge_set_get_per_labelset():
    r = Registry()
    g = r.gauge("g", "help")
    g.set(1.0)
    g.set(2.0, {"chip": "0"})
    g.set(3.0, {"chip": "1"})
    assert g.get() == 1.0
    assert g.get({"chip": "0"}) == 2.0
    assert g.get({"chip": "1"}) == 3.0
    g.set(9.0, {"chip": "0"})  # overwrite, not accumulate
    assert g.get({"chip": "0"}) == 9.0


def test_counter_accumulates_and_never_needs_init():
    r = Registry()
    c = r.counter("c", "help")
    assert c.get() == 0.0
    c.inc()
    c.inc(2.5, {"e": "x"})
    c.inc(0.5, {"e": "x"})
    assert c.get() == 1.0
    assert c.get({"e": "x"}) == 3.0


def test_same_name_returns_same_metric():
    r = Registry()
    a = r.gauge("dup", "h")
    b = r.gauge("dup", "h")
    assert a is b
    a.set(5.0)
    assert b.get() == 5.0


def test_label_order_is_canonical():
    r = Registry()
    g = r.gauge("g", "h")
    g.set(1.0, {"b": "2", "a": "1"})
    assert g.get({"a": "1", "b": "2"}) == 1.0  # order-insensitive identity
    out = r.render_prometheus()
    assert 'g{a="1",b="2"} 1' in out  # rendered sorted


def test_prometheus_escaping_label_values_and_help():
    r = Registry()
    g = r.gauge("esc", 'help with "quotes" and \\slash\nnewline')
    g.set(1.0, {"path": 'C:\\dir "x"\nend'})
    out = r.render_prometheus()
    # label value escaping per exposition format
    assert '\\"x\\"' in out
    assert "\\n" in out
    # HELP line must stay a single line
    help_lines = [ln for ln in out.splitlines() if ln.startswith("# HELP esc")]
    assert len(help_lines) == 1


def test_float_formatting_stable():
    r = Registry()
    g = r.gauge("f", "h")
    g.set(0.30000000000000004)
    g.set(float("inf"), {"k": "i"})
    out = r.render_prometheus()
    assert "+Inf" in out or "inf" in out.lower()
    g.set(float("nan"), {"k": "n"})
    out = r.render_prometheus()
    assert "NaN" in out or "nan" in out.lower()


def test_remove_and_clear_labelsets():
    r = Registry()
    g = r.gauge("rm", "h")
    g.set(1.0, {"chip": "0"})
    g.set(2.0, {"chip": "1"})
    g.remove({"chip": "0"})
    assert g.get({"chip": "0"}) is None
    assert g.get({"chip": "1"}) == 2.0
    g.clear()
    assert g.get({"chip": "1"}) is None


def test_gather_rows_roundtrip_through_store(tmp_db):
    r = Registry()
    g = r.gauge("pipe_metric", "h")
    g.set(42.5, {"chip": "3"})
    rows = r.gather(now=1700000000.0)
    store = MetricsStore(tmp_db)
    store.record(rows)
    got = store.read(0, name="pipe_metric")
    assert len(got) == 1
    m = got[0]
    assert m.value == 42.5 and m.labels == {"chip": "3"}
    assert m.unix_seconds == 1700000000


def test_syncer_sync_once_and_retention(tmp_db):
    r = Registry()
    g = r.gauge("sync_metric", "h")
    store = MetricsStore(tmp_db, retention_seconds=3600)
    sy = Syncer(registry=r, store=store, interval_seconds=60)
    clock = [1_700_000_000.0]
    sy.time_now_fn = lambda: clock[0]
    g.set(1.0)
    n1 = sy.sync_once()
    assert n1 >= 1
    g.set(2.0)
    clock[0] += 60
    sy.sync_once()
    vals = [m.value for m in store.read(0, name="sync_metric")]
    assert vals.count(1.0) == 1 and vals.count(2.0) == 1
    # retention actually purges: advance past the window and sync again —
    # the first sample ages out, the newer ones survive
    clock[0] += 3600
    g.set(3.0)
    sy.sync_once()
    vals = [m.value for m in store.read(0, name="sync_metric")]
    assert 1.0 not in vals, "retention purge never ran"
    assert 3.0 in vals


def test_concurrent_metric_updates_no_corruption():
    r = Registry()
    c = r.counter("conc", "h")
    g = r.gauge("conc_g", "h")

    def work(tid):
        for i in range(500):
            c.inc(1.0, {"t": str(tid)})
            g.set(float(i), {"t": str(tid)})

    threads = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for t in range(4):
        assert c.get({"t": str(t)}) == 500.0
        assert g.get({"t": str(t)}) == 499.0
    # render under the final state never raises / truncates
    out = r.render_prometheus()
    assert out.count("conc{") == 4


def test_unregister_removes_from_exposition():
    r = Registry()
    r.gauge("gone", "h").set(1.0)
    assert "gone" in r.render_prometheus()
    r.unregister("gone")
    assert "gone" not in r.render_prometheus()
