"""Disk failure-detection scenarios (VERDICT r3 #2; reference:
components/disk + pkg/disk/lsblk.go depth). Kernel I/O / filesystem /
device-offline kmsg lines must flip the disk component unhealthy with
suggested actions, sticky until set-healthy; a read-only remount visible
in /proc/mounts is caught even without a kmsg line."""

import time

from gpud_tpu.api.v1.types import EventType, HealthStateType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.components.disk import DiskComponent, match_disk_error
from gpud_tpu.eventstore import EventStore
from gpud_tpu.kmsg.syncer import Syncer
from gpud_tpu.kmsg.watcher import Message


# ---------------------------------------------------------------------------
# matcher
# ---------------------------------------------------------------------------

IO_ERROR_LINES = [
    "blk_update_request: I/O error, dev sda, sector 12345 op 0x0:(READ)",
    "blk_update_request: critical medium error, dev nvme0n1, sector 99",
    "print_req_error: I/O error, dev sdb, sector 2048",
    "Buffer I/O error on dev sda1, logical block 2, lost async page write",
]

FATAL_LINES = {
    "EXT4-fs error (device sda1): ext4_find_entry:1455: inode #2: comm ls: reading directory lblock 0": "disk_fs_error",
    "EXT4-fs (sda1): Remounting filesystem read-only": "disk_remount_ro",
    "XFS (nvme0n1p1): Corruption detected. Unmount and run xfs_repair": "disk_fs_error",
    "JBD2: Error -5 detected when updating journal superblock for sda1-8. aborting": "disk_fs_error",
    "sd 0:0:0:0: rejecting I/O to offline device": "disk_device_offline",
    "nvme nvme0: controller is down; will reset: CSTS=0x3": "disk_device_offline",
    "nvme nvme0: I/O 22 QID 3 timeout, aborting": "disk_device_offline",
}


def test_matcher_io_error_lines():
    for ln in IO_ERROR_LINES:
        m = match_disk_error(ln)
        assert m is not None, ln
        assert m[0] == "disk_io_error" and m[1] == EventType.CRITICAL


def test_matcher_fatal_lines():
    for ln, want in FATAL_LINES.items():
        m = match_disk_error(ln)
        assert m is not None, ln
        assert m[0] == want, ln
        assert m[1] == EventType.FATAL


def test_matcher_extracts_device():
    m = match_disk_error(IO_ERROR_LINES[0])
    assert m[3] == {"device": "sda"}
    m = match_disk_error("EXT4-fs error (device sda1): bad things")
    assert m[3] == {"device": "sda1"}


def test_matcher_ignores_normal_lines():
    for ln in [
        "EXT4-fs (sda1): mounted filesystem with ordered data mode",
        "systemd[1]: Started Daily apt download activities.",
        "nvme nvme0: 8/0/0 default/read/poll queues",
        "accel0: device lost",  # TPU-class, not disk-class
    ]:
        assert match_disk_error(ln) is None, ln


# ---------------------------------------------------------------------------
# component scenarios
# ---------------------------------------------------------------------------

def _comp(tmp_db):
    inst = TpudInstance(db_rw=tmp_db, event_store=EventStore(tmp_db))
    c = DiskComponent(inst)
    return c


def _pump(c, lines, t=None):
    """Route lines through a real Syncer into the component's bucket —
    the same path server._wire_kmsg_syncers builds."""
    s = Syncer(match_disk_error, c._event_bucket)
    t = t if t is not None else time.time()
    for i, ln in enumerate(lines):
        s.process(Message(time=t + i * 0.001, message=ln, priority=3))


def test_fs_error_flips_unhealthy_with_actions(tmp_db):
    c = _comp(tmp_db)
    assert c.check().health_state_type() in (
        HealthStateType.HEALTHY, HealthStateType.DEGRADED,
    )
    _pump(c, ["EXT4-fs error (device sda1): ext4_journal_check_start: Detected aborted journal"])
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert "sda1" in cr.summary()
    actions = cr.suggested_actions
    assert actions is not None and actions.repair_actions


def test_io_errors_degrade(tmp_db):
    c = _comp(tmp_db)
    _pump(c, IO_ERROR_LINES[:2])
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.DEGRADED
    assert "I/O error" in cr.summary()
    assert "sda" in cr.summary() or "nvme0n1" in cr.summary()


def test_sticky_until_set_healthy(tmp_db):
    c = _comp(tmp_db)
    _pump(c, ["sd 0:0:0:0: rejecting I/O to offline device"])
    assert c.check().health_state_type() == HealthStateType.UNHEALTHY
    # still unhealthy on re-check (no new lines)
    assert c.check().health_state_type() == HealthStateType.UNHEALTHY
    c.set_healthy()
    assert c.check().health_state_type() in (
        HealthStateType.HEALTHY, HealthStateType.DEGRADED,
    )


def test_event_recurrence_after_set_healthy_realarms(tmp_db):
    c = _comp(tmp_db)
    _pump(c, ["nvme nvme0: controller is down; will reset: CSTS=0x3"])
    assert c.check().health_state_type() == HealthStateType.UNHEALTHY
    c.set_healthy()
    assert c.check().health_state_type() != HealthStateType.UNHEALTHY
    # the fault recurs — a different line so the deduper doesn't eat it
    _pump(c, ["nvme nvme0: Removing after probe failure status: -19"])
    assert c.check().health_state_type() == HealthStateType.UNHEALTHY


def test_lookback_window_expires_events(tmp_db):
    c = _comp(tmp_db)
    old = time.time() - 4 * 3600  # outside the 3h lookback
    _pump(c, ["EXT4-fs error (device sda1): whatever"], t=old)
    assert c.check().health_state_type() in (
        HealthStateType.HEALTHY, HealthStateType.DEGRADED,
    )


def test_read_only_mount_detected_without_kmsg(tmp_db, tmp_path):
    c = _comp(tmp_db)
    mounts = tmp_path / "mounts"
    # '/' is always watched; model it remounted ro
    mounts.write_text(
        "/dev/sda1 / ext4 ro,relatime,errors=remount-ro 0 0\n"
        "tmpfs /run tmpfs rw,nosuid 0 0\n"
    )
    c.proc_mounts_path = str(mounts)
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert "read-only" in cr.summary()


def test_rw_mounts_not_flagged(tmp_db, tmp_path):
    c = _comp(tmp_db)
    mounts = tmp_path / "mounts"
    mounts.write_text("/dev/sda1 / ext4 rw,relatime,errors=remount-ro 0 0\n")
    c.proc_mounts_path = str(mounts)
    cr = c.check()
    assert cr.health_state_type() in (
        HealthStateType.HEALTHY, HealthStateType.DEGRADED,
    )


def test_deliberate_ro_volume_not_flagged(tmp_db, tmp_path):
    """A read-only *data* volume (ro without an errors= policy) is an
    operator choice, not a trip — e.g. ro-mounted dataset disks."""
    c = _comp(tmp_db)
    mounts = tmp_path / "mounts"
    mounts.write_text("/dev/vdb / ext4 ro,relatime 0 0\n")
    c.proc_mounts_path = str(mounts)
    cr = c.check()
    assert cr.health_state_type() in (
        HealthStateType.HEALTHY, HealthStateType.DEGRADED,
    )


def test_events_surface_via_component_events(tmp_db):
    c = _comp(tmp_db)
    _pump(c, ["blk_update_request: I/O error, dev sda, sector 1 op 0x0:(READ)"])
    evs = c.events(0)
    assert any(e.name == "disk_io_error" for e in evs)
    (ev,) = [e for e in evs if e.name == "disk_io_error"]
    assert ev.extra_info.get("device") == "sda"
    assert "kmsg" in ev.extra_info
