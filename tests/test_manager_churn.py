"""Control-plane churn: agents joining/dropping/reconnecting while
operators spam requests and drains — the fleet-lifecycle stress the
single-flow e2e tests can't produce. Invariants: no crashed manager, no
cross-paired responses, registry converges to the live set."""

import queue
import threading
import time

import pytest

from gpud_tpu.manager.control_plane import AgentGone, ControlPlane
from gpud_tpu.session.session import Session

pytest.importorskip("grpc")
requests = pytest.importorskip("requests")

N_AGENTS = 6
CHURN_SECONDS = 8.0


def _mk_agent(cp, i, monkeypatch_env):
    """A v2 agent whose dispatcher tags responses with its identity."""
    ident = f"churn-{i}"

    def dispatch(req):
        return {"who": ident, "method": req.get("method")}

    s = Session(
        endpoint=cp.endpoint,
        machine_id=ident,
        token="t",
        machine_proof="p",
        dispatch_fn=dispatch,
        protocol="v2",
        jitter_fn=lambda b: 0.05,
    )
    s.start()
    return ident, s


def test_fleet_churn_under_operator_load(monkeypatch):
    monkeypatch.setenv("TPUD_SESSION_V2_TARGET", "")
    cp = ControlPlane()
    cp.start()
    monkeypatch.setenv("TPUD_SESSION_V2_TARGET", f"127.0.0.1:{cp.grpc_port}")
    sessions = {}
    errors: "queue.Queue[str]" = queue.Queue()
    stop = threading.Event()

    try:
        for i in range(N_AGENTS):
            ident, s = _mk_agent(cp, i, monkeypatch)
            sessions[ident] = s
        deadline = time.time() + 15
        while time.time() < deadline and len(cp.agents) < N_AGENTS:
            time.sleep(0.05)
        assert len(cp.agents) == N_AGENTS

        def operator(tid):
            """Spam requests at random-ish agents; verify response pairing."""
            n = 0
            while not stop.is_set():
                ident = f"churn-{(tid + n) % N_AGENTS}"
                n += 1
                try:
                    resp = cp.agent(ident).request(
                        {"method": "states"}, timeout=5
                    )
                    # the CORE invariant: responses never cross agents
                    if resp.get("who") not in (ident, None) and "error" not in resp:
                        errors.put(f"cross-pairing: asked {ident} got {resp}")
                except (AgentGone, TimeoutError):
                    pass  # churn makes these legitimate
                except Exception as e:  # noqa: BLE001
                    errors.put(f"operator crash: {e!r}")
                time.sleep(0.01)

        def churner():
            """Kill and resurrect agents continuously."""
            n = 0
            while not stop.is_set():
                idx = n % N_AGENTS
                ident = f"churn-{idx}"
                n += 1
                s = sessions.get(ident)
                if s is not None:
                    s.stop()
                    time.sleep(0.05)
                    # resurrect the SAME identity that was killed
                    _, s2 = _mk_agent(cp, idx, monkeypatch)
                    sessions[ident] = s2
                time.sleep(0.15)

        threads = [
            threading.Thread(target=operator, args=(i,), daemon=True)
            for i in range(3)
        ] + [threading.Thread(target=churner, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(CHURN_SECONDS)
        # one drain mid-churn: must not wedge anything
        cp.drain("chaos drain")
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        assert errors.empty(), errors.get()
        # after churn ends, the fleet reconverges: every agent usable
        deadline = time.time() + 20
        alive = set()
        while time.time() < deadline and len(alive) < N_AGENTS:
            for i in range(N_AGENTS):
                ident = f"churn-{i}"
                if ident in alive:
                    continue
                try:
                    resp = cp.agent(ident).request({"method": "states"}, timeout=5)
                    if resp.get("who") == ident:
                        alive.add(ident)
                except (AgentGone, TimeoutError):
                    pass
            time.sleep(0.1)
        assert len(alive) == N_AGENTS, f"only reconverged: {sorted(alive)}"
        # operator surface consistent with the live set
        machines = {m["machine_id"] for m in cp.machines()}
        assert machines == {f"churn-{i}" for i in range(N_AGENTS)}
    finally:
        stop.set()
        for s in sessions.values():
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass
        cp.stop()
