"""Generative wire-contract properties for every API v1 dataclass.

The reference's api/v1 types are its ONE compatibility surface — agent,
CLI, SDK and control plane all speak them — and its tests roundtrip each
type through JSON (reference: api/v1/types_test.go). This suite does
that generatively: seeded randomized instances of every dataclass that
declares to_dict/from_dict are checked for

- roundtrip stability: from_dict(to_dict(x)).to_dict() == to_dict(x)
- JSON-encodability of every to_dict (the HTTP layer json.dumps's them)
- tolerance of unknown keys (a NEWER peer added fields; from_dict must
  ignore them, not raise — forward wire compat)
- tolerance of the empty/None payload where from_dict declares it
- numeric coercion: ints/floats arriving as JSON strings do not crash
  the numeric fields that declare coercion (int(d.get(...)))
"""

import dataclasses
import json
import random
import string
import typing

import pytest

from gpud_tpu.api.v1 import types as T

SEED = 20260729
ROUNDS = 25

# every dataclass with BOTH to_dict and from_dict participates
WIRE_TYPES = [
    obj
    for obj in vars(T).values()
    if dataclasses.is_dataclass(obj)
    and callable(getattr(obj, "to_dict", None))
    and callable(getattr(obj, "from_dict", None))
]


def _assert_wire_types_discovered():
    names = {t.__name__ for t in WIRE_TYPES}
    # the core wire surface must be present — if a rename drops one out
    # of discovery this suite would silently shrink
    for expected in (
        "HealthState", "Event", "Metric", "SuggestedActions",
        "ComponentHealthStates", "ComponentEvents", "ComponentMetrics",
        "ComponentInfo", "PackageStatus", "TPUChipInfo", "TPUInfo",
        "MachineInfo", "LoginRequest", "LoginResponse",
    ):
        assert expected in names, f"{expected} lost to_dict/from_dict"


_assert_wire_types_discovered()


def _rand_str(rng: random.Random) -> str:
    alphabet = string.ascii_letters + string.digits + " .:/-_%\"'\\"
    s = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 24)))
    if rng.random() < 0.2:
        s += "µ∆-雪-🙂"  # non-ASCII survives the JSON boundary
    return s


def _value_for(ftype, rng: random.Random, depth: int):
    origin = typing.get_origin(ftype)
    args = typing.get_args(ftype)
    if ftype is str:
        return _rand_str(rng)
    if ftype is float:
        return round(rng.uniform(0, 2_000_000_000), 3)
    if ftype is int:
        return rng.randint(0, 10**12)
    if ftype is bool:
        return rng.random() < 0.5
    if origin in (list, typing.List):
        inner = args[0] if args else str
        return [
            _value_for(inner, rng, depth + 1)
            for _ in range(rng.randint(0, 3))
        ]
    if origin in (dict, typing.Dict):
        kt = args[0] if args else str
        vt = args[1] if len(args) > 1 else str
        return {
            _value_for(kt, rng, depth + 1): _value_for(vt, rng, depth + 1)
            for _ in range(rng.randint(0, 3))
        }
    if origin is typing.Union:  # Optional[X]
        non_none = [a for a in args if a is not type(None)]
        if rng.random() < 0.4:
            return None
        return _value_for(non_none[0], rng, depth + 1)
    if dataclasses.is_dataclass(ftype):
        return _instance(ftype, rng, depth + 1)
    if ftype is typing.Any:
        return _rand_str(rng)
    # unhandled annotation: fall back to the field default by signalling
    return None


def _instance(cls, rng: random.Random, depth: int = 0):
    if depth > 3:
        return cls()
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        v = _value_for(hints.get(f.name, str), rng, depth)
        if v is not None:
            kwargs[f.name] = v
    return cls(**kwargs)


@pytest.mark.parametrize("cls", WIRE_TYPES, ids=lambda c: c.__name__)
def test_roundtrip_stability(cls):
    rng = random.Random(SEED + hash(cls.__name__) % 1000)
    for _ in range(ROUNDS):
        x = _instance(cls, rng)
        d1 = x.to_dict()
        # the HTTP layer serializes this verbatim
        encoded = json.dumps(d1)
        back = cls.from_dict(json.loads(encoded))
        if back is None:
            # Optional-payload from_dicts return None only for empty input
            assert not d1 or not any(d1.values()), (cls.__name__, d1)
            continue
        d2 = back.to_dict()
        assert d2 == d1, f"{cls.__name__} roundtrip drift:\n{d1}\n{d2}"


@pytest.mark.parametrize("cls", WIRE_TYPES, ids=lambda c: c.__name__)
def test_unknown_keys_ignored(cls):
    """A newer peer may add fields; decoding must ignore them (the
    reference's JSON decoding behavior) rather than raise."""
    rng = random.Random(SEED)
    x = _instance(cls, rng)
    d = x.to_dict()
    d["__future_field__"] = {"nested": [1, 2, 3]}
    back = cls.from_dict(d)
    assert back is not None


@pytest.mark.parametrize("cls", WIRE_TYPES, ids=lambda c: c.__name__)
def test_empty_payload_tolerated(cls):
    """from_dict({}) must produce a defaulted instance (or None for the
    Optional-payload decoders) — a minimal peer sends sparse objects."""
    out = cls.from_dict({})
    if out is not None:
        json.dumps(out.to_dict())  # still encodable


@pytest.mark.parametrize(
    "cls", [T.TPUChipInfo, T.TPUInfo, T.Event, T.HealthState, T.Metric],
    ids=lambda c: c.__name__,
)
def test_numeric_fields_coerce_from_strings(cls):
    """JSON writers in other languages sometimes emit numbers as strings;
    the numeric fields that declare coercion must accept them."""
    rng = random.Random(SEED)
    x = _instance(cls, rng)
    d = x.to_dict()
    for k, v in list(d.items()):
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d[k] = str(v)
    back = cls.from_dict(d)
    assert back is not None
    json.dumps(back.to_dict())


def test_health_state_raw_output_truncated_on_the_wire():
    hs = T.HealthState(raw_output="x" * (T.HealthState.MAX_RAW_OUTPUT + 500))
    assert len(hs.raw_output) == T.HealthState.MAX_RAW_OUTPUT
    back = T.HealthState.from_dict(hs.to_dict())
    assert len(back.raw_output) == T.HealthState.MAX_RAW_OUTPUT


def test_event_type_from_string_rejects_unknown():
    assert T.EventType.from_string("Fatal") == T.EventType.FATAL
    assert T.EventType.from_string("???") == T.EventType.UNKNOWN
    assert T.EventType.from_string("") == T.EventType.UNKNOWN
