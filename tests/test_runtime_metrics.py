"""Runtime-metrics gRPC backend (the TPU-side NVML analog; reference
boundary: pkg/nvidia/nvml/lib/lib.go:11-16 — side-band library API with
mock injection). Covers: wire codec (incl. hand-built golden bytes so
the decoder is not merely checked against its own encoder), client
merge/failure semantics, chip folding, backend selection, capability
degradation, and ICI-over-runtime-metrics."""

import struct

import pytest

pytest.importorskip("grpc")  # optional 'v2' extra; skip, don't error, without it

from gpud_tpu.tpu import runtime_metrics as rtm
from gpud_tpu.tpu.instance import (
    ENV_DEV_ROOT,
    ENV_SYSFS_ROOT,
    SysfsBackend,
    new_instance,
)
from tests.fake_runtime_metrics import FakeRuntimeMetricsServer, hbm_table

GiB = 1024**3


@pytest.fixture
def accel_tree(tmp_path):
    """4-chip fixture: bare /dev/accel nodes + empty sysfs root."""
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(4):
        (dev / f"accel{i}").write_text("")
    return dev


def sysfs_inner(accel_tree):
    return SysfsBackend(
        dev_root=str(accel_tree), sysfs_root="", accelerator_type="v5e-4"
    )


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_golden_metric_response_bytes():
    """Hand-assembled MetricResponse for one chip: device-id=2 (int_attr,
    field 3 varint), gauge as_int=12345 (field 2 varint). Field/wire
    bytes computed by hand, not by our encoder."""
    # AttrValue{int_attr=2}: key=(3<<3|0)=0x18, value 2
    attr_value = bytes([0x18, 0x02])
    # Attribute{key="device-id"(1), value(2)}
    key = b"\x0a\x09device-id"
    attribute = key + bytes([0x12, len(attr_value)]) + attr_value
    # Gauge{as_int=12345}: field 2 varint → key 0x10, varint 0xb9 0x60
    gauge = bytes([0x10, 0xB9, 0x60])
    # Metric{attribute=1, gauge=2}
    metric = (
        bytes([0x0A, len(attribute)]) + attribute
        + bytes([0x12, len(gauge)]) + gauge
    )
    # TPUMetric{name=1, metrics=3}
    name = b"\x0a\x1btpu.runtime.hbm.memory.usag"  # 27-byte name
    tpu_metric = name + bytes([0x1A, len(metric)]) + metric
    resp = bytes([0x0A, len(tpu_metric)]) + tpu_metric

    samples = rtm.decode_metric_response(resp)
    assert len(samples) == 1
    s = samples[0]
    assert s.device_id == 2
    assert s.value == 12345 and s.is_int


def test_golden_double_gauge():
    """Gauge carrying as_double (field 1 fixed64) decodes as float."""
    gauge = bytes([0x09]) + struct.pack("<d", 87.5)  # field 1, wire 1
    metric = bytes([0x12, len(gauge)]) + gauge
    tpu_metric = bytes([0x1A, len(metric)]) + metric
    resp = bytes([0x0A, len(tpu_metric)]) + tpu_metric
    (s,) = rtm.decode_metric_response(resp)
    assert s.value == pytest.approx(87.5) and not s.is_int


def test_roundtrip_with_renumbered_gauge_oneof():
    """The decoder keys off wire type, so a runtime that renumbered the
    Gauge oneof arms (int at field 7) still decodes correctly."""
    payload = rtm.encode_metric_response(
        rtm.METRIC_HBM_USAGE,
        [({"device-id": 0}, 5 * GiB)],
        gauge_int_field=7,
    )
    (s,) = rtm.decode_metric_response(payload)
    assert s.value == 5 * GiB and s.is_int and s.device_id == 0


def test_negative_int_gauge_roundtrip():
    payload = rtm.encode_metric_response("m", [({"device-id": 0}, -3)])
    # encoder writes plain two's-complement varint like protobuf int64
    (s,) = rtm.decode_metric_response(payload)
    assert s.value == -3


def test_list_supported_roundtrip():
    names = [rtm.METRIC_HBM_USAGE, rtm.METRIC_DUTY_CYCLE]
    assert rtm.decode_list_supported_response(
        rtm.encode_list_supported_response(names)
    ) == names


def test_attr_string_and_device_fallback():
    payload = rtm.encode_metric_response(
        "m", [({"zone": "us-central2-b", "chip_id": 3}, 1)]
    )
    (s,) = rtm.decode_metric_response(payload)
    assert s.attrs["zone"] == "us-central2-b"
    assert s.device_id == 3


# ---------------------------------------------------------------------------
# fold
# ---------------------------------------------------------------------------

def _samples(pairs):
    return [
        rtm.MetricSample(value=v, attrs={"device-id": d}) for d, v in pairs
    ]


def test_fold_direct_id_match():
    got = rtm._fold_to_chips(_samples([(0, 10), (1, 20)]), [0, 1])
    assert got == {0: 10, 1: 20}


def test_fold_rank_mapping_for_shifted_ids():
    # global ids 4..7 on worker 1 of a multi-host slice map onto local 0..3
    got = rtm._fold_to_chips(
        _samples([(4, 1), (5, 2), (6, 3), (7, 4)]), [0, 1, 2, 3]
    )
    assert got == {0: 1, 1: 2, 2: 3, 3: 4}


def test_fold_per_core_sum_and_max():
    # 8 cores onto 4 chips: v2/v3 style
    cores = _samples([(i, 10 * (i + 1)) for i in range(8)])
    summed = rtm._fold_to_chips(cores, [0, 1, 2, 3], "sum")
    assert summed == {0: 30, 1: 70, 2: 110, 3: 150}
    maxed = rtm._fold_to_chips(cores, [0, 1, 2, 3], "max")
    assert maxed == {0: 20, 1: 40, 2: 60, 3: 80}


def test_fold_unmappable_returns_empty():
    assert rtm._fold_to_chips(_samples([(0, 1), (1, 2), (2, 3)]), [0, 1]) == {}


# ---------------------------------------------------------------------------
# client ↔ fake server
# ---------------------------------------------------------------------------

@pytest.fixture
def server():
    srv = FakeRuntimeMetricsServer(
        values=hbm_table({0: (2 * GiB, 16 * GiB, 55.5), 1: (GiB, 16 * GiB, 12.25)})
    )
    srv.start()
    yield srv
    srv.stop()


def test_client_list_and_get(server):
    c = rtm.RuntimeMetricsClient(addrs=[server.addr], timeout=5.0)
    try:
        names = c.list_supported()
        assert rtm.METRIC_HBM_USAGE in names and rtm.METRIC_DUTY_CYCLE in names
        samples = c.get_metric(rtm.METRIC_DUTY_CYCLE)
        got = {s.device_id: s.value for s in samples}
        assert got == {0: pytest.approx(55.5), 1: pytest.approx(12.25)}
    finally:
        c.close()


def test_client_multi_port_merge():
    s1 = FakeRuntimeMetricsServer(values=hbm_table({0: (GiB, 16 * GiB, 10.0)}))
    s2 = FakeRuntimeMetricsServer(values=hbm_table({1: (2 * GiB, 16 * GiB, 20.0)}))
    s1.start()
    s2.start()
    try:
        c = rtm.RuntimeMetricsClient(addrs=[s1.addr, s2.addr], timeout=5.0)
        samples = c.get_metric(rtm.METRIC_HBM_USAGE)
        assert {s.device_id: s.value for s in samples} == {0: GiB, 1: 2 * GiB}
        c.close()
    finally:
        s1.stop()
        s2.stop()


def test_client_partial_port_failure_keeps_other_chips():
    s1 = FakeRuntimeMetricsServer(values=hbm_table({0: (GiB, 16 * GiB, 10.0)}))
    s1.start()
    try:
        c = rtm.RuntimeMetricsClient(
            addrs=[s1.addr, "127.0.0.1:1"], timeout=2.0
        )
        samples = c.get_metric(rtm.METRIC_HBM_USAGE)
        assert [s.device_id for s in samples] == [0]
        c.close()
    finally:
        s1.stop()


def test_client_all_ports_down_raises():
    c = rtm.RuntimeMetricsClient(addrs=["127.0.0.1:1"], timeout=1.0)
    with pytest.raises(rtm.RuntimeMetricsError):
        c.list_supported()
    with pytest.raises(rtm.RuntimeMetricsError):
        c.get_metric(rtm.METRIC_HBM_USAGE)
    c.close()


# ---------------------------------------------------------------------------
# backend
# ---------------------------------------------------------------------------

def test_backend_telemetry_no_subprocess(server, accel_tree):
    inner = sysfs_inner(accel_tree)
    b = rtm.RuntimeMetricsBackend(
        inner=inner, client=rtm.RuntimeMetricsClient(addrs=[server.addr], timeout=5.0)
    )
    assert b.available() and b.telemetry_supported()
    assert b.telemetry_source() == "runtime-metrics"
    tel = b.telemetry()
    assert tel[0].hbm_used_bytes == 2 * GiB
    assert tel[0].hbm_total_bytes == 16 * GiB
    assert tel[0].duty_cycle_pct == pytest.approx(55.5)
    assert tel[1].duty_cycle_pct == pytest.approx(12.25)
    # chips 2,3 had no samples: telemetry rows exist with inventory totals
    assert tel[2].hbm_used_bytes == 0 and tel[2].hbm_total_bytes > 0
    # identity still comes from the enumeration backend
    assert b.accelerator_type() == "v5e-4"
    assert len(b.devices()) == 4


def test_backend_capability_degrades_per_metric(accel_tree):
    srv = FakeRuntimeMetricsServer(
        values={rtm.METRIC_DUTY_CYCLE: [({"device-id": 0}, 99.0)]}
    )
    srv.start()
    try:
        b = rtm.RuntimeMetricsBackend(
            inner=sysfs_inner(accel_tree),
            client=rtm.RuntimeMetricsClient(addrs=[srv.addr], timeout=5.0),
        )
        assert b.available()   # duty cycle is a core metric
        tel = b.telemetry()
        assert tel[0].duty_cycle_pct == pytest.approx(99.0)
        assert tel[0].hbm_used_bytes == 0  # HBM metric not advertised → untouched
    finally:
        srv.stop()


def test_backend_ecc_metric_feeds_pending(accel_tree):
    values = hbm_table({0: (GiB, 16 * GiB, 10.0)})
    values[rtm.METRIC_HBM_ECC_UNCORRECTABLE] = [({"device-id": 0}, 2)]
    srv = FakeRuntimeMetricsServer(values=values)
    srv.start()
    try:
        b = rtm.RuntimeMetricsBackend(
            inner=sysfs_inner(accel_tree),
            client=rtm.RuntimeMetricsClient(addrs=[srv.addr], timeout=5.0),
        )
        tel = b.telemetry()
        assert tel[0].hbm_ecc_uncorrectable == 2 and tel[0].hbm_ecc_pending
    finally:
        srv.stop()


def test_backend_unavailable_when_no_core_metrics(accel_tree):
    srv = FakeRuntimeMetricsServer(values={"tpu.runtime.something.else": []})
    srv.start()
    try:
        b = rtm.RuntimeMetricsBackend(
            inner=sysfs_inner(accel_tree),
            client=rtm.RuntimeMetricsClient(addrs=[srv.addr], timeout=5.0),
        )
        assert not b.available()
    finally:
        srv.stop()


def test_backend_probe_failure_reports_error(accel_tree):
    b = rtm.RuntimeMetricsBackend(
        inner=sysfs_inner(accel_tree),
        client=rtm.RuntimeMetricsClient(addrs=["127.0.0.1:1"], timeout=1.0),
    )
    assert not b.available()
    assert b.probe_error()


def test_backend_ici_over_runtime_metrics(accel_tree):
    values = hbm_table({0: (GiB, 16 * GiB, 10.0)})
    values["tpu.runtime.ici.link.state"] = [
        ({"device-id": 0, "link-id": 0}, 1),
        ({"device-id": 0, "link-id": 1}, 0),
    ]
    values["tpu.runtime.ici.link.crc.errors"] = [
        ({"device-id": 0, "link-id": 1}, 7),
    ]
    srv = FakeRuntimeMetricsServer(values=values)
    srv.start()
    try:
        b = rtm.RuntimeMetricsBackend(
            inner=sysfs_inner(accel_tree),
            client=rtm.RuntimeMetricsClient(addrs=[srv.addr], timeout=5.0),
        )
        assert b.ici_source() == "runtime-metrics"
        links = {l.name: l for l in b.ici_links()}
        assert links["chip0/ici0"].state == "up"
        assert links["chip0/ici1"].state == "down"
        assert links["chip0/ici1"].crc_errors == 7
    finally:
        srv.stop()


def test_backend_ici_falls_back_to_inner(server, accel_tree):
    b = rtm.RuntimeMetricsBackend(
        inner=sysfs_inner(accel_tree),
        client=rtm.RuntimeMetricsClient(addrs=[server.addr], timeout=5.0),
    )
    # no ICI metrics advertised → derived-topology inventory from sysfs
    assert b.ici_source() == "derived-topology"
    assert len(b.ici_links()) == len(b.devices()) * 4  # v5e: 4 links/chip


# ---------------------------------------------------------------------------
# factory selection
# ---------------------------------------------------------------------------

def test_factory_prefers_runtime_metrics(server, accel_tree, monkeypatch):
    monkeypatch.setenv(ENV_DEV_ROOT, str(accel_tree))
    monkeypatch.setenv(ENV_SYSFS_ROOT, "")
    monkeypatch.setenv(rtm.ENV_ADDR, server.addr)
    monkeypatch.delenv("TPUD_TPU_MOCK_ALL_SUCCESS", raising=False)
    inst = new_instance(accelerator_type="v5e-4")
    assert inst.telemetry_source() == "runtime-metrics"
    assert inst.telemetry_supported()
    tel = inst.telemetry()
    assert tel[0].hbm_used_bytes == 2 * GiB


def test_factory_disable_env(server, accel_tree, monkeypatch):
    monkeypatch.setenv(ENV_DEV_ROOT, str(accel_tree))
    monkeypatch.setenv(ENV_SYSFS_ROOT, "")
    monkeypatch.setenv(rtm.ENV_ADDR, server.addr)
    monkeypatch.setenv(rtm.ENV_DISABLE, "0")
    monkeypatch.delenv("TPUD_TPU_MOCK_ALL_SUCCESS", raising=False)
    inst = new_instance(accelerator_type="v5e-4")
    assert inst.telemetry_source() != "runtime-metrics"


def test_factory_fixture_roots_without_addr_skip_probe(accel_tree, monkeypatch):
    monkeypatch.setenv(ENV_DEV_ROOT, str(accel_tree))
    monkeypatch.setenv(ENV_SYSFS_ROOT, "")
    monkeypatch.delenv(rtm.ENV_ADDR, raising=False)
    monkeypatch.delenv("TPUD_TPU_MOCK_ALL_SUCCESS", raising=False)
    inst = new_instance(accelerator_type="v5e-4")
    assert isinstance(inst, SysfsBackend)


def test_resolve_addrs(monkeypatch):
    monkeypatch.delenv(rtm.ENV_ADDR, raising=False)
    monkeypatch.delenv(rtm.ENV_LIBTPU_PORTS, raising=False)
    assert rtm.resolve_addrs() == [f"localhost:{rtm.DEFAULT_PORT}"]
    monkeypatch.setenv(rtm.ENV_LIBTPU_PORTS, "8431, 8432")
    assert rtm.resolve_addrs() == ["localhost:8431", "localhost:8432"]
    monkeypatch.setenv(rtm.ENV_ADDR, "10.0.0.2:9000,9001")
    assert rtm.resolve_addrs() == ["10.0.0.2:9000", "localhost:9001"]
