"""Multi-node without a cluster (reference test strategy §4.6): two full
daemons share an 'NFS' directory (a local tmp dir) and observe each other
through their nfs components — each host sees the peer's freshness file,
and a dead peer surfaces as missing members on the survivor."""

import time

import pytest

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.config import default_config
from gpud_tpu.server.server import Server


def _mk_server(tmp_path, name, group_dir):
    kmsg = tmp_path / f"{name}.kmsg"
    kmsg.write_text("")
    cfg = default_config(
        data_dir=str(tmp_path / name),
        port=0,
        tls=False,
        kmsg_path=str(kmsg),
        machine_id=name,
        components_disabled=["network-latency"],
        nfs_group_dirs=[str(group_dir)],
    )
    return Server(config=cfg)


def test_two_daemons_see_each_other_via_nfs_group(tmp_path):
    group = tmp_path / "shared-nfs"
    a = _mk_server(tmp_path, "host-a", group)
    b = _mk_server(tmp_path, "host-b", group)
    a.start()
    b.start()
    try:
        na = a.registry.get("nfs")
        nb = b.registry.get("nfs")
        assert na.is_supported() and nb.is_supported()
        # both write + read the shared dir
        cra = na.check()
        crb = nb.check()
        assert crb.health_state_type() == HealthStateType.HEALTHY
        assert crb.extra_info[f"{group}:members_fresh"] == "2"
        assert cra.health_state_type() == HealthStateType.HEALTHY

        # the control plane pins the expected membership; a host checking
        # alone (peer's file gone stale/removed) goes unhealthy
        for c in (na, nb):
            c.group_configs[0].expected_members = 2
        assert nb.check().health_state_type() == HealthStateType.HEALTHY
        # host-a "dies": its freshness file disappears
        for f in group.glob("host-a*"):
            f.unlink()
        # host-b alone now misses a member (its own write still succeeds)
        crb = nb.check()
        assert crb.health_state_type() == HealthStateType.UNHEALTHY
        assert "1/2 members fresh" in crb.reason
    finally:
        a.stop()
        b.stop()
