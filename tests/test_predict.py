"""Predict subsystem (gpud_tpu/predict/): seeded deterministic feature
extractors (EWMA + CUSUM changepoint, cadence, trajectory, n-gram
novelty), noisy-OR fusion bounds, the engine's hysteresis no-flap
property, warn/clear lifecycle under a fake clock, lead-time
measurement, and the predicted-action dry-run invariant in the
remediation audit ledger."""

import math

import pytest

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
)
from gpud_tpu.predict import PredictEngine
from gpud_tpu.predict.engine import EVENT_NAME_PREDICTED
from gpud_tpu.predict.features import (
    FEATURE_WEIGHTS,
    Ewma,
    LatencyDrift,
    NgramNovelty,
    cadence_score,
    clamp01,
    fuse,
    trajectory_score,
)
from gpud_tpu.remediation.audit import AuditStore
from gpud_tpu.remediation.policy import (
    ACTION_PREDICTED,
    DECISION_DRY_RUN,
    OUTCOME_DRY_RUN,
    map_suggested_action,
)


@pytest.fixture()
def clock():
    state = {"now": 1000.0}

    def now():
        return state["now"]

    now.advance = lambda dt: state.__setitem__("now", state["now"] + dt)
    now.set = lambda t: state.__setitem__("now", t)
    return now


# -- fusion ------------------------------------------------------------------


def test_fuse_bounds_and_weights():
    assert fuse({}) == 0.0
    assert fuse({k: 0.0 for k in FEATURE_WEIGHTS}) == 0.0
    full = fuse({k: 1.0 for k in FEATURE_WEIGHTS})
    assert 0.0 < full < 1.0  # noisy-OR never saturates to exactly 1
    # each feature alone contributes exactly its weight
    for name, w in FEATURE_WEIGHTS.items():
        assert fuse({name: 1.0}) == pytest.approx(w)
    # structural zero-false-positive guard: latency drift alone can
    # never cross the default 0.6 warning threshold
    assert fuse({"latency": 1.0}) < 0.6


def test_fuse_monotone_and_hostile_inputs():
    base = {"latency": 0.3, "cadence": 0.4, "trajectory": 0.2, "ngram": 0.1}
    prev = fuse(base)
    for step in (0.5, 0.8, 1.0):
        cur = fuse({**base, "cadence": step})
        assert cur >= prev
        prev = cur
    # NaN / out-of-range evidence is neutralized, not propagated
    assert fuse({"cadence": float("nan")}) == 0.0
    assert fuse({"cadence": 7.0}) == fuse({"cadence": 1.0})
    assert fuse({"cadence": -3.0}) == 0.0
    assert clamp01(float("nan")) == 0.0


# -- EWMA + latency changepoint ---------------------------------------------


def test_ewma_deterministic_replay():
    series = [0.1, 0.12, 0.11, 0.13, 0.1, 0.5, 0.52]
    a, b = Ewma(alpha=0.3), Ewma(alpha=0.3)
    for x in series:
        a.update(x)
        b.update(x)
    assert a.mean == b.mean and a.var == b.var  # bit-identical replay
    assert a.z(0.11) < a.z(5.0)
    assert Ewma().z(99.0) == 0.0  # no baseline yet → no score


def test_latency_drift_warmup_then_changepoint():
    d = LatencyDrift(warmup=5)
    total_sum, total_count = 0.0, 0
    # warmup + stable phase: 10ms checks, never scores
    for _ in range(12):
        total_sum += 0.010
        total_count += 1
        assert d.update(total_sum, total_count) == 0.0
    # persistent 10x drift accumulates through the CUSUM
    scores = []
    for _ in range(10):
        total_sum += 0.100
        total_count += 1
        scores.append(d.update(total_sum, total_count))
    assert scores[-1] > 0.5
    assert scores == sorted(scores)  # monotone ramp under sustained drift


def test_latency_drift_holds_and_resets():
    d = LatencyDrift(warmup=2)
    total_sum, total_count = 0.0, 0
    for _ in range(8):
        total_sum += 0.010
        total_count += 1
        d.update(total_sum, total_count)
    for _ in range(6):
        total_sum += 0.200
        total_count += 1
        last = d.update(total_sum, total_count)
    assert last > 0.0
    # no new checks landed → hold the score, don't decay through a stall
    assert d.update(total_sum, total_count) == last
    # cumulative counters going backwards (registry reset) → full reset
    assert d.update(0.0, 0) == last  # count delta <= 0: still a hold
    assert d.update(total_sum - 1.0, total_count + 1) == 0.0


def test_latency_drift_single_spike_forgiven():
    d = LatencyDrift(warmup=5)
    total_sum, total_count = 0.0, 0
    for _ in range(10):
        total_sum += 0.010
        total_count += 1
        d.update(total_sum, total_count)
    total_sum += 0.500  # one slow check
    total_count += 1
    spike = d.update(total_sum, total_count)
    for _ in range(6):
        total_sum += 0.010
        total_count += 1
        calm = d.update(total_sum, total_count)
    assert calm <= spike  # CUSUM drains back on a return to baseline


# -- cadence / trajectory ----------------------------------------------------


def test_cadence_score_threshold_proximity_and_accel():
    now, window = 1000.0, 600.0
    assert cadence_score([], now, window, saturation=5) == 0.0
    assert cadence_score([100.0], now, window, saturation=5) == 0.0  # aged out
    # three old-half transitions: pure proximity, no acceleration bonus
    old = [500.0, 550.0, 600.0]
    assert cadence_score(old, now, window, saturation=5) == pytest.approx(0.6)
    # same count in the recent half-window → +0.2 acceleration
    fresh = [900.0, 950.0, 990.0]
    assert cadence_score(fresh, now, window, saturation=5) == pytest.approx(0.8)
    assert cadence_score([now - i for i in range(20)], now, window) == 1.0


def test_trajectory_requires_fresh_deterioration(clock):
    now, window = 1000.0, 600.0
    degraded = HealthStateType.DEGRADED
    healthy = HealthStateType.HEALTHY
    # chronically degraded with no in-window transition scores ZERO —
    # steady-state badness is the reactive detector's business
    assert trajectory_score(degraded, [], now, window) == 0.0
    assert (
        trajectory_score(
            degraded, [(100.0, healthy, degraded)], now, window
        )
        == 0.0
    )
    # fresh transition into a bad state while still bad → full evidence
    assert (
        trajectory_score(
            degraded, [(950.0, healthy, degraded)], now, window
        )
        == 1.0
    )
    # recovered: decayed evidence from the newest in-window excursion
    s = trajectory_score(healthy, [(950.0, healthy, degraded)], now, window)
    assert 0.0 < s <= 0.6
    assert s == pytest.approx(0.6 * math.exp(-50.0 / 150.0))
    # transitions INTO healthy are not deterioration
    assert (
        trajectory_score(healthy, [(990.0, degraded, healthy)], now, window)
        == 0.0
    )


# -- n-gram novelty ----------------------------------------------------------


def test_ngram_novelty_watermark_and_decay():
    ng = NgramNovelty(hold_decay=0.5)
    first = ng.update([(10.0, "tpu_ici_link_down")])
    assert first > 0.0
    # replaying the SAME window (ts <= watermark) mints nothing new and
    # the held score decays instead of re-spiking
    second = ng.update([(10.0, "tpu_ici_link_down")])
    assert second < first
    # a never-seen class at a newer ts is news again
    third = ng.update(
        [(10.0, "tpu_ici_link_down"), (20.0, "tpu_hbm_ecc_error")]
    )
    assert third > second
    # decay floors to exactly zero, not a forever-epsilon
    for _ in range(30):
        last = ng.update([])
    assert last == 0.0


def test_ngram_novelty_known_sequence_scores_below_novel():
    a = NgramNovelty()
    a.update([(1.0, "x"), (2.0, "y")])
    for _ in range(40):
        a.update([])  # drain the hold
    known = a.update([(100.0, "x"), (101.0, "y")])
    novel = NgramNovelty().update([(100.0, "x"), (101.0, "y")])
    assert known < novel


# -- engine: stubs -----------------------------------------------------------


class StubRegistry:
    def __init__(self, *names):
        self._names = list(names)

    def names(self):
        return list(self._names)


class StubLedger:
    flap_threshold = 5

    def __init__(self):
        self.transitions = []
        self.state = None
        self.annotations = {}

    def recent_transitions(self, component, limit=0):
        rows = list(self.transitions)
        if limit:
            rows = rows[-limit:]
        return rows

    def last_state(self, component):
        return {"state": self.state, "since": 0.0, "last_seen": 0.0} \
            if self.state else None

    def set_annotation(self, component, key, value):
        self.annotations.setdefault(component, {})[key] = value

    def clear_annotation(self, component, key):
        self.annotations.get(component, {}).pop(key, None)


class StubBucket:
    def __init__(self):
        self.events = []

    def get(self, since):
        return [e for e in self.events if e.time >= since]

    def insert(self, ev):
        self.events.append(ev)


class StubEventStore:
    def __init__(self):
        self.buckets = {}

    def bucket(self, name):
        return self.buckets.setdefault(name, StubBucket())


def _engine(clock, scripted_scores=None, monkeypatch=None, **kw):
    """Engine over stub collaborators; optionally replaces the fusion
    with a scripted score sequence to drive hysteresis directly."""
    kw.setdefault("registry", StubRegistry("c0"))
    kw.setdefault("ledger", StubLedger())
    kw.setdefault("event_store", StubEventStore())
    kw.setdefault("arm_ticks", 2)
    kw.setdefault("clear_ticks", 3)
    kw.setdefault("threshold", 0.6)
    kw.setdefault("hysteresis", 0.15)
    eng = PredictEngine(**kw)
    eng.time_now_fn = clock
    if scripted_scores is not None:
        it = iter(scripted_scores)
        monkeypatch.setattr(
            "gpud_tpu.predict.engine.fuse",
            lambda features, weights=None: next(it),
        )
    return eng


# -- engine: hysteresis no-flap property ------------------------------------


def test_hysteresis_dead_band_neither_arms_nor_clears(clock, monkeypatch):
    # dead band is (threshold - hysteresis, threshold) = (0.45, 0.6):
    # a score dithering inside it must not arm, and once armed must
    # not clear — the no-flap property
    script = (
        [0.55, 0.50, 0.58, 0.46, 0.59, 0.55]   # dither below arm line
        + [0.70, 0.70]                         # arm (arm_ticks=2)
        + [0.50, 0.46, 0.58, 0.55, 0.50, 0.59]  # dither: stays armed
        + [0.30, 0.30, 0.30]                   # clear (clear_ticks=3)
    )
    eng = _engine(clock, script, monkeypatch)
    events = []
    eng.on_publish = lambda body: events.append(body["event"])

    for _ in range(6):
        eng.tick_once()
        clock.advance(1.0)
    assert eng.scores()["components"]["c0"]["armed"] is False
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    assert eng.scores()["components"]["c0"]["armed"] is True
    for _ in range(6):
        eng.tick_once()
        clock.advance(1.0)
    assert eng.scores()["components"]["c0"]["armed"] is True  # no flap
    for _ in range(3):
        eng.tick_once()
        clock.advance(1.0)
    snap = eng.scores()["components"]["c0"]
    assert snap["armed"] is False
    assert snap["warnings"] == 1  # exactly one warn over the whole dither
    assert events.count("warn") == 1 and events.count("clear") == 1


def test_single_spike_does_not_arm(clock, monkeypatch):
    eng = _engine(clock, [0.2, 0.9, 0.2, 0.9, 0.2, 0.9], monkeypatch)
    for _ in range(6):
        eng.tick_once()
        clock.advance(1.0)
    snap = eng.scores()["components"]["c0"]
    assert snap["armed"] is False and snap["warnings"] == 0


# -- engine: warn/clear lifecycle -------------------------------------------


def test_warn_emits_event_annotation_and_publish(clock, monkeypatch):
    ledger = StubLedger()
    store = StubEventStore()
    eng = _engine(
        clock, [0.8, 0.8, 0.8, 0.1, 0.1, 0.1], monkeypatch,
        ledger=ledger, event_store=store,
    )
    bodies = []
    eng.on_publish = bodies.append
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    # warned: ledger annotation set, Warning event in the bucket
    assert ledger.annotations["c0"]["predicted"] == "true"
    evs = store.bucket("c0").events
    predicted = [e for e in evs if e.name == EVENT_NAME_PREDICTED]
    assert len(predicted) == 1
    assert predicted[0].type == EventType.WARNING
    assert float(predicted[0].extra_info["score"]) >= 0.6
    assert bodies and bodies[0]["event"] == "warn"
    assert bodies[0]["component"] == "c0" and bodies[0]["armed"] is True
    # armed ticks refresh the live score annotation
    eng.tick_once()
    clock.advance(1.0)
    assert "predicted_score" in ledger.annotations["c0"]
    # clear: annotations dropped, clear published
    for _ in range(3):
        eng.tick_once()
        clock.advance(1.0)
    assert ledger.annotations["c0"] == {}
    assert [b["event"] for b in bodies][-1] == "clear"


def test_reset_drops_state_and_annotations(clock, monkeypatch):
    ledger = StubLedger()
    eng = _engine(clock, [0.8, 0.8], monkeypatch, ledger=ledger)
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    assert eng.scores()["components"]["c0"]["armed"] is True
    eng.reset(component="c0")
    assert "c0" not in eng.scores()["components"]
    assert ledger.annotations.get("c0", {}) == {}


def test_lead_measured_once_per_episode(clock, monkeypatch):
    ledger = StubLedger()
    eng = _engine(
        clock, [0.8] * 6, monkeypatch, ledger=ledger,
    )
    leads = []
    eng.on_publish = lambda b: leads.append(b) if b["event"] == "lead" else None
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    warned_at = eng.scores()["components"]["c0"]["warned_at"]
    assert warned_at is not None
    # the reactive detector trips 5s after the warning
    ledger.transitions = [{
        "component": "c0", "time": warned_at + 5.0,
        "from": HealthStateType.DEGRADED, "to": HealthStateType.UNHEALTHY,
        "reason": "hard fault",
    }]
    clock.set(warned_at + 6.0)
    eng.tick_once()
    snap = eng.scores()["components"]["c0"]
    assert snap["lead_seconds"] == pytest.approx(5.0)
    assert len(leads) == 1
    # further ticks do not re-measure the same episode
    eng.tick_once()
    assert len(leads) == 1
    assert eng.scores()["components"]["c0"]["lead_seconds"] == pytest.approx(5.0)


def test_transitions_before_warning_never_measure_lead(clock, monkeypatch):
    ledger = StubLedger()
    # an Unhealthy transition that happened BEFORE the warning is not a
    # "predicted" fault — the measurement must wait for the next one
    ledger.transitions = [{
        "component": "c0", "time": clock() - 10.0,
        "from": HealthStateType.HEALTHY, "to": HealthStateType.UNHEALTHY,
        "reason": "old fault",
    }]
    eng = _engine(clock, [0.8] * 4, monkeypatch, ledger=ledger)
    for _ in range(4):
        eng.tick_once()
        clock.advance(1.0)
    assert eng.scores()["components"]["c0"]["lead_seconds"] is None


# -- predicted-action dry-run invariant -------------------------------------


def test_predicted_audit_rows_are_dry_run_only(clock, monkeypatch, tmp_db):
    audit = AuditStore(tmp_db)
    audit.time_now_fn = clock

    class StubRemediation:
        pass

    rem = StubRemediation()
    rem.audit = audit
    eng = _engine(
        clock, [0.8] * 2, monkeypatch,
        remediation=rem, warn_cooldown_seconds=300.0,
    )
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    rows = audit.read(component="c0")
    assert len(rows) == 1
    row = rows[0]
    assert row["action"] == ACTION_PREDICTED
    assert row["suggested"] == RepairActionType.PREDICTED_DEGRADATION
    assert row["decision"] == DECISION_DRY_RUN
    assert row["outcome"] == OUTCOME_DRY_RUN
    # the suggestion is unmappable by design: no executor path exists
    assert map_suggested_action(
        RepairActionType.PREDICTED_DEGRADATION, None
    ) is None
    # lane isolation: the predicted row anchors ONLY the predict lane —
    # the reactive engine's cooldown anchor must not see it
    assert audit.last_attempt_time("c0", action=ACTION_PREDICTED) is not None
    assert audit.last_attempt_time(
        "c0", exclude_action=ACTION_PREDICTED
    ) is None


def test_predicted_warn_cooldown_limits_audit_rows(clock, monkeypatch, tmp_db):
    audit = AuditStore(tmp_db)
    audit.time_now_fn = clock

    class StubRemediation:
        pass

    rem = StubRemediation()
    rem.audit = audit
    # arm → clear → re-arm inside the cooldown window: one audit row;
    # re-arm after the window: a second row
    script = [0.8, 0.8, 0.1, 0.1, 0.1, 0.8, 0.8, 0.1, 0.1, 0.1, 0.8, 0.8]
    eng = _engine(
        clock, script, monkeypatch,
        remediation=rem, warn_cooldown_seconds=300.0,
    )
    for _ in range(10):
        eng.tick_once()
        clock.advance(1.0)
    assert len(audit.read(component="c0")) == 1  # second warn suppressed
    assert eng.scores()["components"]["c0"]["warnings"] == 2  # but counted
    clock.advance(400.0)  # cooldown expires
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    assert len(audit.read(component="c0")) == 2


# -- engine robustness -------------------------------------------------------


def test_one_component_failure_does_not_stop_the_scan(clock, monkeypatch):
    class ExplodingLedger(StubLedger):
        def recent_transitions(self, component, limit=0):
            if component == "bad":
                raise RuntimeError("boom")
            return super().recent_transitions(component, limit)

    eng = _engine(
        clock, None, monkeypatch,
        registry=StubRegistry("bad", "good"), ledger=ExplodingLedger(),
    )
    out = eng.tick_once()
    assert "good" in out and "bad" not in out
    assert eng.status()["ticks"] == 1


def test_disabled_engine_is_inert(clock):
    eng = PredictEngine(enabled=False, registry=StubRegistry("c0"))
    eng.time_now_fn = clock
    assert eng.tick_once() == {}
    eng.poke()  # must not raise, must not tick
    assert eng.status()["ticks"] == 0


def test_scores_view_shapes(clock, monkeypatch):
    eng = _engine(clock, [0.3, 0.3], monkeypatch)
    for _ in range(2):
        eng.tick_once()
        clock.advance(1.0)
    full = eng.scores(history_limit=8)
    comp = full["components"]["c0"]
    assert set(comp) >= {
        "score", "features", "armed", "warned_at", "lead_seconds",
        "warnings", "history",
    }
    assert len(comp["history"]) == 2
    assert [h["score"] for h in comp["history"]] == [0.3, 0.3]
    # unknown-component filter is empty-ok, not an error
    assert eng.scores(component="nope")["components"] == {}
    st = eng.status()
    assert st["components_tracked"] == 1 and st["armed"] == []
    assert st["feature_weights"] == FEATURE_WEIGHTS
