import os

from gpud_tpu.components.base import FailureInjector
from gpud_tpu.tpu.instance import (
    InjectedInstance,
    JaxBackend,
    LinkState,
    MockBackend,
    SysfsBackend,
    new_instance,
)
from gpud_tpu.tpu.topology import (
    expected_local_chips,
    normalize_generation,
    parse_accelerator_type,
)


def test_parse_accelerator_types():
    t = parse_accelerator_type("v5p-256")
    assert t.generation == "v5p"
    assert t.total_chips == 128
    assert t.total_cores == 256
    assert t.hosts == 32
    assert t.chips_per_host == 4
    assert t.ici_links_per_chip == 6
    assert t.multi_host

    t = parse_accelerator_type("v5e-64")
    assert t.total_chips == 64 and t.hosts == 8 and t.chips_per_host == 8
    assert t.ici_links_per_chip == 4

    t = parse_accelerator_type("v4-8")
    assert t.total_chips == 4 and t.hosts == 1 and not t.multi_host

    t = parse_accelerator_type("v5litepod-16")
    assert t.generation == "v5e" and t.total_chips == 16

    assert parse_accelerator_type("h100-8") is None
    assert parse_accelerator_type("") is None


def test_normalize_generation():
    assert normalize_generation("TPU v5 lite0") == "v5e"
    assert normalize_generation("v5p") == "v5p"
    assert normalize_generation("TPU v4") == "v4"


def test_expected_local_chips():
    assert expected_local_chips("v5e-8") == 8
    assert expected_local_chips("v5e-4") == 4
    assert expected_local_chips("v5p-256") == 4
    assert expected_local_chips("unknown-1") == 0


def test_mock_backend_v5e8():
    b = MockBackend(accelerator_type="v5e-8")
    assert b.tpu_lib_exists()
    assert len(b.devices()) == 8
    assert b.telemetry_supported() and b.ici_supported()
    tel = b.telemetry()
    assert len(tel) == 8
    assert 30 < tel[0].temperature_c < 60
    assert tel[0].hbm_total_bytes > 0
    links = b.ici_links()
    assert len(links) == 8 * 4
    assert all(l.state == LinkState.UP for l in links)


def test_mock_backend_v5p_host():
    b = MockBackend(accelerator_type="v5p-256")
    assert len(b.devices()) == 4  # per-host view
    assert len(b.ici_links()) == 4 * 6


def test_mock_env_injections(monkeypatch):
    monkeypatch.setenv("TPUD_TPU_INJECT_HBM_ECC_PENDING", "1,2")
    monkeypatch.setenv("TPUD_TPU_INJECT_ICI_LINK_DOWN", "chip0/ici1")
    b = MockBackend(accelerator_type="v5e-8")
    tel = b.telemetry()
    assert tel[1].hbm_ecc_pending and tel[2].hbm_ecc_pending
    assert not tel[0].hbm_ecc_pending
    down = [l for l in b.ici_links() if l.state == LinkState.DOWN]
    assert [l.name for l in down] == ["chip0/ici1"]


def test_failure_injector_wrapper():
    inj = FailureInjector(
        chip_ids_lost=[0],
        chip_ids_thermal_slowdown=[1],
        ici_links_down=["chip2/ici0"],
        product_name_override="TPU v6e",
    )
    b = InjectedInstance(MockBackend(accelerator_type="v5e-8"), inj)
    assert b.product_name() == "TPU v6e"
    devs = b.devices()
    assert devs[0].lost and not devs[1].lost
    tel = b.telemetry()
    assert 0 not in tel  # lost chip drops out of telemetry
    assert tel[1].thermal_slowdown
    down = [l.name for l in b.ici_links() if l.state == LinkState.DOWN]
    assert down == ["chip2/ici0"]


def test_injector_enumeration_error():
    inj = FailureInjector(tpu_enumeration_error=True)
    b = InjectedInstance(MockBackend(accelerator_type="v5e-8"), inj)
    assert not b.tpu_lib_exists()
    assert b.devices() == {}
    assert "injected" in b.init_error()


def test_sysfs_backend_fixture(tmp_path):
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(4):
        (dev / f"accel{i}").write_text("")
    sys_accel = tmp_path / "sys_accel"
    (sys_accel / "accel0").mkdir(parents=True)
    os.symlink("/sys/devices/pci0000:00/0000:00:05.0", sys_accel / "accel0" / "device")
    b = SysfsBackend(
        dev_root=str(dev),
        sys_accel_root=str(sys_accel),
        accelerator_type="v4-8",
    )
    assert b.tpu_lib_exists()
    devs = b.devices()
    assert len(devs) == 4
    assert devs[0].pci_address == "0000:00:05.0"
    assert devs[0].generation == "v4"
    assert b.generation() == "v4"


def test_sysfs_backend_empty(tmp_path):
    b = SysfsBackend(dev_root=str(tmp_path), accelerator_type="")
    assert not b.tpu_lib_exists()


def test_factory_mock_env(monkeypatch):
    monkeypatch.setenv("TPUD_TPU_MOCK_ALL_SUCCESS", "1")
    inst = new_instance()
    assert isinstance(inst, MockBackend)
    inst2 = new_instance(FailureInjector(chip_ids_lost=[0]))
    assert isinstance(inst2, InjectedInstance)
    inst3 = new_instance(FailureInjector())  # empty injector → no wrapper
    assert isinstance(inst3, MockBackend)


def test_jax_backend_cpu_only():
    # under JAX_PLATFORMS=cpu there are no tpu/axon devices → clean absence
    b = JaxBackend()
    assert not b.tpu_lib_exists() or b.devices()
