#!/usr/bin/env python3
"""Generates the checked-in TPU-VM sysfs fixture trees under
tests/fixtures/tpuvm/ (run once; the trees are committed, the script
documents their provenance and regenerates them if the surface model
changes).

Each tree mirrors what an *unmodified* TPU VM of that generation exposes
(reference pattern: the checked-in H100 /sys/class/infiniband snapshot,
components/accelerator/nvidia/infiniband/class/testdata/):

- v4-8:  gasket/accel-driver era — 4 chips, /dev/accelN char devices,
         /sys/class/accel/accelN class entries, driver "accel".
- v5e-8: vfio era — 8 chips bound to vfio-pci, /dev/vfio/<group> nodes,
         /sys/kernel/iommu_groups/<group>/devices/ back-links.
- v5p-8: vfio era — 4 chips (v5p-8 = 8 TensorCores), NUMA split 0/0/1/1.

PCI device ids follow the public tpu-info chip table
(google/cloud-accelerator-diagnostics, tpu_info/device.py):
v4=0x005e, v5e=0x0063, v5p=0x0062.
"""

from __future__ import annotations

import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.join(HERE, "tpuvm")


def _write(path: str, content: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="ascii") as f:
        f.write(content + "\n")


def _symlink(target: str, link: str) -> None:
    os.makedirs(os.path.dirname(link), exist_ok=True)
    if os.path.islink(link):
        os.unlink(link)
    os.symlink(target, link)


def make_tree(
    name: str,
    n_chips: int,
    device_id: str,
    driver: str,
    numa_nodes: list,
    accel_class: bool,
    vfio: bool,
    first_group: int = 8,
) -> None:
    base = os.path.join(ROOT, name)
    if os.path.isdir(base):
        shutil.rmtree(base)
    sysd = os.path.join(base, "sys")
    devd = os.path.join(base, "dev")
    os.makedirs(devd, exist_ok=True)

    drivers_dir = os.path.join(sysd, "bus", "pci", "drivers", driver)
    os.makedirs(drivers_dir, exist_ok=True)

    for i in range(n_chips):
        bdf = f"0000:00:{0x04 + i:02x}.0"
        dev_dir = os.path.join(sysd, "devices", "pci0000:00", bdf)
        _write(os.path.join(dev_dir, "vendor"), "0x1ae0")
        _write(os.path.join(dev_dir, "device"), device_id)
        _write(os.path.join(dev_dir, "class"), "0x120000")
        _write(os.path.join(dev_dir, "revision"), "0x00")
        _write(os.path.join(dev_dir, "subsystem_vendor"), "0x1ae0")
        _write(os.path.join(dev_dir, "subsystem_device"), "0x0056")
        _write(os.path.join(dev_dir, "numa_node"), str(numa_nodes[i]))
        # driver symlink: sys/devices/pci0000:00/<bdf>/driver -> sys/bus/pci/drivers/<drv>
        _symlink(f"../../../bus/pci/drivers/{driver}",
                 os.path.join(dev_dir, "driver"))
        # bus view: sys/bus/pci/devices/<bdf> -> device dir
        _symlink(f"../../../devices/pci0000:00/{bdf}",
                 os.path.join(sysd, "bus", "pci", "devices", bdf))
        # driver's bound-device back-link
        _symlink(f"../../../../devices/pci0000:00/{bdf}",
                 os.path.join(drivers_dir, bdf))

        if accel_class:
            _symlink(f"../../../devices/pci0000:00/{bdf}",
                     os.path.join(sysd, "class", "accel", f"accel{i}", "device"))
            _write(os.path.join(devd, f"accel{i}"), "")

        if vfio:
            group = first_group + i
            _symlink(f"../../../kernel/iommu_groups/{group}",
                     os.path.join(dev_dir, "iommu_group"))
            _symlink(f"../../../../devices/pci0000:00/{bdf}",
                     os.path.join(sysd, "kernel", "iommu_groups", str(group),
                                  "devices", bdf))
            _write(os.path.join(devd, "vfio", str(group)), "")

    if vfio:
        _write(os.path.join(devd, "vfio", "vfio"), "")


def main() -> int:
    make_tree("v4-8", 4, "0x005e", "accel", [0, 0, 0, 0],
              accel_class=True, vfio=False)
    make_tree("v5e-8", 8, "0x0063", "vfio-pci", [0] * 8,
              accel_class=False, vfio=True)
    make_tree("v5p-8", 4, "0x0062", "vfio-pci", [0, 0, 1, 1],
              accel_class=False, vfio=True, first_group=12)
    print(f"wrote fixture trees under {ROOT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
