"""Catalog coverage with organic kmsg formats (not just TPU-ERR: injection
lines) — every entry must match at least one realistic driver/kernel line,
and first-hit-wins ordering must keep substring-colliding entries apart
(reference: xid catalog tests over real NVRM lines)."""

import pytest

from gpud_tpu.api.v1.types import HealthStateType, RepairActionType
from gpud_tpu.components.tpu import catalog
from gpud_tpu.components.tpu.health_state import evolve_health
from gpud_tpu.api.v1.types import Event

# entry name → organic sample lines (driver/kernel vocabulary, no TPU-ERR:)
ORGANIC = {
    "tpu_chip_lost": [
        "accel3: device lost, marking offline",
        "accel0: PCI device fell off the bus",
    ],
    "tpu_driver_crash": [
        "accel1: firmware crash detected, dumping state",
        "google_tpu: kernel BUG at drivers/accel/tpu.c:1024",
    ],
    "tpu_reset_failed": [
        "accel2: chip reset failed after 3 attempts",
        "apex 0000:00:05.0: reset timed out",
    ],
    "tpu_chip_reset_required": ["accel0: reset required to recover"],
    "tpu_sram_parity": ["accel0: SRAM parity error in vector memory bank 2"],
    "tpu_core_wedged": ["accel1: TensorCore wedged, initiating recovery"],
    "tpu_scalar_core_fault": ["accel0: scalar core halt at pc=0x4ac0"],
    "tpu_page_fault": [
        "accel0: MMU fault on read at 0xdeadbeef",
        "gasket gasket0: page table error mapping host memory",
    ],
    "tpu_interrupt_timeout": [
        "accel2: interrupt timeout waiting for completion",
        "gasket: MSI-X vector 4 lost",
    ],
    "tpu_dma_error": ["apex 0000:00:05.0: DMA error on channel 1"],
    "tpu_firmware_load_failed": ["accel0: firmware image load failed (-110)"],
    "tpu_driver_init_failed": ["gasket: apex probe failed with -12"],
    "tpu_driver_timeout": ["accel0: ioctl timeout after 5000ms"],
    "tpu_hbm_ecc_uncorrectable": [
        "accel1: uncorrectable HBM ECC error at bank 3",
        "HBM2e channel 4: double-bit ECC error",
    ],
    "tpu_edac_uncorrectable": [
        "EDAC MC0: 1 UE memory read error on chip 2",
        # verbatim instance of drivers/edac/edac_mc.c's report format
        "EDAC MC0: 1 UE memory read error on CPU_SrcID#0_MC#0_Chan#0_DIMM#0 "
        "(channel:0 slot:0 page:0x2f8b00 offset:0x0 grain:32)",
    ],
    "tpu_hbm_row_remap_pending": ["accel0: HBM row 0x1f2 remap pending reboot"],
    "tpu_hbm_ecc_correctable": ["accel2: correctable HBM ECC error, count=14"],
    "tpu_edac_correctable": [
        "EDAC MC0: 7 CE memory scrub corrected",
        "EDAC MC0: 1 CE memory scrubbing error on CPU_SrcID#0_MC#0_Chan#1_DIMM#0 "
        "(channel:1 slot:0 page:0x12a offset:0x0 grain:32 syndrome:0x0)",
    ],
    "tpu_hbm_mce": ["mce: [Hardware Error]: Machine Check: memory read error bank 5"],
    "tpu_hbm_oom": ["libtpu: RESOURCE_EXHAUSTED: failed to allocate 2.1G in HBM"],
    "tpu_ici_cable_fault": ["ICI: cable fault on connector J4"],
    "tpu_ici_link_down": [
        "ICI link 5 down on chip 2",
        "accel0: interchip interconnect trunk down",
    ],
    "tpu_ici_retrain_limit": ["ICI link 1 retrain limit exceeded (32 in 60s)"],
    "tpu_ici_width_degraded": ["ICI link 0 width degraded to 2 lanes"],
    "tpu_ici_routing_error": ["ICI fabric routing table corrupt, entry 0x40"],
    "tpu_ici_crc_errors": ["ICI link 3: CRC error burst, 1024 in window"],
    "tpu_ici_port_error": ["ICI port 2 error: remote not responding"],
    "tpu_ici_link_flap": ["ICI link 4 retrained, speed restored"],
    "tpu_power_fault": ["accel0: power fault on 12V rail"],
    "tpu_vrm_fault": ["VRM overcurrent on TPU socket 1"],
    "tpu_thermal_trip": ["accel1: thermal throttle engaged at 96C"],
    "tpu_power_throttle": ["power cap throttling engaged for package 0"],
    "tpu_thermal_warning": ["accel0: temperature above warning threshold (88C)"],
    "tpu_pcie_uncorrectable": [
        # verbatim: drivers/pci/pcie/aer.c "%s error received: %s"
        "pcieport 0000:00:04.0: AER: Uncorrected (Fatal) error received: 0000:00:05.0",
        "pcieport 0000:00:04.0: AER: Multiple Uncorrected (Non-Fatal) error received: 0000:00:05.0",
    ],
    "tpu_vfio_aer": [
        # verbatim: drivers/pci/pcie/aer.c aer_print_error
        # "PCIe Bus Error: severity=%s, type=%s, (%s)" attributed to the
        # vfio-pci-bound TPU function
        "vfio-pci 0000:00:05.0: PCIe Bus Error: severity=Uncorrected (Fatal), "
        "type=Transaction Layer, (Requester ID)",
        "vfio-pci 0000:00:05.0: AER: error status/mask=00100000/00000000",
    ],
    "tpu_pcie_recovery_failed": [
        # verbatim: drivers/pci/pcie/err.c pcie_do_recovery; the vfio-pci
        # form must beat the generic vfio-AER entry (first-hit-wins)
        "pcieport 0000:00:04.0: AER: device recovery failed",
        "vfio-pci 0000:00:05.0: AER: device recovery failed",
    ],
    "tpu_vfio_aer_correctable": [
        # corrected severity must NOT escalate (benign bursts are normal)
        "vfio-pci 0000:00:05.0: PCIe Bus Error: severity=Corrected, "
        "type=Physical Layer, (Receiver ID)",
        "vfio-pci 0000:00:05.0: AER: Corrected error received: 0000:00:05.0",
    ],
    "tpu_pcie_slot_link_down": [
        # verbatim: drivers/pci/hotplug/pciehp_ctrl.c "Slot(%s): Link Down"
        "pciehp 0000:00:04.0:pcie004: Slot(0): Link Down",
        "pciehp 0000:00:04.0:pcie004: Slot(0): Card not present",
    ],
    "tpu_dev_unbind_requested": [
        # verbatim: drivers/vfio/pci/vfio_pci_core.c
        # "Relaying device request to user (#%u)"
        "vfio-pci 0000:00:05.0: Relaying device request to user (#0)",
        "accel 0000:00:04.0: driver unbind requested",
    ],
    "tpu_vfio_reset_recovery": [
        # verbatim: drivers/vfio/pci/vfio_pci_core.c vfio_bar_restore
        # "%s: reset recovery - restoring BARs"
        "vfio-pci 0000:00:05.0: vfio_bar_restore: reset recovery - restoring BARs",
    ],
    "tpu_pcie_surprise_down": ["pcieport 0000:00:04.0: Surprise Down error"],
    "tpu_pcie_completion_timeout": [
        "pcieport 0000:00:04.0: AER: Completion Timeout (First)"
    ],
    "tpu_pcie_link_downgrade": [
        "pcie 0000:00:04.0: link speed dropped to 8.0 GT/s",
        # verbatim: drivers/pci/pci.c pcie_report_downtraining, attributed
        # to the TPU's bound driver (the bare "pci"-prefixed boot print
        # fires for every downtrained device and is deliberately benign)
        "vfio-pci 0000:00:05.0: 31.504 Gb/s available PCIe bandwidth, limited by "
        "8.0 GT/s PCIe x4 link at 0000:00:03.0 (capable of 63.008 Gb/s with "
        "16.0 GT/s PCIe x4 link)",
    ],
    "tpu_pcie_dpc_containment": [
        # verbatim: drivers/pci/pcie/dpc.c
        "pcieport 0000:00:03.0: DPC: containment event, status:0x1f01 source:0x0000",
        "pcieport 0000:00:03.0: DPC: unmasked uncorrectable error detected",
    ],
    "tpu_pcie_correctable": [
        "pcieport 0000:00:04.0: AER: Corrected error received"
    ],
    "tpu_iommu_fault": [
        "DMAR: [DMA Read] Request device [00:05.0] fault addr 0xfffff000",
        # verbatim: drivers/iommu/intel/dmar.c dmar_fault_do_one (newer
        # kernels append the PASID token inside the bracket)
        "DMAR: [DMA Read NO_PASID] Request device [00:05.0] fault addr "
        "0x7f5a000000 [fault reason 0x06] PTE Read access is not set",
        # verbatim: drivers/iommu/amd/iommu.c "Event logged [IO_PAGE_FAULT ...]"
        "AMD-Vi: Event logged [IO_PAGE_FAULT device=00:05.0 domain=0x000a "
        "address=0xdeadbeef000 flags=0x0070]",
    ],
    "tpu_runtime_oom_killed": [
        # verbatim: mm/oom_kill.c "Out of memory: Killed process %d (%s)
        # total-vm:%lukB, ..." — scoped to TPU runtime process names
        "Out of memory: Killed process 2154 (tpu_runtime) total-vm:18874368kB, "
        "anon-rss:17651200kB, file-rss:0kB, shmem-rss:0kB, UID:0 "
        "pgtables:36100kB oom_score_adj:0",
    ],
    "tpu_host_mem_ghes": [
        # verbatim: CPER decode via drivers/acpi/apei (ghes)
        "{1}[Hardware Error]: section_type: memory error",
    ],
    "tpu_pcie_not_ready": [
        # verbatim: drivers/pci/pci.c pci_dev_wait "not ready %dms after
        # %s; giving up" with the TPU's bound-driver prefix
        "vfio-pci 0000:00:05.0: not ready 65535ms after FLR; giving up",
        "accel 0000:00:04.0: not ready 1023ms after bus reset; giving up",
        "apex 0000:00:06.0: not ready 60000ms after resume; giving up",
    ],
    "tpu_pcie_flr_timeout": [
        # verbatim: drivers/pci/pci.c pcie_flr
        "vfio-pci 0000:00:05.0: timed out waiting for pending transaction; "
        "performing function level reset anyway",
    ],
    "tpu_host_thermal_critical": [
        # verbatim: drivers/thermal/thermal_core.c
        # thermal_zone_device_critical (new + legacy formats)
        "thermal thermal_zone0: acpitz: critical temperature reached, shutting down",
        "critical temperature reached (128 C), shutting down",
    ],
    "tpu_msix_init_failed": [
        "accel 0000:00:04.0: MSI-X vector allocation failed (-28)",
        "gasket: interrupt vector init failed for apex device",
    ],
    "tpu_bar_map_failed": [
        "accel 0000:00:04.0: BAR 2 mapping failed",
        "gasket gasket0: register space request failed (-16)",
    ],
    "tpu_runtime_fatal": ["libtpu.so: check failure: tile assignment invalid"],
    "tpu_runtime_init_failed": ["libtpu: TPU platform initialization failed"],
    "tpu_runtime_hang": ["libtpu: execution deadline exceeded, stack dump follows"],
    "tpu_barrier_timeout": ["megascale: barrier timeout waiting for slice 3"],
    "tpu_megascale_dcn_error": ["megascale: peer slice unreachable via DCN"],
    "tpu_slice_degraded": ["slice health: missing worker 12 of 16"],
}


# Entries whose organic lines instantiate verbatim mainline-kernel printk
# formats (file cited next to each line above). The remaining entries are
# class patterns: the production accel/google_tpu driver is out-of-tree
# (the staging gasket framework was removed in v5.9), so no public verbatim
# string exists to assert — the docstring at catalog.py:1 records this.
KERNEL_GROUNDED = {
    "tpu_edac_uncorrectable",     # drivers/edac/edac_mc.c
    "tpu_edac_correctable",       # drivers/edac/edac_mc.c
    "tpu_pcie_uncorrectable",     # drivers/pci/pcie/aer.c
    "tpu_pcie_correctable",       # drivers/pci/pcie/aer.c
    "tpu_vfio_aer",               # drivers/pci/pcie/aer.c (vfio-pci attributed)
    "tpu_vfio_aer_correctable",   # drivers/pci/pcie/aer.c (corrected severity)
    "tpu_pcie_recovery_failed",   # drivers/pci/pcie/err.c
    "tpu_pcie_slot_link_down",    # drivers/pci/hotplug/pciehp_ctrl.c
    "tpu_pcie_dpc_containment",   # drivers/pci/pcie/dpc.c
    "tpu_pcie_link_downgrade",    # drivers/pci/pci.c (bw notification arm)
    "tpu_dev_unbind_requested",   # drivers/vfio/pci/vfio_pci_core.c
    "tpu_vfio_reset_recovery",    # drivers/vfio/pci/vfio_pci_core.c
    "tpu_iommu_fault",            # drivers/iommu/{intel/dmar.c,amd/iommu.c}
    "tpu_runtime_oom_killed",     # mm/oom_kill.c
    "tpu_host_mem_ghes",          # drivers/acpi/apei (CPER decode)
    "tpu_hbm_mce",                # arch/x86 mce + edac decode vocabulary
    "tpu_pcie_not_ready",         # drivers/pci/pci.c pci_dev_wait
    "tpu_pcie_flr_timeout",       # drivers/pci/pci.c pcie_flr
    "tpu_host_thermal_critical",  # drivers/thermal/thermal_core.c
}


def test_catalog_size_and_coverage_table_complete():
    assert len(catalog.CATALOG) >= 50
    assert set(ORGANIC) == {e.name for e in catalog.CATALOG}
    # every kernel-grounded entry exists and keeps >= 1 verbatim line
    assert KERNEL_GROUNDED <= set(ORGANIC)


@pytest.mark.parametrize("name", sorted(ORGANIC))
def test_organic_lines_match_expected_entry(name):
    for line in ORGANIC[name]:
        m = catalog.match(line)
        assert m is not None, f"no match for organic line: {line!r}"
        assert m.entry.name == name, (
            f"{line!r} matched {m.entry.name}, expected {name}"
        )


@pytest.mark.parametrize("name", sorted(ORGANIC))
def test_injection_lines_match_their_entry(name):
    m = catalog.match(catalog.injection_line(name, chip_id=3))
    assert m is not None and m.entry.name == name
    assert m.chip_id == 3


def test_substring_collisions_resolved_by_order():
    # "uncorrectable" contains "correctable"; UE before CE; retrain limit
    # before the generic retrain/flap entry
    assert catalog.match("HBM uncorrectable ECC").entry.name == "tpu_hbm_ecc_uncorrectable"
    assert catalog.match("EDAC MC0: UE error").entry.name == "tpu_edac_uncorrectable"
    assert (
        catalog.match("ICI link 0 retrain limit exceeded").entry.name
        == "tpu_ici_retrain_limit"
    )
    assert catalog.match("ICI link 0 retrained ok").entry.name == "tpu_ici_link_flap"


# benign host-wide kernel lines that used to (or could) false-positive —
# none may match any catalog entry
BENIGN = [
    "mce: [Hardware Error]: Machine check events logged",
    "mce: [Hardware Error]: CPU 2: Machine Check: 0 Bank 6: status",
    "nvme 0000:01:00.0: AER: Completion Timeout error",
    "pcieport 0000:00:1c.5: nvme: Surprise Down Error (First)",
    "thermal thermal_zone0: trip point 1 crossed",
    "DMAR: DRHD: handling fault status reg 2",
    "DMAR: [DMA Read] Request device [02:00.0] nvme fault addr 0x0",
    "xhci_hcd 0000:00:14.0: Completion Timeout on ep 0x81",
    # routine vfio lifecycle lines on a healthy TPU VM
    "vfio-pci 0000:00:05.0: enabling device (0000 -> 0002)",
    "vfio-pci 0000:00:05.0: vfio_cap_init: hiding cap 0x12",
    # OOM kill of a non-TPU process belongs to the memory component
    "Out of memory: Killed process 3452 (chrome) total-vm:8234kB, anon-rss:100kB",
    # AER recovery success is not a failure
    "pcieport 0000:00:04.0: AER: device recovery successful",
    # bandwidth notifications not attributed to a TPU-bound driver must
    # not classify — neither a named NIC nor the bare "pci"-prefixed
    # enumeration print that fires for EVERY downtrained device at boot
    "mlx5_core 0000:01:00.0: 63.008 Gb/s available PCIe bandwidth, limited by "
    "8.0 GT/s PCIe x8 link at 0000:00:01.0",
    "bnxt_en 0000:02:00.0: 31.504 Gb/s available PCIe bandwidth, limited by "
    "8.0 GT/s PCIe x4 link at 0000:00:03.0",
    "pci 0000:01:00.0: 31.504 Gb/s available PCIe bandwidth, limited by "
    "8.0 GT/s PCIe x4 link at 0000:00:03.0",
    # reset-failure / FLR-drain lines from non-TPU devices keep their own
    # driver prefix and must not classify as TPU loss
    "nvme 0000:01:00.0: not ready 65535ms after FLR; giving up",
    "mlx5_core 0000:02:00.0: timed out waiting for pending transaction; "
    "performing function level reset anyway",
    # hotplug insertion (the healthy direction)
    "pciehp 0000:00:1c.0:pcie004: Slot(5): Card present",
    # non-critical thermal trip survives the new thermal-critical entry
    "thermal thermal_zone0: trip point 0 crossed with 45000 milli celsius",
]


@pytest.mark.parametrize("line", BENIGN)
def test_benign_host_lines_do_not_match(line):
    m = catalog.match(line)
    assert m is None, f"{line!r} misclassified as {m.entry.name if m else ''}"


def test_chip_extraction_variants():
    assert catalog.extract_chip("accel7: device lost") == 7
    assert catalog.extract_chip("error on chip 3 bank 1") == 3
    assert catalog.extract_chip("TPU-ERR: x chip=5") == 5
    assert catalog.extract_chip("no chip here") is None


# ---------------------------------------------------------------------------
# per-chip escalation (VERDICT: two-chip scenario, independent tracks)
# ---------------------------------------------------------------------------

def _err(name, t, chip=None):
    msg = f"accel{chip}: synthetic" if chip is not None else "synthetic"
    return Event(component="x", time=t, name=name, type="Fatal", message=msg)


def _reboot(t):
    return Event(component="x", time=t, name="reboot", type="Warning", message="")


def test_two_chips_escalate_independently():
    """chip 0: error → reboot → recurrence (escalates to HW inspection);
    chip 1: single first occurrence of the same error name (reboot only).
    One shared reboot affects both tracks, but only chip 0 recurred."""
    evs = [
        _err("tpu_chip_lost", 100, chip=0),
        _reboot(200),
        _err("tpu_chip_lost", 300, chip=0),  # recurred after 1 reboot
        _reboot(400),
        _err("tpu_chip_lost", 500, chip=0),  # recurred after 2 reboots ⇒ escalate
        _err("tpu_chip_lost", 550, chip=1),  # fresh on chip 1
    ]
    out = evolve_health(evs)
    assert out.health == HealthStateType.UNHEALTHY
    assert "tpu_chip_lost(chip 0) recurred after 2 reboot(s)" in out.reason
    assert "tpu_chip_lost(chip 1) (x1)" in out.reason
    assert out.active_errors["tpu_chip_lost(chip 0)"] == 3
    assert out.active_errors["tpu_chip_lost(chip 1)"] == 1
    # escalation strips the reboot suggestion
    assert RepairActionType.HARDWARE_INSPECTION in out.suggested_actions.repair_actions
    assert RepairActionType.REBOOT_SYSTEM not in out.suggested_actions.repair_actions


def test_reboot_resolves_only_non_recurring_chip():
    """chip 0 recurs after the reboot, chip 1 does not: chip 1's track is
    resolved, chip 0 stays active."""
    evs = [
        _err("tpu_chip_lost", 100, chip=0),
        _err("tpu_chip_lost", 110, chip=1),
        _reboot(200),
        _err("tpu_chip_lost", 300, chip=0),
    ]
    out = evolve_health(evs)
    assert "chip 0" in out.reason
    assert "chip 1" not in out.reason
    assert list(out.active_errors) == ["tpu_chip_lost(chip 0)"]


def test_chipless_events_share_one_track():
    evs = [
        _err("tpu_runtime_fatal", 100),
        _err("tpu_runtime_fatal", 200),
    ]
    out = evolve_health(evs)
    assert out.active_errors == {"tpu_runtime_fatal": 2}


def test_prefilter_complete_over_corpus():
    """The hot-loop prefilter must never reject a line any catalog pattern
    would match — checked over every organic and injection line, plus
    perturbed casings."""
    for name, lines in ORGANIC.items():
        for line in lines + [catalog.injection_line(name, chip_id=1)]:
            for variant in (line, line.upper(), line.lower()):
                assert catalog._PREFILTER.search(variant) is not None, variant
                # and full match agrees with the unfiltered walk
                m = catalog.match(line)
                assert m is not None and m.entry.name == name


def test_prefilter_rejects_typical_benign_lines():
    for line in [
        "audit: type=1400 apparmor=ALLOWED operation=open",
        "systemd[1]: Started Daily apt download activities.",
        "eth0: link becomes ready",
        "EXT4-fs (sda1): mounted filesystem with ordered data mode",
    ]:
        assert catalog._PREFILTER.search(line) is None, line
        assert catalog.match(line) is None


def test_catalog_doc_in_sync():
    """docs/CATALOG.md is generated from the catalog; regen must match
    the committed file (reference ships its catalog as generated code)."""
    import os

    from gpud_tpu.tools.gen_catalog_doc import render

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "CATALOG.md")
    committed = open(path, "r", encoding="utf-8").read()
    assert committed == render(), (
        "docs/CATALOG.md stale — run python -m gpud_tpu.tools.gen_catalog_doc"
    )
