import base64

import pytest

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.plugins.component import (
    PluginComponent,
    build_components,
    run_init_plugins,
)
from gpud_tpu.plugins.spec import (
    MatchRule,
    OutputParser,
    PluginSpec,
    PluginStep,
    extract_path,
    load_specs,
    save_specs,
    specs_from_list,
)


def _spec(**kw):
    base = dict(
        name="p1",
        steps=[PluginStep(name="s1", script="echo hello")],
    )
    base.update(kw)
    return PluginSpec.from_dict(PluginSpec(**base).to_dict())


def test_spec_validate():
    assert _spec().validate() is None
    assert _spec(name="").validate()
    assert _spec(name="bad name!").validate()
    assert _spec(plugin_type="weird").validate()
    assert _spec(steps=[]).validate()
    assert _spec(plugin_type="component_list").validate()  # needs list


def test_specs_yaml_roundtrip(tmp_path):
    specs = [
        _spec(name="a"),
        _spec(name="b", run_mode="manual", tags=["t1"]),
    ]
    p = tmp_path / "plugins.yaml"
    save_specs(str(p), specs)
    back = load_specs(str(p))
    assert [s.name for s in back] == ["a", "b"]
    assert back[1].run_mode == "manual"


def test_specs_duplicate_names_rejected():
    with pytest.raises(ValueError):
        specs_from_list([_spec(name="x").to_dict(), _spec(name="x").to_dict()])


def test_extract_path():
    doc = {"a": {"b": [{"c": 42}]}, "top": "v"}
    assert extract_path(doc, "$.a.b[0].c") == 42
    assert extract_path(doc, "$.top") == "v"
    assert extract_path(doc, "$.missing.x") is None
    assert extract_path(doc, "no-dollar") is None


def test_plugin_component_healthy():
    c = PluginComponent(TpudInstance(), _spec())
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.HEALTHY
    assert "hello" in cr.raw_output
    assert c.can_deregister()


def test_plugin_exit_code_contract():
    spec = _spec(steps=[PluginStep(name="fail", script="echo nope; exit 3")])
    cr = PluginComponent(TpudInstance(), spec).check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert "exited 3" in cr.summary()


def test_plugin_base64_step():
    b64 = base64.b64encode(b"echo from-b64").decode()
    spec = _spec(steps=[PluginStep(name="b", script_base64=b64)])
    cr = PluginComponent(TpudInstance(), spec).check()
    assert "from-b64" in cr.raw_output


def test_plugin_json_parser_and_match_rules():
    spec = _spec(
        steps=[PluginStep(name="j", script='echo \'{"status": "bad", "count": 5}\'')],
        parser=OutputParser(
            json_paths={"status": "$.status", "count": "$.count"},
            match_rules=[
                MatchRule(
                    regex="bad",
                    field="status",
                    health="Unhealthy",
                    suggested_actions=["REBOOT_SYSTEM"],
                    description="status went bad",
                )
            ],
        ),
    )
    cr = PluginComponent(TpudInstance(), spec).check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert cr.extra_info["status"] == "bad"
    assert cr.extra_info["count"] == "5"
    assert cr.suggested_actions.repair_actions == ["REBOOT_SYSTEM"]


def test_plugin_raw_match_rule():
    spec = _spec(
        steps=[PluginStep(name="r", script="echo WARNING something degraded")],
        parser=OutputParser(
            match_rules=[MatchRule(regex="WARNING", health="Degraded")]
        ),
    )
    cr = PluginComponent(TpudInstance(), spec).check()
    assert cr.health_state_type() == HealthStateType.DEGRADED


def test_plugin_timeout():
    spec = _spec(
        steps=[PluginStep(name="slow", script="sleep 5")],
        timeout_seconds=0.3,
    )
    cr = PluginComponent(TpudInstance(), spec).check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert "timed out" in cr.summary()


def test_component_list_fanout():
    spec = _spec(
        name="multi",
        plugin_type="component_list",
        component_list=["a", "b"],
        steps=[PluginStep(name="s", script='echo "item=$TPUD_PLUGIN_ITEM"')],
    )
    comps = build_components(TpudInstance(), [spec])
    assert [c.name() for c in comps] == ["multi.a", "multi.b"]
    cr = comps[1].check()
    assert "item=b" in cr.raw_output


def test_init_plugin_gate():
    ok = _spec(name="init-ok", plugin_type="init")
    assert run_init_plugins(TpudInstance(), [ok]) is None
    bad = _spec(
        name="init-bad",
        plugin_type="init",
        steps=[PluginStep(name="f", script="exit 1")],
    )
    err = run_init_plugins(TpudInstance(), [bad])
    assert err and "init-bad" in err


def test_manual_plugin_not_started():
    spec = _spec(name="man", run_mode="manual")
    c = PluginComponent(TpudInstance(), spec)
    c.start()
    assert c._thread is None  # no poller for manual mode
