"""Checked-in kmsg log replay (reference: pkg/kmsg/testdata and
xid/testdata check in real kernel logs and assert exact match sets).

The fixture is a realistic v5p-VM boot log — benign boot noise that has
historically false-positived (MCE replay, DMAR status, thermal trips,
vfio enable lines) — followed by a correlated fault burst. The scan-mode
path (read_all → catalog) must detect EXACTLY the burst, attribute the
right classes, and stay silent on every boot line."""

import os

from gpud_tpu.components.tpu import catalog
from gpud_tpu.kmsg.watcher import read_all

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "kmsg", "v5p_boot_with_faults.log"
)

EXPECTED = {
    "tpu_vfio_aer",              # uncorrected AER on the vfio-bound TPU
    "tpu_pcie_recovery_failed",  # root port gave up
    "tpu_pcie_slot_link_down",   # hotplug slot lost the device
    "tpu_dev_unbind_requested",  # vfio asked userspace to release it
    "tpu_edac_uncorrectable",    # host DIMM UE in the same window
    "tpu_runtime_oom_killed",    # runtime got OOM-killed in the fallout
    "tpu_vfio_reset_recovery",   # device came back via BAR restore
}


def test_fixture_parses_fully():
    msgs = read_all(path=FIXTURE)
    assert len(msgs) == 25  # every line parses; nothing silently dropped
    assert msgs[0].message.startswith("Linux version")
    # fixture timestamps are monotonic (timestamp_us is pure fixture
    # data; m.time would collapse to wall-clock when boot_time() is 0)
    ts = [m.timestamp_us for m in msgs]
    assert ts == sorted(ts)


def test_exact_detection_set():
    msgs = read_all(path=FIXTURE)
    hits = {}
    for m in msgs:
        r = catalog.match(m.message)
        if r is not None:
            hits.setdefault(r.entry.name, []).append(m.message)
    assert set(hits) == EXPECTED, (
        f"missing={EXPECTED - set(hits)} unexpected={set(hits) - EXPECTED}"
    )
    # each class fired exactly once in this log
    assert all(len(v) == 1 for v in hits.values()), hits


def test_boot_section_is_silent():
    msgs = read_all(path=FIXTURE)
    # first minute since boot, in fixture time (timestamp_us)
    boot = [m for m in msgs if (m.timestamp_us - msgs[0].timestamp_us) < 60e6]
    assert len(boot) == 18  # the whole boot section, none of the burst
    for m in boot:
        r = catalog.match(m.message)
        assert r is None, f"boot line misclassified as {r.entry.name}: {m.message!r}"


def test_burst_classes_have_sane_severities():
    by_name = {e.name: e for e in catalog.CATALOG}
    # the chip-dropping classes must be reboot/hw-actionable
    for name in ("tpu_vfio_aer", "tpu_pcie_recovery_failed",
                 "tpu_pcie_slot_link_down"):
        assert by_name[name].critical
        assert by_name[name].repair_actions
    # fallout records are informational, not health-flipping
    for name in ("tpu_dev_unbind_requested", "tpu_runtime_oom_killed"):
        assert not by_name[name].critical


def test_scan_mode_component_over_fixture(monkeypatch):
    """The error-kmsg component's scan path (no event store) reads the
    whole fixture ring and reports the burst in one check."""
    from gpud_tpu.components.base import TpudInstance
    from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent

    monkeypatch.setenv("TPUD_KMSG_FILE_PATH", FIXTURE)
    c = TPUErrorKmsgComponent(TpudInstance())
    r = c.check_once()
    assert r.health != "Healthy"
    for name in ("tpu_vfio_aer", "tpu_pcie_recovery_failed"):
        assert name in r.reason or name in str(r.extra_info), (name, r.reason)
