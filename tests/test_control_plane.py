"""Standalone control plane e2e: real daemons enrolled with the runnable
manager (gpud_tpu/manager/control_plane.py) over BOTH transports, driven
through the operator API — the server-side counterpart the reference
never ships (its control plane is SaaS; reference: pkg/session/session.go
speaks to it, nothing serves it)."""

import json
import time

import pytest

from gpud_tpu.config import default_config
from gpud_tpu.manager.control_plane import AgentGone, AgentHandle, ControlPlane
from gpud_tpu.server.server import Server
from gpud_tpu.session.session import Session

requests = pytest.importorskip("requests")


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """ControlPlane + a live daemon enrolled over v1 (the aiohttp port is
    not gRPC-capable, so protocol=auto falls back — the split-port v2
    path is covered separately below)."""
    tmp = tmp_path_factory.mktemp("cp-e2e")
    cp = ControlPlane()
    cp.start()
    kmsg = tmp / "kmsg.fixture"
    kmsg.write_text("")
    cfg = default_config(
        data_dir=str(tmp / "data"),
        port=0,
        tls=False,
        kmsg_path=str(kmsg),
        endpoint=cp.endpoint,
        token="join-token",
        machine_id="cp-agent-1",
        components_disabled=["network-latency"],
    )
    srv = Server(config=cfg)
    srv.start()
    deadline = time.time() + 15
    while time.time() < deadline and "cp-agent-1" not in cp.agents:
        time.sleep(0.05)
    yield cp, srv
    srv.stop()
    cp.stop()


def test_daemon_appears_in_machine_list(stack):
    cp, _srv = stack
    machines = cp.machines()
    ids = {m["machine_id"] for m in machines}
    assert "cp-agent-1" in ids
    (m,) = [m for m in machines if m["machine_id"] == "cp-agent-1"]
    assert m["transport"] == "v1"
    assert m["version"]  # daemon advertises its version header


def test_operator_request_states_roundtrip(stack):
    cp, _srv = stack
    resp = cp.agent("cp-agent-1").request({"method": "states"}, timeout=15)
    comps = {s["component"] for s in resp["states"]}
    assert "cpu" in comps and "accelerator-tpu-ici" in comps


def test_operator_http_api_end_to_end(stack):
    cp, _srv = stack
    r = requests.get(f"{cp.endpoint}/v1/machines", timeout=10)
    assert r.status_code == 200
    assert "cp-agent-1" in {m["machine_id"] for m in r.json()["machines"]}

    r = requests.post(
        f"{cp.endpoint}/v1/machines/cp-agent-1/request",
        json={"method": "gossip"},
        timeout=20,
    )
    assert r.status_code == 200
    body = r.json()
    assert body["machine_id"] == "cp-agent-1"
    assert body["response"]["status"] in ("started", "ok")


def test_operator_request_unknown_machine_404(stack):
    cp, _srv = stack
    r = requests.post(
        f"{cp.endpoint}/v1/machines/no-such/request",
        json={"method": "states"},
        timeout=10,
    )
    assert r.status_code == 404


def test_operator_request_validates_body(stack):
    cp, _srv = stack
    base = f"{cp.endpoint}/v1/machines/cp-agent-1/request"
    assert requests.post(base, data=b"not json", timeout=10).status_code == 400
    assert requests.post(base, json={"no": "method"}, timeout=10).status_code == 400


def test_inject_fault_detected_via_manager(stack):
    cp, _srv = stack
    h = cp.agent("cp-agent-1")
    resp = h.request(
        {
            "method": "injectFault",
            "tpu_error_name": "tpu_ici_cable_fault",
            "chip_id": 1,
        },
        timeout=15,
    )
    assert resp["status"] == "ok"
    deadline = time.time() + 10
    while time.time() < deadline:
        states = h.request(
            {"method": "states", "components": ["accelerator-tpu-error-kmsg"]},
            timeout=15,
        )["states"]
        st = states[0]["states"][0]
        if st["health"] == "Unhealthy":
            assert "tpu_ici_cable_fault" in st["reason"]
            return
        time.sleep(0.3)
    raise AssertionError("injected fault never surfaced via the manager")


def test_fleet_plane_correlation_end_to_end(stack):
    """The full stitch: a real check run mints a correlation id, the
    transition rides the outbox to the manager's rollup store, and the
    id resolves BOTH ways — /v1/fleet/traces on the manager and the
    ``traces`` session method against the live agent's ring."""
    cp, _srv = stack
    h = cp.agent("cp-agent-1")
    # the inject test above forced a Healthy→Unhealthy transition inside
    # a component check; wait for its outbox record to reach the rollup
    cid = None
    record = None
    deadline = time.time() + 20
    while time.time() < deadline and cid is None:
        hist = requests.get(
            f"{cp.endpoint}/v1/fleet/agents/cp-agent-1/history?limit=200",
            timeout=10,
        ).json()
        for rec in hist["records"]:
            if rec["kind"] == "transition" and rec["correlation_id"]:
                cid, record = rec["correlation_id"], rec
                break
        if cid is None:
            time.sleep(0.3)
    assert cid, "no correlated transition reached the manager within 20s"

    r = requests.get(
        f"{cp.endpoint}/v1/fleet/traces?correlation_id={cid}", timeout=10
    )
    assert r.status_code == 200
    stitched = r.json()
    assert stitched["count"] >= 1
    assert any(
        rec["dedupe_key"] == record["dedupe_key"]
        for rec in stitched["records"]
    )

    # ...and back down to the agent: the same id finds the originating
    # check span in the live trace ring (if it hasn't aged out of the
    # bounded ring under the stack's check churn, its attrs must match)
    spans = h.request(
        {"method": "traces", "correlation_id": cid, "limit": 16},
        timeout=15,
    )["spans"]
    for sp in spans:
        assert sp["attrs"]["correlation_id"] == cid
        assert sp["component"] == record["payload"]["component"]

    # the rollup view agrees the agent has transitioned
    page = requests.get(
        f"{cp.endpoint}/v1/fleet/agents?limit=10", timeout=10
    ).json()
    (agent,) = [a for a in page["agents"] if a["agent"] == "cp-agent-1"]
    assert sum(
        c["transitions"] for c in agent["components"].values()
    ) >= 1


# -- admin auth ------------------------------------------------------------


def test_admin_token_guards_operator_api(tmp_path):
    cp = ControlPlane(admin_token="s3cret")
    cp.start()
    try:
        r = requests.get(f"{cp.endpoint}/v1/machines", timeout=10)
        assert r.status_code == 401
        r = requests.get(
            f"{cp.endpoint}/v1/machines",
            headers={"Authorization": "Bearer s3cret"},
            timeout=10,
        )
        assert r.status_code == 200
        r = requests.post(
            f"{cp.endpoint}/v1/machines/x/request",
            json={"method": "states"},
            timeout=10,
        )
        assert r.status_code == 401
        r = requests.post(f"{cp.endpoint}/v1/drain", timeout=10)
        assert r.status_code == 401
    finally:
        cp.stop()


def test_login_issues_identity(tmp_path):
    cp = ControlPlane()
    cp.start()
    try:
        r = requests.post(
            f"{cp.endpoint}/api/v1/login", json={"token": "join"}, timeout=10
        )
        body = r.json()
        assert body["machine_id"].startswith("m-")
        assert body["token"].startswith("tok-")
        # a second login with an explicit machine_id keeps it
        r = requests.post(
            f"{cp.endpoint}/api/v1/login",
            json={"token": "join", "machine_id": "keep-me"},
            timeout=10,
        )
        assert r.json()["machine_id"] == "keep-me"
        assert len(cp.logins) == 2
    finally:
        cp.stop()


def test_fixed_session_token_gates_login(tmp_path):
    """Enrollment must present the fleet secret — login must not hand the
    session token to arbitrary callers."""
    cp = ControlPlane(session_token="fleet-token")
    cp.start()
    try:
        r = requests.post(
            f"{cp.endpoint}/api/v1/login", json={"token": "wrong"}, timeout=10
        )
        assert r.status_code == 401
        r = requests.post(
            f"{cp.endpoint}/api/v1/login",
            json={"token": "fleet-token"},
            timeout=10,
        )
        assert r.status_code == 200
        assert r.json()["token"] == "fleet-token"
    finally:
        cp.stop()


def test_request_timeout_param_validated(stack):
    cp, _srv = stack
    r = requests.post(
        f"{cp.endpoint}/v1/machines/cp-agent-1/request",
        json={"method": "gossip"},
        params={"timeout": "abc"},
        timeout=10,
    )
    assert r.status_code == 400


def test_fixed_session_token_rejects_bad_bearer(tmp_path):
    cp = ControlPlane(session_token="fleet-token")
    cp.start()
    try:
        r = requests.post(
            f"{cp.endpoint}/api/v1/session",
            headers={
                "X-TPUD-Session-Type": "write",
                "X-TPUD-Machine-ID": "m1",
                "Authorization": "Bearer wrong",
            },
            data=b"",
            timeout=10,
        )
        assert r.status_code == 401
    finally:
        cp.stop()


# -- v2 (gRPC, split-port) -------------------------------------------------


@pytest.fixture()
def v2_stack(tmp_path, monkeypatch):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    cp = ControlPlane()
    cp.start()
    assert cp.grpc_port > 0
    monkeypatch.setenv("TPUD_SESSION_V2_TARGET", f"127.0.0.1:{cp.grpc_port}")
    yield cp
    cp.stop()


def _mk_session(cp, machine_id, **kw):
    responses = []
    s = Session(
        endpoint=cp.endpoint,
        machine_id=machine_id,
        token="t",
        machine_proof="p",
        dispatch_fn=lambda req: {"echo": req.get("method"), **kw},
        protocol="auto",
    )
    s.start()
    return s, responses


def test_v2_agent_negotiates_rev3_and_answers_typed(v2_stack):
    cp = v2_stack
    s, _ = _mk_session(cp, "v2-agent")
    try:
        deadline = time.time() + 10
        while time.time() < deadline and "v2-agent" not in cp.agents:
            time.sleep(0.05)
        h = cp.agent("v2-agent")
        assert h.transport == "v2-rev3"
        # travels as a typed GetStatesRequest, comes back as a Result
        resp = h.request({"method": "states"}, timeout=10)
        assert resp == {"echo": "states"}
        # parameterized method: typed TriggerComponentRequest
        resp = h.request(
            {"method": "triggerComponent", "component": "cpu", "tag": ""},
            timeout=10,
        )
        assert resp == {"echo": "triggerComponent"}
    finally:
        s.stop()


def test_v2_drain_notifies_agent(v2_stack):
    cp = v2_stack
    s, _ = _mk_session(cp, "v2-drainee")
    try:
        deadline = time.time() + 10
        while time.time() < deadline and "v2-drainee" not in cp.agents:
            time.sleep(0.05)
        cp.drain("test drain")
        deadline = time.time() + 5
        while time.time() < deadline and "v2-drainee" in cp.agents:
            time.sleep(0.05)
        assert "v2-drainee" not in cp.agents
    finally:
        s.stop()


def test_v2_agent_can_reconnect_after_drain(v2_stack):
    """Drain is point-in-time: an agent reconnecting afterwards is served
    normally, not immediately re-drained."""
    cp = v2_stack
    s, _ = _mk_session(cp, "re-enroll")
    try:
        deadline = time.time() + 10
        while time.time() < deadline and "re-enroll" not in cp.agents:
            time.sleep(0.05)
        cp.drain("rolling restart")
        # the session auto-reconnects; wait for a FRESH handle
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                h = cp.agent("re-enroll")
                resp = h.request({"method": "states"}, timeout=10)
                assert resp == {"echo": "states"}
                return
            except (AgentGone, TimeoutError):
                time.sleep(0.2)
        raise AssertionError("agent never usable again after drain")
    finally:
        s.stop()


def test_v2_empty_stream_closes_cleanly(v2_stack):
    """A probe that opens Connect and half-closes without Hello must not
    crash the servicer (PEP 479)."""
    grpc = pytest.importorskip("grpc")
    from gpud_tpu.session.v2 import session_pb2 as pb

    cp = v2_stack
    channel = grpc.insecure_channel(f"127.0.0.1:{cp.grpc_port}")
    stream = channel.stream_stream(
        "/tpud.session.v2.Session/Connect",
        request_serializer=pb.AgentPacket.SerializeToString,
        response_deserializer=pb.ManagerPacket.FromString,
    )
    call = stream(iter(()))  # zero messages, immediate half-close
    assert list(call) == []  # server closes without error status
    channel.close()
    # the manager is still fully operational afterwards
    assert requests.get(f"{cp.endpoint}/v1/machines", timeout=10).status_code == 200


def test_live_daemon_over_v2(v2_stack, tmp_path):
    cp = v2_stack
    kmsg = tmp_path / "kmsg.fixture"
    kmsg.write_text("")
    cfg = default_config(
        data_dir=str(tmp_path / "data"),
        port=0,
        tls=False,
        kmsg_path=str(kmsg),
        endpoint=cp.endpoint,
        token="join-token",
        machine_id="v2-daemon",
        components_disabled=["network-latency"],
    )
    srv = Server(config=cfg)
    srv.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and "v2-daemon" not in cp.agents:
            time.sleep(0.05)
        h = cp.agent("v2-daemon")
        assert h.transport == "v2-rev3"
        states = h.request({"method": "states"}, timeout=15)["states"]
        assert {s["component"] for s in states} >= {"cpu", "memory"}
    finally:
        srv.stop()


def test_bad_operator_params_do_not_kill_v2_stream(v2_stack):
    """An operator request the typed encoder chokes on (since='abc') must
    not tear down the agent's Connect stream — it falls back to the Frame
    tunnel and the agent answers (a structured error or echo)."""
    cp = v2_stack
    s, _ = _mk_session(cp, "sturdy")
    try:
        deadline = time.time() + 10
        while time.time() < deadline and "sturdy" not in cp.agents:
            time.sleep(0.05)
        h = cp.agent("sturdy")
        resp = h.request({"method": "events", "since": "abc"}, timeout=10)
        assert resp == {"echo": "events"}  # delivered via Frame fallback
        # the stream survived: a normal typed request still works
        assert h.request({"method": "states"}, timeout=10) == {"echo": "states"}
        assert not h.gone
    finally:
        s.stop()


def test_agent_min_revision_above_manager_is_rejected(v2_stack):
    """A future agent with min_revision > manager max gets accepted=false,
    not a revision it disclaimed."""
    grpc = pytest.importorskip("grpc")
    from gpud_tpu.session.v2 import session_pb2 as pb

    cp = v2_stack
    channel = grpc.insecure_channel(f"127.0.0.1:{cp.grpc_port}")
    stream = channel.stream_stream(
        "/tpud.session.v2.Session/Connect",
        request_serializer=pb.AgentPacket.SerializeToString,
        response_deserializer=pb.ManagerPacket.FromString,
    )
    hello = pb.AgentPacket()
    hello.hello.machine_id = "future-agent"
    hello.hello.token = "t"
    hello.hello.min_revision = 4
    hello.hello.max_revision = 4
    replies = list(stream(iter([hello])))
    channel.close()
    assert len(replies) == 1
    ack = replies[0].hello_ack
    assert not ack.accepted
    assert "no common revision" in ack.reason
    assert "future-agent" not in cp.agents


def test_grpc_bind_conflict_fails_loudly():
    pytest.importorskip("grpc")
    cp1 = ControlPlane()
    cp1.start()
    try:
        cp2 = ControlPlane(grpc_port=cp1.grpc_port)
        with pytest.raises(RuntimeError, match="bind failed|Failed to bind"):
            cp2.start()
        # start() failed atomically: the HTTP side was torn down too, and
        # a redundant stop() is a safe no-op
        assert requests_connect_refused(cp2.port)
        cp2.stop()
    finally:
        cp1.stop()


def requests_connect_refused(port):
    import socket

    s = socket.socket()
    s.settimeout(2)
    try:
        return s.connect_ex(("127.0.0.1", port)) != 0
    finally:
        s.close()


def test_drain_reason_reaches_v2_agents(v2_stack):
    """The operator's drain reason must arrive in the DrainNotice, not a
    hard-coded string."""
    grpc = pytest.importorskip("grpc")
    from gpud_tpu.session.v2 import session_pb2 as pb

    cp = v2_stack
    channel = grpc.insecure_channel(f"127.0.0.1:{cp.grpc_port}")
    stream = channel.stream_stream(
        "/tpud.session.v2.Session/Connect",
        request_serializer=pb.AgentPacket.SerializeToString,
        response_deserializer=pb.ManagerPacket.FromString,
    )
    import queue as q_mod

    feed = q_mod.Queue()
    hello = pb.AgentPacket()
    hello.hello.machine_id = "drain-watch"
    hello.hello.token = "t"
    hello.hello.max_revision = 2

    def gen():
        yield hello
        while True:
            item = feed.get()
            if item is None:
                return
            yield item

    call = stream(gen())
    replies = iter(call)
    ack = next(replies)
    assert ack.hello_ack.accepted
    deadline = time.time() + 5
    while time.time() < deadline and "drain-watch" not in cp.agents:
        time.sleep(0.05)
    cp.drain("rolling restart xyz")
    notice = next(replies)
    assert notice.WhichOneof("payload") == "drain_notice"
    assert notice.drain_notice.reason == "rolling restart xyz"
    feed.put(None)
    channel.close()


def test_v2_target_resolution_pins_tls_mode():
    from gpud_tpu.session.v2.client import resolve_v2_target

    # no override: derived from the endpoint
    assert resolve_v2_target("https://cp.example", "") == ("cp.example:443", True)
    assert resolve_v2_target("http://cp.example:8080", "") == (
        "cp.example:8080",
        False,
    )
    # scheme on the override pins its own TLS mode
    assert resolve_v2_target("https://cp.example", "http://127.0.0.1:9") == (
        "127.0.0.1:9",
        False,
    )
    assert resolve_v2_target("http://cp.example", "https://sec:9") == (
        "sec:9",
        True,
    )
    # bare host:port inherits the endpoint's scheme
    assert resolve_v2_target("https://cp.example", "127.0.0.1:9") == (
        "127.0.0.1:9",
        True,
    )


def test_session_v2_target_param_beats_env(monkeypatch):
    monkeypatch.setenv("TPUD_SESSION_V2_TARGET", "env:1")
    s = Session(
        endpoint="http://cp",
        machine_id="m",
        dispatch_fn=lambda r: {},
        v2_target="param:2",
    )
    assert s.v2_target == "param:2"
    s2 = Session(endpoint="http://cp", machine_id="m", dispatch_fn=lambda r: {})
    assert s2.v2_target == "env:1"


def test_cli_manager_clean_errors_without_manager(capsys):
    """Operator CLI failures print one-line errors, never tracebacks."""
    from gpud_tpu.cli import main

    rc = main(
        ["manager", "machines", "--endpoint", "http://127.0.0.1:1"]  # closed
    )
    assert rc == 1
    err = capsys.readouterr().err
    assert err.startswith("error:")
    rc = main(
        [
            "manager",
            "request",
            "m1",
            "states",
            "--endpoint",
            "http://127.0.0.1:1",
            "--params",
            "{bad json",
        ]
    )
    assert rc == 1
    assert "error:" in capsys.readouterr().err


# -- handle semantics ------------------------------------------------------


def test_control_plane_is_one_shot():
    """start() after stop() must refuse loudly (pools are shut down), and
    double-start is an error — not a silent half-working restart."""
    cp = ControlPlane()
    cp.start()
    with pytest.raises(RuntimeError, match="already started"):
        cp.start()
    cp.stop()
    with pytest.raises(RuntimeError, match="cannot be restarted"):
        cp.start()


def test_agent_gone_fails_pending_requests():
    h = AgentHandle("m", "v1")
    import threading

    got = []
    t = threading.Thread(
        target=lambda: got.append(h.request({"method": "states"}, timeout=5))
    )
    t.start()
    time.sleep(0.1)
    h.mark_gone()
    t.join(timeout=5)
    assert got == [{"error": "agent disconnected"}]
    with pytest.raises(AgentGone):
        h.request({"method": "states"})


def test_unsolicited_responses_bounded():
    h = AgentHandle("m", "v1")
    for i in range(200):
        h.resolve(f"unknown-{i}", {"i": i})
    assert len(h.unsolicited) == 64
    assert h.unsolicited[-1]["data"]["i"] == 199


def test_reconnect_replaces_stale_handle(tmp_path):
    cp = ControlPlane()
    cp.start()
    try:
        h1 = AgentHandle("dup", "v1")
        cp._register(h1)
        h2 = AgentHandle("dup", "v1")
        cp._register(h2)
        assert h1.gone and not h2.gone
        assert cp.agent("dup") is h2
    finally:
        cp.stop()


# -- CLI surface -----------------------------------------------------------


def test_cli_manager_machines_and_request(stack, capsys):
    cp, _srv = stack
    from gpud_tpu.cli import main

    rc = main(["manager", "machines", "--endpoint", cp.endpoint])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert "cp-agent-1" in {m["machine_id"] for m in out["machines"]}

    rc = main(
        [
            "manager",
            "request",
            "cp-agent-1",
            "states",
            "--endpoint",
            cp.endpoint,
            "--params",
            '{"components": ["cpu"]}',
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    states = out["response"]["states"]
    assert [s["component"] for s in states] == ["cpu"]


def test_cli_manager_request_unknown_machine_fails(stack, capsys):
    cp, _srv = stack
    from gpud_tpu.cli import main

    rc = main(
        ["manager", "request", "ghost", "states", "--endpoint", cp.endpoint]
    )
    assert rc == 1
    assert "404" in capsys.readouterr().err


def test_cli_manager_positional_method_wins_over_params(stack, capsys):
    """--params must not smuggle a different method past the positional
    argument (states stays states, no reboot)."""
    cp, _srv = stack
    from gpud_tpu.cli import main

    rc = main(
        [
            "manager",
            "request",
            "cp-agent-1",
            "gossip",
            "--endpoint",
            cp.endpoint,
            "--params",
            '{"method": "reboot"}',
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["response"]["status"] in ("started", "ok")  # gossip ran


def test_cli_manager_machines_clean_error_on_401(capsys):
    from gpud_tpu.cli import main

    cp = ControlPlane(admin_token="adm")
    cp.start()
    try:
        rc = main(["manager", "machines", "--endpoint", cp.endpoint])
        assert rc == 1
        assert "401" in capsys.readouterr().err
    finally:
        cp.stop()


def test_login_records_machine_info_tree(tmp_path):
    """The manager decodes the agent's LoginRequest through the shared
    wire type and records the MachineInfo tree, served back on the
    operator API (reference: control-plane machine view fed by login)."""
    import requests

    cp = ControlPlane()
    cp.start()
    try:
        body = {
            "token": "join",
            "machine_id": "mi-box",
            "machine_info": {
                "machine_id": "mi-box",
                "hostname": "host-1",
                "os": "Linux",
                "tpu_info": {
                    "accelerator_type": "v5p-8",
                    "chip_count": 4,
                    "chips": [{"chip_id": 0, "device_path": "/dev/accel0"}],
                },
            },
        }
        r = requests.post(f"{cp.endpoint}/api/v1/login", json=body, timeout=10)
        assert r.status_code == 200
        resp = r.json()
        assert resp["machine_id"] == "mi-box"
        assert resp["token"]
        mi = requests.get(
            f"{cp.endpoint}/v1/machines/mi-box/machine-info", timeout=10
        )
        assert mi.status_code == 200
        tree = mi.json()["machine_info"]
        assert tree["hostname"] == "host-1"
        assert tree["os"] == "Linux"
        assert tree["tpu_info"]["accelerator_type"] == "v5p-8"
        assert tree["tpu_info"]["chips"][0]["device_path"] == "/dev/accel0"
        # unknown machine → 404, not a stack trace
        missing = requests.get(
            f"{cp.endpoint}/v1/machines/nope/machine-info", timeout=10
        )
        assert missing.status_code == 404
    finally:
        cp.stop()


def test_gossip_result_refreshes_machine_info(stack):
    """An operator gossip request whose answer carries machine_info must
    refresh the manager's recorded tree (normalized through the shared
    wire type)."""
    import requests

    cp, _srv = stack
    r = requests.post(
        f"{cp.endpoint}/v1/machines/cp-agent-1/request",
        json={"method": "gossip"},
        params={"timeout": "15"},
        timeout=25,
    )
    assert r.status_code == 200
    # gossip computes machine info async; poll until the answer carries it
    deadline = time.time() + 20
    tree = None
    while time.time() < deadline:
        r = requests.post(
            f"{cp.endpoint}/v1/machines/cp-agent-1/request",
            json={"method": "gossip"},
            params={"timeout": "15"},
            timeout=25,
        )
        if r.json()["response"].get("machine_info"):
            mi = requests.get(
                f"{cp.endpoint}/v1/machines/cp-agent-1/machine-info",
                timeout=10,
            )
            if mi.status_code == 200:
                tree = mi.json()["machine_info"]
                break
        time.sleep(0.3)
    assert tree and tree.get("hostname"), tree


def test_machine_infos_bounded_with_fifo_eviction(tmp_path):
    """Unauthenticated dev-mode logins mint fresh machine ids; the
    recorded trees must stay bounded (FIFO eviction past the cap)."""
    import requests

    cp = ControlPlane()
    cp.start()
    try:
        cp.machine_infos_max = 5
        for i in range(8):
            r = requests.post(
                f"{cp.endpoint}/api/v1/login",
                json={
                    "token": "x",
                    "machine_id": f"churn-{i}",
                    "machine_info": {"hostname": f"h{i}"},
                },
                timeout=10,
            )
            assert r.status_code == 200
        assert len(cp.machine_infos) == 5
        assert "churn-0" not in cp.machine_infos  # oldest evicted
        assert "churn-7" in cp.machine_infos
        # evicted machine 404s; survivor serves its tree
        assert requests.get(
            f"{cp.endpoint}/v1/machines/churn-0/machine-info", timeout=10
        ).status_code == 404
        assert requests.get(
            f"{cp.endpoint}/v1/machines/churn-7/machine-info", timeout=10
        ).json()["machine_info"]["hostname"] == "h7"
    finally:
        cp.stop()


def test_oversized_machine_info_not_recorded(tmp_path):
    """Dev mode accepts unauthenticated logins, so a multi-megabyte
    machine_info tree must not be pinned in manager memory: entries over
    the per-entry byte cap are dropped (login still succeeds)."""
    import requests

    cp = ControlPlane()
    cp.start()
    try:
        # oversize a *known* wire field — unknown keys are stripped by the
        # LoginRequest wire type before the manager ever sees them
        big = {"machine_id": "fat-box",
               "hostname": "h" * (ControlPlane.MACHINE_INFO_MAX_BYTES + 1024)}
        r = requests.post(
            f"{cp.endpoint}/api/v1/login",
            json={"token": "join", "machine_id": "fat-box", "machine_info": big},
            timeout=10,
        )
        assert r.status_code == 200  # enrollment itself unaffected
        mi = requests.get(
            f"{cp.endpoint}/v1/machines/fat-box/machine-info", timeout=10
        )
        assert mi.status_code == 404  # tree not recorded
    finally:
        cp.stop()
