"""The sys.monitoring line-coverage tool (tools/cov.py) — the stand-in
for the reference's go-test -cover CI gate."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from gpud_tpu.tools import cov


def test_executable_lines_includes_nested_defs(tmp_path):
    p = tmp_path / "m.py"
    p.write_text(
        textwrap.dedent(
            """\
            x = 1

            def f():
                def g():
                    return 2
                return g()

            class C:
                def m(self):
                    return 3
            """
        )
    )
    lines = cov.executable_lines(str(p))
    # assignment, both function bodies, and the method body are all present
    assert {1, 5, 6, 10} <= lines


def test_executable_lines_tolerates_syntax_errors(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("def broken(:\n")
    assert cov.executable_lines(str(p)) == set()


def test_ranges_compression():
    assert cov._ranges([]) == ""
    assert cov._ranges([3]) == "3"
    assert cov._ranges([1, 2, 3, 7, 9, 10]) == "1-3,7,9-10"


def test_collector_records_only_root_files(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    mod = pkg / "target.py"
    mod.write_text("def hit():\n    return 41\n\n\ndef missed():\n    return 0\n")
    sys.path.insert(0, str(pkg))
    try:
        c = cov.LineCollector(str(pkg))
        c.start()
        try:
            import target  # noqa: F401

            assert target.hit() == 41
        finally:
            c.stop()
        hit_files = {os.path.basename(f) for f in c.hits}
        assert "target.py" in hit_files
        (tfile,) = [f for f in c.hits if f.endswith("target.py")]
        assert 2 in c.hits[tfile]      # hit() body ran
        assert 6 not in c.hits[tfile]  # missed() body did not
    finally:
        sys.path.remove(str(pkg))
        sys.modules.pop("target", None)


def test_double_start_defers_to_existing_owner(tmp_path):
    a = cov.LineCollector(str(tmp_path))
    b = cov.LineCollector(str(tmp_path))
    a.start()
    try:
        b.start()  # must not raise "tool already in use"
        b.stop()   # no-op: b never owned the tool id
        assert sys.monitoring.get_tool(sys.monitoring.COVERAGE_ID) == "tpud-cov"
    finally:
        a.stop()
    assert sys.monitoring.get_tool(sys.monitoring.COVERAGE_ID) is None


def test_foreign_tool_owner_degrades_to_no_coverage(tmp_path, capsys):
    """A debugger/profiler owning COVERAGE_ID must not crash the host
    process (conftest import) — coverage just disables itself."""
    sys.monitoring.use_tool_id(sys.monitoring.COVERAGE_ID, "other-profiler")
    try:
        c = cov.LineCollector(str(tmp_path))
        c.start()  # must not raise
        assert not c._active
        c.stop()   # no-op
        assert (
            sys.monitoring.get_tool(sys.monitoring.COVERAGE_ID)
            == "other-profiler"
        )
    finally:
        sys.monitoring.free_tool_id(sys.monitoring.COVERAGE_ID)


def test_dump_and_report_roundtrip(tmp_path):
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "mod.py").write_text("a = 1\nb = 2\n")
    c = cov.LineCollector(str(pkg))
    c.hits[str(pkg / "mod.py")] = {1}
    out = tmp_path / "cov.json"
    c.dump(str(out))
    data = json.loads(out.read_text())
    assert data["hits"][str(pkg / "mod.py")] == [1]

    reports = cov.build_report(str(out))
    (r,) = reports
    assert r.total == 2 and r.hit == 1 and r.missing == [2]
    assert r.pct == 50.0
    text = cov.format_report(reports, show_missing_for="mod.py")
    assert "50.0%" in text and "missing: 2" in text
    assert "TOTAL" in text


def test_report_skips_comment_and_blank_lines(tmp_path):
    pkg = tmp_path / "proj2"
    pkg.mkdir()
    (pkg / "m.py").write_text("# comment\n\nx = 1\n")
    c = cov.LineCollector(str(pkg))
    c.hits[str(pkg / "m.py")] = {3}
    out = tmp_path / "c.json"
    c.dump(str(out))
    (r,) = cov.build_report(str(out))
    assert r.total == 1 and r.hit == 1


def test_cli_report_entrypoint(tmp_path):
    pkg = tmp_path / "proj3"
    pkg.mkdir()
    (pkg / "m.py").write_text("x = 1\ny = 2\n")
    c = cov.LineCollector(str(pkg))
    c.hits[str(pkg / "m.py")] = {1, 2}
    out = tmp_path / "c.json"
    c.dump(str(out))
    res = subprocess.run(
        [sys.executable, "-m", "gpud_tpu.tools.cov", "report", str(out)],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=60,
    )
    assert res.returncode == 0
    assert "100.0%" in res.stdout


def test_cli_usage_on_bad_args():
    res = subprocess.run(
        [sys.executable, "-m", "gpud_tpu.tools.cov"],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=60,
    )
    assert res.returncode == 2


def test_pytest_hook_produces_coverage(tmp_path):
    """e2e: TPUD_COV through a real nested pytest run over one tiny test."""
    out = tmp_path / "cov.json"
    env = dict(os.environ, TPUD_COV=str(out))
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/test_eventstore.py",
            "-q",
        ],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        env=env,
        timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(out.read_text())
    assert any(f.endswith("eventstore.py") for f in data["hits"])
    # the collector must not trace itself (cov.py is excluded by design)
    assert not any(f.endswith("tools/cov.py") for f in data["hits"])
