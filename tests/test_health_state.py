"""Evolve-state machine scenario tests (reference test style:
components/accelerator/nvidia/xid health_state tests + infiniband
component_production_scenarios_test.go)."""

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType, RepairActionType
from gpud_tpu.components.tpu.health_state import evolve_health


def _err(t, name):
    return Event(time=t, name=name, type=EventType.FATAL, message=name)


def _reboot(t):
    return Event(time=t, name="reboot", type=EventType.WARNING, message="boot")


def _set_healthy(t):
    return Event(time=t, name="SetHealthy", type=EventType.INFO, message="op")


def test_no_events_healthy():
    ev = evolve_health([])
    assert ev.health == HealthStateType.HEALTHY


def test_first_occurrence_suggests_reboot():
    ev = evolve_health([_err(10, "tpu_driver_timeout")])
    assert ev.health == HealthStateType.UNHEALTHY
    assert ev.suggested_actions.repair_actions == [RepairActionType.REBOOT_SYSTEM]
    assert ev.active_errors == {"tpu_driver_timeout": 1}


def test_reboot_clears_error():
    ev = evolve_health([_err(10, "tpu_driver_timeout"), _reboot(20)])
    assert ev.health == HealthStateType.HEALTHY
    assert "cleared by reboot" in ev.reason


def test_recurrence_below_threshold_still_suggests_reboot():
    # tpu_driver_timeout threshold=2: one reboot then recurrence → still reboot
    ev = evolve_health(
        [_err(10, "tpu_driver_timeout"), _reboot(20), _err(30, "tpu_driver_timeout")]
    )
    assert ev.health == HealthStateType.UNHEALTHY
    assert RepairActionType.REBOOT_SYSTEM in ev.suggested_actions.repair_actions


def test_escalation_to_hw_inspection_after_threshold():
    events = [
        _err(10, "tpu_driver_timeout"),
        _reboot(20),
        _err(30, "tpu_driver_timeout"),
        _reboot(40),
        _err(50, "tpu_driver_timeout"),
    ]
    ev = evolve_health(events)
    assert ev.health == HealthStateType.UNHEALTHY
    assert ev.suggested_actions.repair_actions == [RepairActionType.HARDWARE_INSPECTION]
    assert "recurred after 2 reboot(s)" in ev.reason


def test_hbm_ecc_escalates_after_one_reboot():
    # tpu_hbm_ecc_uncorrectable threshold=1
    events = [
        _err(10, "tpu_hbm_ecc_uncorrectable"),
        _reboot(20),
        _err(30, "tpu_hbm_ecc_uncorrectable"),
    ]
    ev = evolve_health(events)
    assert ev.suggested_actions.repair_actions == [RepairActionType.HARDWARE_INSPECTION]


def test_set_healthy_clears_slate():
    events = [
        _err(10, "tpu_hbm_ecc_uncorrectable"),
        _reboot(20),
        _err(30, "tpu_hbm_ecc_uncorrectable"),
        _set_healthy(40),
    ]
    ev = evolve_health(events)
    assert ev.health == HealthStateType.HEALTHY

    # new error after set-healthy starts fresh (first occurrence → reboot)
    ev2 = evolve_health(events + [_err(50, "tpu_hbm_ecc_uncorrectable")])
    assert ev2.health == HealthStateType.UNHEALTHY
    assert RepairActionType.REBOOT_SYSTEM in ev2.suggested_actions.repair_actions


def test_non_critical_error_degraded_only():
    ev = evolve_health(
        [Event(time=10, name="tpu_hbm_ecc_correctable", type=EventType.WARNING)]
    )
    assert ev.health == HealthStateType.DEGRADED
    assert ev.suggested_actions is None  # ignore-only action suppressed


def test_multiple_errors_merge():
    events = [
        _err(10, "tpu_ici_link_down"),
        _err(20, "tpu_hbm_ecc_uncorrectable"),
    ]
    ev = evolve_health(events)
    assert ev.health == HealthStateType.UNHEALTHY
    assert set(ev.active_errors) == {"tpu_ici_link_down", "tpu_hbm_ecc_uncorrectable"}


def test_unknown_event_names_ignored():
    ev = evolve_health([Event(time=10, name="not-in-catalog", type=EventType.FATAL)])
    assert ev.health == HealthStateType.HEALTHY


def test_out_of_order_events_sorted():
    events = [
        _err(50, "tpu_driver_timeout"),
        _reboot(40),
        _err(30, "tpu_driver_timeout"),
        _reboot(20),
        _err(10, "tpu_driver_timeout"),
    ]
    ev = evolve_health(events)
    assert ev.suggested_actions.repair_actions == [RepairActionType.HARDWARE_INSPECTION]
