"""Regressions for the update-pipeline hardening: aside-rename reinstall,
the Python 3.10.0–3.10.11 tarfile filter= fallback, distsign exceptions
surfacing as error strings, target-version whitelisting, and the watcher's
failed-target backoff."""

import io
import os
import tarfile

import pytest

import gpud_tpu.update_install as ui
from gpud_tpu.update import BACKOFF_INITIAL, VersionFileWatcher, write_target_version
from gpud_tpu.update_install import (
    _safe_extract,
    install_tree,
    perform_update,
    resolve_signing_pub,
)


def _tree(tmp_path, name, marker):
    d = tmp_path / name
    d.mkdir()
    (d / "VERSION").write_text(marker)
    return str(d)


def _make_tar(tmp_path, files):
    pkg = str(tmp_path / "pkg.tar.gz")
    with tarfile.open(pkg, "w:gz") as tf:
        for name, content in files.items():
            data = content.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return pkg


# -- install_tree: reinstall must never destroy the installed tree -----------

def test_reinstall_same_version_succeeds_and_swaps(tmp_path):
    inst = tmp_path / "install"
    assert install_tree(_tree(tmp_path, "a", "one"), str(inst), "1.0") is None
    assert install_tree(_tree(tmp_path, "b", "two"), str(inst), "1.0") is None
    final = inst / "versions" / "1.0"
    assert (final / "VERSION").read_text() == "two"
    # no staging or aside leftovers
    assert sorted(os.listdir(inst / "versions")) == ["1.0"]
    assert os.readlink(inst / "current") == os.path.join("versions", "1.0")


def test_reinstall_rolls_back_when_swap_fails(tmp_path, monkeypatch):
    inst = tmp_path / "install"
    assert install_tree(_tree(tmp_path, "a", "one"), str(inst), "1.0") is None
    final = str(inst / "versions" / "1.0")

    real_rename = os.rename

    def failing_rename(src, dst):
        # fail only the staging → final swap; the aside and rollback
        # renames must still work
        if src.endswith(f".staging-{os.getpid()}"):
            raise OSError("simulated rename failure")
        return real_rename(src, dst)

    monkeypatch.setattr(ui.os, "rename", failing_rename)
    err = install_tree(_tree(tmp_path, "b", "two"), str(inst), "1.0")
    assert err is not None and "install failed" in err
    # the previously installed tree survived, restored under its real name
    assert open(os.path.join(final, "VERSION")).read() == "one"
    assert sorted(os.listdir(inst / "versions")) == ["1.0"]


def test_failed_rollback_leaves_aside_tree_on_disk(tmp_path, monkeypatch):
    inst = tmp_path / "install"
    assert install_tree(_tree(tmp_path, "a", "one"), str(inst), "1.0") is None

    real_rename = os.rename

    def failing_rename(src, dst):
        if src.endswith(f".staging-{os.getpid()}"):
            raise OSError("simulated swap failure")
        if src.endswith(f".old-{os.getpid()}"):
            raise OSError("simulated rollback failure")
        return real_rename(src, dst)

    monkeypatch.setattr(ui.os, "rename", failing_rename)
    err = install_tree(_tree(tmp_path, "b", "two"), str(inst), "1.0")
    assert err is not None
    # worst case: rollback also failed — the old tree must still exist
    # somewhere recoverable, never rmtree'd by cleanup
    aside = inst / "versions" / f"1.0.old-{os.getpid()}"
    assert (aside / "VERSION").read_text() == "one"


# -- tarfile filter= fallback (Python 3.10.0–3.10.11) ------------------------

def test_safe_extract_falls_back_when_filter_unsupported(
    tmp_path, monkeypatch
):
    pkg = _make_tar(tmp_path, {"bin/tpud": "x", "VERSION": "9"})
    real_extract = tarfile.TarFile.extract

    def old_extract(self, member, path="", set_attrs=True, **kw):
        if "filter" in kw:
            raise TypeError(
                "extract() got an unexpected keyword argument 'filter'"
            )
        return real_extract(self, member, path, set_attrs=set_attrs)

    monkeypatch.setattr(tarfile.TarFile, "extract", old_extract)
    dest = tmp_path / "out"
    dest.mkdir()
    assert _safe_extract(pkg, str(dest)) is None
    assert (dest / "VERSION").read_text() == "9"
    assert (dest / "bin" / "tpud").exists()


def test_safe_extract_still_rejects_traversal_without_filter(
    tmp_path, monkeypatch
):
    pkg = _make_tar(tmp_path, {"../escape": "x"})
    dest = tmp_path / "out"
    dest.mkdir()
    err = _safe_extract(pkg, str(dest))
    assert err is not None and "unsafe member path" in err
    assert not (tmp_path / "escape").exists()


# -- version whitelist -------------------------------------------------------

@pytest.mark.parametrize(
    "bad",
    ["", "1.0?x", "1.0#frag", "1 0", "../../etc", ".hidden", "-flag",
     "v1/../../x", "a\nb"],
)
def test_perform_update_rejects_hostile_versions(tmp_path, bad):
    err = perform_update(
        bad, base_url="http://127.0.0.1:9", install_dir=str(tmp_path)
    )
    assert err is not None and "invalid target version" in err


@pytest.mark.parametrize("good", ["1.2.3", "v2.0.0-rc1", "2024.01_hotfix"])
def test_version_whitelist_accepts_normal_versions(tmp_path, good):
    # passes the whitelist; fails later (no trust anchor), proving the
    # version check is not what rejected it
    err = perform_update(
        good, base_url="http://127.0.0.1:9", install_dir=str(tmp_path)
    )
    assert err is not None and "invalid target version" not in err


# -- distsign exceptions become error strings --------------------------------

def test_verify_key_exception_becomes_error_string(tmp_path, monkeypatch):
    root = tmp_path / "root.pub"
    root.write_text("not a real key")
    monkeypatch.setattr(
        ui, "_download", lambda url, dest, max_bytes=0: (
            open(dest, "w").write("x") and None
        )
    )

    def boom(*a, **kw):
        raise ValueError("Unable to load PEM")

    monkeypatch.setattr(ui.distsign, "verify_key", boom)
    path, err = resolve_signing_pub(
        "http://127.0.0.1:9", str(tmp_path), root_pub=str(root)
    )
    assert path == ""
    assert "signing key verification failed" in err
    assert "Unable to load PEM" in err


def test_verify_package_exception_becomes_error_string(tmp_path, monkeypatch):
    pub = tmp_path / "sign.pub"
    pub.write_text("pinned")
    written = []

    def fake_download(url, dest, max_bytes=0):
        open(dest, "w").write("x")
        written.append(url)
        return None

    monkeypatch.setattr(ui, "_download", fake_download)

    def boom(*a, **kw):
        raise RuntimeError("cryptography backend unavailable")

    monkeypatch.setattr(ui.distsign, "verify_package", boom)
    err = perform_update(
        "1.0.0",
        base_url="http://127.0.0.1:9",
        install_dir=str(tmp_path / "inst"),
        signing_pub=str(pub),
    )
    assert err is not None
    assert "package signature rejected" in err
    assert "cryptography backend unavailable" in err


# -- watcher failed-target backoff -------------------------------------------

def _watcher(tmp_path, installer):
    vf = str(tmp_path / "target")
    w = VersionFileWatcher(
        vf, current_version="1.0.0", installer=installer, interval=3600
    )
    state = {"now": 1000.0}
    w._now = lambda: state["now"]
    w.clock = state
    return w, vf


def test_failing_target_backs_off_instead_of_retrying_every_poll(tmp_path):
    calls = []

    def installer(target):
        calls.append(target)
        return "simulated install failure"

    w, vf = _watcher(tmp_path, installer)
    write_target_version(vf, "2.0.0")
    assert w.check_once() is True       # first attempt runs the installer
    assert w.check_once() is False      # in backoff: no re-download
    assert calls == ["2.0.0"]
    w.clock["now"] += BACKOFF_INITIAL + 1
    assert w.check_once() is True       # backoff lapsed: retried
    assert calls == ["2.0.0", "2.0.0"]
    # consecutive failure doubled the backoff
    w.clock["now"] += BACKOFF_INITIAL + 1
    assert w.check_once() is False
    w.clock["now"] += BACKOFF_INITIAL + 1
    assert w.check_once() is True


def test_new_target_resets_the_failure_memo(tmp_path):
    calls = []

    def installer(target):
        calls.append(target)
        return "simulated install failure"

    w, vf = _watcher(tmp_path, installer)
    write_target_version(vf, "2.0.0")
    assert w.check_once() is True
    assert w.check_once() is False
    write_target_version(vf, "2.0.1")   # operator pushed a fixed build
    assert w.check_once() is True       # no waiting out the old backoff
    assert calls == ["2.0.0", "2.0.1"]


def test_successful_install_does_not_engage_backoff(tmp_path):
    exits = []

    w, vf = _watcher(tmp_path, lambda target: None)
    w._exit = exits.append
    write_target_version(vf, "2.0.0")
    assert w.check_once() is True
    assert exits == [244]
    assert w._failed_target == ""
