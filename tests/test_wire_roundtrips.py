"""Wire-type serialization roundtrips for the whole api/v1 surface
(reference: api/v1/types.go — these shapes ARE the control-plane
contract; a lossy to_dict/from_dict pair corrupts fleet state silently).

Three properties per type: (1) populated → dict → object is lossless,
(2) from_dict of an EMPTY dict yields working defaults (an old manager
omitting new fields must not crash a new agent), (3) unknown extra keys
are ignored (a NEW manager must not crash an old agent)."""

import pytest

from gpud_tpu.api.v1.types import (
    BlockDeviceInfo,
    ComponentInfo,
    DiskInfo,
    Event,
    HealthState,
    MachineInfo,
    Metric,
    NICInfo,
    PackageStatus,
    SuggestedActions,
    TPUChipInfo,
    TPUInfo,
)

SAMPLES = [
    (
        HealthState,
        HealthState(
            component="accelerator-tpu-ici",
            health="Unhealthy",
            reason="link down",
            error="",
            suggested_actions=SuggestedActions(
                description="reboot",
                repair_actions=["REBOOT_SYSTEM", "HARDWARE_INSPECTION"],
            ),
            extra_info={"links_up": "22", "poll_mode": "fast"},
        ),
    ),
    (
        Event,
        Event(
            component="x",
            time=1700000000.5,
            name="tpu_chip_lost",
            type="Fatal",
            message="accel2: device lost",
            extra_info={"chip": "2"},
        ),
    ),
    (
        Metric,
        Metric(
            unix_seconds=1700000000,
            name="tpud_tpu_temperature_celsius",
            labels={"chip": "3"},
            value=61.5,
        ),
    ),
    (
        SuggestedActions,
        SuggestedActions(description="d", repair_actions=["IGNORE_NO_ACTION_REQUIRED"]),
    ),
    (
        TPUChipInfo,
        TPUChipInfo(
            chip_id=2,
            device_path="/dev/vfio/14",
            pci_address="0000:00:06.0",
            serial="s-2",
            hbm_total_bytes=95 * 1024**3,
            cores_per_chip=2,
        ),
    ),
    (
        TPUInfo,
        TPUInfo(
            product="TPU v5p",
            accelerator_type="v5p-256",
            topology="128 chips / 32 hosts",
            generation="v5p",
            chip_count=4,
            hosts_per_slice=32,
            worker_id=7,
            runtime_version="rt",
            driver_version="drv",
            chips=[TPUChipInfo(chip_id=0), TPUChipInfo(chip_id=1)],
        ),
    ),
    (DiskInfo, DiskInfo(device="/dev/sda1", mount_point="/", fstype="ext4",
                        total_bytes=10, used_bytes=5)),
    (
        NICInfo,
        NICInfo(name="eth0", mac="aa:bb", addresses=["10.0.0.2"], mtu=1460,
                speed_mbps=10000, driver="gve", virtual=False),
    ),
    (
        BlockDeviceInfo,
        BlockDeviceInfo(
            name="sda", type="disk", size_bytes=1 << 40, model="PD",
            rotational=False, removable=False,
            children=[
                BlockDeviceInfo(name="sda1", type="part", mount_point="/",
                                fstype="ext4", used_bytes=9)
            ],
        ),
    ),
    (
        PackageStatus,
        PackageStatus(name="p", is_installed=True, installing=False,
                      progress=100, target_version="2", current_version="2"),
    ),
]


@pytest.mark.parametrize(
    "cls,obj", SAMPLES, ids=[c.__name__ for c, _ in SAMPLES]
)
def test_roundtrip_lossless(cls, obj):
    d = obj.to_dict()
    again = cls.from_dict(d)
    assert again.to_dict() == d


@pytest.mark.parametrize(
    "cls,obj", SAMPLES, ids=[c.__name__ for c, _ in SAMPLES]
)
def test_from_empty_dict_yields_defaults(cls, obj):
    again = cls.from_dict({})
    if again is None:
        # optional wire types (SuggestedActions, TPUInfo) decode an empty
        # payload as "absent" — that IS the default contract
        return
    # must serialize without raising; roundtrip of defaults is stable
    assert cls.from_dict(again.to_dict()).to_dict() == again.to_dict()


@pytest.mark.parametrize(
    "cls,obj", SAMPLES, ids=[c.__name__ for c, _ in SAMPLES]
)
def test_unknown_keys_ignored(cls, obj):
    d = obj.to_dict()
    d["__future_field__"] = {"nested": [1, 2]}
    again = cls.from_dict(d)
    assert "__future_field__" not in again.to_dict()


def test_machine_info_nested_roundtrip():
    mi = MachineInfo(
        machine_id="m",
        hostname="h",
        containerized=True,
        tpu_info=TPUInfo(product="TPU v5e", chip_count=8),
        disks=[DiskInfo(device="/dev/sda1")],
        nics=[NICInfo(name="eth0", driver="gve")],
        block_devices=[
            BlockDeviceInfo(name="sda", children=[BlockDeviceInfo(name="sda1")])
        ],
    )
    d = mi.to_dict()
    again = MachineInfo.from_dict(d)
    assert again.to_dict() == d
    assert again.tpu_info.chip_count == 8
    assert again.block_devices[0].children[0].name == "sda1"


def test_health_state_without_actions_omits_key():
    hs = HealthState(component="cpu", health="Healthy", reason="ok")
    d = hs.to_dict()
    again = HealthState.from_dict(d)
    assert again.suggested_actions is None


def test_event_time_precision_preserved():
    e = Event(component="x", time=1700000000.123456, name="n", message="")
    assert Event.from_dict(e.to_dict()).time == pytest.approx(
        1700000000.123456, abs=1e-6
    )


def test_component_info_roundtrip():
    ci = ComponentInfo(
        component="cpu",
        start_time=1.0,
        end_time=2.0,
        states=[HealthState(component="cpu", health="Healthy", reason="ok")],
        events=[Event(component="cpu", time=1.5, name="e", message="m")],
        metrics=[Metric(unix_seconds=1, name="n", labels={}, value=0.5)],
    )
    d = ci.to_dict()
    again = ComponentInfo.from_dict(d)
    assert again.to_dict() == d
    assert again.states[0].health == "Healthy"
    assert again.events[0].name == "e"
    assert again.metrics[0].value == 0.5
