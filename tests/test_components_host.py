from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import TpudInstance
from gpud_tpu.components.cpu import CPUComponent, match_cpu_lockup
from gpud_tpu.components.disk import DiskComponent
from gpud_tpu.components.memory import MemoryComponent, match_oom
from gpud_tpu.components.os_comp import OSComponent, match_kernel_panic


def test_cpu_check_healthy():
    c = CPUComponent(TpudInstance())
    c.get_usage_fn = lambda: 12.5
    c.get_load_fn = lambda: (0.5, 0.4, 0.3)
    c.get_core_count_fn = lambda: 8
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.HEALTHY
    assert "12.5%" in cr.summary()


def test_cpu_degraded_on_load():
    c = CPUComponent(TpudInstance())
    c.get_usage_fn = lambda: 99.0
    c.get_load_fn = lambda: (50.0, 40.0, 30.0)
    c.get_core_count_fn = lambda: 4
    assert c.check().health_state_type() == HealthStateType.DEGRADED


def test_cpu_lockup_matcher():
    assert match_cpu_lockup("watchdog: BUG: soft lockup - CPU#2 stuck") is not None
    assert match_cpu_lockup("normal boot line") is None


def test_memory_check_and_matcher():
    class VM:
        total = 16 << 30
        used = 8 << 30
        available = 8 << 30
        percent = 50.0

    c = MemoryComponent(TpudInstance())
    c.get_vm_fn = lambda: VM()
    assert c.check().health_state_type() == HealthStateType.HEALTHY
    VM.percent = 97.0
    assert c.check().health_state_type() == HealthStateType.DEGRADED
    assert match_oom("Out of memory: Killed process 1234 (python)") is not None
    assert match_oom("plenty of memory") is None


def test_disk_check_real_fs():
    c = DiskComponent(TpudInstance())
    cr = c.check()
    assert cr.health_state_type() in (
        HealthStateType.HEALTHY,
        HealthStateType.DEGRADED,
    )


def test_disk_missing_mount_point():
    c = DiskComponent(TpudInstance(mount_points=["/definitely/not/here"]))
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.UNHEALTHY
    assert "missing" in cr.summary()


def test_os_check_and_fd_threshold():
    c = OSComponent(TpudInstance())
    cr = c.check()
    assert cr.health_state_type() == HealthStateType.HEALTHY
    assert cr.extra_info["kernel_version"]
    c.get_file_nr_fn = lambda: (95, 100)
    assert c.check().health_state_type() == HealthStateType.DEGRADED
    assert match_kernel_panic("Kernel panic - not syncing: Fatal exception") is not None
