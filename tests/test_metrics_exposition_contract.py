"""Prometheus text-exposition contract, validated by an in-test parser.

Existing tests assert specific escapes; this suite implements the actual
exposition-format grammar (the consumer's view — what a Prometheus
scraper does) and runs randomized registry content through it: every
emitted line must parse, every labelset must roundtrip to the exact
value that was set, HELP/TYPE metadata must precede samples, and the
hostile cases (quotes, backslashes, newlines, unicode, +/-Inf, NaN)
must survive the full render→parse cycle. Reference: the reference
daemon exposes the same format and its scrape integration is its main
fleet interface (pkg/metrics + /metrics handler)."""

import math
import random
import re
import string

import pytest

from gpud_tpu.metrics.registry import Registry

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_exposition(text: str):
    """Parse per the text format; raises AssertionError on any violation.
    Returns {(name, frozenset(labels.items())): float_value}."""
    samples = {}
    seen_meta = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            parts = ln.split(" ", 3)
            assert len(parts) >= 3, ln
            seen_meta.setdefault(parts[2], set()).add("help")
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split(" ", 4)
            assert len(parts) >= 4, ln
            assert parts[3] in ("gauge", "counter", "histogram", "summary",
                                "untyped"), ln
            seen_meta.setdefault(parts[2], set()).add("type")
            continue
        assert not ln.startswith("#"), f"unknown comment line: {ln!r}"
        m = _SAMPLE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        name = m.group("name")
        labels = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL.finditer(raw):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed = lm.end()
            rest = raw[consumed:].strip(", ")
            assert not rest, f"unparsed label residue {rest!r} in {ln!r}"
        vs = m.group("value")
        if vs == "+Inf":
            value = math.inf
        elif vs == "-Inf":
            value = -math.inf
        elif vs == "NaN":
            value = math.nan
        else:
            value = float(vs)  # raises on malformed output
        key = (name, frozenset(labels.items()))
        assert key not in samples, f"duplicate sample {key}"
        samples[key] = value
        # metadata must precede the first sample of its family; histogram
        # samples carry _bucket/_sum/_count suffixes over the family name
        candidates = {name}
        for suffix in ("_total", "_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                candidates.add(name[: -len(suffix)])
        assert candidates & seen_meta.keys(), (
            f"sample {name} before its HELP/TYPE"
        )
    return samples


HOSTILE_STRINGS = [
    'quote"inside',
    "back\\slash",
    "new\nline",
    "tab\tchar",
    "unicode-雪-µ",
    "trailing-space ",
    "",
    "a" * 200,
    '{"json": "looking"}',
    "comma,equals=brace}",
]


def test_randomized_registry_roundtrips_through_parser():
    rng = random.Random(20260729)
    r = Registry()
    expected = {}
    for i in range(40):
        name = "rt_" + "".join(
            rng.choice(string.ascii_lowercase) for _ in range(8)
        ) + f"_{i}"
        g = r.gauge(name, f"help {i}")
        for _ in range(rng.randint(1, 4)):
            labels = {
                "l" + str(j): rng.choice(HOSTILE_STRINGS)
                for j in range(rng.randint(0, 3))
            }
            value = rng.choice(
                [rng.uniform(-1e12, 1e12), 0.0, math.inf, -math.inf]
            )
            g.set(value, labels)
            expected[(name, frozenset(labels.items()))] = value
    samples = parse_exposition(r.render_prometheus())
    for key, want in expected.items():
        assert key in samples, f"labelset lost in exposition: {key}"
        got = samples[key]
        assert got == pytest.approx(want) or (
            math.isinf(want) and got == want
        ), (key, want, got)


def test_nan_survives_as_nan_token():
    r = Registry()
    r.gauge("nan_metric", "h").set(math.nan, {"x": "y"})
    samples = parse_exposition(r.render_prometheus())
    (value,) = [
        v for (n, _), v in samples.items() if n == "nan_metric"
    ]
    assert math.isnan(value)


def test_counter_families_render_as_counters():
    r = Registry()
    c = r.counter("ops_total", "operations")
    c.inc(labels={"op": "scan"})
    c.inc(labels={"op": "scan"})
    text = r.render_prometheus()
    samples = parse_exposition(text)
    assert samples[("ops_total", frozenset({("op", "scan")}.__iter__()))] == 2.0
    assert "# TYPE ops_total counter" in text


def test_live_daemon_exposition_parses(tmp_path):
    """The real /metrics endpoint — the full default registry with every
    component's gauges — must satisfy the same grammar a scraper
    enforces."""
    import urllib.request

    from gpud_tpu.config import default_config
    from gpud_tpu.server.server import Server

    kmsg = tmp_path / "kmsg"
    kmsg.write_text("")
    cfg = default_config(
        data_dir=str(tmp_path / "data"),
        port=0,
        tls=False,
        kmsg_path=str(kmsg),
        components_disabled=["network-latency"],
        endpoint="",
        token="",
    )
    s = Server(config=cfg)
    try:
        s.start()
        with urllib.request.urlopen(
            f"{s.base_url()}/metrics", timeout=10
        ) as resp:
            body = resp.read().decode("utf-8")
        samples = parse_exposition(body)
        assert any(n.startswith("tpud_") for n, _ in samples), (
            "no daemon self-metrics exposed"
        )
    finally:
        s.stop()
