"""Checkpoint/resume (SURVEY §5.4): daemon state is the SQLite file —
events, health evaluation, and the ICI baseline survive a full daemon
restart; --db-in-memory trades that persistence away deliberately."""

import time

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.config import default_config
from gpud_tpu.fault_injector import Request as InjectRequest
from gpud_tpu.server.server import Server


def _cfg(tmp_path, **kw):
    kmsg = tmp_path / "kmsg"
    kmsg.touch()
    return default_config(
        data_dir=str(tmp_path / "data"),
        port=0,
        tls=False,
        kmsg_path=str(kmsg),
        components_disabled=["network-latency"],
        **kw,
    )


def _wait_unhealthy(srv, name, timeout=10):
    comp = srv.registry.get(name)
    deadline = time.time() + timeout
    while time.time() < deadline:
        states = comp.last_health_states()
        if states and states[0].health == HealthStateType.UNHEALTHY:
            return states[0]
        time.sleep(0.1)
    raise AssertionError(f"{name} never went unhealthy: {states}")


def test_events_and_health_survive_daemon_restart(tmp_path):
    cfg = _cfg(tmp_path)
    s1 = Server(config=cfg)
    s1.start()
    try:
        res = s1.fault_injector.inject(
            InjectRequest(tpu_error_name="tpu_hbm_ecc_uncorrectable", chip_id=2)
        )
        assert res.ok
        st = _wait_unhealthy(s1, "accelerator-tpu-error-kmsg")
        assert "tpu_hbm_ecc_uncorrectable" in st.reason
    finally:
        s1.stop()

    # fresh process equivalent: new Server over the same state file; the
    # persisted events must re-evaluate to the same unhealthy state with
    # per-chip attribution intact
    s2 = Server(config=_cfg(tmp_path))
    s2.start()
    try:
        st = _wait_unhealthy(s2, "accelerator-tpu-error-kmsg")
        assert "tpu_hbm_ecc_uncorrectable(chip 2)" in st.reason
        comp = s2.registry.get("accelerator-tpu-error-kmsg")
        evs = comp.events(0)
        assert any(e.name == "tpu_hbm_ecc_uncorrectable" for e in evs)
        # operator clears; the clear also persists
        comp.set_healthy()
    finally:
        s2.stop()

    s3 = Server(config=_cfg(tmp_path))
    s3.start()
    try:
        comp = s3.registry.get("accelerator-tpu-error-kmsg")
        deadline = time.time() + 10
        while time.time() < deadline:
            states = comp.last_health_states()
            if states and states[0].health == HealthStateType.HEALTHY:
                break
            time.sleep(0.1)
        assert states[0].health == HealthStateType.HEALTHY
    finally:
        s3.stop()


def test_db_in_memory_mode_leaves_no_state_file(tmp_path):
    cfg = _cfg(tmp_path, db_in_memory=True)
    s = Server(config=cfg)
    s.start()
    try:
        assert s.fault_injector.inject(
            InjectRequest(tpu_error_name="tpu_power_fault", chip_id=0)
        ).ok
        _wait_unhealthy(s, "accelerator-tpu-error-kmsg")
    finally:
        s.stop()
    state = tmp_path / "data" / "tpud.state"
    assert not state.exists(), "in-memory mode must not write the state DB"

    # a restart starts from a clean slate (the traded-away persistence)
    s2 = Server(config=_cfg(tmp_path, db_in_memory=True))
    s2.start()
    try:
        comp = s2.registry.get("accelerator-tpu-error-kmsg")
        time.sleep(1.0)
        assert comp.events(0) == []
    finally:
        s2.stop()
