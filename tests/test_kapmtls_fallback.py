"""kapmtls re-push fallback path: filesystems WITHOUT renameat2
RENAME_EXCHANGE (pre-3.15 kernels, some network filesystems) take the
move-aside + pivot path (kapmtls.py install fallback). The exchange
helper is scripted to fail so every fallback branch runs, including the
crash-recovery restores."""

import os

import pytest

import gpud_tpu.kapmtls as kapmtls_mod
from gpud_tpu.kapmtls import CertManager

pytest.importorskip("cryptography")
from tests.helpers import keypair

# distinct real keypairs (the readiness probe parses the cert); CERTS
# maps marker -> PEM so content assertions stay readable
CERTS = {}
KEYS = {}
for marker in ("CERT1", "CERT1b", "CERT1-new", "CERT2", "C", "C2"):
    CERTS[marker], KEYS[marker] = keypair(marker)


def _install(store, version, marker):
    return store.install(version, CERTS[marker], KEYS[marker])


@pytest.fixture()
def no_exchange(monkeypatch):
    monkeypatch.setattr(kapmtls_mod, "_exchange_dirs", lambda a, b: False)


@pytest.fixture()
def store(tmp_path):
    return CertManager(root=str(tmp_path / "kap"))


def _read_current(store):
    cur = os.path.join(store.root, "current")
    with open(os.path.join(cur, "client.crt")) as f:
        return f.read()


def test_repush_fallback_inactive_version(store, no_exchange):
    """Re-push of a NON-active version: old dir parked, new content in
    place, no `current` involvement."""
    assert _install(store, "v1", "CERT1") is None
    assert _install(store, "v2", "CERT2") is None
    assert store.activate("v2") is None
    # re-push v1 (inactive) with new content via the fallback
    assert _install(store, "v1", "CERT1b") is None
    with open(os.path.join(store.releases_dir, "v1", "client.crt")) as f:
        assert f.read() == CERTS["CERT1b"]
    assert _read_current(store) == CERTS["CERT2"]  # untouched
    # the old content is parked for deferred GC, not deleted
    parked = [e for e in os.listdir(store.releases_dir) if ".old-" in e]
    assert parked


def test_repush_fallback_active_version_pivots_current(store, no_exchange):
    """Re-push of the ACTIVE version: `current` pivots to the staged dir
    first, then back to the version path — it must resolve to complete
    credentials at every step, and end on the new content."""
    assert _install(store, "v1", "CERT1") is None
    assert store.activate("v1") is None
    assert _install(store, "v1", "CERT1-new") is None
    assert _read_current(store) == CERTS["CERT1-new"]
    # current points at the canonical version path again (not a tmp dir)
    target = os.readlink(os.path.join(store.root, "current"))
    assert target == os.path.join("releases", "v1")


def test_repush_fallback_vacate_failure_restores_current(
    store, no_exchange, monkeypatch
):
    """If moving the old dir aside fails, the pivot is rolled back and the
    active release keeps serving the OLD content."""
    assert _install(store, "v1", "CERT1") is None
    assert store.activate("v1") is None

    real_rename = os.rename

    def failing_rename(src, dst):
        if ".old-" in dst:
            raise OSError(16, "Device or resource busy")
        return real_rename(src, dst)

    monkeypatch.setattr(os, "rename", failing_rename)
    err = _install(store, "v1", "CERT1-new")
    assert err is not None and "busy" in err
    monkeypatch.undo()
    assert _read_current(store) == CERTS["CERT1"]
    target = os.readlink(os.path.join(store.root, "current"))
    assert target == os.path.join("releases", "v1")


def test_repush_fallback_final_rename_failure_restores_old(
    store, no_exchange, monkeypatch
):
    """If the final tmp→version rename fails, the previous release dir is
    restored and `current` still serves the old credentials."""
    assert _install(store, "v1", "CERT1") is None
    assert store.activate("v1") is None

    real_rename = os.rename
    state = {"vacated": False}

    def failing_rename(src, dst):
        if ".old-" in dst:
            state["vacated"] = True
            return real_rename(src, dst)
        if state["vacated"] and ".tmp-" in src:
            raise OSError(5, "I/O error")
        return real_rename(src, dst)

    monkeypatch.setattr(os, "rename", failing_rename)
    err = _install(store, "v1", "CERT1-new")
    assert err is not None
    monkeypatch.undo()
    # old release restored at the version path; current serves it
    with open(os.path.join(store.releases_dir, "v1", "client.crt")) as f:
        assert f.read() == CERTS["CERT1"]
    assert _read_current(store) == CERTS["CERT1"]


def test_retarget_current_cleans_staging_link_on_failure(store, monkeypatch):
    assert _install(store, "v1", "C") is None
    assert store.activate("v1") is None

    real_replace = os.replace

    def failing_replace(src, dst):
        raise OSError(30, "Read-only file system")

    monkeypatch.setattr(os, "replace", failing_replace)
    with pytest.raises(OSError):
        store._retarget_current(os.path.join("releases", "v1"))
    monkeypatch.undo()
    # no dangling current.tmp-* staging links left behind
    stale = [e for e in os.listdir(store.root) if e.startswith("current.tmp-")]
    assert stale == []


def test_invalid_versions_rejected(store):
    for bad in ("", "a/b", ".hidden", "v1.tmp-1", "v1.old-2"):
        err = store.install(bad, CERTS["C"], KEYS["C"])
        assert err is not None, bad


def test_gc_collects_parked_dirs_after_grace(store, no_exchange):
    assert _install(store, "v1", "C") is None
    assert _install(store, "v1", "C2") is None  # parks the old dir
    parked = [e for e in os.listdir(store.releases_dir) if ".old-" in e]
    assert parked
    store._gc_stale_dirs(grace=0.0)
    left = [e for e in os.listdir(store.releases_dir) if ".old-" in e]
    assert left == []
    # the real release is never GC-eligible
    assert os.path.isdir(os.path.join(store.releases_dir, "v1"))
