"""Session tests with injectable transports (reference:
pkg/session/mock_session_test.go, session_reconnect_test.go)."""

import queue
import threading
import time

from gpud_tpu.session.session import Frame, Session


class LoopbackTransport:
    """Fake control plane: requests pushed via push(); responses collected."""

    def __init__(self, fail_connects=0):
        self.responses = []
        self.fail_connects = fail_connects
        self.connects = 0
        self.reader_stops = 0
        self.writer_stops = 0
        self._session = None

    def start_reader(self, session):
        self.connects += 1
        if self.connects <= self.fail_connects:
            raise ConnectionError("refused")
        self._session = session

        def stop():
            self.reader_stops += 1

        return stop

    def start_writer(self, session):
        self._drain = threading.Thread(target=self._pump, daemon=True)
        self._alive = True
        self._drain.start()

        def stop():
            self._alive = False
            self.writer_stops += 1

        return stop

    def _pump(self):
        while self._alive:
            try:
                frame = self._session.writer.get(timeout=0.05)
                self.responses.append(frame)
            except queue.Empty:
                continue

    def push(self, frame):
        self._session.reader.put(frame)


def _mk_session(transport, dispatch=None, **kw):
    return Session(
        endpoint="https://cp.example",
        machine_id="m1",
        token="t",
        dispatch_fn=dispatch or (lambda req: {"echo": req}),
        start_reader_fn=transport.start_reader,
        start_writer_fn=transport.start_writer,
        jitter_fn=lambda b: 0.01,
        **kw,
    )


def _wait(cond, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_request_response_roundtrip():
    tr = LoopbackTransport()
    s = _mk_session(tr)
    s.start()
    assert _wait(lambda: s.connected)
    tr.push(Frame(req_id="r1", data={"method": "states"}))
    assert _wait(lambda: tr.responses)
    resp = tr.responses[0]
    assert resp.req_id == "r1"
    assert resp.data == {"echo": {"method": "states"}}
    s.stop()


def test_dispatch_exception_becomes_error_response():
    tr = LoopbackTransport()

    def bad_dispatch(req):
        raise ValueError("kaboom")

    s = _mk_session(tr, dispatch=bad_dispatch)
    s.start()
    assert _wait(lambda: s.connected)
    tr.push(Frame(req_id="r2", data={"method": "x"}))
    assert _wait(lambda: tr.responses)
    assert "kaboom" in tr.responses[0].data["error"]
    s.stop()


def test_reconnect_with_backoff():
    tr = LoopbackTransport(fail_connects=2)
    s = _mk_session(tr)
    s.start()
    assert _wait(lambda: s.connected)
    assert tr.connects == 3  # two failures then success
    assert "refused" in s.last_connect_error

    # remote drop → reconnect; old streams stopped
    s.signal_reconnect("remote closed")
    assert _wait(lambda: tr.connects == 4)
    assert _wait(lambda: s.connected)
    assert s.reconnect_count == 1
    assert tr.reader_stops >= 1 and tr.writer_stops >= 1
    s.stop()


def test_frame_json_roundtrip():
    f = Frame(req_id="a", data={"x": 1})
    back = Frame.from_json(f.to_json())
    assert back.req_id == "a" and back.data == {"x": 1}
    assert Frame.from_json("not json") is None
    assert Frame.from_json('"a string"') is None
