"""Session tests with injectable transports (reference:
pkg/session/mock_session_test.go, session_reconnect_test.go)."""

import queue
import threading
import time

from gpud_tpu.session.session import Frame, Session


class LoopbackTransport:
    """Fake control plane: requests pushed via push(); responses collected."""

    def __init__(self, fail_connects=0):
        self.responses = []
        self.fail_connects = fail_connects
        self.connects = 0
        self.reader_stops = 0
        self.writer_stops = 0
        self._session = None

    def start_reader(self, session):
        self.connects += 1
        if self.connects <= self.fail_connects:
            raise ConnectionError("refused")
        self._session = session

        def stop():
            self.reader_stops += 1

        return stop

    def start_writer(self, session):
        self._drain = threading.Thread(target=self._pump, daemon=True)
        self._alive = True
        self._drain.start()

        def stop():
            self._alive = False
            self.writer_stops += 1

        return stop

    def _pump(self):
        while self._alive:
            try:
                frame = self._session.writer.get(timeout=0.05)
                self.responses.append(frame)
            except queue.Empty:
                continue

    def push(self, frame):
        self._session.reader.put(frame)


def _mk_session(transport, dispatch=None, **kw):
    return Session(
        endpoint="https://cp.example",
        machine_id="m1",
        token="t",
        dispatch_fn=dispatch or (lambda req: {"echo": req}),
        start_reader_fn=transport.start_reader,
        start_writer_fn=transport.start_writer,
        jitter_fn=lambda b: 0.01,
        **kw,
    )


def _wait(cond, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_request_response_roundtrip():
    tr = LoopbackTransport()
    s = _mk_session(tr)
    s.start()
    assert _wait(lambda: s.connected)
    tr.push(Frame(req_id="r1", data={"method": "states"}))
    assert _wait(lambda: tr.responses)
    resp = tr.responses[0]
    assert resp.req_id == "r1"
    assert resp.data == {"echo": {"method": "states"}}
    s.stop()


def test_dispatch_exception_becomes_error_response():
    tr = LoopbackTransport()

    def bad_dispatch(req):
        raise ValueError("kaboom")

    s = _mk_session(tr, dispatch=bad_dispatch)
    s.start()
    assert _wait(lambda: s.connected)
    tr.push(Frame(req_id="r2", data={"method": "x"}))
    assert _wait(lambda: tr.responses)
    assert "kaboom" in tr.responses[0].data["error"]
    s.stop()


def test_reconnect_with_backoff():
    tr = LoopbackTransport(fail_connects=2)
    s = _mk_session(tr)
    s.start()
    assert _wait(lambda: s.connected)
    assert tr.connects == 3  # two failures then success
    assert "refused" in s.last_connect_error

    # remote drop → reconnect; old streams stopped
    s.signal_reconnect("remote closed")
    assert _wait(lambda: tr.connects == 4)
    assert _wait(lambda: s.connected)
    assert s.reconnect_count == 1
    assert tr.reader_stops >= 1 and tr.writer_stops >= 1
    s.stop()


def test_frame_json_roundtrip():
    f = Frame(req_id="a", data={"x": 1})
    back = Frame.from_json(f.to_json())
    assert back.req_id == "a" and back.data == {"x": 1}
    assert Frame.from_json("not json") is None
    assert Frame.from_json('"a string"') is None


class AuthFailTransport(LoopbackTransport):
    """Rejects connects with a 401-shaped error until the token changes."""

    def __init__(self, good_token="t2"):
        super().__init__()
        self.good_token = good_token

    def start_reader(self, session):
        self.connects += 1
        self._session = session
        if session.token != self.good_token:
            raise ConnectionError("HTTP 401 Unauthorized: invalid token")

        def stop():
            self.reader_stops += 1

        return stop


def test_auth_failure_parks_reconnect_until_token_changes():
    """A revoked token must not cause a retry storm (reference:
    session_reconnect.go:38-226): the loop parks, records the failure,
    and resumes only when the token changes (updateToken path)."""
    from gpud_tpu.session.session import AUTH_RECHECK_INTERVAL  # noqa: F401

    tr = AuthFailTransport(good_token="t2")
    failures = []
    s = _mk_session(tr)
    s.on_auth_failure = failures.append
    # fast park loop for the test
    s.time_sleep_fn = lambda secs: s._stop.wait(min(secs, 0.02))
    s.start()
    assert _wait(lambda: s.auth_failed)
    connects_at_park = tr.connects
    # parked: no further connect attempts while the token is unchanged
    time.sleep(0.3)
    assert tr.connects == connects_at_park, "retry storm while auth-parked"
    assert failures and "401" in failures[0]
    # token rotated (what _m_updateToken / the FIFO does) → reconnects
    s.token = "t2"
    assert _wait(lambda: s.connected)
    assert not s.auth_failed
    s.stop()


def test_network_errors_still_retry_with_backoff():
    tr = LoopbackTransport(fail_connects=3)
    s = _mk_session(tr)
    s.time_sleep_fn = lambda secs: s._stop.wait(min(secs, 0.02))
    s.start()
    assert _wait(lambda: s.connected)
    assert tr.connects >= 4
    assert not s.auth_failed
    s.stop()


def test_is_auth_error_classification():
    from gpud_tpu.session.session import is_auth_error

    class Resp:
        status_code = 401

    class HTTPError(Exception):
        def __init__(self):
            self.response = Resp()

    assert is_auth_error(HTTPError())
    assert is_auth_error("grpc UNAUTHENTICATED: bad creds")
    assert is_auth_error("403 Forbidden")
    assert not is_auth_error("connection refused")
    assert not is_auth_error("read timeout")


def test_is_auth_error_rejects_lookalikes():
    from gpud_tpu.session.session import is_auth_error

    # incidental digits and OS permission errors are NOT auth failures
    assert not is_auth_error("connection refused to http://cp:4013/api")
    assert not is_auth_error("[Errno 13] Permission denied: '/var/run/x'")
    # a definite non-auth HTTP status short-circuits text matching
    class Resp:
        status_code = 503
    class HTTPError(Exception):
        def __init__(self):
            self.response = Resp()
        def __str__(self):
            return "503 unavailable (was 401 earlier)"
    assert not is_auth_error(HTTPError())
    # anchored matches still hit
    assert is_auth_error("401 Client Error: Unauthorized for url")


def test_reconnect_drains_stale_reader_queue():
    """Reference: drainReaderChannel on reconnect — requests queued for a
    dead stream must not replay into the new connection."""
    tr = LoopbackTransport()
    s = _mk_session(tr)
    s.time_sleep_fn = lambda secs: s._stop.wait(min(secs, 0.02))
    s.start()
    assert _wait(lambda: s.connected)
    # simulate requests stuck in the reader when the stream dies
    s.reader.put(Frame(req_id="stale-1", data={"method": "x"}))
    s.reader.put(Frame(req_id="stale-2", data={"method": "x"}))
    # block the serve loop from consuming them first: kill via reconnect
    s.signal_reconnect("stream died")
    assert _wait(lambda: s.reconnect_count >= 1)
    assert _wait(lambda: s.connected)
    # fresh connection: push a real request and expect exactly its response
    tr.push(Frame(req_id="fresh", data={"n": 1}))
    assert _wait(lambda: any(f.req_id == "fresh" for f in tr.responses))
    # the queue itself was drained at reconnect
    assert s.reader.empty()
    s.stop()
