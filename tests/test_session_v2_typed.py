"""Session v2 revision-2 typed protocol (VERDICT r2 Missing #2).

Reference shape: pkg/session/v2/session.proto:16-60 — per-method request
messages in the ManagerPacket oneof with a top-level request_id, Result
agent packets, Hello advertising a revision range. Covers negotiation in
both directions: a rev-2 manager drives typed requests; a rev-1 (old)
manager keeps the legacy Frame tunnel against the same agent.
"""

import json
import time

import pytest

grpc = pytest.importorskip("grpc")

from gpud_tpu.session.session import Session
from gpud_tpu.session.v2 import session_pb2 as pb
from gpud_tpu.session.v2 import typed
from tests.test_session_v2 import FakeManagerV2, _wait


def _session(manager, dispatch_fn):
    return Session(
        endpoint=f"http://127.0.0.1:{manager.port}",
        machine_id="m-typed",
        token="tok",
        machine_proof="proof",
        dispatch_fn=dispatch_fn,
        protocol="v2",
        jitter_fn=lambda b: 0.05,
    )


# -- negotiation + wire roundtrips ----------------------------------------

def test_hello_advertises_revision_range_and_capabilities():
    m = FakeManagerV2(revision=1)
    m.start()
    try:
        s = _session(m, lambda req: {})
        s.start()
        assert _wait(lambda: s.connected)
        h = m.hellos[0]
        assert h.min_revision == 1
        assert h.max_revision == 3
        assert "typed-requests" in list(h.capabilities)
        assert "wire-zlib" in list(h.capabilities)
        assert h.revision == 1  # legacy compat field for old managers
        s.stop()
    finally:
        m.stop()


def test_rev2_manager_gets_typed_dispatch_and_result():
    m = FakeManagerV2(revision=2)
    m.start()
    try:
        seen = []

        def dispatch(req):
            seen.append(req)
            return {"status": "ok", "method_seen": req.get("method")}

        s = _session(m, dispatch)
        s.start()
        assert _wait(lambda: s.connected)

        pkt = pb.ManagerPacket()
        pkt.request_id = "req-42"
        pkt.get_states.components.append("cpu")
        m.outbound.put(pkt)

        assert _wait(lambda: m.results)
        request_id, payload = m.results[0]
        assert request_id == "req-42"
        assert payload == {"status": "ok", "method_seen": "states"}
        assert seen[0] == {"method": "states", "components": ["cpu"]}
        assert m.responses == []  # nothing rode the legacy Frame tunnel
        s.stop()
    finally:
        m.stop()


def test_rev1_manager_keeps_frame_tunnel():
    # old manager ↔ new agent: ack pins rev 1, requests/responses stay
    # JSON Frames even though the agent could speak rev 2
    m = FakeManagerV2(revision=1)
    m.start()
    try:
        s = _session(m, lambda req: {"echo": req})
        s.start()
        assert _wait(lambda: s.connected)
        m.outbound.put(("r1", {"method": "ping"}))
        assert _wait(lambda: m.responses)
        assert m.responses[0] == ("r1", {"echo": {"method": "ping"}})
        assert m.results == []
        s.stop()
    finally:
        m.stop()


def test_legacy_manager_acking_zero_means_rev1():
    m = FakeManagerV2(revision=0)
    m.start()
    try:
        s = _session(m, lambda req: {"ok": True})
        s.start()
        assert _wait(lambda: s.connected)
        m.outbound.put(("rz", {"method": "ping"}))
        assert _wait(lambda: m.responses)
        assert m.results == []
        s.stop()
    finally:
        m.stop()


def test_unknown_future_payload_answers_error_result():
    # manager newer than agent: a payload field this agent's schema does
    # not know decodes as "no payload"; the agent must answer an error
    # Result instead of dangling the request_id
    m = FakeManagerV2(revision=2)
    m.start()
    try:
        s = _session(m, lambda req: {"ok": True})
        s.start()
        assert _wait(lambda: s.connected)

        raw = pb.ManagerPacket()
        raw.request_id = "req-future"
        blob = raw.SerializeToString()
        # append an unknown length-delimited field (#99, varint-encoded
        # tag 0x9a 0x06) — simulates a future request type; python
        # protobuf preserves unknown fields through reserialization
        blob += b"\x9a\x06\x03" + b"xyz"
        pkt = pb.ManagerPacket.FromString(blob)
        m.outbound.put(pkt)

        assert _wait(lambda: m.results)
        request_id, payload = m.results[0]
        assert request_id == "req-future"
        assert "error" in payload
        s.stop()
    finally:
        m.stop()


def test_rev2_typed_inject_fault_roundtrip():
    m = FakeManagerV2(revision=2)
    m.start()
    try:
        seen = []
        s = _session(m, lambda req: (seen.append(req), {"status": "ok"})[1])
        s.start()
        assert _wait(lambda: s.connected)

        pkt = pb.ManagerPacket()
        pkt.request_id = "req-if"
        pkt.inject_fault.tpu_error_name = "hbm_ecc_uncorrectable"
        pkt.inject_fault.chip_id = 2
        m.outbound.put(pkt)

        assert _wait(lambda: m.results)
        assert seen[0] == {
            "method": "injectFault",
            "tpu_error_name": "hbm_ecc_uncorrectable",
            "chip_id": 2,
        }
        s.stop()
    finally:
        m.stop()


# -- conversion contract (no gRPC) ----------------------------------------

def test_convert_get_events_defaults():
    pkt = pb.ManagerPacket()
    pkt.request_id = "r"
    pkt.get_events.SetInParent()
    assert typed.request_to_dict(pkt) == {"method": "events"}
    pkt.get_events.since_unix = 123.5
    assert typed.request_to_dict(pkt) == {"method": "events", "since": 123.5}


def test_convert_inject_fault_kernel_message():
    pkt = pb.ManagerPacket()
    pkt.inject_fault.kernel_message.message = "accel0: oops"
    pkt.inject_fault.kernel_message.priority = 3
    assert typed.request_to_dict(pkt) == {
        "method": "injectFault",
        "kernel_message": "accel0: oops",
        "priority": 3,
    }


def test_convert_update_config_parses_json_sections():
    pkt = pb.ManagerPacket()
    pkt.update_config.configs_json["ici"] = json.dumps({"expected_links": 24})
    pkt.update_config.configs_json["chip_count"] = "4"
    req = typed.request_to_dict(pkt)
    assert req == {
        "method": "updateConfig",
        "configs": {"ici": {"expected_links": 24}, "chip_count": 4},
    }


def test_convert_update_config_rejects_bad_json():
    pkt = pb.ManagerPacket()
    pkt.update_config.configs_json["ici"] = "{not json"
    with pytest.raises(typed.UnsupportedRequest):
        typed.request_to_dict(pkt)


def test_convert_plugin_specs_full_shape():
    pkt = pb.ManagerPacket()
    spec = pkt.set_plugin_specs.specs.add()
    spec.name = "nv-check"
    spec.plugin_type = "component"
    spec.run_mode = "auto"
    spec.interval_seconds = 120.0
    st = spec.steps.add()
    st.name = "probe"
    st.script = "echo '{\"ok\": 1}'"
    spec.parser.json_paths["ok"] = "$.ok"
    r = spec.parser.match_rules.add()
    r.field = "ok"
    r.regex = "1"
    r.health = "Healthy"
    req = typed.request_to_dict(pkt)
    assert req["method"] == "setPluginSpecs"
    got = req["specs"][0]
    assert got["name"] == "nv-check"
    assert got["steps"] == [{"name": "probe", "script": "echo '{\"ok\": 1}'"}]
    assert got["parser"]["json_paths"] == {"ok": "$.ok"}
    assert got["parser"]["match_rules"][0]["health"] == "Healthy"

    # the converted dict satisfies the plugin spec model end to end
    from gpud_tpu.plugins.spec import specs_from_list

    specs = specs_from_list(req["specs"])
    assert specs[0].validate() is None


def test_convert_parameterless_methods():
    for field, method in (
        ("gossip", "gossip"),
        ("get_token", "getToken"),
        ("logout", "logout"),
        ("delete_machine", "delete"),
        ("get_package_status", "packageStatus"),
        ("kap_mtls_status", "kapMTLSStatus"),
        ("get_plugin_specs", "getPluginSpecs"),
    ):
        pkt = pb.ManagerPacket()
        getattr(pkt, field).SetInParent()
        assert typed.request_to_dict(pkt) == {"method": method}


def test_negotiate_revision_clamps():
    assert typed.negotiate_revision(0, 2) == 1   # legacy manager
    assert typed.negotiate_revision(1, 2) == 1
    assert typed.negotiate_revision(2, 2) == 2
    assert typed.negotiate_revision(3, 2) == 2   # future manager clamped
    assert typed.negotiate_revision(3, 3) == 3   # rev-3 compressed wire
    assert typed.negotiate_revision(2, 3) == 2   # rev-2 peer: no compression


# -- manager-side encoder (control plane) ----------------------------------

# one representative request dict per typed method: the encoder
# (dict_to_request, used by the standalone control plane) must roundtrip
# through the agent-side decoder (request_to_dict) without loss
ROUNDTRIP_CASES = [
    {"method": "states"},
    {"method": "states", "components": ["cpu", "memory"]},
    {"method": "events", "since": 1700000000.5},
    {"method": "metrics", "since": 1700000001.0},
    {"method": "gossip"},
    {"method": "diagnostic", "script_base64": "ZWNobyBoaQ==",
     "since": 123.0, "timeout_seconds": 5.0},
    {"method": "reboot", "delay_seconds": 30.0},
    {"method": "setHealthy", "component": "accelerator-tpu-ici"},
    {"method": "triggerComponent", "component": "cpu", "tag": "smoke"},
    {"method": "deregisterComponent", "component": "nfs"},
    {"method": "injectFault", "tpu_error_name": "tpu_ici_cable_fault",
     "chip_id": 3, "detail": "bench"},
    {"method": "injectFault", "kernel_message": "oops line", "priority": 0},
    {"method": "bootstrap", "script_base64": "ZWNobyBoaQ==",
     "timeout_seconds": 9.0},
    {"method": "updateConfig",
     "configs": {"ici": {"expected_links": 4}, "chip_count": 8}},
    {"method": "updateToken", "token": "new-tok"},
    {"method": "getToken"},
    {"method": "logout"},
    {"method": "delete"},
    {"method": "packageStatus"},
    {"method": "update", "version": "1.2.3"},
    {"method": "kapMTLSStatus"},
    {"method": "kapMTLSUpdateCredentials", "version": "v7",
     "cert_pem": "CERT", "key_pem": "KEY", "activate": True},
    {"method": "kapMTLSActivate", "version": "v7"},
    {"method": "getPluginSpecs"},
    {"method": "setPluginSpecs", "specs": [
        {"name": "p1", "plugin_type": "component", "run_mode": "auto",
         "interval_seconds": 60.0, "timeout_seconds": 10.0,
         "steps": [{"name": "s1", "script_base64": "ZWNobyBoaQ=="}],
         "tags": ["t1"],
         "parser": {"json_paths": {"out": "result.value"},
                    "match_rules": [{"regex": "bad", "field": "out",
                                     "health": "Unhealthy",
                                     "suggested_actions": ["RMA"],
                                     "description": "d"}]}}]},
]


@pytest.mark.parametrize(
    "req", ROUNDTRIP_CASES, ids=[c["method"] + str(i) for i, c in enumerate(ROUNDTRIP_CASES)]
)
def test_encoder_decoder_roundtrip(req):
    mpkt = typed.dict_to_request(req, "rt-1")
    assert mpkt.request_id == "rt-1"
    # wire trip: serialize + reparse like the real stream does
    wire = pb.ManagerPacket.FromString(mpkt.SerializeToString())
    got = typed.request_to_dict(wire)
    assert got == req


def test_encoder_covers_every_typed_method():
    covered = {c["method"] for c in ROUNDTRIP_CASES}
    assert covered == set(typed.FIELD_TO_METHOD.values())


def test_encoder_rejects_unknown_method():
    with pytest.raises(typed.UnsupportedRequest):
        typed.dict_to_request({"method": "notAThing"}, "x")
